"""Runnable drivers for every BASELINE.json config.

Each config prints one JSON line (same shape as bench.py).  Sizes scale
with the backend: full BASELINE sizes on an accelerator, reduced on CPU
so the suite stays runnable in CI.  Usage::

    python benchmarks/baseline_configs.py            # all configs
    python benchmarks/baseline_configs.py -c 3       # one config

Configs (BASELINE.json):
  1 dhtnode single-process: 1K get() lookups over a 10K-node routing
    table — CPU reference (the native C++ sorted walk) vs the device
    batched lookup.
  2 batched findClosestNodes: 131K queries × 1M ids, top-16 (the
    headline bench — delegates to bench.py's measurement).
  3 iterative Search simulation: α-parallel lookups vs a 10M-node
    simulated network, k=8 convergence, hop counts.
  4 bucket-refresh sweep: full radix partition + per-bucket stats over
    10M ids.
  5 multi-chip sharded table: row-sharded lookup with ICI top-k merge
    (one real chip here; the same code dry-runs on an 8-device virtual
    mesh — __graft_entry__.dryrun_multichip).

Timing: all device numbers use the serialized-chain slope
(bench.chain_slope) — a jitted while_loop (traced trip count) repeats
the workload with index-perturbed inputs and the per-rep time is the slope between two
rep counts.  Wall-clock timing of dispatched work is NOT trusted:
block_until_ready() on a tunneled device can return before execution
completes (see bench.py docstring; it inflated round-1 numbers ~100×).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def config1() -> dict:
    """1K get() lookups over a 10K-node table: native C++ scalar walk
    (the CPU reference) vs the batched device kernel."""
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops.ids import ids_to_bytes
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              expand_table, expanded_topk)
    from opendht_tpu import native

    N, Q, K = 10_000, 1_000, 8
    rng = np.random.default_rng(1)
    table = rng.integers(0, 2**32, size=(N, 5), dtype=np.uint32)
    queries = rng.integers(0, 2**32, size=(Q, 5), dtype=np.uint32)

    sorted_ids, perm, n_valid = jax.block_until_ready(
        sort_table(jnp.asarray(table)))
    lut = build_prefix_lut(sorted_ids, n_valid)
    expanded = expand_table(sorted_ids)

    def body(q, sorted_ids, expanded, n_valid, lut):
        # fast2 + LUT-only positioning: the get() contract returns node
        # sets, and at N=10K the 16-bit LUT has ~0.15-row buckets —
        # measured 27.9M vs 8.5M lookups/s for fast3 with the bounded
        # search at this size
        d, idx, c = expanded_topk(sorted_ids, expanded, n_valid, q, k=K,
                                  select="fast2", lut=lut, lut_steps=0)
        return jnp.sum(c.astype(jnp.float32))

    # per-rep work is ~40 µs at this size: use deep rep counts so the
    # slope rises above run-to-run noise (single compile either way —
    # the trip count is traced)
    dt_dev = chain_slope(body, jnp.asarray(queries), sorted_ids, expanded,
                         n_valid, lut, r1=64, r2=512)
    _, _, cert = jax.block_until_ready(
        expanded_topk(sorted_ids, expanded, n_valid, jnp.asarray(queries),
                      k=K, select="fast2", lut=lut, lut_steps=0))
    cert_frac = float(np.asarray(cert).mean())

    baseline = None
    if native.available():
        t_bytes = ids_to_bytes(np.asarray(sorted_ids)).reshape(N, 20)
        q_bytes = ids_to_bytes(queries).reshape(Q, 20)
        # native path runs on the host CPU: plain wall timing is honest
        from bench import best_of
        baseline = best_of(
            lambda: native.sorted_closest(t_bytes, q_bytes, k=K), tries=7)
    return {"metric": "config1 1K get() over 10K-node table "
                      "(device-serialized chain slope, fast2 + LUT-only "
                      "positioning, certified %.5f)" % cert_frac,
            "value": round(Q / dt_dev, 1), "unit": "lookups/s",
            "vs_baseline": round((Q / dt_dev) / (Q / baseline), 2)
            if baseline else None}


def config3_tp(Q: int = 0, N: int = 0, limbs: int = 0) -> dict:
    """Iterative search with the TABLE SHARDED over the mesh t axis
    (parallel.tp_simulate_lookups) — each shard holds a contiguous range
    of the global sorted order; positioning and row fetch are one psum
    each.  This is the mode whose table exceeds one shard (and, on a
    v5e pod slice, one chip's HBM).  Timed like every other device
    number here: serialized-chain slope over the pre-placed compiled
    callable (wall-clocking dispatches is never trusted — see module
    docstring)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from bench import chain_slope
    from opendht_tpu.ops.sorted_table import default_lut_bits, sort_table
    from opendht_tpu.core.search import ALPHA, SEARCH_NODES
    from opendht_tpu.parallel import make_mesh, pad_to_multiple
    from opendht_tpu.parallel.sharded import build_tp_lookup

    n_dev = len(jax.devices())
    N = N or (1_000_000 if n_dev > 1 else 262_144)
    mesh = make_mesh(n_dev)
    n_q = mesh.shape["q"]
    Q = max(n_q, (Q or 4_096))
    if Q % n_q:
        Q += n_q - Q % n_q                 # round UP: never drop lookups
    limbs = limbs or 2
    k1, k2 = jax.random.split(jax.random.PRNGKey(30))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    padded, _ = pad_to_multiple(np.asarray(sorted_ids), mesh.shape["t"])
    shard_n = padded.shape[0] // mesh.shape["t"]

    fn = build_tp_lookup(mesh, shard_n, Q, 8, 3, SEARCH_NODES, 48,
                         default_lut_bits(shard_n), limbs)
    sorted_placed = jax.device_put(jnp.asarray(padded),
                                   NamedSharding(mesh, P("t", None)))
    targets_placed = jax.device_put(targets, NamedSharding(mesh, P("q", None)))
    nv = jnp.asarray(n_valid, jnp.int32)

    out = jax.block_until_ready(
        fn(sorted_placed, nv, targets_placed, jnp.int32(1)))
    hops = np.asarray(out["hops"])
    conv = float(np.asarray(out["converged"]).mean())

    def body(t, sorted_placed, nv):
        o = fn(sorted_placed, nv, t, jnp.int32(1))
        return (jnp.sum(o["hops"].astype(jnp.float32))
                + jnp.sum(o["converged"].astype(jnp.float32)))

    dt = chain_slope(body, targets_placed, sorted_placed, nv, r1=1, r2=4)
    return {"metric": "config3-tp table-sharded iterative search, mesh "
                      "q=%d t=%d (table %d rows/shard), %d lookups x %d "
                      "nodes, state_limbs=%d; p50 hops %d, converged %.3f "
                      "(device-serialized chain slope)"
                      % (mesh.shape["q"], mesh.shape["t"], shard_n, Q,
                         N, limbs, int(np.percentile(hops, 50)), conv),
            "value": round(Q / dt, 1), "unit": "lookups/s",
            "vs_baseline": None}


def config3(Q: int = 0, N: int = 0, chunk: int = 0,
            limbs: int = 0) -> dict:
    """α-parallel iterative lookups to k=8 convergence.

    The north-star shape is ``-Q 1000000`` against the 10M-node table
    (BASELINE.json configs[2]): the query burst is streamed through the
    device in fixed-shape waves (one compiled executable; search state
    for one wave resident at a time) so HBM holds wave state + the
    sorted table, never the full burst.

    Throughput is the chain slope of one wave (device-serialized), and
    burst numbers derive from it: burst time = n_waves × wave time.
    The separately-reported ``p50 burst completion`` is wave-time ×
    (wave index holding the median lookup + 1) — FIFO retire order.
    """
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              default_lut_bits)

    on_accel = jax.devices()[0].platform != "cpu"
    N = N or (10_000_000 if on_accel else 100_000)
    Q = Q or (16_384 if on_accel else 1_024)
    # measured optimum wave width on v5e (chunk sweep at -Q 1000000:
    # 16384 → 63.2K/s, 131072 → 56.7K/s — smaller waves keep the
    # while_loop's straggler tail short)
    chunk = min(Q, chunk or (16_384 if on_accel else 1_024))
    key = jax.random.PRNGKey(3)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    n_waves = (Q + chunk - 1) // chunk
    pad = n_waves * chunk - Q
    if pad:
        targets = jnp.concatenate([targets, targets[:pad]], axis=0)
    waves = [targets[i * chunk:(i + 1) * chunk] for i in range(n_waves)]

    # state_limbs=2: merge sorts move 5 operands instead of 8 and the
    # per-round reply gather fetches 2 planes instead of 5 — bitwise
    # identical to the exact engine on random ids
    # (tests/test_search.py::test_state_limbs_2_bitwise_identical)
    limbs = limbs or 2

    def run_wave(t, sorted_ids=sorted_ids, n_valid=n_valid, lut=lut):
        return simulate_lookups(sorted_ids, n_valid, t, alpha=3, k=8, lut=lut,
                                state_limbs=limbs)

    # stats pass over the full burst (hops / convergence are exact)
    hops_all, conv_all = [], []
    for w in waves:
        o = run_wave(w)
        hops_all.append(np.asarray(o["hops"]))
        conv_all.append(np.asarray(o["converged"]))
    hops = np.concatenate(hops_all)[:Q]
    conv = float(np.concatenate(conv_all)[:Q].mean())

    # timed pass: serialized-chain slope of one wave
    def body(t, sorted_ids, n_valid, lut):
        o = run_wave(t, sorted_ids, n_valid, lut)
        return (jnp.sum(o["hops"].astype(jnp.float32))
                + jnp.sum(o["converged"].astype(jnp.float32)))

    wave_dt = chain_slope(body, waves[0], sorted_ids, n_valid, lut,
                          r1=1, r2=4)
    dt = wave_dt * n_waves
    p50_wave = min((Q // 2) // chunk, n_waves - 1)
    return {"metric": "config3 iterative search sim, alpha=3 k=8, "
                      "%d lookups x %d nodes, %d waves of %d; p50 hops %d, "
                      "converged %.3f, p50 burst completion %.3fs "
                      "(wave chain slope %.3fs)"
                      % (Q, N, n_waves, chunk,
                         int(np.percentile(hops, 50)), conv,
                         wave_dt * (p50_wave + 1), wave_dt),
            "value": round(Q / dt, 1), "unit": "lookups/s/chip",
            "vs_baseline": None}


def config4() -> dict:
    """Bucket-refresh sweep: radix partition + per-bucket stats."""
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops import radix

    on_accel = jax.devices()[0].platform != "cpu"
    N = 10_000_000 if on_accel else 1_000_000
    key = jax.random.PRNGKey(4)
    ids = jax.random.bits(key, (N, 5), dtype=jnp.uint32)
    self_id = jax.random.bits(jax.random.PRNGKey(5), (5,), dtype=jnp.uint32)
    valid = jnp.ones((N,), bool)
    last = jnp.zeros((N,), jnp.float32)

    def body(x, self_id, valid, last):
        b = radix.bucket_of(self_id, x)
        c = radix.bucket_counts(self_id, x, valid)
        s = radix.bucket_last_seen(self_id, x, valid, last)
        return (jnp.sum(b.astype(jnp.float32)) * 1e-9
                + jnp.sum(c.astype(jnp.float32))
                + jnp.sum(s) * 1e-9)

    # the compare-and-reduce kernels run the full sweep in ~6 ms — deep
    # rep counts keep the slope above the tunnel noise floor
    r1, r2 = (32, 256) if on_accel else (2, 8)
    dt = chain_slope(body, ids, self_id, valid, last, r1=r1, r2=r2)
    return {"metric": "config4 radix bucket sweep over %d ids "
                      "(device-serialized chain slope)" % N,
            "value": round(N / dt, 1), "unit": "ids/s/chip",
            "vs_baseline": None}


def config5() -> dict:
    """Sharded lookup with top-k merge at REAL table scale.

    On the accelerator this runs N=64M ids (1.28 GB of ids; the
    expanded window-row form is 3x that) — an actual slice of the 100M-
    node BASELINE shape, bounded by one chip's HBM here (the v5e-8 in
    BASELINE.json holds 8 such shards = 512M ids).  Alongside the
    throughput measurement it characterizes the ICI merge cost as a
    model, because this host has one real chip:

      - wire volume is exact by construction: each query all_gathers
        n_t per-shard top-k candidate sets of k rows x (20 B id + 4 B
        index) = n_t * k * 24 B per query over the t axis;
      - the merge RE-SORT is pure per-chip compute — measured here on
        the real chip as select_topk over [Q, n_t*k] candidates for
        n_t in {2,4,8} (chain slope, printed in the metric), so the
        v5e-8 projection = per-shard lookup + measured merge(n_t=8)
        + wire/ICI-bandwidth.
    """
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops.sorted_table import default_lut_bits
    from opendht_tpu.ops.xor_topk import select_topk
    from opendht_tpu.parallel import (make_mesh, sharded_sort_table,
                                      sharded_expand_table,
                                      sharded_window_lookup)

    n_dev = len(jax.devices())
    on_accel = jax.devices()[0].platform != "cpu"
    N = 64_000_000 if on_accel else 262_144
    Q = 65_536 if on_accel else 4_096
    K = 8
    k1, k2 = jax.random.split(jax.random.PRNGKey(6))
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    mesh = make_mesh(n_dev)

    if on_accel and n_dev == 1:
        # One real chip: run the PER-SHARD kernel at the full 64M scale
        # (= one chip's share of a 512M-id v5e-8 table).  Memory is
        # budgeted deliberately: the id matrix is generated INSIDE the
        # sort program (no persistent input buffer) and the 3.9 GB
        # window-row expansion is built via the chunked low-peak
        # builder — the one-shot expand peaks ~2.5x output and OOMs.
        # The all_gather merge is t=1-trivial here; its cost is the
        # separately measured model below.
        from opendht_tpu.ops.sorted_table import (build_prefix_lut,
                                                  expand_table_chunked,
                                                  expanded_topk, sort_table)

        @jax.jit
        def make_sorted(k):
            return sort_table(jax.random.bits(k, (N, 5), dtype=jnp.uint32))

        sorted_ids, perm, n_valid = jax.block_until_ready(make_sorted(k1))
        del perm             # unused here; 256 MB off the expansion peak
        expanded = jax.block_until_ready(
            expand_table_chunked(sorted_ids, chunks=8))
        lut = jax.block_until_ready(
            build_prefix_lut(sorted_ids, n_valid, bits=default_lut_bits(N)))

        def body(q, sorted_ids, expanded, n_valid, lut):
            d, idx, c = expanded_topk(sorted_ids, expanded, n_valid, q,
                                      k=K, select="fast2", lut=lut,
                                      lut_steps=0)
            return (jnp.sum(c.astype(jnp.float32))
                    + jnp.sum(idx[:, 0].astype(jnp.float32)) * 1e-9)

        dt = chain_slope(body, queries, sorted_ids, expanded, n_valid, lut,
                         r1=4, r2=32)
        _, _, cert = jax.block_until_ready(
            expanded_topk(sorted_ids, expanded, n_valid, queries, k=K,
                          select="fast2", lut=lut, lut_steps=0))
        cert_frac = float(np.asarray(cert).mean())
    else:
        cert_frac = None
        table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
        sorted_ids, perm, n_valid = jax.block_until_ready(
            sharded_sort_table(mesh, table))
        del table
        expanded, lut = jax.block_until_ready(
            sharded_expand_table(mesh, sorted_ids, n_valid,
                                 bits=default_lut_bits(N // mesh.shape['t'])))

        def body(q, sorted_ids, perm, n_valid, expanded, lut):
            d, idx = sharded_window_lookup(mesh, q, sorted_ids, perm, n_valid,
                                           k=K, expanded=expanded, lut=lut)
            return jnp.sum((idx >= 0).astype(jnp.float32))

        dt = chain_slope(body, queries, sorted_ids, perm, n_valid, expanded,
                         lut, r1=1, r2=3)

    # merge-cost model: re-sort time vs shard count (single-chip compute)
    merge_ms = {}
    for n_t in (2, 4, 8):
        kc = jax.random.split(jax.random.PRNGKey(60 + n_t))
        cd = jax.random.bits(kc[0], (Q, n_t * K, 5), dtype=jnp.uint32)
        ci = jax.random.randint(kc[1], (Q, n_t * K), 0, N, dtype=jnp.int32)

        def merge_body(q, cd, ci):
            # perturb indices by the rep counter via q's first column so
            # reps stay distinct; inv=0 (all candidates valid)
            cj = ci ^ (q[:, :1] & 1).astype(jnp.int32)
            d, i, inv = select_topk(cd, cj, jnp.zeros_like(cj), K)
            return jnp.sum(i.astype(jnp.float32)) * 1e-9

        # sub-ms workload: deep rep chains lift the slope above the
        # tunnel noise floor (shallow chains measured non-monotonic)
        mdt = chain_slope(merge_body, queries, cd, ci, r1=64, r2=512)
        merge_ms[n_t] = round(mdt * 1e3, 2)
        del cd, ci

    return {"metric": "config5 sharded lookup, %d device(s), %d queries x "
                      "%d ids (device-serialized chain slope%s); ICI merge "
                      "model: wire = n_t*%d*24 B/query, re-sort ms/batch "
                      "%s (measured vs n_t)"
                      % (n_dev, Q, N,
                         "" if cert_frac is None
                         else ", certified %.5f" % cert_frac,
                         K, json.dumps(merge_ms, sort_keys=True)),
            "value": round(Q / dt, 1), "unit": "lookups/s",
            "vs_baseline": None}


def config2() -> dict:
    """Delegates to the headline bench (bench.py)."""
    from bench import measure
    out = measure()
    out["metric"] = "config2 " + out["metric"]
    return out


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="BASELINE.json config drivers")
    p.add_argument("-c", "--config", type=int, default=0,
                   help="config number (default: all)")
    p.add_argument("-Q", type=int, default=0,
                   help="config3: concurrent lookup count "
                        "(north star: 1000000)")
    p.add_argument("-N", type=int, default=0,
                   help="config3: network size (default 10M on device)")
    p.add_argument("--chunk", type=int, default=0,
                   help="config3: lookups per device wave (not used "
                        "with --tp: the tp engine runs one batch)")
    p.add_argument("--tp", action="store_true",
                   help="config3: shard the table over the mesh t axis "
                        "(tp_simulate_lookups) instead of replicating it")
    p.add_argument("--limbs", type=int, default=0,
                   help="config3: distance limbs carried through the "
                        "merge sorts (2 = fast default, 5 = exact-order)")
    args = p.parse_args(argv)
    todo = [args.config] if args.config else sorted(CONFIGS)
    for c in todo:
        if c == 3 and args.tp:
            print(json.dumps(config3_tp(Q=args.Q, N=args.N,
                                        limbs=args.limbs)))
            continue
        kw = ({"Q": args.Q, "N": args.N, "chunk": args.chunk,
               "limbs": args.limbs}
              if c == 3 else {})
        print(json.dumps(CONFIGS[c](**kw)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
