"""Runnable drivers for every BASELINE.json config.

Each config prints one JSON line (same shape as bench.py).  Sizes scale
with the backend: full BASELINE sizes on an accelerator, reduced on CPU
so the suite stays runnable in CI.  Usage::

    python benchmarks/baseline_configs.py            # all configs
    python benchmarks/baseline_configs.py -c 3       # one config

Configs (BASELINE.json):
  1 dhtnode single-process: 1K get() lookups over a 10K-node routing
    table — CPU reference (the native C++ sorted walk) vs the device
    batched lookup.
  2 batched findClosestNodes: 131K queries × 1M ids, top-16 (the
    headline bench, see bench.py).
  3 iterative Search simulation: α-parallel lookups vs a 10M-node
    simulated network, k=8 convergence, hop counts.
  4 bucket-refresh sweep: full radix partition + per-bucket stats over
    10M ids.
  5 multi-chip sharded table: row-sharded lookup with ICI top-k merge
    (one real chip here; the same code dry-runs on an 8-device virtual
    mesh — __graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _rates(fn, reps: int = 5, warm: int = 2):
    import jax
    for _ in range(warm):
        jax.block_until_ready(fn())
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def config1() -> dict:
    """1K get() lookups over a 10K-node table: native C++ scalar walk
    (the CPU reference) vs the batched device kernel."""
    import jax
    import jax.numpy as jnp
    from opendht_tpu.ops.ids import ids_to_bytes
    from opendht_tpu.ops.sorted_table import sort_table, window_topk
    from opendht_tpu import native

    N, Q, K = 10_000, 1_000, 8
    rng = np.random.default_rng(1)
    table = rng.integers(0, 2**32, size=(N, 5), dtype=np.uint32)
    queries = rng.integers(0, 2**32, size=(Q, 5), dtype=np.uint32)

    sorted_ids, perm, n_valid = jax.block_until_ready(
        sort_table(jnp.asarray(table)))
    dt_dev = _rates(lambda: window_topk(sorted_ids, n_valid,
                                        jnp.asarray(queries), k=K))

    baseline = None
    if native.available():
        t_bytes = ids_to_bytes(np.asarray(sorted_ids)).reshape(N, 20)
        q_bytes = ids_to_bytes(queries).reshape(Q, 20)
        # same warm + best-of-N treatment as the device path
        baseline = _rates(
            lambda: native.sorted_closest(t_bytes, q_bytes, k=K))
    return {"metric": "config1 1K get() over 10K-node table",
            "value": round(Q / dt_dev, 1), "unit": "lookups/s",
            "vs_baseline": round((Q / dt_dev) / (Q / baseline), 2)
            if baseline else None}


def config3(Q: int = 0, N: int = 0, chunk: int = 0) -> dict:
    """α-parallel iterative lookups to k=8 convergence.

    The north-star shape is ``-Q 1000000`` against the 10M-node table
    (BASELINE.json configs[2]): the query burst is streamed through the
    device in fixed-shape waves (one compiled executable; search state
    for one wave resident at a time) so HBM holds wave state + the
    sorted table, never the full burst.  Reported latency is honest
    FIFO-burst completion: every lookup in wave *i* completes when its
    wave retires, so the p50 lookup latency is the retire time of the
    wave holding the median lookup, measured from burst submission.
    """
    import jax
    import jax.numpy as jnp
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import sort_table

    on_accel = jax.devices()[0].platform != "cpu"
    N = N or (10_000_000 if on_accel else 100_000)
    Q = Q or (16_384 if on_accel else 1_024)
    chunk = min(Q, chunk or (131_072 if on_accel else 1_024))
    key = jax.random.PRNGKey(3)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    del table

    n_waves = (Q + chunk - 1) // chunk
    pad = n_waves * chunk - Q
    if pad:
        targets = jnp.concatenate([targets, targets[:pad]], axis=0)
    waves = [targets[i * chunk:(i + 1) * chunk] for i in range(n_waves)]

    def run_wave(t):
        return simulate_lookups(sorted_ids, n_valid, t, alpha=3, k=8)

    out = run_wave(waves[0])          # compile + stats for wave 0
    hops_all = [np.asarray(out["hops"])]
    conv_all = [np.asarray(out["converged"])]
    for w in waves[1:]:               # stats pass (also warms caches)
        o = run_wave(w)
        hops_all.append(np.asarray(o["hops"]))
        conv_all.append(np.asarray(o["converged"]))
    hops = np.concatenate(hops_all)[:Q]
    conv = float(np.concatenate(conv_all)[:Q].mean())

    # timed pass: a sequential FIFO train over the full burst, recording
    # per-wave retire times; best total of 2 trains (after 1 warm train)
    def train():
        t0 = time.perf_counter()
        ends = []
        for w in waves:
            jax.block_until_ready(tuple(run_wave(w).values()))
            ends.append(time.perf_counter() - t0)
        return ends
    train()
    ends = min((train() for _ in range(2)), key=lambda e: e[-1])
    dt = ends[-1]
    p50_wave = min((Q // 2) // chunk, n_waves - 1)
    return {"metric": "config3 iterative search sim, alpha=3 k=8, "
                      "%d lookups x %d nodes, %d waves of %d; p50 hops %d, "
                      "converged %.3f, p50 burst completion %.3fs"
                      % (Q, N, n_waves, chunk,
                         int(np.percentile(hops, 50)), conv, ends[p50_wave]),
            "value": round(Q / dt, 1), "unit": "lookups/s/chip",
            "vs_baseline": None}


def config4() -> dict:
    """Bucket-refresh sweep: radix partition + per-bucket stats."""
    import jax
    import jax.numpy as jnp
    from opendht_tpu.ops import radix

    on_accel = jax.devices()[0].platform != "cpu"
    N = 10_000_000 if on_accel else 1_000_000
    key = jax.random.PRNGKey(4)
    ids = jax.random.bits(key, (N, 5), dtype=jnp.uint32)
    self_id = jax.random.bits(jax.random.PRNGKey(5), (5,), dtype=jnp.uint32)
    valid = jnp.ones((N,), bool)
    last = jnp.zeros((N,), jnp.float32)

    def run():
        b = radix.bucket_of(self_id, ids)
        c = radix.bucket_counts(self_id, ids, valid)
        s = radix.bucket_last_seen(self_id, ids, valid, last)
        return b, c, s

    dt = _rates(run)
    return {"metric": "config4 radix bucket sweep over %d ids" % N,
            "value": round(N / dt, 1), "unit": "ids/s/chip",
            "vs_baseline": None}


def config5() -> dict:
    """Sharded lookup with top-k merge over the mesh (all local
    devices; multi-chip validated by dryrun_multichip)."""
    import jax
    import jax.numpy as jnp
    from opendht_tpu.parallel import make_mesh, sharded_lookup

    n_dev = len(jax.devices())
    on_accel = jax.devices()[0].platform != "cpu"
    N = 8_000_000 if on_accel else 262_144
    Q = 65_536 if on_accel else 4_096
    k1, k2 = jax.random.split(jax.random.PRNGKey(6))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    mesh = make_mesh(n_dev)

    def run():
        return sharded_lookup(mesh, queries, table, k=8)

    dt = _rates(run, reps=3, warm=2)
    return {"metric": "config5 sharded lookup, %d devices, "
                      "%d queries x %d ids" % (n_dev, Q, N),
            "value": round(Q / dt, 1), "unit": "lookups/s",
            "vs_baseline": None}


def config2() -> dict:
    """Delegates to the headline bench (bench.py) parameters."""
    import jax
    import jax.numpy as jnp
    from opendht_tpu.ops.sorted_table import sort_table, window_topk

    on_accel = jax.devices()[0].platform != "cpu"
    N = 1_000_000 if on_accel else 100_000
    Q = 131_072 if on_accel else 8_192
    CHUNK = 16_384 if on_accel else 4_096
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))

    def run():
        return [window_topk(sorted_ids, n_valid, queries[s:s + CHUNK],
                            k=16, window=256)
                for s in range(0, Q, CHUNK)]

    dt = _rates(run, reps=5, warm=3)
    return {"metric": "config2 batched findClosestNodes top-16, "
                      "%d queries x %d ids" % (Q, N),
            "value": round(Q / dt, 1), "unit": "lookups/s/chip",
            "vs_baseline": None}


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="BASELINE.json config drivers")
    p.add_argument("-c", "--config", type=int, default=0,
                   help="config number (default: all)")
    p.add_argument("-Q", type=int, default=0,
                   help="config3: concurrent lookup count "
                        "(north star: 1000000)")
    p.add_argument("-N", type=int, default=0,
                   help="config3: network size (default 10M on device)")
    p.add_argument("--chunk", type=int, default=0,
                   help="config3: lookups per device wave")
    args = p.parse_args(argv)
    todo = [args.config] if args.config else sorted(CONFIGS)
    for c in todo:
        kw = ({"Q": args.Q, "N": args.N, "chunk": args.chunk}
              if c == 3 else {})
        print(json.dumps(CONFIGS[c](**kw)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
