"""Runnable drivers for every BASELINE.json config.

Each config prints one JSON line (same shape as bench.py).  Sizes scale
with the backend: full BASELINE sizes on an accelerator, reduced on CPU
so the suite stays runnable in CI.  Usage::

    python benchmarks/baseline_configs.py            # all configs
    python benchmarks/baseline_configs.py -c 3       # one config

Configs (BASELINE.json):
  1 dhtnode single-process: 1K get() lookups over a 10K-node routing
    table — CPU reference (the native C++ sorted walk) vs the device
    batched lookup.
  2 batched findClosestNodes: 131K queries × 1M ids, top-16 (the
    headline bench — delegates to bench.py's measurement).
  3 iterative Search simulation: α-parallel lookups vs a 10M-node
    simulated network, k=8 convergence, hop counts.
  4 bucket-refresh sweep: full radix partition + per-bucket stats over
    10M ids.
  5 multi-chip sharded table: row-sharded lookup with ICI top-k merge
    (one real chip here; the same code dry-runs on an 8-device virtual
    mesh — __graft_entry__.dryrun_multichip).

Timing: all device numbers use the serialized-chain slope
(bench.chain_slope) — a jitted while_loop (traced trip count) repeats
the workload with index-perturbed inputs and the per-rep time is the slope between two
rep counts.  Wall-clock timing of dispatched work is NOT trusted:
block_until_ready() on a tunneled device can return before execution
completes (see bench.py docstring; it inflated round-1 numbers ~100×).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def config1() -> dict:
    """1K get() lookups over a 10K-node table: native C++ scalar walk
    (the CPU reference) vs the batched device kernel."""
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops.ids import ids_to_bytes
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              expand_table, expanded_topk)
    from opendht_tpu import native

    N, Q, K = 10_000, 1_000, 8
    rng = np.random.default_rng(1)
    table = rng.integers(0, 2**32, size=(N, 5), dtype=np.uint32)
    queries = rng.integers(0, 2**32, size=(Q, 5), dtype=np.uint32)

    sorted_ids, perm, n_valid = jax.block_until_ready(
        sort_table(jnp.asarray(table)))
    lut = build_prefix_lut(sorted_ids, n_valid)
    expanded = expand_table(sorted_ids, limbs=2)     # 2-plane fast2 (r5)

    def body(q, sorted_ids, expanded, n_valid, lut):
        # fast2 + LUT-only positioning: the get() contract returns node
        # sets, and at N=10K the 16-bit LUT has ~0.15-row buckets —
        # measured 27.9M vs 8.5M lookups/s for fast3 with the bounded
        # search at this size
        d, idx, c = expanded_topk(sorted_ids, expanded, n_valid, q, k=K,
                                  select="fast2", lut=lut, lut_steps=0,
                                  planes=2)
        return jnp.sum(c.astype(jnp.float32))

    # per-rep work is ~30 µs at this size: tunnel noise swamped shallow
    # chains (captured 10-52M across sessions at r2=512), so the slope
    # uses very deep rep counts AND a median of 5 samples — the band
    # ci/check_docs.py holds quotes to is only as tight as this
    # measurement is stable
    dt_dev, _lo, _hi = chain_slope(
        body, jnp.asarray(queries), sorted_ids, expanded,
        n_valid, lut, r1=256, r2=2048, samples=5)
    _, _, cert = jax.block_until_ready(
        expanded_topk(sorted_ids, expanded, n_valid, jnp.asarray(queries),
                      k=K, select="fast2", lut=lut, lut_steps=0, planes=2))
    cert_frac = float(np.asarray(cert).mean())

    baseline = None
    if native.available():
        t_bytes = ids_to_bytes(np.asarray(sorted_ids)).reshape(N, 20)
        q_bytes = ids_to_bytes(queries).reshape(Q, 20)
        # native path runs on the host CPU: plain wall timing is honest
        from bench import best_of
        baseline = best_of(
            lambda: native.sorted_closest(t_bytes, q_bytes, k=K), tries=7)
    return {"metric": "config1 1K get() over 10K-node table "
                      "(device-serialized chain slope, fast2 + LUT-only "
                      "positioning, certified %.5f)" % cert_frac,
            "value": round(Q / dt_dev, 1), "unit": "lookups/s",
            "vs_baseline": round((Q / dt_dev) / (Q / baseline), 2)
            if baseline else None}


def config3_tp(Q: int = 0, N: int = 0, limbs: int = 0) -> dict:
    """Iterative search with the TABLE SHARDED over the mesh t axis
    (parallel.tp_simulate_lookups) — each shard holds a contiguous range
    of the global sorted order; positioning and row fetch are one psum
    each.  This is the mode whose table exceeds one shard (and, on a
    v5e pod slice, one chip's HBM).  Timed like every other device
    number here: serialized-chain slope over the pre-placed compiled
    callable (wall-clocking dispatches is never trusted — see module
    docstring)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from bench import chain_slope
    from opendht_tpu.ops.sorted_table import sort_table
    from opendht_tpu.core.search import ALPHA, SEARCH_NODES
    from opendht_tpu.parallel import (make_mesh, pad_to_multiple,
                                      shard_table_state)
    from opendht_tpu.parallel.sharded import build_tp_lookup

    n_dev = len(jax.devices())
    N = N or (1_000_000 if n_dev > 1 else 262_144)
    mesh = make_mesh(n_dev)
    n_q = mesh.shape["q"]
    Q = max(n_q, (Q or 4_096))
    if Q % n_q:
        Q += n_q - Q % n_q                 # round UP: never drop lookups
    limbs = limbs or 2
    k1, k2 = jax.random.split(jax.random.PRNGKey(30))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    padded, _ = pad_to_multiple(np.asarray(sorted_ids), mesh.shape["t"])
    shard_n = padded.shape[0] // mesh.shape["t"]

    # round 13: one shard_table_state call builds + places the
    # row-sharded table state (sorted rows, per-shard LUT, replicated
    # global block LUT) — the block width defaults to
    # default_lut_bits(N) for single-device bit-identity
    state = shard_table_state(mesh, padded, n_valid)
    fn = build_tp_lookup(mesh, shard_n, Q, 8, 3, SEARCH_NODES, 48, limbs)
    a = state.arrays
    targets_placed = jax.device_put(targets, NamedSharding(mesh, P("q", None)))

    out = jax.block_until_ready(
        fn(a["sorted_ids"], a["local_lut"], a["block_lut"], a["n_valid"],
           targets_placed, jnp.int32(1)))
    hops = np.asarray(out["hops"])
    conv = float(np.asarray(out["converged"]).mean())

    def body(t, s, lut, blk, nv):
        o = fn(s, lut, blk, nv, t, jnp.int32(1))
        return (jnp.sum(o["hops"].astype(jnp.float32))
                + jnp.sum(o["converged"].astype(jnp.float32)))

    dt = chain_slope(body, targets_placed, a["sorted_ids"], a["local_lut"],
                     a["block_lut"], a["n_valid"], r1=1, r2=4)
    return {"metric": "config3-tp table-sharded iterative search, mesh "
                      "q=%d t=%d (table %d rows/shard), %d lookups x %d "
                      "nodes, state_limbs=%d; p50 hops %d, converged %.3f "
                      "(device-serialized chain slope)"
                      % (mesh.shape["q"], mesh.shape["t"], shard_n, Q,
                         N, limbs, int(np.percentile(hops, 50)), conv),
            "value": round(Q / dt, 1), "unit": "lookups/s",
            "vs_baseline": None}


def config3(Q: int = 0, N: int = 0, chunk: int = 0,
            limbs: int = 0, latency: bool = False) -> dict:
    """α-parallel iterative lookups to k=8 convergence.

    The north-star shape is ``-Q 1000000`` against the 10M-node table
    (BASELINE.json configs[2]): the query burst is streamed through the
    device in fixed-shape waves (one compiled executable; search state
    for one wave resident at a time) so HBM holds wave state + the
    sorted table, never the full burst.

    Throughput is the chain slope of one wave (device-serialized), and
    burst numbers derive from it: burst time = n_waves × wave time.
    The separately-reported ``p50 burst completion`` is wave-time ×
    (wave index holding the median lookup + 1) — FIFO retire order.
    """
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              default_lut_bits)

    on_accel = jax.devices()[0].platform != "cpu"
    N = N or (10_000_000 if on_accel else 100_000)
    Q = Q or (65_536 if on_accel else 1_024)
    # measured optimum wave width on v5e AFTER the round-5 LUT block
    # bounds removed the per-round positioning search (exp_search_r5
    # sweep, 10M table: 8K/16K/32K/64K/128K/256K waves = 282/270/401/
    # 442/421/350 K lookups/s) — with the serial search gone, wider
    # waves amortize the issue-bound gathers until HBM pressure turns
    # over past 128K.  Pre-r5 the optimum was 16384.
    chunk = min(Q, chunk or (65_536 if on_accel else 1_024))
    key = jax.random.PRNGKey(3)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    n_waves = (Q + chunk - 1) // chunk
    pad = n_waves * chunk - Q
    if pad:
        targets = jnp.concatenate([targets, targets[:pad]], axis=0)
    waves = [targets[i * chunk:(i + 1) * chunk] for i in range(n_waves)]

    # state_limbs=2: merge sorts move 5 operands instead of 8 and the
    # per-round reply gather fetches 2 planes instead of 5 — bitwise
    # identical to the exact engine on random ids
    # (tests/test_search.py::test_state_limbs_2_bitwise_identical)
    limbs = limbs or 2

    def run_wave(t, sorted_ids=sorted_ids, n_valid=n_valid, lut=lut):
        return simulate_lookups(sorted_ids, n_valid, t, alpha=3, k=8, lut=lut,
                                state_limbs=limbs)

    # stats pass over the full burst (hops / convergence are exact)
    hops_all, conv_all = [], []
    for w in waves:
        o = run_wave(w)
        hops_all.append(np.asarray(o["hops"]))
        conv_all.append(np.asarray(o["converged"]))
    hops = np.concatenate(hops_all)[:Q]
    conv = float(np.concatenate(conv_all)[:Q].mean())

    # timed pass: serialized-chain slope of one wave
    def body(t, sorted_ids, n_valid, lut):
        o = run_wave(t, sorted_ids, n_valid, lut)
        return (jnp.sum(o["hops"].astype(jnp.float32))
                + jnp.sum(o["converged"].astype(jnp.float32)))

    wave_dt = chain_slope(body, waves[0], sorted_ids, n_valid, lut,
                          r1=1, r2=4)
    dt = wave_dt * n_waves
    p50_wave = min((Q // 2) // chunk, n_waves - 1)
    out = {"metric": "config3 iterative search sim, alpha=3 k=8, "
                     "%d lookups x %d nodes, %d waves of %d; p50 hops %d, "
                     "converged %.3f, p50 burst completion %.3fs "
                     "(wave chain slope %.3fs)"
                     % (Q, N, n_waves, chunk,
                        int(np.percentile(hops, 50)), conv,
                        wave_dt * (p50_wave + 1), wave_dt),
           "value": round(Q / dt, 1), "unit": "lookups/s/chip",
           "vs_baseline": None}
    if not latency:
        return out

    # ---- per-lookup LATENCY (verdict r3 #3: the BASELINE "<1 ms p50
    # per lookup" has a latency reading, not just amortized
    # throughput).  A lookup's latency is its wave's completion time:
    # per-wave chain slopes vary with the wave's straggler hop count,
    # so sample ≤16 waves across the burst for a p50/p95 histogram
    # (one compile serves all same-shape waves), then sweep smaller
    # wave widths — the low-latency mode trades throughput for wave
    # time.
    sample_idx = sorted(set(
        int(i) for i in np.linspace(0, n_waves - 1,
                                    num=min(16, n_waves))))
    wave_ms = [1e3 * chain_slope(body, waves[i], sorted_ids, n_valid, lut,
                                 r1=1, r2=4)
               for i in sample_idx]
    out["wave_ms_p50"] = round(float(np.percentile(wave_ms, 50)), 2)
    out["wave_ms_p95"] = round(float(np.percentile(wave_ms, 95)), 2)
    out["wave_ms_sampled"] = [round(m, 2) for m in wave_ms]

    sweep = {chunk: {"latency_ms": round(wave_dt * 1e3, 2),
                     "lookups_per_s": round(chunk / wave_dt, 1)}}
    for c in (1024, 4096):
        if c > Q or c in sweep:
            continue
        w = targets[:c]
        # small waves are ~3-15 ms — far below the tunnel noise floor
        # at shallow rep counts (r2=4 captured 8.65 vs 14.48 ms for the
        # same 4096-wave across sessions, nonmonotonic vs 1024).  Deep
        # chains + a median-of-3 make the sweep quotable.
        r1s = max(2, 32_768 // c)
        cdt, _lo, _hi = chain_slope(body, w, sorted_ids, n_valid, lut,
                                    r1=r1s, r2=4 * r1s, samples=3)
        sweep[c] = {"latency_ms": round(cdt * 1e3, 2),
                    "lookups_per_s": round(c / cdt, 1)}
    out["latency_sweep"] = sweep
    out["metric"] += ("; LATENCY reading: wave completion p50 %.1f ms / "
                      "p95 %.1f ms (a lookup's latency = its wave's "
                      "completion; amortized per-lookup time is NOT a "
                      "latency), small-wave sweep %s"
                      % (out["wave_ms_p50"], out["wave_ms_p95"],
                         json.dumps(sweep, sort_keys=True)))
    return out


def config4() -> dict:
    """Bucket-refresh sweep: radix partition + per-bucket stats."""
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops import radix

    on_accel = jax.devices()[0].platform != "cpu"
    N = 10_000_000 if on_accel else 1_000_000
    key = jax.random.PRNGKey(4)
    ids = jax.random.bits(key, (N, 5), dtype=jnp.uint32)
    self_id = jax.random.bits(jax.random.PRNGKey(5), (5,), dtype=jnp.uint32)
    valid = jnp.ones((N,), bool)
    # nonzero reply clocks: zeros would be "never replied" under the
    # round-10 staleness semantics and read back as -inf bucket maxes
    last = jax.random.uniform(jax.random.PRNGKey(6), (N,), jnp.float32,
                              1.0, 100.0)

    def body(x, self_id, valid, last):
        b = radix.bucket_of(self_id, x)
        c = radix.bucket_counts(self_id, x, valid)
        s = radix.bucket_last_seen(self_id, x, valid, last)
        # empty buckets are -inf by contract — mask before consuming
        return (jnp.sum(b.astype(jnp.float32)) * 1e-9
                + jnp.sum(c.astype(jnp.float32))
                + jnp.sum(jnp.where(jnp.isfinite(s), s, 0.0)) * 1e-9)

    # the compare-and-reduce kernels run the full sweep in ~6 ms — deep
    # rep counts keep the slope above the tunnel noise floor
    r1, r2 = (32, 256) if on_accel else (2, 8)
    dt = chain_slope(body, ids, self_id, valid, last, r1=r1, r2=r2)
    return {"metric": "config4 radix bucket sweep over %d ids "
                      "(device-serialized chain slope)" % N,
            "value": round(N / dt, 1), "unit": "ids/s/chip",
            "vs_baseline": None}


def config5() -> dict:
    """Sharded lookup with top-k merge at REAL table scale.

    On the accelerator this runs N=64M ids (1.28 GB of ids; the
    expanded window-row form is 3x that) — an actual slice of the 100M-
    node BASELINE shape, bounded by one chip's HBM here (the v5e-8 in
    BASELINE.json holds 8 such shards = 512M ids).  Alongside the
    throughput measurement it characterizes the ICI merge cost as a
    model, because this host has one real chip:

      - wire volume is exact by construction: each query all_gathers
        n_t per-shard top-k candidate sets of k rows x (20 B id + 4 B
        index) = n_t * k * 24 B per query over the t axis;
      - the merge RE-SORT is pure per-chip compute — measured here on
        the real chip as select_topk over [Q, n_t*k] candidates for
        n_t in {2,4,8} (chain slope, printed in the metric), so the
        v5e-8 projection = per-shard lookup + measured merge(n_t=8)
        + wire/ICI-bandwidth.
    """
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops.sorted_table import default_lut_bits
    from opendht_tpu.ops.xor_topk import select_topk
    from opendht_tpu.parallel import (make_mesh, sharded_sort_table,
                                      sharded_expand_table,
                                      sharded_window_lookup)

    n_dev = len(jax.devices())
    on_accel = jax.devices()[0].platform != "cpu"
    N = 64_000_000 if on_accel else 262_144
    Q = 65_536 if on_accel else 4_096
    K = 8
    k1, k2 = jax.random.split(jax.random.PRNGKey(6))
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    mesh = make_mesh(n_dev)

    if on_accel and n_dev == 1:
        # One real chip: run the PER-SHARD kernel at the full 64M scale
        # (= one chip's share of a 512M-id v5e-8 table).  Memory is
        # budgeted deliberately: the id matrix is generated INSIDE the
        # sort program (no persistent input buffer) and the 3.9 GB
        # window-row expansion is built via the chunked low-peak
        # builder — the one-shot expand peaks ~2.5x output and OOMs.
        # The all_gather merge is t=1-trivial here; its cost is the
        # separately measured model below.
        from opendht_tpu.ops.sorted_table import (build_prefix_lut,
                                                  expand_table_chunked,
                                                  expanded_topk, sort_table)

        @jax.jit
        def make_sorted(k):
            return sort_table(jax.random.bits(k, (N, 5), dtype=jnp.uint32))

        sorted_ids, perm, n_valid = jax.block_until_ready(make_sorted(k1))
        del perm             # unused here; 256 MB off the expansion peak
        # 2-plane expansion (r5): 1.56 GB instead of 3.9 for 64M ids —
        # the fast2 sort + clamped certificate never read planes 2-4
        expanded = jax.block_until_ready(
            expand_table_chunked(sorted_ids, chunks=8, limbs=2))
        lut = jax.block_until_ready(
            build_prefix_lut(sorted_ids, n_valid, bits=default_lut_bits(N)))

        def body(q, sorted_ids, expanded, n_valid, lut):
            d, idx, c = expanded_topk(sorted_ids, expanded, n_valid, q,
                                      k=K, select="fast2", lut=lut,
                                      lut_steps=0, planes=2)
            return (jnp.sum(c.astype(jnp.float32))
                    + jnp.sum(idx[:, 0].astype(jnp.float32)) * 1e-9)

        dt = chain_slope(body, queries, sorted_ids, expanded, n_valid, lut,
                         r1=4, r2=32)
        _, _, cert = jax.block_until_ready(
            expanded_topk(sorted_ids, expanded, n_valid, queries, k=K,
                          select="fast2", lut=lut, lut_steps=0, planes=2))
        cert_frac = float(np.asarray(cert).mean())
    else:
        cert_frac = None
        table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
        sorted_ids, perm, n_valid = jax.block_until_ready(
            sharded_sort_table(mesh, table))
        del table
        expanded, lut = jax.block_until_ready(
            sharded_expand_table(mesh, sorted_ids, n_valid,
                                 bits=default_lut_bits(N // mesh.shape['t'])))

        def body(q, sorted_ids, perm, n_valid, expanded, lut):
            d, idx = sharded_window_lookup(mesh, q, sorted_ids, perm, n_valid,
                                           k=K, expanded=expanded, lut=lut)
            return jnp.sum((idx >= 0).astype(jnp.float32))

        dt = chain_slope(body, queries, sorted_ids, perm, n_valid, expanded,
                         lut, r1=1, r2=3)

    # merge-cost model: re-sort time vs shard count (single-chip compute)
    merge_ms = {}
    for n_t in (2, 4, 8):
        kc = jax.random.split(jax.random.PRNGKey(60 + n_t))
        cd = jax.random.bits(kc[0], (Q, n_t * K, 5), dtype=jnp.uint32)
        ci = jax.random.randint(kc[1], (Q, n_t * K), 0, N, dtype=jnp.int32)

        def merge_body(q, cd, ci):
            # perturb indices by the rep counter via q's first column so
            # reps stay distinct; inv=0 (all candidates valid)
            cj = ci ^ (q[:, :1] & 1).astype(jnp.int32)
            d, i, inv = select_topk(cd, cj, jnp.zeros_like(cj), K)
            return jnp.sum(i.astype(jnp.float32)) * 1e-9

        # sub-ms workload: deep rep chains lift the slope above the
        # tunnel noise floor (shallow chains measured non-monotonic)
        mdt = chain_slope(merge_body, queries, cd, ci, r1=64, r2=512)
        merge_ms[n_t] = round(mdt * 1e3, 2)
        del cd, ci

    return {"metric": "config5 sharded lookup, %d device(s), %d queries x "
                      "%d ids (device-serialized chain slope%s); ICI merge "
                      "model: wire = n_t*%d*24 B/query, re-sort ms/batch "
                      "%s (measured vs n_t)"
                      % (n_dev, Q, N,
                         "" if cert_frac is None
                         else ", certified %.5f" % cert_frac,
                         K, json.dumps(merge_ms, sort_keys=True)),
            "value": round(Q / dt, 1), "unit": "lookups/s",
            "vs_baseline": None}


def config2() -> dict:
    """Delegates to the headline bench (bench.py)."""
    from bench import measure
    out = measure()
    out["metric"] = "config2 " + out["metric"]
    return out


def config6(churn: int = 0, dcap: int = 0) -> dict:
    """Sustained churn: mutations absorbed WHILE lookups run (SURVEY §7
    "incremental updates" — the round-3 verdict's top ask; reference
    mutation path src/routing_table.cpp:204-262).

    One timed *round* = one device call that (a) absorbs E evictions as
    tombstone-word writes, (b) appends E inserts to the delta slab,
    (c) re-sorts + re-expands the delta, and (d) answers a Q-query
    lookup wave through the churn kernel (tombstone-masked base window
    + delta window + 2k merge; ops/sorted_table.churn_lookup_topk) —
    chain-slope timed like every device number here.  The tombstone
    writes are whole-word ``set`` scatters (values precomputed on the
    host), so reps of the chain are idempotent — required for the
    slope methodology.

    Sustained throughput composes measured parts:
      Q / (round_dt + host_prep_dt + compact_dt / rounds_per_compaction)
    where compaction (re-sort + re-expand + re-LUT of base ∪ delta,
    all on device) runs every delta_cap/E rounds, and host_prep is the
    numpy mutation bookkeeping (host wall-clock — trustworthy for host
    work).  The static comparator is the same-shape plain lookup
    (expanded_topk, no churn structures); the verdict bar is churny
    within ~20% of static at reference-realistic churn (a node table
    fully turning over on the ~10-minute NODE_EXPIRE_TIME scale,
    node.h:151 — ≈ N/600 mutations/s, which the default E meets at the
    measured round rate).

    Exactness: at the advanced churn state, a sampled query batch must
    match the brute-force oracle over (live base ∪ delta) — the full
    re-sort semantics — bit-for-bit (node set, order, distances).
    """
    import jax
    import jax.numpy as jnp
    from bench import chain_slope, best_of
    from opendht_tpu.ops.sorted_table import (
        sort_table, build_prefix_lut, default_lut_bits, expand_table,
        churn_lookup_topk, expanded_topk, unpack_tomb_bits)
    from opendht_tpu.ops.xor_topk import xor_topk

    on_accel = jax.devices()[0].platform != "cpu"
    N = 10_000_000 if on_accel else 200_000
    Q = 131_072 if on_accel else 8_192
    # dcap sweep on v5e (round 5, 2-plane kernels): 262144 → 4.37M
    # lookups/s (0.34× static), 65536 → 5.20M (0.43×), 16384 → see
    # captures/; smaller slabs cut the per-round delta re-sort/expand
    # while the 149 ms compaction amortizes over fewer rounds — 65536
    # is the measured optimum at the default churn rate
    DCAP = dcap or (65_536 if on_accel else 8_192)
    # evictions AND inserts per round: absorption is scatter-cheap, so
    # the mutation rate scales with E at ~constant round cost — 512
    # holds the sustained rate comfortably above the reference-realistic
    # N/600 ≈ 16.7K/s even on slow tunnel sessions
    E = churn or (512 if on_accel else 64)
    K = 8
    lut_bits = default_lut_bits(N)

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(7), 3)
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    del table
    # 2-plane expansion (r5): the whole serving path is fast2
    expanded = jax.block_until_ready(expand_table(sorted_ids, limbs=2))
    lut = jax.block_until_ready(
        build_prefix_lut(sorted_ids, n_valid, bits=lut_bits))
    nv = int(jax.device_get(n_valid))

    # ---- host churn state (mirrors ChurnView bookkeeping, vectorized)
    rng = np.random.default_rng(70)
    nwords = (N + 31) // 32
    tomb_np = np.zeros(nwords, np.uint32)
    live_np = np.zeros(N, bool)
    live_np[:nv] = True
    delta_np = np.zeros((DCAP, 5), np.uint32)
    n_delta = 0

    def prep_round():
        """Pick E fresh live positions + E new ids; returns the device
        args for one round and applies them to the host mirror."""
        nonlocal n_delta
        # exactly E DISTINCT live positions: dedupe within the batch and
        # across retry iterations (live_np is only written below, so a
        # duplicate draw would otherwise pass the liveness filter and
        # the round would evict fewer rows than it inserts)
        picks: list = []
        seen: set = set()
        while len(picks) < E:
            for c in rng.integers(0, nv, size=2 * E):
                c = int(c)
                if live_np[c] and c not in seen:
                    seen.add(c)
                    picks.append(c)
                    if len(picks) == E:
                        break
        pos = np.array(picks, dtype=np.int64)
        live_np[pos] = False
        w = np.unique(pos >> 5)
        np.bitwise_or.at(tomb_np, pos >> 5,
                         np.uint32(1) << (pos & 31).astype(np.uint32))
        new_ids = rng.integers(0, 2**32, size=(E, 5), dtype=np.uint32)
        nd0 = n_delta
        delta_np[nd0:nd0 + E] = new_ids
        n_delta = nd0 + E
        widx = np.zeros(E, np.int64)            # pad to fixed length E
        widx[:len(w)] = w
        widx[len(w):] = w[-1] if len(w) else 0
        return (jnp.asarray(widx), jnp.asarray(tomb_np[widx]),
                jnp.asarray(new_ids), nd0)

    # advance to a representative mid-cycle state (half the compaction
    # cycle) so the timed round sees realistic tombstone/delta volume;
    # warm_rounds * E (the warm loop + the timed round's inserts) must
    # fit the slab — small --dcap / big --churn would overflow delta_np
    if 2 * E > DCAP:
        raise ValueError(f"--churn {E}: the warm round + the timed round "
                         f"need 2*E <= delta capacity (DCAP={DCAP})")
    warm_rounds = max(4, (DCAP // E) // 2) if on_accel else 8
    warm_rounds = max(2, min(warm_rounds, DCAP // E))
    t0 = __import__("time").perf_counter()
    for _ in range(warm_rounds - 1):
        prep_round()
    host_prep_dt = (__import__("time").perf_counter() - t0) / (warm_rounds - 1)
    widx, wval, new_ids, nd0 = prep_round()
    # the scatter/update values are the post-round state, so chain reps
    # are idempotent (required by the slope methodology) while the
    # scatter + slice-update ops still execute at full cost every rep
    tomb_base = jnp.asarray(tomb_np)
    dslab = jnp.asarray(delta_np)
    nd_after = jnp.int32(n_delta)

    d_bits = default_lut_bits(DCAP)

    def round_body(q, sorted_ids, expanded, lut, n_valid, tomb_base,
                   widx, wval, dslab, new_ids, nd_after):
        tomb = tomb_base.at[widx].set(wval)
        ds_slab = jax.lax.dynamic_update_slice(
            dslab, new_ids, (jnp.int32(nd0), 0))
        dvalid = jnp.arange(DCAP) < nd_after
        ds, _dp, dnv = sort_table(ds_slab, dvalid)
        # narrow stride-16 delta windows (64-lane sorts — measured 27×
        # cheaper than stride 32's 128-lane at this Q) + a wide rescue
        # expansion for the ~0.7% of rows the narrow margin decertifies
        # (cascade inside churn_lookup_topk — exp_churn_r5.py)
        de = expand_table(ds, stride=16, limbs=2)
        dew = expand_table(ds, stride=64, limbs=2)
        dlut = build_prefix_lut(ds, dnv, bits=d_bits)
        # LUT-only positioning on BOTH sides (the sequential probe-gather
        # steps dominate otherwise); fast2 = nodes-not-distances contract
        _dist, enc, cert = churn_lookup_topk(
            sorted_ids, expanded, n_valid, tomb, ds, de, dnv, q,
            lut=lut, d_lut=dlut, d_exp_wide=dew, k=K, select="fast2",
            lut_steps=0, planes=2, d_cap=4096)
        return (jnp.sum(cert.astype(jnp.float32))
                + jnp.sum(enc[:, 0].astype(jnp.float32)) * 1e-9)

    r1, r2 = (2, 8) if on_accel else (1, 3)
    round_dt = chain_slope(round_body, queries, sorted_ids, expanded, lut,
                           n_valid, tomb_base, widx, wval, dslab, new_ids,
                           nd_after, r1=r1, r2=r2)

    # ---- static comparator: same-shape plain lookup, no churn structures
    def static_body(q, sorted_ids, expanded, lut, n_valid):
        d, idx, c = expanded_topk(sorted_ids, expanded, n_valid, q, k=K,
                                  select="fast2", lut=lut, lut_steps=0,
                                  planes=2)
        return (jnp.sum(c.astype(jnp.float32))
                + jnp.sum(idx[:, 0].astype(jnp.float32)) * 1e-9)

    static_dt = chain_slope(static_body, queries, sorted_ids, expanded, lut,
                            n_valid, r1=r1, r2=r2)

    # ---- compaction: re-sort + re-expand + re-LUT of (live base ∪ delta)
    # on device.  Wall-clock is trustworthy here because the result is
    # forced back to the HOST (device_get of a dependent scalar cannot
    # return before execution finishes) and the op is hundreds of ms —
    # the completion-poll artifact that breaks micro-timing is noise.
    tomb_dev = jnp.asarray(tomb_np)

    @jax.jit
    def compact(sorted_ids, dslab, tomb, n_valid, nd):
        live = (jnp.arange(N) < n_valid) & ~unpack_tomb_bits(tomb, N)
        cat = jnp.concatenate([sorted_ids, dslab], axis=0)
        cval = jnp.concatenate([live, jnp.arange(DCAP) < nd])
        s2, _p2, nv2 = sort_table(cat, cval)
        e2 = expand_table(s2, limbs=2)          # the serving form (fast2)
        l2 = build_prefix_lut(s2, nv2, bits=lut_bits)
        return (s2[0, 0].astype(jnp.float32) + e2[0, 0].astype(jnp.float32)
                + l2[0].astype(jnp.float32) + nv2.astype(jnp.float32))

    compact_dt = best_of(lambda: float(compact(
        sorted_ids, dslab, tomb_dev, n_valid, nd_after)), tries=3)
    rounds_per_compaction = max(1, DCAP // E)

    # ---- exactness at the advanced state vs the full re-sort oracle:
    # fast3 carries full distances (compared bit-for-bit) and the timed
    # fast2 path must agree on the node encoding
    qs = jax.random.bits(k3, (256, 5), dtype=jnp.uint32)
    dvalid = np.zeros(DCAP, bool)
    dvalid[:n_delta] = True
    ds, _dp, dnv = sort_table(jnp.asarray(delta_np), jnp.asarray(dvalid))
    de = expand_table(ds, stride=16, limbs=2)
    dew = expand_table(ds, stride=64, limbs=2)
    dlut = build_prefix_lut(ds, dnv, bits=d_bits)
    # fast3 oracle needs full limb planes — built transiently here only
    exp5 = expand_table(sorted_ids)
    de5 = expand_table(ds, stride=32)
    dist_c, enc_c, _ = churn_lookup_topk(
        sorted_ids, exp5, n_valid, jnp.asarray(tomb_np), ds, de5, dnv,
        qs, lut=lut, d_lut=dlut, k=K, select="fast3")
    del exp5, de5
    _n, enc_f2, _ = churn_lookup_topk(
        sorted_ids, expanded, n_valid, jnp.asarray(tomb_np), ds, de, dnv,
        qs, lut=lut, d_lut=dlut, d_exp_wide=dew, k=K, select="fast2",
        lut_steps=0, planes=2, d_cap=4096)
    cat = jnp.concatenate([sorted_ids, ds], axis=0)
    cval = jnp.concatenate([jnp.asarray(live_np),
                            jnp.arange(DCAP) < dnv])
    d_ref, i_ref = xor_topk(qs, cat, k=K, tile=4096, valid=cval)
    exact = bool(np.array_equal(np.asarray(dist_c), np.asarray(d_ref))
                 and np.array_equal(np.asarray(enc_c), np.asarray(enc_f2)))

    denom = round_dt + host_prep_dt + compact_dt / rounds_per_compaction
    churny = Q / denom
    static = Q / static_dt
    muts = 2 * E / denom
    return {"metric": "config6 sustained churn, %d lookups/wave x %d-node "
                      "table, %d+%d mutations/round absorbed on device "
                      "(tombstone words + delta append+resort), delta cap "
                      "%d, compaction every %d rounds (%.0f ms measured); "
                      "churn-exact vs full-resort oracle: %s; static "
                      "same-shape lookup %.0f lookups/s; churny/static "
                      "%.3f; %.0f mutations/s sustained"
                      % (Q, N, E, E, DCAP, rounds_per_compaction,
                         compact_dt * 1e3, exact, static,
                         churny / static, muts),
            "value": round(churny, 1), "unit": "lookups/s/chip",
            "mutations_per_s": round(muts, 1),
            "exact_vs_oracle": exact,
            "vs_baseline": round(churny / static, 4)}


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5,
           6: config6}


def save_capture(name: str, out: dict) -> None:
    """Persist a config result as ``captures/<name>.json`` (accelerator
    runs only — CPU smoke numbers are not quotable).  README/PARITY
    quote these files and ci/check_docs.py enforces agreement — no
    hand-typed perf number in the docs (round-4 verdict ask #4)."""
    import jax
    if jax.devices()[0].platform == "cpu":
        return
    d = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "captures")
    try:
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, name + ".json"), "w") as f:
            json.dump(out, f, indent=1)
    except OSError:
        pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="BASELINE.json config drivers")
    p.add_argument("-c", "--config", type=int, default=0,
                   help="config number (default: all)")
    p.add_argument("-Q", type=int, default=0,
                   help="config3: concurrent lookup count "
                        "(north star: 1000000)")
    p.add_argument("-N", type=int, default=0,
                   help="config3: network size (default 10M on device)")
    p.add_argument("--chunk", type=int, default=0,
                   help="config3: lookups per device wave (not used "
                        "with --tp: the tp engine runs one batch)")
    p.add_argument("--tp", action="store_true",
                   help="config3: shard the table over the mesh t axis "
                        "(tp_simulate_lookups) instead of replicating it")
    p.add_argument("--limbs", type=int, default=0,
                   help="config3: distance limbs carried through the "
                        "merge sorts (2 = fast default, 5 = exact-order)")
    p.add_argument("--churn", type=int, default=0,
                   help="config6: evictions (= inserts) per round")
    p.add_argument("--dcap", type=int, default=0,
                   help="config6: delta slab capacity (trades delta "
                        "lookup cost vs compaction frequency)")
    p.add_argument("--latency", action="store_true",
                   help="config3: add the per-wave completion-time "
                        "histogram + small-wave latency sweep")
    args = p.parse_args(argv)
    todo = [args.config] if args.config else sorted(CONFIGS)
    for c in todo:
        if c == 3 and args.tp:
            out = config3_tp(Q=args.Q, N=args.N, limbs=args.limbs)
            name = "config3_tp"
            if args.Q or args.N or args.limbs:
                name += "_custom"        # exploration shape, not quotable
            save_capture(name, out)
            print(json.dumps(out))
            continue
        kw = {}
        name = "config%d" % c
        if c == 3:
            kw = {"Q": args.Q, "N": args.N, "chunk": args.chunk,
                  "limbs": args.limbs, "latency": args.latency}
            if args.Q >= 1_000_000:
                name = "config3_star"        # the north-star shape
            if args.latency:
                name += "_latency"
        elif c == 6:
            kw = {"churn": args.churn, "dcap": args.dcap}
        out = CONFIGS[c](**kw)
        # non-default shapes (exploration runs) must not overwrite the
        # quotable artifact for the canonical shape.  Canonical config3
        # shapes are Q unset (default burst) and Q=1M exactly (the
        # north star), both at the default chunk; ANY N/chunk/limbs
        # override or any other Q is exploration.
        custom3 = bool(args.N or args.limbs or args.chunk
                       or args.Q not in (0, 1_000_000))
        if (c == 3 and custom3) or (c == 6 and (args.churn or args.dcap)):
            name += "_custom"
        save_capture(name, out)
        print(json.dumps(out))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
