"""Round-body cost attribution for the ROUND-FUSED iterative engine
(round 6 tentpole) + the CI wave-latency smoke.

Same fixed-trip methodology as exp_round_r5.py: each variant runs the
REAL round body in a fixed ``ROUNDS``-trip ``fori_loop`` (no
convergence exit) with one piece disabled, so (full − variant)
attributes cost inside the real compiled loop, fusion effects
included.  The body here mirrors the ROUND-6 engine
(core/search.py): the reply blocks are positioned from the CARRIED
candidate distance limb (no per-round peer gather), both LUT block
edges ride one stacked take, and the round's only table gather is the
fused [W·α·k] reply fetch.  The ``r5_unfused`` variant re-enables the
round-5 per-round peer gather + split LUT reads inside the same loop,
so (r5_unfused − fused) is the measured fusion win at this shape.

Like exp_round_r5.py, the round body here is a MIRROR of the engine's,
maintained by hand so pieces can be disabled — it is NOT
core/search.py's own code.  What pins the SHIPPING engine is the
committed reply-stream goldens (tests/test_search.py::
test_engine_reply_stream_goldens, run by the CI suite before this
driver); this file's claims are about the mirrored body, and an engine
edit that changes the round structure must be ported here for the
attribution to stay meaningful (the goldens catch output drift, this
note is what covers attribution drift).

``--smoke`` (the ci/run_ci.sh wave-latency entry) additionally asserts

  1. the mirrored fused and r5_unfused round bodies produce
     BIT-IDENTICAL final search states end-to-end through the
     compiled loop — the fusion-equivalence argument, demonstrated on
     the same body the attribution numbers come from;
  2. the fused round is not slower than the unfused round by more
     than a generous 1.5× band — a p50 wave-latency regression on the
     fused path fails CI without running the full bench.

The per-stage numbers printed by a full run are the inputs to the
wave-latency ARCHITECTURAL BOUND recorded in README/PARITY: the fused
round's serial chain is one fused reply gather + one stacked LUT read
+ two (S+R)-wide merge sorts + dispatch residue, and a wave's p50
completion is bounded below by rounds × that floor.  Exploration tool.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)

VARIANTS = ("fused", "r5_unfused", "no_reply_gather", "no_lut_reads",
            "no_dedup_sort", "no_alpha_select")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="small-shape CI smoke: bit-identity + regression "
                        "band only")
    p.add_argument("-N", type=int, default=0, help="table rows")
    p.add_argument("-W", type=int, default=0, help="wave width")
    p.add_argument("--rounds", type=int, default=10)
    p.add_argument("--capture", default="",
                   help="write captures/<name>.json with the attribution")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax import lax
    from bench import chain_slope
    from opendht_tpu.ops.ids import N_LIMBS, clz32
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              default_lut_bits, _lut_bits,
                                              fused_gather_planar)
    from opendht_tpu.core import search as SE

    _U32 = jnp.uint32
    on_accel = jax.devices()[0].platform != "cpu"
    if args.smoke:
        N = args.N or 65_536
        W = args.W or 1_024
    else:
        N = args.N or (10_000_000 if on_accel else 262_144)
        W = args.W or (16_384 if on_accel else 1_024)
    NL, ALPHA, S, K = 2, 3, 14, 8
    R = ALPHA * K
    ROUNDS = args.rounds

    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets0 = jax.random.bits(k2, (W, 5), dtype=jnp.uint32)
    sorted_ids, _p, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table
    n = jnp.asarray(n_valid, jnp.int32)

    def split_lut_block_bounds(lut, t0, prefix_len):
        """The ROUND-5 form: two separate LUT takes per edge pair."""
        bits = _lut_bits(lut)
        Lc = jnp.clip(prefix_len, 0, bits)
        shift = (jnp.int32(bits) - Lc).astype(_U32)
        top = (t0 >> _U32(32 - bits)).astype(_U32)
        pfx = (top >> shift) << shift
        lo = jnp.take(lut, pfx.astype(jnp.int32))
        ub = jnp.take(lut, (pfx + (_U32(1) << shift)).astype(jnp.int32))
        return lo, ub

    def make_wave(variant, return_state=False):
        def wave(targets, sorted_ids, lut):
            lower = SE._guarded_lower_bound(sorted_ids, n, lut)
            sorted_t = sorted_ids.T

            def gather_planar(rows, limbs=N_LIMBS):
                return fused_gather_planar(sorted_t, rows, limbs)

            Q = targets.shape[0]
            seed_u = _U32(1)
            q_index = jnp.arange(Q, dtype=jnp.int32)
            pos_t_full = lower(targets)

            def reply_gather(tgt, pt, qidx, x_rows, round_no, x_d0):
                Wd = tgt.shape[0]
                if variant == "r5_unfused" or x_d0 is None:
                    x0 = gather_planar(x_rows, 1)[0]
                    x_d0 = x0 ^ tgt[:, 0:1]
                b = clz32(x_d0)
                if variant == "no_lut_reads":
                    lo = jnp.zeros_like(b)
                    ub = jnp.full_like(b, jnp.int32(1) << 20)
                elif variant == "r5_unfused":
                    lo, ub = split_lut_block_bounds(lut, tgt[:, 0:1], b + 1)
                else:
                    lo, ub = SE._lut_block_bounds(lut, tgt[:, 0:1], b + 1)
                size = jnp.maximum(ub - lo, 0)
                qi = qidx.astype(_U32)[:, None, None]
                ai = jnp.arange(x_rows.shape[1], dtype=_U32)[None, :, None]
                ji = jnp.arange(K, dtype=_U32)[None, None, :]
                ctr = (((round_no.astype(_U32) * _U32(Q) + qi) * _U32(ALPHA)
                        + ai) * _U32(K) + ji) ^ seed_u
                h = SE._mix32(ctr)
                blk = lo[..., None] + (
                    h % jnp.maximum(size[..., None], 1).astype(_U32)
                ).astype(jnp.int32)
                base = jnp.clip(pt[:, None, None] - R // 2, 0,
                                jnp.maximum(n - R, 0))
                fb = jnp.clip(base + (ai * _U32(K) + ji).astype(jnp.int32),
                              0, jnp.maximum(n - 1, 0))
                rows = jnp.where((size[..., None] >= K), blk, fb)
                rows = jnp.where((x_rows >= 0)[..., None], rows, -1)
                return rows.reshape(Wd, R)

            def merge(tgt, cand_node, cand_l, queried, new_rows):
                Wd = tgt.shape[0]
                if variant == "no_reply_gather":
                    new_l = [jnp.zeros((Wd, R), _U32) for _ in range(NL)]
                else:
                    new_l = gather_planar(new_rows, NL)
                node = jnp.concatenate([cand_node, new_rows], axis=1)
                d_l = [jnp.concatenate(
                    [cand_l[l], new_l[l] ^ tgt[:, l:l + 1]], axis=1)
                    for l in range(NL)]
                qd = jnp.concatenate([queried,
                                      jnp.zeros((Wd, R), jnp.int32)], axis=1)
                inv = (node < 0).astype(jnp.int32)
                big = jnp.uint32(0xFFFFFFFF)
                d_l = [jnp.where(inv == 0, dl, big) for dl in d_l]
                out = lax.sort((inv,) + tuple(d_l) + (node, 1 - qd),
                               dimension=1, num_keys=3 + NL)
                inv_s, node_s = out[0], out[1 + NL]
                qd_s = 1 - out[2 + NL]
                if variant == "no_dedup_sort":
                    present = inv_s[:, :S] == 0
                    node_f = jnp.where(present, node_s[:, :S], -1)
                    d_f = [jnp.where(present, out[1 + l][:, :S], big)
                           for l in range(NL)]
                    qd_f = qd_s[:, :S] * present
                    return node_f, d_f, qd_f
                dup = jnp.concatenate(
                    [jnp.zeros((Wd, 1), bool),
                     (node_s[:, 1:] == node_s[:, :-1]) & (node_s[:, 1:] >= 0)],
                    axis=1)
                inv2 = jnp.where(dup, 1, inv_s)
                out2 = lax.sort(
                    (inv2,) + tuple(out[1:1 + NL]) + (node_s, 1 - qd_s),
                    dimension=1, num_keys=2 + NL)
                present = out2[0][:, :S] == 0
                node_f = jnp.where(present, out2[1 + NL][:, :S], -1)
                d_f = [jnp.where(present, out2[1 + l][:, :S], big)
                       for l in range(NL)]
                qd_f = (1 - out2[2 + NL])[:, :S] * present
                return node_f, d_f, qd_f

            boot = jnp.full((Q, ALPHA), -1, jnp.int32).at[:, 0].set(
                (SE._mix32(q_index.astype(_U32) ^ seed_u)
                 % jnp.maximum(n, 1).astype(_U32)).astype(jnp.int32))
            cand_node = jnp.full((Q, S), -1, jnp.int32)
            cand_l = [jnp.full((Q, S), 0xFFFFFFFF, _U32) for _ in range(NL)]
            queried = jnp.zeros((Q, S), jnp.int32)
            first = reply_gather(targets, pos_t_full, q_index, boot,
                                 jnp.int32(0), None)
            cand_node, cand_l, queried = merge(targets, cand_node, cand_l,
                                               queried, first)

            def body(rnd, state):
                cand_node, cand_l, queried = state
                can = (cand_node >= 0) & (queried == 0)
                rank = jnp.cumsum(can.astype(jnp.int32), axis=1)
                sel = can & (rank <= ALPHA)
                if variant == "no_alpha_select":
                    x_rows = cand_node[:, :ALPHA]
                    x_d0 = cand_l[0][:, :ALPHA]
                else:
                    x_rows = jnp.stack(
                        [jnp.max(jnp.where(sel & (rank == j + 1),
                                           cand_node, -1), axis=1)
                         for j in range(ALPHA)], axis=1)
                    # the round-6 fusion: d0 rides the same reductions
                    x_d0 = jnp.stack(
                        [jnp.max(jnp.where(sel & (rank == j + 1),
                                           cand_l[0], _U32(0)), axis=1)
                         for j in range(ALPHA)], axis=1)
                new_rows = reply_gather(targets, pos_t_full, q_index,
                                        x_rows, rnd + 1, x_d0)
                queried = jnp.where(sel, 1, queried)
                cand_node, cand_l, queried = merge(
                    targets, cand_node, cand_l, queried, new_rows)
                return cand_node, cand_l, queried

            cand_node, cand_l, queried = lax.fori_loop(
                0, ROUNDS, body, (cand_node, cand_l, queried))
            if return_state:
                return cand_node, cand_l, queried
            return (jnp.sum(cand_node[:, :K].astype(jnp.float32)) * 1e-9
                    + jnp.sum(queried.astype(jnp.float32)) * 1e-9)
        return wave

    if args.smoke:
        # 1) end-to-end bit-identity of the fusion through the loop
        st_f = jax.jit(make_wave("fused", return_state=True))(
            targets0, sorted_ids, lut)
        st_u = jax.jit(make_wave("r5_unfused", return_state=True))(
            targets0, sorted_ids, lut)
        for a, b, name in ((st_f[0], st_u[0], "cand_node"),
                           (st_f[2], st_u[2], "queried"),
                           *((x, y, f"cand_l{i}") for i, (x, y)
                             in enumerate(zip(st_f[1], st_u[1])))):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                print(f"SMOKE FAIL: fused vs r5_unfused diverge on {name}")
                return 1
        # 2) regression band on the fused round.  The two chains sit at
        # near-parity by design, so host-load stalls are the flake
        # risk: each variant is measured twice (same compiled chain —
        # chain_slope caches per body) and the band compares the MIN
        # of each pair, which filters a one-sided scheduling stall
        # while a real code regression shifts every sample.
        r1, r2 = (2, 8)
        wf, wu = make_wave("fused"), make_wave("r5_unfused")
        dts_f = [chain_slope(wf, targets0, sorted_ids, lut, r1=r1, r2=r2)
                 for _ in range(2)]
        dts_u = [chain_slope(wu, targets0, sorted_ids, lut, r1=r1, r2=r2)
                 for _ in range(2)]
        dt_f, dt_u = min(dts_f), min(dts_u)
        rec = {"smoke": True, "N": N, "W": W, "rounds": ROUNDS,
               "fused_ms_per_round": round(dt_f * 1e3 / ROUNDS, 3),
               "r5_unfused_ms_per_round": round(dt_u * 1e3 / ROUNDS, 3),
               "samples_ms": [round(d * 1e3, 2) for d in dts_f + dts_u],
               "bit_identical": True}
        dc.emit(rec)
        if dt_f > 1.5 * dt_u:
            print(f"SMOKE FAIL: fused round {dt_f * 1e3:.2f} ms > "
                  f"1.5x unfused {dt_u * 1e3:.2f} ms (min of 2 each)")
            return 1
        print("wave-latency smoke ok")
        return 0

    base = None
    recs = []
    for v in VARIANTS:
        dt = chain_slope(make_wave(v), targets0, sorted_ids, lut, r1=1, r2=4)
        rec = {"variant": v, "ms": round(dt * 1e3, 2),
               "ms_per_round": round(dt * 1e3 / ROUNDS, 3)}
        if v == "fused":
            base = dt
        elif base:
            rec["saves_ms"] = round((base - dt) * 1e3, 2)
        recs.append(rec)
        print(json.dumps(rec), flush=True)
    by = {r["variant"]: r for r in recs}
    bound = {
        "platform": jax.devices()[0].platform,
        "N": N, "W": W, "rounds": ROUNDS,
        "round_floor_ms": by["fused"]["ms_per_round"],
        "wave_bound_ms": round(by["fused"]["ms_per_round"] * ROUNDS, 2),
        # a stage's per-round cost = how much the wave SPEEDS UP with it
        # disabled (saves_ms / rounds); negative values are measurement
        # noise on stages at the dispatch floor
        "stage_ms_per_round": {
            "reply_gather": round(by["no_reply_gather"].get("saves_ms", 0)
                                  / ROUNDS, 3),
            "lut_reads": round(by["no_lut_reads"].get("saves_ms", 0)
                               / ROUNDS, 3),
            "dedup_sort": round(by["no_dedup_sort"].get("saves_ms", 0)
                                / ROUNDS, 3),
            "alpha_select": round(by["no_alpha_select"].get("saves_ms", 0)
                                  / ROUNDS, 3),
            "r5_peer_gather_removed": round(
                (by["r5_unfused"]["ms"] - by["fused"]["ms"]) / ROUNDS, 3),
        },
    }
    print(json.dumps({"bound": bound}), flush=True)
    if args.capture:
        out = {
            "metric": ("round-fused engine attribution, fixed-trip "
                       "%d-round fori_loop, W=%d x N=%d, alpha=%d k=%d "
                       "state_limbs=%d, platform=%s; per-variant ms and "
                       "the per-round floor the wave-latency bound "
                       "quotes (wave p50 >= rounds x floor)"
                       % (ROUNDS, W, N, ALPHA, K, NL,
                          jax.devices()[0].platform)),
            "value": by["fused"]["ms_per_round"],
            "unit": "ms/round (%s)" % jax.devices()[0].platform,
            "vs_baseline": None,
            "variants": recs,
            "bound": bound,
        }
        dc.write_capture(args.capture, out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
