"""Churn ROUND-BODY attribution inside the real compiled program
(ask 5 continued) — the isolated-stage numbers (exp_churn_r5.py) do not
add up to the measured round, so, as with the search engine
(exp_round_r5.py), each variant disables one piece of the REAL round
body and (full − variant) attributes cost with fusion effects included.

Fixtures (base table, delta slab, idempotent mutation arrays) come
from benchmarks/churn_fixtures.py — the shared scaffolding of every
churn driver since round 7.
"""

from __future__ import annotations

import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)          # churn_fixtures + driver_common
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from bench import chain_slope
    from opendht_tpu.ops.sorted_table import (
        sort_table, build_prefix_lut, default_lut_bits, expand_table,
        churn_lookup_topk, expanded_topk)
    import churn_fixtures as FX

    on_accel = jax.devices()[0].platform != "cpu"
    N, Q, DCAP = FX.sizes(on_accel, dcap=65_536 if on_accel else 8_192)
    E, K = 256, 8
    d_bits = default_lut_bits(DCAP)

    base = FX.build_base(N, Q, limbs=2)
    sorted_ids, expanded = base["sorted_ids"], base["expanded"]
    lut, n_valid, queries = base["lut"], base["n_valid"], base["queries"]

    mut = FX.build_mutations(N, DCAP, E)
    tomb_base, widx, wval = mut["tomb_base"], mut["widx"], mut["wval"]
    dslab, new_ids = mut["dslab"], mut["new_ids"]
    nd0, nd_after = mut["nd0"], mut["nd_after"]

    # pre-built delta structures for the no-rebuild variant
    ds0, (de0, dew0), dlut0, _dnv0 = FX.build_delta_structs(
        dslab.at[nd0:nd0 + E].set(new_ids), nd0 + E, strides=(16, 64))

    def make_round(variant):
        def round_body(q, sorted_ids, expanded, lut, n_valid, tomb_base,
                       widx, wval, dslab, new_ids, nd_after,
                       ds0, de0, dew0, dlut0):
            tomb = tomb_base.at[widx].set(wval)
            if variant == "no_rebuild":
                ds, de, dew, dlut, dnv = ds0, de0, dew0, dlut0, nd_after
            else:
                ds_slab = lax.dynamic_update_slice(
                    dslab, new_ids, (jnp.int32(nd0), 0))
                dvalid = jnp.arange(DCAP) < nd_after
                ds, _dp, dnv = sort_table(ds_slab, dvalid)
                de = expand_table(ds, stride=16, limbs=2)
                dew = expand_table(ds, stride=64, limbs=2)
                dlut = build_prefix_lut(ds, dnv, bits=d_bits)
            if variant == "base_only":
                _d, enc, cert = expanded_topk(
                    sorted_ids, expanded, n_valid, q, k=K, select="fast2",
                    lut=lut, lut_steps=0, planes=2, tomb_bits=tomb)
                return (jnp.sum(cert.astype(jnp.float32))
                        + jnp.sum(enc[:, 0].astype(jnp.float32)) * 1e-9
                        + de[0, 0].astype(jnp.float32) * 1e-9
                        + dew[0, 0].astype(jnp.float32) * 1e-9
                        + dlut[1].astype(jnp.float32) * 1e-9)
            if variant == "delta_only":
                from opendht_tpu.ops.sorted_table import cascade_topk
                _d, enc, cert = cascade_topk(
                    ds, de, dew, dnv, q, dlut, k=K, select="fast2",
                    cap=4096, planes=2, fast2_limbs=True)
                return (jnp.sum(cert.astype(jnp.float32))
                        + jnp.sum(enc[:, 0].astype(jnp.float32)) * 1e-9)
            _dist, enc, cert = churn_lookup_topk(
                sorted_ids, expanded, n_valid, tomb, ds, de, dnv, q,
                lut=lut, d_lut=dlut, d_exp_wide=dew, k=K, select="fast2",
                lut_steps=0, planes=2, d_cap=4096)
            return (jnp.sum(cert.astype(jnp.float32))
                    + jnp.sum(enc[:, 0].astype(jnp.float32)) * 1e-9)
        return round_body

    base_dt = None
    for v in ("full", "no_rebuild", "base_only", "delta_only"):
        dt = chain_slope(make_round(v), queries, sorted_ids, expanded, lut,
                         n_valid, tomb_base, widx, wval, dslab, new_ids,
                         nd_after, ds0, de0, dew0, dlut0, r1=2, r2=8)
        rec = {"variant": v, "ms": round(dt * 1e3, 2)}
        if v == "full":
            base_dt = dt
        elif base_dt:
            rec["delta_vs_full_ms"] = round((base_dt - dt) * 1e3, 2)
        print(json.dumps(rec), flush=True)

    # static comparator, same session
    def static_body(q, sorted_ids, expanded, lut, n_valid):
        d, idx, c = expanded_topk(sorted_ids, expanded, n_valid, q, k=K,
                                  select="fast2", lut=lut, lut_steps=0,
                                  planes=2)
        return (jnp.sum(c.astype(jnp.float32))
                + jnp.sum(idx[:, 0].astype(jnp.float32)) * 1e-9)

    dt = chain_slope(static_body, queries, sorted_ids, expanded, lut,
                     n_valid, r1=2, r2=8)
    print(json.dumps({"variant": "static (no churn structures)",
                      "ms": round(dt * 1e3, 2)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
