"""Keyspace-observatory on-cost on the 8192-wave search round (round 15).

The ISSUE-10 acceptance gate: with the :class:`~opendht_tpu.keyspace.
KeyspaceObservatory` observing every wave's full [W] target batch (one
batched sketch scatter-add launch + the sample-and-hold candidate
admission per wave — a far HIGHER duty cycle than production, where the
observatory sees Q<=64-id ingest waves) and ticking every 32 waves
(decay + heavy-hitter re-score), the 8192-wave iterative-search round
must cost < 1% over the observatory-free run.  The sketch update is an
ASYNC dispatch that never blocks the wave, so the expectation is
dispatch-overhead-level; this driver measures it with the round-9
paired-delta methodology (benchmarks/exp_trace_r9.py) and commits the
result as ``captures/keyspace_overhead.json``.

Methodology: both modes run the SAME compiled wave executable,
interleaved over ``--reps`` trips with the mode order rotating per rep,
and the committed number is the MEDIAN OF PER-REP PAIRED differences
(pairing cancels background-load drift on shared hosts).  The driver
also pins the wave outputs bit-identical between an observed and an
untouched trip — the "kernels stay bit-identical with the sketch on"
acceptance line, checked again in tests/test_keyspace.py.

Usage::

    python benchmarks/exp_keyspace_r15.py --save     # writes capture
    python benchmarks/exp_keyspace_r15.py --smoke    # CI band check
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=0,
                   help="table rows (default: 1M on accelerator, 128K cpu)")
    p.add_argument("-W", type=int, default=8192, help="wave width")
    p.add_argument("--reps", type=int, default=15,
                   help="timed trips per mode (interleaved)")
    p.add_argument("--tick-every", type=int, default=32,
                   help="observatory ticks (decay + re-score) per this "
                        "many observed waves")
    p.add_argument("--save", action="store_true",
                   help="write captures/keyspace_overhead.json")
    p.add_argument("--smoke", action="store_true",
                   help="assert observed overhead < 5%% (generous CI "
                        "band; the committed capture documents the "
                        "tight number against the <1%% acceptance)")
    args = p.parse_args(argv)

    import jax
    from opendht_tpu import telemetry
    from opendht_tpu.keyspace import KeyspaceConfig, KeyspaceObservatory
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, sort_table,
                                              default_lut_bits)

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (1_000_000 if on_accel else 131_072)
    W = args.W

    key = jax.random.PRNGKey(15)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jax.numpy.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table
    targets_np = np.asarray(targets)      # the wave builder's host-side form

    telemetry.get_registry().enabled = True      # telemetry ON in both modes
    obs = KeyspaceObservatory(KeyspaceConfig(tick=0))
    obs_waves = [0]

    def trip(mode: str) -> float:
        t0 = time.perf_counter()
        out = simulate_lookups(sorted_ids, n_valid, targets, alpha=3,
                               k=8, lut=lut, state_limbs=2)
        jax.block_until_ready(out)
        if mode == "observed":
            obs.observe_ids(targets_np)
            obs_waves[0] += 1
            if obs_waves[0] % max(1, args.tick_every) == 0:
                obs.tick()
        return time.perf_counter() - t0

    # shared warmup: one executable serves both modes (and the sketch
    # update/tick kernels compile outside the timed region)
    for mode in ("observed", "off"):
        trip(mode)
    obs.tick()

    # bit-identity: an observed trip and an untouched trip return the
    # same arrays (the sketch is a SEPARATE launch — it never touches
    # the wave computation)
    base = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    obs.observe_ids(targets_np)
    obs.tick()
    observed = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(observed)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "wave outputs diverged with the keyspace sketch enabled"
    del base, observed

    # observatory sanity: it actually observed and ranked something
    snap = obs.snapshot()
    assert snap["enabled"] and snap["observed_total"] >= W

    times: dict = {"off": [], "observed": []}
    order = ["off", "observed"]
    for i in range(args.reps):
        for mode in order[i % 2:] + order[:i % 2]:
            times[mode].append(trip(mode))

    on_pct = float(np.median([(s - o) / o for s, o in
                              zip(times["observed"], times["off"])])) * 100
    med = {m: float(np.median(v) * 1e3) for m, v in times.items()}
    rec = {
        "name": "keyspace_overhead",
        "value": round(on_pct, 3),
        "unit": "percent",
        "acceptance_pct": 1.0,
        "wave": W, "N": N, "reps": args.reps,
        "tick_every": args.tick_every,
        "wave_ms_observed": round(med["observed"], 3),
        "wave_ms_off": round(med["off"], 3),
        "platform": jax.devices()[0].platform,
        "note": "8192-wave search round, median of per-rep paired "
                "deltas over rotation-interleaved trips: keyspace "
                "observatory ingesting the FULL [W] target batch per "
                "wave (one async count-min scatter-add launch + "
                "sample-and-hold candidate admission, tick every %d "
                "waves) vs no observatory; same executable, telemetry "
                "on in both modes; wave outputs pinned bit-identical"
                % args.tick_every,
    }
    dc.emit(rec)

    if args.save:
        dc.write_capture("keyspace_overhead", rec)

    if args.smoke and on_pct >= 5.0:
        print("keyspace overhead %.2f%% exceeds the 5%% smoke band"
              % on_pct, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
