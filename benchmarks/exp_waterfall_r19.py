"""Stage-profiler on-cost on the 8192-wave search round (round 19).

The round-19 acceptance gate: with the always-on latency waterfall
observing every wave (the ``record_wave`` device-stage hook — a
compile/execute-split ``dht_stage_seconds`` observe with exemplar
stamping, the same hook the serving wave builder fires), the 8192-wave
iterative-search round must cost < 1% over the profiler-disabled run.
The profiler is host-side histogram arithmetic only — a dict lookup, a
bisect and two adds per stage sample; it never touches the device — so
the expectation is noise-level.  Measured with the shared paired-delta
estimator (``driver_common.paired_delta``, the round-9 methodology
extracted to one copy this round) and committed as
``captures/waterfall_overhead.json``.

The driver also pins the wave outputs bit-identical between a
profiler-on trip and a profiler-off trip — the "kernels stay
bit-identical with the profiler on" acceptance line, checked again in
tests/test_waterfall.py — and ``--stages`` prints the measured
per-stage waterfall (p50/p95 vs budget) next to the headline delta.

Usage::

    python benchmarks/exp_waterfall_r19.py --save      # writes capture
    python benchmarks/exp_waterfall_r19.py --smoke     # CI band check
    python benchmarks/exp_waterfall_r19.py --stages    # + waterfall
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=0,
                   help="table rows (default: 1M on accelerator, 128K cpu)")
    p.add_argument("-W", type=int, default=8192, help="wave width")
    dc.add_paired_delta_args(p)
    p.add_argument("--save", action="store_true",
                   help="write captures/waterfall_overhead.json")
    p.add_argument("--smoke", action="store_true",
                   help="assert profiler overhead < 5%% (generous CI "
                        "band; the committed capture documents the "
                        "tight number against the <1%% acceptance)")
    args = p.parse_args(argv)

    import jax
    from opendht_tpu import telemetry, waterfall
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, sort_table,
                                              default_lut_bits)
    from opendht_tpu.waterfall import WaterfallConfig

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (1_000_000 if on_accel else 131_072)
    W = args.W

    key = jax.random.PRNGKey(19)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jax.numpy.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    reg = telemetry.get_registry()
    reg.enabled = True                      # telemetry ON in both modes
    wf = waterfall.get_profiler()

    def trip(mode: str) -> float:
        wf.configure(WaterfallConfig(enabled=(mode == "on")))
        t0 = time.perf_counter()
        out = simulate_lookups(sorted_ids, n_valid, targets, alpha=3,
                               k=8, lut=lut, state_limbs=2)
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    # bit-identity: a profiler-on trip and a profiler-off trip return
    # the same arrays (the profiler only observes host wall-clock)
    wf.configure(WaterfallConfig(enabled=False))
    base = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    wf.configure(WaterfallConfig(enabled=True))
    profiled = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(profiled)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "wave outputs diverged with the stage profiler enabled"
    del base, profiled

    pd = dc.paired_delta(trip, args.reps, modes=("off", "on"))
    wf.configure(WaterfallConfig())

    # profiler sanity: the timed "on" trips observed real device stages
    snap = wf.snapshot()
    dev = (snap["stages"]["device_compile"]["count"]
           + snap["stages"]["device_launch"]["count"])
    assert dev >= args.reps, \
        "profiler saw %d device-stage samples over %d reps" % (
            dev, args.reps)

    rec_doc = {
        "name": "waterfall_overhead",
        "value": round(pd["on_pct"], 3),
        "unit": "percent",
        "acceptance_pct": 1.0,
        "wave": W, "N": N, "reps": args.reps,
        "wave_ms_on": round(pd["med_ms"]["on"], 3),
        "wave_ms_off": round(pd["med_ms"]["off"], 3),
        "device_stage_samples": int(dev),
        "platform": jax.devices()[0].platform,
        "note": "8192-wave search round, median of per-rep paired "
                "deltas over rotation-interleaved trips "
                "(driver_common.paired_delta): always-on stage "
                "profiler observing every wave's device stage with "
                "compile/execute split + exemplar stamping vs profiler "
                "disabled; same executable, telemetry on in both "
                "modes; wave outputs pinned bit-identical",
    }
    dc.emit(rec_doc)
    if args.stages:
        dc.print_stage_waterfall(snap)

    if args.save:
        dc.write_capture("waterfall_overhead", rec_doc)

    if args.smoke and pd["on_pct"] >= 5.0:
        print("waterfall overhead %.2f%% exceeds the 5%% smoke band"
              % pd["on_pct"], file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
