#!/bin/sh
# CI entry point (↔ the reference's travis/cmake test tier, SURVEY.md §4
# tier 4): full test suite on the virtual 8-device CPU mesh, then the
# driver entry checks and a CPU-scaled bench smoke.
set -e
cd "$(dirname "$0")/.."
python -m pytest tests/ -q
python - <<'PY'
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
fn, args = g.entry()
jax.block_until_ready(jax.jit(fn)(*args))
g.dryrun_multichip(8)
print("entry + dryrun ok")
PY
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # env var alone loses to sitecustomize
import bench
bench.main()
PY
# CPU-scaled smoke of the BASELINE config drivers — catches driver-level
# errors (e.g. a NameError in one config) that unit tests cannot see.
# config2 is skipped: it delegates to bench.measure(), which the step
# above already ran.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib
spec = importlib.util.spec_from_file_location(
    "baseline_configs", pathlib.Path("benchmarks/baseline_configs.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
for c in (1, 3, 4, 5):
    m.main(["-c", str(c)])
PY
