#!/bin/sh
# CI entry point (↔ the reference's travis/cmake test tier, SURVEY.md §4
# tier 4): full test suite on the virtual 8-device CPU mesh, then the
# driver entry checks and a CPU-scaled bench smoke.
set -e
cd "$(dirname "$0")/.."
# smoke drivers drop their JSON records here (benchmarks/driver_common.py
# emit); the perf gate at the end of this script soft-checks the timing
# ceilings in perf_budgets.json against them
export OPENDHT_TPU_SMOKE_RECORD_DIR="$(mktemp -d /tmp/odt-smoke.XXXXXX)"
trap 'rm -rf "$OPENDHT_TPU_SMOKE_RECORD_DIR"' EXIT
# packaging smoke: the wheel must build and every console entry point
# must resolve (catches pyproject drift before the Docker tier does)
python -m pip wheel --no-build-isolation --no-deps -q -w /tmp/odt-ci-wheel .
python - <<'PY'
from opendht_tpu.tools.dhtnode import main as a
from opendht_tpu.tools.dhtchat import main as b
from opendht_tpu.tools.dhtscanner import main as c
print("entry points ok")
PY
python -m pytest tests/ -q
# README/PARITY headline quotes must agree with the last accelerator
# bench capture (within the stated cross-run drift band), and the
# committed PERF_TRAJECTORY.json must equal a fresh assembly of its
# sources (BENCH_r* / captures / TP_SCALING) with the README trajectory
# table quoting it — both directions
python ci/check_docs.py
python - <<'PY'
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
fn, args = g.entry()
jax.block_until_ready(jax.jit(fn)(*args))
g.dryrun_multichip(8)
print("entry + dryrun ok")
PY
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # env var alone loses to sitecustomize
import bench
bench.main()
PY
# CPU-scaled smoke of the BASELINE config drivers — catches driver-level
# errors (e.g. a NameError in one config) that unit tests cannot see.
# config2 is skipped: it delegates to bench.measure(), which the step
# above already ran.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib
spec = importlib.util.spec_from_file_location(
    "baseline_configs", pathlib.Path("benchmarks/baseline_configs.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
for c in (1, 3, 4, 5, 6):
    m.main(["-c", str(c)])
PY
# wave-latency smoke (round 6): the fixed-trip round-attribution driver
# at a small wave asserts (1) the driver's MIRROR of the round-fused
# engine body is bit-identical to its round-5 unfused form through the
# compiled loop (the SHIPPING engine's reply streams are pinned by the
# goldens test in the suite above) and (2) the fused round has not
# regressed past a generous 1.5x band — p50 wave-latency regressions on
# the fused path fail here without the full bench.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib
spec = importlib.util.spec_from_file_location(
    "exp_round_r6", pathlib.Path("benchmarks/exp_round_r6.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke"])
assert rc == 0, "wave-latency smoke failed"
PY
# churn-merge smoke (round 7): the lane-packed merge must stay
# BIT-IDENTICAL to the unpacked merge through the SHIPPING
# churn_lookup_topk (fast2 + fast3, ragged wave) and the packed round
# must not regress past a generous 1.5x band vs the unpacked round —
# a merge-stage latency regression fails here without the full bench.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_churn_r7", pathlib.Path("benchmarks/exp_churn_r7.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "-N", "16384", "-Q", "1025", "--dcap", "1024",
             "-E", "64"])
assert rc == 0, "churn-merge smoke failed"
PY
# telemetry smoke (round 8): boot a small real-UDP cluster, run
# puts/gets, scrape the proxy's GET /stats and DhtRunner.get_metrics(),
# assert the exercised counters advanced, the two exports agree, and
# the Prometheus text exposition parses line-by-line.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.telemetry_smoke import main
rc = main()
assert rc == 0, "telemetry smoke failed"
PY
# tracing smoke (round 9): boot a 5-node real-UDP cluster, run one
# traced put+get, assemble the cross-node span tree (>=3 nodes
# contributed spans, correct parentage, monotone timestamps), check
# the Chrome/Perfetto dump round-trips with the exact ph/pid/tid/ts/
# dur fields, the flight-recorder dump parses, and the ring's
# bounded-memory property (10x capacity pushed -> oldest evicted,
# RSS-stable).
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.trace_assembler import main
rc = main()
assert rc == 0, "tracing smoke failed"
PY
# tracing overhead smoke (round 9): the sampled-on 8192-wave round must
# stay inside a generous 10% band vs the tracer-disabled run (the
# committed captures/trace_overhead.json documents the tight number,
# enforced against the README quote by check_docs above).
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib
spec = importlib.util.spec_from_file_location(
    "exp_trace_r9", pathlib.Path("benchmarks/exp_trace_r9.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "-N", "16384", "-W", "1024", "--reps", "7"])
assert rc == 0, "tracing overhead smoke failed"
PY
# round-fused stage-profile smoke (round 11): the per-stage chain-slope
# decomposition mirroring the ROUND-6 fused round body must run end to
# end at a small shape (a stage-level compile break or an
# order-of-magnitude wave stall fails here without the full bench)
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "profile_search", pathlib.Path("benchmarks/profile_search.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke"])
assert rc == 0, "profile_search smoke failed"
PY
# kernel-ledger overhead smoke (round 11): with the cost ledger computed
# and the wave_attrs hook live on the traced record_wave path, the wave
# must stay inside a generous 5% band vs the ledger-disabled run (the
# committed captures/ledger_overhead.json documents the tight number,
# enforced against the README quote by check_docs above)
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_ledger_r11", pathlib.Path("benchmarks/exp_ledger_r11.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "-N", "16384", "-W", "1024", "--reps", "7"])
assert rc == 0, "ledger overhead smoke failed"
PY
# kernel-ledger export smoke (round 11): boot a node + proxy, compute a
# ledger subset, scrape GET /stats and get_metrics(), assert the
# dht_kernel_* series are present, agree, and the exposition parses
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.ledger_smoke import main
rc = main()
assert rc == 0, "ledger smoke failed"
PY
# ingest-amortization smoke (round 12): the coalesced [Q] resolve must
# still amortize the per-op dispatch (>2x at a small shape) through the
# SHIPPING find_closest_nodes_batched stack — a refactor that sneaks a
# per-target dispatch back into the wave path fails here without the
# full bench.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_ingest_r12", pathlib.Path("benchmarks/exp_ingest_r12.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke"])
assert rc == 0, "ingest amortization smoke failed"
PY
# burst-ingest smoke (round 12): boot a real-UDP cluster + proxy, fire
# concurrent gets/puts/listens from threads, assert the wave builder
# actually coalesced them (mean wave occupancy > 1 on the new
# histogram, dht_ingest_* series on the proxy /stats exposition, zero
# sheds), and that the identical workload rerun with
# ingest_batching="off" returns the same values and leaves the same
# per-node storage state — the acceptance-criteria equivalence pin.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.ingest_smoke import main
rc = main()
assert rc == 0, "ingest smoke failed"
PY
# health observatory smoke (round 14): boot a 3-node real-UDP cluster +
# proxy, assert GET /healthz flips 503->200 through bootstrap, run the
# batched replica-coverage probe (the whole sampled key set's true
# closest-8 in ONE launch) against the live stores, then choke ingest
# admission and assert the availability SLO fast-burns the verdict to
# unhealthy with health_transition/slo_violation events in the flight
# recorder and dhtmon exiting non-zero on the lookup-success invariant.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.health_smoke import main
rc = main()
assert rc == 0, "health smoke failed"
PY
# health-evaluator overhead smoke (round 14): with the evaluator
# ticking once per wave, the search round must stay inside a generous
# 5% band vs the evaluator-free run (the committed
# captures/health_overhead.json documents the tight number against the
# <1% acceptance, enforced against the README quote by check_docs).
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_health_r14", pathlib.Path("benchmarks/exp_health_r14.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "-N", "16384", "-W", "1024", "--reps", "7"])
assert rc == 0, "health overhead smoke failed"
PY
# keyspace observatory smoke (round 15): boot a 3-node real-UDP cluster
# + proxy, drive Zipf-skewed gets/puts through the wave builder, assert
# the hot key surfaces in GET /keyspace as hot (with a hot_key_emerged
# flight event), the dht_shard_imbalance gauge exports a known value on
# GET /stats, and dhtmon --max-imbalance exits 0 on the mixed load then
# 1 under an injected single-key flood.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.keyspace_smoke import main
rc = main()
assert rc == 0, "keyspace smoke failed"
PY
# keyspace-observatory overhead smoke (round 15): with the count-min
# sketch observing every wave's full target batch (one async batched
# scatter-add per wave + candidate sampling), the search round must
# stay inside a generous 5% band vs the observatory-free run (the
# committed captures/keyspace_overhead.json documents the tight number
# against the <1% acceptance, enforced against the README quote by
# check_docs above).
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_keyspace_r15", pathlib.Path("benchmarks/exp_keyspace_r15.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "-N", "16384", "-W", "1024", "--reps", "7"])
assert rc == 0, "keyspace overhead smoke failed"
PY
# load-aware resharding smoke (round 21): boot a 3-node real-UDP
# cluster + proxy, flood one hot key past the rebalance threshold, and
# assert the closed loop live: a burst shorter than the sustain window
# causes ZERO swaps (hysteresis skips advance, dhtmon --max-imbalance
# exits 1), the sustained flood swaps a new layout generation (virtual
# mode, reshard_swap flight event, dht_reshard_* on /stats), fold
# attribution follows the new traffic-weighted edges (live imbalance
# drops under the gate, dhtmon flips to 0), and get/put/listen are
# identical across the swap.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.reshard_smoke import main
rc = main()
assert rc == 0, "reshard smoke failed"
PY
# reshard balance smoke (round 21): the boundary-solver benchmark at a
# small shape — Zipf-hot traffic on the uniform split must read
# imbalanced, the solved layout must refold balanced, the weighted
# shard state must stay BIT-IDENTICAL to the single-device engine
# (including an in-flight wave crossing the swap), and the committed
# captures/reshard_balance.json quotes are enforced against README/
# PARITY by check_docs above.
python - <<'PY'
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_reshard_r17", pathlib.Path("benchmarks/exp_reshard_r17.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke"])
assert rc == 0, "reshard balance smoke failed"
PY
# hot-cache smoke (round 16): boot a 3-node real-UDP cluster + proxy
# (node 0 caches, nodes 1-2 cache-off), Zipf-flood the hot key until
# hot_key_emerged, and assert the observe→act loop closes live: the
# cache admits the key off the observatory tick, hot gets serve from
# cache (hit counters advance, wave occupancy attributable to the hot
# key ~0), the windowed hit ratio reaches >=0.9 with dhtmon
# --min-cache-hit exiting 0 then 1 under a cold-key miss storm, a
# fresh put invalidates with the new value visible on every surface
# (runner ops, proxy REST, listeners), and cache-on == cache-off
# results throughout.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.cache_smoke import main
rc = main()
assert rc == 0, "cache smoke failed"
PY
# hot-cache probe overhead smoke (round 16): with the probe running
# over every wave's full target batch against a full device table (all
# misses — the worst case), the search round must stay inside a
# generous 5% band vs the cache-free run (the committed
# captures/cache_overhead.json documents the tight number against the
# <1% acceptance, enforced against the README quote by check_docs
# above).
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_cache_r16", pathlib.Path("benchmarks/exp_cache_r16.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "-N", "16384", "-W", "1024", "--reps", "7"])
assert rc == 0, "cache overhead smoke failed"
PY
# flight-data-recorder smoke (round 17): boot a 3-node real-UDP cluster
# + proxy, assert dhtmon's windowed invariants read each node's
# GET /history frames (no scrape-diff wait; pinned equal to the legacy
# paths), induce an SLO burn and assert a black-box bundle
# auto-captures with the burn visible in its frames and GET
# /debug/bundle serving fresh ones, dhtmon --since exits 1 during the
# burn window then 0 after recovery, the bundle round-trips through the
# cluster timeline assembler with the health transition present, and
# the ring + on-disk spill stay bounded under a 10x flood.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.history_smoke import main
rc = main()
assert rc == 0, "history smoke failed"
PY
# flight-data-recorder overhead smoke (round 17): with the recorder
# ticking once per wave (full-registry delta frame + spill armed), the
# search round must stay inside a generous 5% band vs the recorder-free
# run (the committed captures/history_overhead.json documents the tight
# number against the <1% acceptance, enforced against the README quote
# by check_docs above).
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_history_r17", pathlib.Path("benchmarks/exp_history_r17.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "-N", "16384", "-W", "1024", "--reps", "7"])
assert rc == 0, "history overhead smoke failed"
PY
# adversarial chaos smoke (round 18): (1) a scripted partition+heal on
# a small real-UDP cluster — the isolated node's gets fail, /healthz
# degrades to 503, a black-box bundle auto-captures on the unhealthy
# transition and dhtmon --since flags the burn window; healing rolls
# the verdict back (healthz 200, dhtmon clean).  (2) the virtual-net
# storm: chaos-off == baseline pinned (armed-but-empty plan delivers
# identical results with zero drops), then per-link loss/dup/reorder +
# an asymmetric partition phase + join/leave storm steps with per-rule
# drop accounting and every stored key still resolvable post-heal.
# (3) a 4096-node device swarm steps the same storm arc: invariants
# degrade mid-partition and are restored after healing.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.chaos_smoke import main
rc = main()
assert rc == 0, "chaos smoke failed"
PY
# swarm-stepper smoke (round 18): the storm arc rerun at S=4096 through
# benchmarks/exp_chaos_r18.py --smoke, asserting bit-for-bit
# determinism under the fixed seed (two runs replay identically) and
# feeding the perf gate's swarm_tick_ms timing record; the full
# S=50000 acceptance run is committed as captures/swarm_storm.json.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_chaos_r18", pathlib.Path("benchmarks/exp_chaos_r18.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "--ticks", "22"])
assert rc == 0, "swarm stepper smoke failed"
PY
# per-op latency waterfall smoke (round 19): boot a 3-node real-UDP
# cluster + proxy, run mixed put/get traffic, assert the always-on
# dht_stage_seconds{stage=} histograms advance on the scrape (queue
# wait, device launch, scatter-back, real-UDP rpc_wait), GET /profile
# serves the waterfall JSON + ?fmt=folded flamegraph stacks (400 on a
# bad fmt), a hot-bucket exemplar trace id reassembles into a span
# tree through the trace assembler, dhtmon --max-stage exits 0 at a
# gate above the healthy baseline then 1 under an injected
# scatter-path stall, and the OPEN-bound tracker drops a well-formed
# settling record (status="unsettled" on CPU).
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.waterfall_smoke import main
rc = main()
assert rc == 0, "waterfall smoke failed"
PY
# stage-profiler overhead smoke (round 19): with the always-on profiler
# observing every wave's device stage (compile/execute split + exemplar
# stamping), the search round must stay inside a generous 5% band vs
# the profiler-disabled run (the committed
# captures/waterfall_overhead.json documents the tight number against
# the <1% acceptance, enforced against the README quote by check_docs
# above), and the wave outputs stay bit-identical profiler on vs off.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_waterfall_r19", pathlib.Path("benchmarks/exp_waterfall_r19.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "-N", "16384", "-W", "1024", "--reps", "7"])
assert rc == 0, "waterfall overhead smoke failed"
PY
# wave-pipeline smoke (round 20): boot a 3-node real-UDP cluster +
# proxy, run the concurrent mixed burst at ingest_pipeline_depth=2 and
# assert the double-buffer actually stacks (the
# dht_ingest_pipeline_inflight_peak gauge reaches >=2 via the
# deterministic stack probe, both pipeline series ride the proxy
# /stats exposition), the always-on stage histograms keep advancing
# with the device stage now measured at consume, and the identical
# workload rerun at depth=1 (the exact pre-pipeline serial path)
# returns the same values / listener deliveries / per-node storage.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.pipeline_smoke import main
rc = main()
assert rc == 0, "pipeline smoke failed"
PY
# wave-pipeline overlap smoke (round 20): sustained ingest through the
# SHIPPING WaveBuilder at a small shape — depth-2 results must stay
# bit-identical to depth-1, the in-flight machinery must hold two
# waves (slow-ready shim), and the paired-delta band guards against
# the pipeline REGRESSING sustained ingest (the committed
# captures/pipeline_overlap.json documents the full-shape figure,
# enforced against the README quote by check_docs above).
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_pipeline_r20", pathlib.Path("benchmarks/exp_pipeline_r20.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke"])
assert rc == 0, "wave pipeline smoke failed"
PY
# pipeline-utilization smoke (round 22): boot a 3-node real-UDP
# cluster + proxy at depth 2, drive a Zipf-skewed get flood, and
# assert the utilization observatory measured it — the
# dht_pipeline_occupancy gauge leaves unknown for a value in (0, 1]
# consistent with the stage histograms (device-stage samples <= waves,
# both > 0, busy <= window), GET /pipeline serves the snapshot and
# ?fmt=trace the three-lane Perfetto doc, both pipeline-occupancy
# series ride the proxy /stats exposition, a forced admission choke is
# attributed as a queue_empty bubble, and dhtmon --min-occupancy exits
# 0 below the measured gauge then 1 at an impossible floor.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.pipeline_util_smoke import main
rc = main()
assert rc == 0, "pipeline utilization smoke failed"
PY
# observatory overhead smoke (round 22): with the full per-wave
# lifecycle (fill/dispatch/bubble-classify/device_done/scatter_done +
# frame checkpoint) tracking every wave, the search round must stay
# inside a generous 5% band vs the observatory-disabled run (the
# committed captures/pipeutil_overhead.json documents the tight number
# against the <1% acceptance, enforced against the README quote by
# check_docs above), the wave outputs stay bit-identical on vs off,
# and the timed trips must leave a CLOSED ledger
# (Σ(busy)+Σ(bubbles)==window).
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_pipeutil_r21", pathlib.Path("benchmarks/exp_pipeutil_r21.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "-N", "16384", "-W", "1024", "--reps", "7"])
assert rc == 0, "observatory overhead smoke failed"
PY
# per-peer observatory smoke (round 23): boot 3-node real-UDP clusters
# and inject chaos-plane faults on ONE link — the same delay+jitter
# rule (RTTs straddling the fixed 1.0s timer) runs once with the
# fixed timetable and once with the adaptive per-peer RTO, and the
# adaptive run must record measurably fewer spurious retransmits while
# the untouched link's srtt/RTO stay baseline; then a one-way loss
# rule on node0->node2 must land on exactly that directed edge of the
# cluster wire map (testing/wiremap_assembler.py over every node's
# GET /peers), tick dht_net_attempt_timeouts_total at the EXPIRED
# transitions, and flip dhtmon --max-peer-fail from 0 to 1 across the
# injected fail ratio.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.peer_smoke import main
rc = main()
assert rc == 0, "per-peer observatory smoke failed"
PY
# per-peer ledger overhead smoke (round 23): with 256 synthetic
# request lifecycles per wave over 32 peers (every completion a clean
# Karn sample driving the RFC 6298 estimator + per-peer histogram +
# gauge writes), the search round must stay inside a generous 5% band
# vs the ledger-disabled run (the committed
# captures/peers_overhead.json documents the tight number against the
# <1% acceptance, enforced against the README quote by check_docs
# above), and the wave outputs stay bit-identical on vs off.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_peers_r23", pathlib.Path("benchmarks/exp_peers_r23.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "-N", "16384", "-W", "1024", "--reps", "7"])
assert rc == 0, "per-peer ledger overhead smoke failed"
PY
# wave-scale listen/push smoke (round 24): boot a 3-node real-UDP
# cluster + proxy with >= 512 live listeners across runner ops and
# proxy SUBSCRIBE/LISTEN registrations, flood a Zipf put mix, and pin
# the batched listener match result-equivalent to the synchronous
# listen_batching="off" arm on EVERY delivery surface (runner
# callbacks with all of a key's listeners agreeing, the proxy LISTEN
# stream, SUBSCRIBE push dispatches); dht_listener_* occupancy/
# latency series must advance on GET /stats and dhtmon
# --max-listener-lag must read 0 healthy and flip to 1 under an
# injected drain stall.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
from opendht_tpu.testing.listener_smoke import main
rc = main()
assert rc == 0, "listener smoke failed"
PY
# listener amortization + on-cost smoke (round 24): the batched
# per-listener delivery slope must sit below the host per-put dispatch
# slope, and with the table ACTIVE at full capacity plus a worst-case
# all-miss flush per trip the 8192-wave search round must stay inside
# a generous 5% band vs the table-free run (the committed
# captures/listener_match.json + captures/listener_overhead.json
# document the tight numbers against the slope-ratio and <1%
# acceptances, enforced against the README quotes by check_docs
# above), wave outputs bit-identical in both modes.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_listener_r24", pathlib.Path("benchmarks/exp_listener_r24.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke", "-N", "16384", "-W", "1024", "--reps", "7"])
assert rc == 0, "listener amortization smoke failed"
PY

# maintenance smoke (round 10): boot a 3-node real-UDP cluster, pin the
# fused maintenance sweep bit-identical to the host stale set on the
# LIVE routing table, force a bucket refresh + a due republish, and
# assert the dht_maintenance_* counters advanced with the refresh
# find_nodes actually on the wire.
python - <<'PY'
import jax
jax.config.update("jax_platforms", "cpu")   # keep off the tunnel backend
import importlib.util, pathlib
spec = importlib.util.spec_from_file_location(
    "exp_maint_r10", pathlib.Path("benchmarks/exp_maint_r10.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke"])
assert rc == 0, "maintenance smoke failed"
PY
# table-sharded iterative mode on a REAL 8-device virtual mesh.  The
# heredoc (rather than env vars + the module CLI) is deliberate: on
# hosts that register an accelerator backend via sitecustomize, the
# JAX_PLATFORMS env var alone LOSES to the registration hook — only a
# jax.config.update before first backend use wins, and the 8-device
# flag must land before the first jax import.
python - <<'PY'
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib
spec = importlib.util.spec_from_file_location(
    "baseline_configs", pathlib.Path("benchmarks/baseline_configs.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
assert len(jax.devices()) == 8
m.main(["-c", "3", "--tp", "-N", "65536", "-Q", "1024"])
PY
# row-sharded table smoke (round 13, ROADMAP item 1): one t=4 sharded
# wave on the 8-device virtual mesh.  Asserts the compiled HLO's
# in-loop collective-site count AND bytes/query/hop EQUAL the
# committed TP_SCALING.json values (drift fails BOTH directions — an
# extra in-loop collective and an unrecorded fusion alike), the
# per-shard resident table stays inside the N/t*5*4 B*(1+eps) bound,
# and the wave is bit-identical to the single-device engine.
python - <<'PY'
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
import jax
jax.config.update("jax_platforms", "cpu")
import importlib.util, pathlib, sys
sys.path.insert(0, str(pathlib.Path("benchmarks")))
spec = importlib.util.spec_from_file_location(
    "exp_shard_r13", pathlib.Path("benchmarks/exp_shard_r13.py"))
m = importlib.util.module_from_spec(spec)
spec.loader.exec_module(m)
rc = m.main(["--smoke"])
assert rc == 0, "row-sharded table smoke failed"
PY
# kernel cost-model perf gate (round 11, ROADMAP item 3): every shipped
# kernel's lowered XLA cost model (flops / bytes accessed / arg+output
# bytes at its canonical shape) must sit inside the committed
# perf_budgets.json tolerances — DETERMINISTIC on the CPU runner, so a
# refactor that doubles a kernel's HBM traffic fails CI here with a
# budget-vs-observed diff.  Wall-clock stays advisory: the smoke records
# collected above are checked against the timing_soft ceilings as
# warnings only (shared runners flake; cost gates, timing informs).
python ci/perf_gate.py --records "$OPENDHT_TPU_SMOKE_RECORD_DIR"
