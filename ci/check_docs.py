"""Docs-vs-capture consistency check (VERDICT r2 'what's weak' #1).

The headline numbers in README.md and PARITY.md must AGREE with the
last captured bench run (bench_capture.json, written by bench.measure
on accelerator hardware) — the checker exists to catch stale quotes
(2x-class drift, the round-1/round-2 failure mode), not day-to-day
variance: bench_capture.json is rewritten by whichever harness ran
last, and cross-run medians on the tunneled device wander beyond a
single run's min/max, so quotes are accepted inside the captured
run-to-run range widened by 10% (15% for ms/batch).

Convention: docs quote the headline as  "<X.XX>M lookups/s"  and
"<Y.Y> ms/batch" where X = value/1e6 rounded to 2 decimals and
Y = ms_per_batch rounded to 1 decimal.  Docs may additionally quote the
run-to-run range verbatim from ``rate_range``.

Usage: python ci/check_docs.py   (exit 1 on drift)
"""

import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    cap_path = os.path.join(ROOT, "bench_capture.json")
    if not os.path.exists(cap_path):
        print("check_docs: no bench_capture.json (no accelerator capture "
              "yet) — skipping")
        return 0
    with open(cap_path) as f:
        cap = json.load(f)

    want_rate = f"{cap['value'] / 1e6:.2f}M lookups/s"
    want_ms = f"{cap['ms_per_batch']:.1f} ms/batch"
    lo, hi = cap["rate_range"]

    # Only lines TAGGED as headline quotes are checked — docs quote many
    # other benchmark figures (scenario rates, sharded-path rates,
    # historical numbers) that can never sit inside the headline range.
    # Convention: the headline line carries the invisible marker
    # "<!-- bench:headline -->"; at least one tagged line must exist in
    # each doc, so the quote cannot silently disappear either.
    failures = []
    for name in ("README.md", "PARITY.md"):
        path = os.path.join(ROOT, name)
        if not os.path.exists(path):
            continue
        tagged = [ln for ln in open(path).read().splitlines()
                  if "bench:headline" in ln]
        if not tagged:
            failures.append(f"{name}: no '<!-- bench:headline -->'-tagged "
                            f"headline quote found")
            continue
        for ln in tagged:
            quoted = re.findall(r"(\d+(?:\.\d+)?)M lookups/s", ln)
            if not quoted:
                failures.append(f"{name}: tagged line quotes no "
                                f"'X.XXM lookups/s' figure: {ln.strip()!r}")
            # tolerance: the captured single-run range widened by 10%
            # each way — bench_capture.json is rewritten by whichever
            # harness ran last (driver or local), and cross-run medians
            # on the tunneled device drift beyond one run's min/max;
            # the check exists to catch STALE quotes (2x-class drift),
            # not to flag normal day-to-day variance
            for q in quoted:
                rate = float(q) * 1e6
                if not (lo * 0.90 <= rate <= hi * 1.10):
                    failures.append(
                        f"{name}: quotes {q}M lookups/s — outside the "
                        f"captured run-to-run range [{lo / 1e6:.2f}M, "
                        f"{hi / 1e6:.2f}M] +/-10% "
                        f"(median {cap['value'] / 1e6:.2f}M)")
            for q in re.findall(r"(\d+(?:\.\d+)?) ?ms/batch", ln):
                if abs(float(q) - cap["ms_per_batch"]) > 0.1 + 0.15 * cap[
                        "ms_per_batch"]:
                    failures.append(
                        f"{name}: quotes {q} ms/batch vs captured "
                        f"{cap['ms_per_batch']:.1f}")
    if failures:
        print("DOCS DRIFT from bench_capture.json:")
        for fmsg in failures:
            print(" -", fmsg)
        print(f"capture: {want_rate} ({want_ms}); range "
              f"[{lo / 1e6:.2f}M, {hi / 1e6:.2f}M]")
        return 1
    print(f"docs agree with capture: {want_rate}, {want_ms}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
