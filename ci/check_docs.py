"""Docs-vs-capture consistency check (VERDICT r2 weak #1, r4 ask #4).

EVERY quoted perf number in README.md / PARITY.md must agree with a
committed capture artifact — the checker exists to catch stale quotes
(2x-class drift, the round-1/round-2 failure mode), not day-to-day
variance.  Two artifact kinds:

- ``bench_capture.json`` (written by bench.measure on accelerator
  hardware): the headline.  Docs lines carrying the invisible marker
  ``<!-- bench:headline -->`` are checked against it, inside the
  captured run-to-run range widened by 10% (15% for ms/batch).
- ``captures/<name>.json`` (written by benchmarks/baseline_configs.py
  save_capture, one per BASELINE config): docs lines carrying
  ``<!-- capture:<name> -->`` are checked against that file's
  ``value`` within ±15% (single-slope configs have no captured range;
  15% covers tunneled-device run-to-run wander while still catching
  stale quotes).  Extra structured fields are checked where quoted:
  ``p50 X ms`` vs ``wave_ms_p50`` (±30%) and ``XK mutations/s`` vs
  ``mutations_per_s`` (±15%).  Captures with ``unit: "percent"`` (the
  telemetry/tracing overhead artifacts) check ``measures X%`` quotes
  against ``value`` and ``X% with sampling off`` against
  ``sampling_off_pct``, within max(1 percentage point, 50% relative)
  — overhead numbers are noise-level, so the band is absolute-floored
  while still catching the 2x-class drift this checker exists for.

For every capture artifact that exists, at least one tagged line must
exist in README.md — a quote cannot silently disappear.  Usage:
``python ci/check_docs.py`` (exit 1 on drift).
"""

import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SUFFIX = {"K": 1e3, "M": 1e6, "B": 1e9}

# capture name -> whether README must carry a tagged quote.  Exploration
# artifacts (``*_custom``) and redundant shapes are never doc-enforced.
_OPTIONAL = ("config3_tp",)


def _para_at(lines, idx):
    """The markdown paragraph (contiguous non-blank lines) containing
    line ``idx``, joined with spaces — wrapped prose puts a tag's
    quoted figures on neighboring lines.  The ONE copy of the
    boundary scan every paragraph-scoped rule uses."""
    lo = idx
    while lo > 0 and lines[lo - 1].strip():
        lo -= 1
    hi = idx
    while hi + 1 < len(lines) and lines[hi + 1].strip():
        hi += 1
    return " ".join(lines[lo:hi + 1])


def _rate_quotes(line):
    """All 'X.XX[KMB] <unit>/s' figures on a doc line."""
    return [(float(v) * _SUFFIX[s], v + s)
            for v, s in re.findall(
                r"(\d+(?:\.\d+)?)([KMB]) (?:converged )?"
                r"(?:lookups|ids)/s", line)]


def check_headline(failures):
    cap_path = os.path.join(ROOT, "bench_capture.json")
    if not os.path.exists(cap_path):
        print("check_docs: no bench_capture.json (no accelerator capture "
              "yet) — skipping headline")
        return None
    with open(cap_path) as f:
        cap = json.load(f)
    lo, hi = cap["rate_range"]
    for name in ("README.md", "PARITY.md"):
        path = os.path.join(ROOT, name)
        if not os.path.exists(path):
            continue
        tagged = [ln for ln in open(path).read().splitlines()
                  if "bench:headline" in ln]
        if not tagged:
            failures.append(f"{name}: no '<!-- bench:headline -->'-tagged "
                            f"headline quote found")
            continue
        for ln in tagged:
            quoted = re.findall(r"(\d+(?:\.\d+)?)M lookups/s", ln)
            if not quoted:
                failures.append(f"{name}: tagged line quotes no "
                                f"'X.XXM lookups/s' figure: {ln.strip()!r}")
            for q in quoted:
                rate = float(q) * 1e6
                if not (lo * 0.90 <= rate <= hi * 1.10):
                    failures.append(
                        f"{name}: quotes {q}M lookups/s — outside the "
                        f"captured run-to-run range [{lo / 1e6:.2f}M, "
                        f"{hi / 1e6:.2f}M] +/-10% "
                        f"(median {cap['value'] / 1e6:.2f}M)")
            for q in re.findall(r"(\d+(?:\.\d+)?) ?ms/batch", ln):
                if abs(float(q) - cap["ms_per_batch"]) > 0.1 + 0.15 * cap[
                        "ms_per_batch"]:
                    failures.append(
                        f"{name}: quotes {q} ms/batch vs captured "
                        f"{cap['ms_per_batch']:.1f}")
    return cap


def check_config_captures(failures):
    """Each captures/<name>.json must back at least one tagged README
    quote, every tagged quote must sit within its band, and — the
    other direction — every ``<!-- capture:name -->`` tag in the docs
    must have its artifact on disk (a tag whose artifact is missing
    would otherwise be silently unenforced)."""
    checked = []
    readme = os.path.join(ROOT, "README.md")
    docs = {}
    for name in ("README.md", "PARITY.md"):
        path = os.path.join(ROOT, name)
        if os.path.exists(path):
            docs[name] = open(path).read().splitlines()
    for doc, lines in docs.items():
        for ln in lines:
            for tag in re.findall(r"<!-- capture:([\w-]+) -->", ln):
                if not os.path.exists(os.path.join(ROOT, "captures",
                                                   tag + ".json")):
                    failures.append(
                        f"{doc}: tagged quote 'capture:{tag}' has no "
                        f"captures/{tag}.json artifact — the quote is "
                        f"unenforced")
    for cap_path in sorted(glob.glob(os.path.join(ROOT, "captures",
                                                  "*.json"))):
        cname = os.path.splitext(os.path.basename(cap_path))[0]
        if cname.endswith("_custom"):
            continue                      # exploration shape, not quotable
        with open(cap_path) as f:
            cap = json.load(f)
        # full marker, not substring: 'capture:config3' must not match
        # lines tagged capture:config3_star / _tp / _latency
        tag = f"<!-- capture:{cname} -->"
        any_tagged = False
        for doc, lines in docs.items():
            for li, ln in enumerate(lines):
                if tag not in ln:
                    continue
                any_tagged = True
                para = _para_at(lines, li)
                # only the line's FIRST rate figure is the artifact's
                # primary value; later figures on the same line quote
                # secondary fields (e.g. the latency sweep's per-wave
                # rates), each checked by its own field rule below
                for rate, txt in _rate_quotes(ln)[:1]:
                    if not (0.85 * cap["value"] <= rate
                            <= 1.15 * cap["value"]):
                        failures.append(
                            f"{doc}: [{tag}] quotes {txt} vs captured "
                            f"{cap['value']:.1f} {cap.get('unit', '')} "
                            f"(±15%)")
                if "wave_ms_p50" in cap:
                    for q in re.findall(r"p50 (\d+(?:\.\d+)?) ?ms", ln):
                        if not (0.7 * cap["wave_ms_p50"] <= float(q)
                                <= 1.3 * cap["wave_ms_p50"]):
                            failures.append(
                                f"{doc}: [{tag}] quotes p50 {q} ms vs "
                                f"captured {cap['wave_ms_p50']} (±30%)")
                if "mutations_per_s" in cap:
                    for q in re.findall(
                            r"(\d+(?:\.\d+)?)K mutations/s", ln):
                        if not (0.85 * cap["mutations_per_s"]
                                <= float(q) * 1e3
                                <= 1.15 * cap["mutations_per_s"]):
                            failures.append(
                                f"{doc}: [{tag}] quotes {q}K mutations/s "
                                f"vs captured {cap['mutations_per_s']:.0f} "
                                f"(±15%)")
                bound = cap.get("bound", {})
                # round-10 maintenance attribution: the amortization
                # factor and the per-stage ms figures quoted in the
                # docs must track the committed capture
                if "republish_amortization_x" in bound:
                    for q in re.findall(r"(\d+(?:\.\d+)?)× amortization",
                                        para):
                        w = bound["republish_amortization_x"]
                        if not (0.85 * w <= float(q) <= 1.15 * w):
                            failures.append(
                                f"{doc}: [{tag}] quotes {q}x amortization "
                                f"vs captured {w} (±15%)")
                    for pat, field in (
                            (r"republish resolve (?:at )?(\d+(?:\.\d+)?) ms",
                             "republish_batched_ms"),
                            (r"(\d+(?:\.\d+)?) ms(?:/key| per batch-1)",
                             "republish_per_key_ms_each"),
                            (r"fused sweep (?:at )?(\d+(?:\.\d+)?) ms",
                             "sweep_fused_ms"),
                            (r"(\d+(?:\.\d+)?) ms split",
                             "sweep_split_ms")):
                        for q in re.findall(pat, para):
                            w = bound[field]
                            if not (0.85 * w <= float(q) <= 1.15 * w):
                                failures.append(
                                    f"{doc}: [{tag}] quotes {q} ms vs "
                                    f"captured {field}={w} (±15%)")
                # round-12 ingest attribution: the per-op amortization
                # factor and both per-op µs figures quoted in the docs
                # must track captures/ingest_wave.json
                if "ingest_amortization_x" in bound:
                    for q in re.findall(
                            r"(\d+(?:\.\d+)?)× per-op amortization", para):
                        w = bound["ingest_amortization_x"]
                        if not (0.85 * w <= float(q) <= 1.15 * w):
                            failures.append(
                                f"{doc}: [{tag}] quotes {q}x per-op "
                                f"amortization vs captured {w} (±15%)")
                    for pat, field in (
                            (r"(\d+(?:\.\d+)?) ?µs/op per-op",
                             "per_op_us"),
                            (r"(\d+(?:\.\d+)?) ?µs/op coalesced",
                             "coalesced_us_per_op")):
                        for q in re.findall(pat, para):
                            w = bound[field]
                            if not (0.85 * w <= float(q) <= 1.15 * w):
                                failures.append(
                                    f"{doc}: [{tag}] quotes {q} µs/op vs "
                                    f"captured {field}={w} (±15%)")
                if cap.get("unit") == "percent":
                    def _pct_band(quoted, captured, what):
                        tol = max(1.0, 0.5 * abs(captured))
                        if abs(quoted - captured) > tol:
                            failures.append(
                                f"{doc}: [{tag}] quotes {what} "
                                f"{quoted}% vs captured {captured} "
                                f"(±{tol:.1f}pp)")
                    for q in re.findall(r"measures (\d+(?:\.\d+)?)%", ln):
                        _pct_band(float(q), cap["value"], "overhead")
                    if "sampling_off_pct" in cap:
                        for q in re.findall(
                                r"(-?\d+(?:\.\d+)?)% with sampling off",
                                ln):
                            _pct_band(float(q), cap["sampling_off_pct"],
                                      "sampling-off overhead")
        if not any_tagged and os.path.exists(readme) \
                and cname not in _OPTIONAL:
            failures.append(f"README.md: no '{tag}'-tagged quote "
                            f"for committed capture {cname}.json")
        checked.append(cname)
    return checked


def check_tp_wire(failures):
    """Round-13 rule, BOTH directions: README and PARITY must each
    carry a ``<!-- tp:wire -->``-tagged paragraph quoting the
    t-sharded engine's in-loop collective budget — the per-hop
    bytes/query figure ('NNN B per query per hop') and the in-loop
    site count ('N in-loop collective') — and every quoted figure must
    EQUAL the committed TP_SCALING.json (the values are read off the
    compiled HLO, deterministic, so the band is exact).  A regenerated
    artifact with stale quotes fails; a quote with no artifact backing
    fails via the missing-tag branch."""
    tp_path = os.path.join(ROOT, "TP_SCALING.json")
    if not os.path.exists(tp_path):
        failures.append("TP_SCALING.json missing — regenerate with "
                        "python benchmarks/tp_scaling.py")
        return
    with open(tp_path) as f:
        rows = json.load(f).get("rows") or []
    if not rows:
        failures.append("TP_SCALING.json has no rows")
        return
    want_bytes = rows[0]["bytes_per_local_query_per_hop"]
    want_sites = rows[0]["collective_sites_in_loop"]
    for name in ("README.md", "PARITY.md"):
        path = os.path.join(ROOT, name)
        if not os.path.exists(path):
            continue
        lines = open(path).read().splitlines()
        tagged = [i for i, ln in enumerate(lines) if "<!-- tp:wire -->" in ln]
        if not tagged:
            failures.append(f"{name}: no '<!-- tp:wire -->'-tagged "
                            f"paragraph quoting the t-sharded collective "
                            f"budget (TP_SCALING.json)")
            continue
        for li in tagged:
            para = _para_at(lines, li)
            quoted_b = [float(v) for v in re.findall(
                r"(\d+(?:\.\d+)?) ?B(?:ytes)? per query per hop", para)]
            quoted_s = [int(v) for v in re.findall(
                r"(\d+) in-loop collective", para)]
            if not quoted_b:
                failures.append(f"{name}: [tp:wire] paragraph quotes no "
                                f"'NNN B per query per hop' figure")
            for qb in quoted_b:
                if qb != float(want_bytes):
                    failures.append(
                        f"{name}: [tp:wire] quotes {qb:g} B per query per "
                        f"hop vs TP_SCALING.json {want_bytes} (exact match "
                        f"required — the value is read off the HLO)")
            if not quoted_s:
                failures.append(f"{name}: [tp:wire] paragraph quotes no "
                                f"'N in-loop collective' count")
            for qs in quoted_s:
                if qs != int(want_sites):
                    failures.append(
                        f"{name}: [tp:wire] quotes {qs} in-loop "
                        f"collective(s) vs TP_SCALING.json {want_sites}")


#: overhead-acceptance artifacts (the round-14 health rule, extended
#: round 15 to the keyspace observatory and round 16 to the hot-cache
#: probe): each capture must beat its own recorded acceptance bound,
#: and both docs must state the bound
_OVERHEAD_CAPS = ("health_overhead", "keyspace_overhead",
                  "cache_overhead", "history_overhead",
                  "waterfall_overhead", "pipeutil_overhead",
                  "peers_overhead", "listener_overhead")


def check_overhead_captures(failures):
    """Rounds 14/15 rule, BOTH directions and for EVERY overhead
    artifact in :data:`_OVERHEAD_CAPS`: the measured on-cost
    acceptance (<1% on the 8192-wave round) is quote-enforced against
    ``captures/<name>.json`` — (1) the artifact itself must satisfy
    the acceptance bound it records (``value`` < ``acceptance_pct``: a
    regression that pushes the instrumented path past its budget fails
    CI here even before the docs drift), and (2) README *and* PARITY
    must each carry a ``<!-- capture:<name> -->``-tagged paragraph
    stating the ``<{acceptance}%`` bound next to the measured quote
    (the generic percent rule in check_config_captures checks the
    measured value; this rule checks the *claim* survives in both
    docs)."""
    for cname in _OVERHEAD_CAPS:
        cap_path = os.path.join(ROOT, "captures", cname + ".json")
        if not os.path.exists(cap_path):
            continue
        with open(cap_path) as f:
            cap = json.load(f)
        acc = float(cap.get("acceptance_pct", 1.0))
        if cap["value"] >= acc:
            failures.append(
                f"captures/{cname}.json: measured overhead "
                f"{cap['value']}% breaks its own <{acc:g}% acceptance "
                f"bound — the instrumented path got expensive")
        tag = f"<!-- capture:{cname} -->"
        for name in ("README.md", "PARITY.md"):
            path = os.path.join(ROOT, name)
            if not os.path.exists(path):
                continue
            lines = open(path).read().splitlines()
            tagged = [i for i, ln in enumerate(lines) if tag in ln]
            if not tagged:
                failures.append(f"{name}: no '{tag}'-tagged paragraph "
                                f"quoting the {cname} measurement")
                continue
            for li in tagged:
                para = _para_at(lines, li)
                quoted = re.findall(r"<(\d+(?:\.\d+)?)% acceptance", para)
                if not quoted:
                    failures.append(
                        f"{name}: [capture:{cname}] paragraph "
                        f"states no '<N% acceptance' bound")
                for q in quoted:
                    if float(q) != acc:
                        failures.append(
                            f"{name}: [capture:{cname}] states a "
                            f"<{q}% acceptance vs the artifact's "
                            f"acceptance_pct={acc:g}")


def check_swarm_storm(failures):
    """Round-18 rule, BOTH directions: the committed swarm-storm
    acceptance artifact (``captures/swarm_storm.json``) must itself
    satisfy the ISSUE-13 acceptance — a >=50k-node swarm with both
    invariants restored (>=0.95) after healing — and README *and*
    PARITY must each carry a ``<!-- capture:swarm_storm -->``-tagged
    paragraph quoting the node count and the mid-cut coverage
    collapse; a tagged claim without the artifact (or vice versa)
    fails."""
    cap_path = os.path.join(ROOT, "captures", "swarm_storm.json")
    cap = None
    if os.path.exists(cap_path):
        with open(cap_path) as f:
            cap = json.load(f)
        if cap.get("n_nodes", 0) < 50_000:
            failures.append(
                "captures/swarm_storm.json: n_nodes=%r is under the "
                "50000-node acceptance floor" % cap.get("n_nodes"))
        for inv in ("final_lookup_success", "final_replica_coverage"):
            if cap.get(inv, 0.0) < 0.95:
                failures.append(
                    f"captures/swarm_storm.json: {inv}={cap.get(inv)} — "
                    f"invariants not restored after healing")
    tag = "<!-- capture:swarm_storm -->"
    for name in ("README.md", "PARITY.md"):
        path = os.path.join(ROOT, name)
        if not os.path.exists(path):
            continue
        lines = open(path).read().splitlines()
        tagged = [i for i, ln in enumerate(lines) if tag in ln]
        if cap is None:
            if tagged:
                failures.append(f"{name}: '{tag}' claim with no "
                                f"captures/swarm_storm.json artifact")
            continue
        if not tagged:
            failures.append(f"{name}: no '{tag}'-tagged paragraph "
                            f"quoting the swarm-storm acceptance run")
            continue
        want_nodes = "%d-node" % cap.get("n_nodes", 0)
        want_cov = "%.2f" % cap.get("min_coverage_during_cut", -1.0)
        for li in tagged:
            para = _para_at(lines, li)
            if want_nodes not in para:
                failures.append(
                    f"{name}: [capture:swarm_storm] paragraph does not "
                    f"quote the {want_nodes} scale")
            if want_cov not in para:
                failures.append(
                    f"{name}: [capture:swarm_storm] paragraph does not "
                    f"quote the {want_cov} mid-cut coverage collapse")


def check_pipeline_overlap(failures):
    """Round-20 rule, BOTH directions: the committed wave-pipeline
    acceptance artifact (``captures/pipeline_overlap.json``) must
    itself record the two non-negotiables — depth-2 bit-identical to
    depth-1 and >=2 waves held in flight — and README *and* PARITY
    must each carry a ``<!-- capture:pipeline_overlap -->``-tagged
    paragraph quoting the measured overlap figure and the in-flight
    peak; a tagged claim without the artifact (or vice versa) fails."""
    cap_path = os.path.join(ROOT, "captures", "pipeline_overlap.json")
    cap = None
    if os.path.exists(cap_path):
        with open(cap_path) as f:
            cap = json.load(f)
        bound = cap.get("bound", {})
        if not bound.get("bit_identical"):
            failures.append(
                "captures/pipeline_overlap.json: bit_identical is not "
                "true — the pipeline's results diverged from depth 1")
        if bound.get("inflight_peak", 0) < 2:
            failures.append(
                "captures/pipeline_overlap.json: inflight_peak=%r — the "
                "double-buffer never held 2 waves in flight"
                % bound.get("inflight_peak"))
    tag = "<!-- capture:pipeline_overlap -->"
    for name in ("README.md", "PARITY.md"):
        path = os.path.join(ROOT, name)
        if not os.path.exists(path):
            continue
        lines = open(path).read().splitlines()
        tagged = [i for i, ln in enumerate(lines) if tag in ln]
        if cap is None:
            if tagged:
                failures.append(f"{name}: '{tag}' claim with no "
                                f"captures/pipeline_overlap.json artifact")
            continue
        if not tagged:
            failures.append(f"{name}: no '{tag}'-tagged paragraph "
                            f"quoting the wave-pipeline measurement")
            continue
        want_val = "%.1f%%" % cap.get("value", 0.0)
        want_peak = "%d waves in flight" % cap.get(
            "bound", {}).get("inflight_peak", 0)
        dev1 = cap.get("stages_depth1", {}).get("device_launch", {})
        dev2 = cap.get("stages_depth2", {}).get("device_launch", {})
        for li in tagged:
            para = _para_at(lines, li)
            if want_val not in para:
                failures.append(
                    f"{name}: [capture:pipeline_overlap] paragraph does "
                    f"not quote the measured {want_val} overlap delta")
            if want_peak not in para:
                failures.append(
                    f"{name}: [capture:pipeline_overlap] paragraph does "
                    f"not quote the '{want_peak}' pipeline peak")
            # the stage-histogram evidence: the quoted device-stage
            # shrink must track the artifact's dht_stage_seconds deltas
            if dev1 and dev2:
                quoted = re.findall(
                    r"device stage mean (\d+(?:\.\d+)?) → "
                    r"(\d+(?:\.\d+)?) ms", para)
                if not quoted:
                    failures.append(
                        f"{name}: [capture:pipeline_overlap] paragraph "
                        f"does not quote the 'device stage mean A → B "
                        f"ms' histogram shrink")
                for q1, q2 in quoted:
                    for q, w, which in ((q1, dev1["mean_ms"], "depth-1"),
                                        (q2, dev2["mean_ms"], "depth-2")):
                        if not (0.85 * w <= float(q) <= 1.15 * w):
                            failures.append(
                                f"{name}: [capture:pipeline_overlap] "
                                f"quotes {q} ms vs the artifact's "
                                f"{which} device-stage mean {w} (±15%)")


def check_reshard_balance(failures):
    """Round-21 rule, BOTH directions: the committed load-aware
    resharding artifact (``captures/reshard_balance.json``) must
    itself record the acceptance — the Zipf(1.1) flood at t=4 reads
    >2.0 imbalanced on the uniform split and <1.3 at the solved
    traffic-weighted edges, with lookups bit-identical including a
    wave in flight across the swap — and README *and* PARITY must
    each carry a ``<!-- capture:reshard_balance -->``-tagged
    paragraph quoting the measured before/after figures; a tagged
    claim without the artifact (or vice versa) fails."""
    cap_path = os.path.join(ROOT, "captures", "reshard_balance.json")
    cap = None
    if os.path.exists(cap_path):
        with open(cap_path) as f:
            cap = json.load(f)
        t4 = cap.get("t4", {})
        if not t4.get("imbalance_before", 0.0) > 2.0:
            failures.append(
                "captures/reshard_balance.json: t4 imbalance_before=%r "
                "— the Zipf flood did not skew the uniform split past "
                "2.0, so the capture proves nothing"
                % t4.get("imbalance_before"))
        if not t4.get("imbalance_after", 99.0) < 1.3:
            failures.append(
                "captures/reshard_balance.json: t4 imbalance_after=%r "
                "— the solved boundaries left the load imbalanced"
                % t4.get("imbalance_after"))
        for tk in ("t2", "t4"):
            sec = cap.get(tk, {})
            if not sec.get("bit_identical"):
                failures.append(
                    "captures/reshard_balance.json: %s bit_identical is "
                    "not true — the weighted layout diverged from the "
                    "single-device engine" % tk)
            if not sec.get("inflight_identical"):
                failures.append(
                    "captures/reshard_balance.json: %s "
                    "inflight_identical is not true — a wave launched "
                    "before the swap was remapped" % tk)
    tag = "<!-- capture:reshard_balance -->"
    for name in ("README.md", "PARITY.md"):
        path = os.path.join(ROOT, name)
        if not os.path.exists(path):
            continue
        lines = open(path).read().splitlines()
        tagged = [i for i, ln in enumerate(lines) if tag in ln]
        if cap is None:
            if tagged:
                failures.append(f"{name}: '{tag}' claim with no "
                                f"captures/reshard_balance.json artifact")
            continue
        if not tagged:
            failures.append(f"{name}: no '{tag}'-tagged paragraph "
                            f"quoting the resharding measurement")
            continue
        t4 = cap.get("t4", {})
        want_before = "%.2f" % t4.get("imbalance_before", -1.0)
        want_after = "%.2f" % t4.get("imbalance_after", -1.0)
        for li in tagged:
            para = _para_at(lines, li)
            if want_before not in para:
                failures.append(
                    f"{name}: [capture:reshard_balance] paragraph does "
                    f"not quote the measured {want_before} pre-swap "
                    f"imbalance")
            if want_after not in para:
                failures.append(
                    f"{name}: [capture:reshard_balance] paragraph does "
                    f"not quote the measured {want_after} post-swap "
                    f"imbalance")


def check_pipeline_util(failures):
    """Round-22 rule, BOTH directions: the committed observatory
    overhead artifact (``captures/pipeutil_overhead.json``) must
    itself record the tentpole invariant — a CLOSED ledger
    (``accounting_closed``: Σ(busy) + Σ(bubbles) == observed window
    on the timed trips) with at least one wave tracked per rep — and
    README *and* PARITY must each carry a
    ``<!-- capture:pipeutil_overhead -->``-tagged paragraph stating
    that closed-accounting claim next to the measured quote (the
    ``<1%`` bound itself rides the generic :func:`check_overhead_captures`
    rule); a tagged claim without the artifact (or vice versa)
    fails."""
    cap_path = os.path.join(ROOT, "captures", "pipeutil_overhead.json")
    cap = None
    if os.path.exists(cap_path):
        with open(cap_path) as f:
            cap = json.load(f)
        if not cap.get("accounting_closed"):
            failures.append(
                "captures/pipeutil_overhead.json: accounting_closed is "
                "not true — the timed trips left an unclosed ledger "
                "(Σ(busy) + Σ(bubbles) != observed window)")
        if cap.get("waves_observed", 0) < cap.get("reps", 1):
            failures.append(
                "captures/pipeutil_overhead.json: waves_observed=%r "
                "under reps=%r — the timed trips were not all tracked"
                % (cap.get("waves_observed"), cap.get("reps")))
    tag = "<!-- capture:pipeutil_overhead -->"
    for name in ("README.md", "PARITY.md"):
        path = os.path.join(ROOT, name)
        if not os.path.exists(path):
            continue
        lines = open(path).read().splitlines()
        tagged = [i for i, ln in enumerate(lines) if tag in ln]
        if cap is None:
            if tagged:
                failures.append(f"{name}: '{tag}' claim with no "
                                f"captures/pipeutil_overhead.json "
                                f"artifact")
            continue
        if not tagged:
            failures.append(f"{name}: no '{tag}'-tagged paragraph "
                            f"quoting the observatory overhead "
                            f"measurement")
            continue
        for li in tagged:
            para = _para_at(lines, li)
            if "Σ(busy)" not in para or "Σ(bubbles)" not in para:
                failures.append(
                    f"{name}: [capture:pipeutil_overhead] paragraph "
                    f"does not state the closed-ledger claim "
                    f"(Σ(busy) + Σ(bubbles) == observed window)")


def check_peer_ledger(failures):
    """Round-23 rule, BOTH directions: the committed per-peer ledger
    overhead artifact (``captures/peers_overhead.json``) must itself
    record a real lifecycle load (at least one full request lifecycle
    per tracked peer per wave — an empty event stream would make the
    <1% quote vacuous), and README *and* PARITY must each carry a
    ``<!-- capture:peers_overhead -->``-tagged paragraph stating the
    pure-observation claim (wave outputs pinned **bit-identical** with
    the ledger on) next to the measured quote (the ``<1%`` bound
    itself rides the generic :func:`check_overhead_captures` rule); a
    tagged claim without the artifact (or vice versa) fails."""
    cap_path = os.path.join(ROOT, "captures", "peers_overhead.json")
    cap = None
    if os.path.exists(cap_path):
        with open(cap_path) as f:
            cap = json.load(f)
        if cap.get("lifecycles_per_wave", 0) < cap.get("peers", 1):
            failures.append(
                "captures/peers_overhead.json: lifecycles_per_wave=%r "
                "under peers=%r — the timed trips did not drive a full "
                "lifecycle per tracked peer, the overhead quote is "
                "vacuous" % (cap.get("lifecycles_per_wave"),
                             cap.get("peers")))
    tag = "<!-- capture:peers_overhead -->"
    for name in ("README.md", "PARITY.md"):
        path = os.path.join(ROOT, name)
        if not os.path.exists(path):
            continue
        lines = open(path).read().splitlines()
        tagged = [i for i, ln in enumerate(lines) if tag in ln]
        if cap is None:
            if tagged:
                failures.append(f"{name}: '{tag}' claim with no "
                                f"captures/peers_overhead.json "
                                f"artifact")
            continue
        if not tagged:
            failures.append(f"{name}: no '{tag}'-tagged paragraph "
                            f"quoting the per-peer ledger overhead "
                            f"measurement")
            continue
        for li in tagged:
            para = _para_at(lines, li)
            if "bit-identical" not in para:
                failures.append(
                    f"{name}: [capture:peers_overhead] paragraph does "
                    f"not state the pure-observation claim (wave "
                    f"outputs bit-identical with the ledger on)")


def check_listener_match(failures):
    """Round-24 rule, BOTH directions: the committed listener
    amortization artifact (``captures/listener_match.json``) must
    itself satisfy the ISSUE-20 acceptance — the batched per-listener
    delivery slope below the host per-put dispatch slope, measured out
    to L=100k listeners — and README *and* PARITY must each carry a
    ``<!-- capture:listener_match -->``-tagged paragraph stating the
    result-equivalence claim (batched deliveries **result-equivalent**
    to the synchronous path) next to a quoted slope ratio that matches
    the artifact (±15%); a tagged claim without the artifact (or vice
    versa) fails."""
    cap_path = os.path.join(ROOT, "captures", "listener_match.json")
    cap = None
    if os.path.exists(cap_path):
        with open(cap_path) as f:
            cap = json.load(f)
        host = float(cap.get("host_slope_ns_per_listener", 0.0))
        bat = float(cap.get("batched_slope_ns_per_listener", 0.0))
        if not bat < host:
            failures.append(
                "captures/listener_match.json: batched slope %r "
                "ns/listener not below the host slope %r — the "
                "amortization claim fails in the artifact itself"
                % (bat, host))
        if max((r.get("L", 0) for r in cap.get("rows", [])),
               default=0) < 100_000:
            failures.append(
                "captures/listener_match.json: rows stop short of the "
                "L=100000 acceptance point")
    tag = "<!-- capture:listener_match -->"
    for name in ("README.md", "PARITY.md"):
        path = os.path.join(ROOT, name)
        if not os.path.exists(path):
            continue
        lines = open(path).read().splitlines()
        tagged = [i for i, ln in enumerate(lines) if tag in ln]
        if cap is None:
            if tagged:
                failures.append(f"{name}: '{tag}' claim with no "
                                f"captures/listener_match.json artifact")
            continue
        if not tagged:
            failures.append(f"{name}: no '{tag}'-tagged paragraph "
                            f"quoting the listener amortization "
                            f"measurement")
            continue
        ratio = float(cap.get("slope_ratio", 0.0))
        for li in tagged:
            para = _para_at(lines, li)
            if "result-equivalent" not in para:
                failures.append(
                    f"{name}: [capture:listener_match] paragraph does "
                    f"not state the result-equivalence claim (batched "
                    f"deliveries result-equivalent to the synchronous "
                    f"path)")
            quoted = [float(q) for q in
                      re.findall(r"(\d+(?:\.\d+)?)[×x]\b", para)]
            if not any(0.85 * ratio <= q <= 1.15 * ratio
                       for q in quoted):
                failures.append(
                    f"{name}: [capture:listener_match] paragraph "
                    f"quotes no slope ratio matching the artifact's "
                    f"{ratio:g}x (±15%): {quoted!r}")


#: the observability index (ISSUE-10 satellite): every serving surface
#: and the reference counterpart(s) it maps to.  BOTH directions: each
#: surface must appear as a row of the tagged table in README AND
#: PARITY, and every row of that table must name a surface registered
#: here — adding a surface without registering it fails CI.
OBS_SURFACES = ("GET /stats", "GET /trace", "GET /healthz",
                "GET /keyspace", "GET /cache", "GET /history",
                "GET /debug/bundle", "GET /profile", "GET /pipeline",
                "GET /peers", "GET /listeners", "kernel ledger",
                "dhtscanner --json")
OBS_REFERENCES = ("getNodesStats", "dumpTables", "STATS /",
                  "DhtRunner::loop_")


def check_observability_index(failures):
    """The ``<!-- obs:index -->``-tagged table in README and PARITY
    must list every surface in :data:`OBS_SURFACES` with at least one
    reference counterpart from :data:`OBS_REFERENCES` on its row, and
    must contain no row naming an unregistered surface (so a new
    surface forces this rule — and hence the mapping — to be
    updated)."""
    for name in ("README.md", "PARITY.md"):
        path = os.path.join(ROOT, name)
        if not os.path.exists(path):
            continue
        lines = open(path).read().splitlines()
        tagged = [i for i, ln in enumerate(lines)
                  if "<!-- obs:index -->" in ln]
        if not tagged:
            failures.append(f"{name}: no '<!-- obs:index -->'-tagged "
                            f"observability-index table mapping the "
                            f"serving surfaces to the reference")
            continue
        # every tagged table is validated (a stale second copy must
        # not escape the unregistered-row direction); the
        # missing-surface direction checks the union across tables
        seen = []
        for ti in tagged:
            # the table: contiguous '|' rows following the tag line
            rows = []
            li = ti + 1
            while li < len(lines) and lines[li].lstrip().startswith("|"):
                cells = [c.strip() for c in lines[li].strip().strip("|")
                         .split("|")]
                if cells and not set(cells[0]) <= set("-: "):
                    rows.append((cells[0], lines[li]))
                li += 1
            body = [r for r in rows[1:]]          # drop the header row
            if not body:
                failures.append(f"{name}: [obs:index] tag has no table "
                                f"rows under it")
                continue
            for surface, raw in body:
                # exact match after stripping markdown formatting — a
                # substring test would let 'GET /keyspace/top' ride the
                # 'GET /keyspace' registration unflagged, defeating the
                # adding-a-surface-forces-this-rule direction (review
                # finding)
                canon = surface.replace("`", "").replace("*", "").strip()
                matched = next((s for s in OBS_SURFACES
                                if canon.lower() == s.lower()), None)
                if matched is None:
                    failures.append(
                        f"{name}: [obs:index] row names unregistered "
                        f"surface {surface!r} — register it in "
                        f"ci/check_docs.py OBS_SURFACES")
                    continue
                seen.append(matched)
                if not any(ref in raw for ref in OBS_REFERENCES):
                    failures.append(
                        f"{name}: [obs:index] row for {matched!r} names "
                        f"no reference counterpart "
                        f"({', '.join(OBS_REFERENCES)})")
        for s in OBS_SURFACES:
            if s not in seen:
                failures.append(
                    f"{name}: [obs:index] table is missing the "
                    f"{s!r} surface")


def check_trajectory(failures):
    """The BENCH trajectory, enforced BOTH directions (ISSUE-6
    satellite): the committed PERF_TRAJECTORY.json must equal a fresh
    assembly of its sources (BENCH_r*.json / captures / TP_SCALING.json
    — ci/assemble_trajectory.py build()), and README's
    ``<!-- trajectory -->``-tagged table must quote every round's
    vs-baseline figure within 2% — a new BENCH round can't stay
    invisible, and a README claim can't outlive its artifact."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "assemble_trajectory",
        os.path.join(ROOT, "ci", "assemble_trajectory.py"))
    asm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(asm)
    traj_path = os.path.join(ROOT, "PERF_TRAJECTORY.json")
    msg = asm.drift()
    if msg:
        failures.append(msg)
        if not os.path.exists(traj_path):
            return
    with open(traj_path) as f:
        committed = json.load(f)
    readme = os.path.join(ROOT, "README.md")
    if not os.path.exists(readme):
        return
    lines = open(readme).read().splitlines()
    tagged = [i for i, ln in enumerate(lines) if "<!-- trajectory -->" in ln]
    if not tagged:
        failures.append("README.md: no '<!-- trajectory -->'-tagged table "
                        "quoting PERF_TRAJECTORY.json")
        return
    quoted = []
    for li in tagged:
        quoted += [float(v) for v in
                   re.findall(r"(\d+(?:\.\d+)?)[x×]", _para_at(lines, li))]
    for r in committed.get("rounds", []):
        v = r.get("vs_baseline")
        if not v:
            continue
        if not any(abs(q - v) <= 0.02 * v + 0.5 for q in quoted):
            failures.append(
                f"README.md: trajectory table quotes no "
                f"{v}x-vs-baseline figure for round {r['round']} "
                f"({r['source']})")


def main() -> int:
    failures = []
    cap = check_headline(failures)
    checked = check_config_captures(failures)
    check_tp_wire(failures)
    check_overhead_captures(failures)
    check_swarm_storm(failures)
    check_pipeline_overlap(failures)
    check_reshard_balance(failures)
    check_pipeline_util(failures)
    check_peer_ledger(failures)
    check_listener_match(failures)
    check_observability_index(failures)
    check_trajectory(failures)
    if failures:
        print("DOCS DRIFT from capture artifacts:")
        for fmsg in failures:
            print(" -", fmsg)
        return 1
    msg = []
    if cap is not None:
        msg.append(f"{cap['value'] / 1e6:.2f}M lookups/s, "
                   f"{cap['ms_per_batch']:.1f} ms/batch")
    if checked:
        msg.append("configs: " + ", ".join(checked))
    print("docs agree with capture%s: %s"
          % ("s" if checked else "", "; ".join(msg) or "none present"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
