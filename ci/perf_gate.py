"""CI perf-regression gate over the kernel cost ledger (ROADMAP item 3).

Five rounds of kernel perf (920× → 46× → 121× → 131× → 213×,
PERF_TRAJECTORY.json) previously had no gate: a refactor could double a
kernel's HBM traffic and every tier-1 test would stay green.  This gate
closes that hole with the only perf signal that is DETERMINISTIC on a
shared CPU runner — the XLA cost model of each shipped kernel lowered
at its canonical shape (opendht_tpu/profiling.py KERNEL_SPECS):

- **Hard gate** (exit 1): per-kernel ``flops`` / ``bytes_accessed`` /
  ``argument_bytes`` / ``output_bytes`` vs the committed
  ``perf_budgets.json``, inside a per-field relative tolerance that
  absorbs XLA version drift (the cost model's constants move a few
  percent across releases; a real regression moves 2×).  A canonical
  SHAPE change is also hard — a silently moved shape would re-base the
  budget without review (run ``--update`` deliberately instead).
- **Soft warnings** (never fail): ``temp_bytes`` (XLA scheduling
  dependent — buffer assignment legitimately reshuffles across
  versions) and the wall-clock ``timing_soft`` ceilings checked against
  the smoke records the CI drivers drop in
  ``$OPENDHT_TPU_SMOKE_RECORD_DIR`` (benchmarks/driver_common.py) —
  shared runners flake, so timing informs, cost gates.
- **Open accelerator bounds**: the three OPEN on-chip numbers
  (≤8 ms 1024-wave p50, churny/static ≥0.6×, the config-4 maintenance
  sweep) ride along as ``open: true`` entries with their committed
  settling commands — the next accelerator session flips them to
  enforced values here instead of re-plumbing a gate.

Usage::

    python ci/perf_gate.py              # gate against perf_budgets.json
    python ci/perf_gate.py --update     # re-base budgets from live lowering
    python ci/perf_gate.py --records /tmp/odt-smoke   # + timing soft-warn
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

BUDGETS = os.path.join(ROOT, "perf_budgets.json")

#: default relative tolerance per hard-gated field.  flops/bytes move a
#: few percent with XLA version drift (constant folding, fusion
#: decisions); argument/output bytes are pure shape math and barely
#: move.  A regression of interest (2×-class) clears every band.
DEFAULT_TOL = {
    "flops": 0.25,
    "bytes_accessed": 0.25,
    "argument_bytes": 0.05,
    "output_bytes": 0.05,
}
SOFT_TOL = {"temp_bytes": 0.60}


def _load_budgets(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _check_field(failures, warnings, name, field, budget, observed, tol,
                 soft=False):
    if budget == 0 and observed == 0:
        return
    lo, hi = budget * (1 - tol), budget * (1 + tol)
    if lo <= observed <= hi:
        return
    ratio = observed / budget if budget else float("inf")
    msg = (f"{name}.{field}: observed {observed:.6g} vs budget "
           f"{budget:.6g} ({ratio:.2f}x, tolerance ±{tol:.0%})")
    (warnings if soft else failures).append(msg)


def check_costs(budgets: dict, ledger: dict, failures: list,
                warnings: list) -> None:
    tol = dict(DEFAULT_TOL, **budgets.get("tolerance", {}))
    stol = dict(SOFT_TOL, **budgets.get("soft_tolerance", {}))
    for name, b in sorted(budgets.get("kernels", {}).items()):
        e = ledger.get(name)
        if e is None:
            failures.append(f"{name}: budgeted kernel missing from the "
                            f"ledger (KERNEL_SPECS) — removing a shipped "
                            f"kernel needs a deliberate --update")
            continue
        if "error" in e:
            failures.append(f"{name}: ledger failed to lower: {e['error']}")
            continue
        if e.get("shape") != b.get("shape"):
            failures.append(
                f"{name}: canonical shape drifted — budget {b.get('shape')}"
                f" vs ledger {e.get('shape')}; re-base with --update if "
                f"intentional")
            continue
        for field, t in tol.items():
            _check_field(failures, warnings, name, field,
                         float(b.get(field, 0.0)), float(e.get(field, 0.0)),
                         t)
        for field, t in stol.items():
            _check_field(failures, warnings, name, field,
                         float(b.get(field, 0.0)), float(e.get(field, 0.0)),
                         t, soft=True)
    for name in sorted(ledger):
        if name not in budgets.get("kernels", {}) \
                and "error" not in ledger[name]:
            failures.append(f"{name}: shipped kernel has no budget entry — "
                            f"run ci/perf_gate.py --update and commit "
                            f"perf_budgets.json")


def check_timing(budgets: dict, records_dir: str, warnings: list) -> None:
    """Wall-clock ceilings from the CI smoke records — soft by design:
    shared CPU runners stall unpredictably, so a breach WARNS with the
    number while the deterministic cost gate above decides pass/fail."""
    if not records_dir or not os.path.isdir(records_dir):
        return
    recs = {}
    for p in glob.glob(os.path.join(records_dir, "*.json")):
        try:
            with open(p) as f:
                recs[os.path.splitext(os.path.basename(p))[0]] = json.load(f)
        except Exception:
            continue
    for key, spec in sorted(budgets.get("timing_soft", {}).items()):
        rec = recs.get(spec["record"])
        if rec is None:
            # a supplied records dir missing a budgeted record means a
            # driver stopped emitting (or was renamed) — say so, or the
            # ceiling silently becomes dead config
            warnings.append(
                f"timing[{key}]: no {spec['record']}.json in "
                f"{records_dir} — the ceiling was not checked (driver "
                f"renamed or not run?)")
            continue
        # stage records accumulate under "stages" (driver_common.emit);
        # a budgeted field may live top-level or in any stage record
        val = rec.get(spec["field"])
        if val is None:
            for srec in rec.get("stages", {}).values():
                val = srec.get(spec["field"])
                if val is not None:
                    break
        if val is None:
            warnings.append(
                f"timing[{key}]: {spec['record']}.json carries no "
                f"{spec['field']!r} field — the ceiling was not checked "
                f"(field renamed?)")
            continue
        if float(val) > float(spec["max"]):
            warnings.append(
                f"timing[{key}]: {spec['record']}.{spec['field']} = "
                f"{val} exceeds the soft ceiling {spec['max']} "
                f"{spec.get('unit', '')} — wall-clock only, not failing "
                f"({spec.get('note', '')})".rstrip())


def print_open_bounds(budgets: dict) -> None:
    ob = budgets.get("open_bounds", {})
    if not ob:
        return
    print("perf_gate: %d OPEN accelerator bound(s) awaiting settlement "
          "(not gated until an accelerator run commits them):" % len(ob))
    for key, b in sorted(ob.items()):
        print(f"  - {key}: target {b['target']} on "
              f"{b['metric']}\n    settle: {b['settle']}")


def compute_ledger(kernels=None) -> dict:
    import jax
    jax.config.update("jax_platforms", "cpu")   # deterministic CI platform
    if jax.default_backend() != "cpu":
        # config updates are a no-op once a backend is initialized: an
        # in-process caller that already touched an accelerator would
        # lower there and fail every cpu budget with confusing
        # tolerance diffs — fail loudly with the fix instead
        raise SystemExit(
            "perf_gate: jax backend is %r but perf_budgets.json is "
            "cpu-lowered — run in a fresh process with JAX_PLATFORMS=cpu"
            % jax.default_backend())
    from opendht_tpu import profiling
    return profiling.get_ledger().compute(kernels)


def update_budgets(path: str, ledger: dict, merge: bool = False) -> None:
    """Re-base the budget file from the live ledger, preserving the
    curated sections (tolerances, open bounds, timing ceilings).
    ``merge=True`` (a ``--kernels`` subset re-base) updates only the
    named entries and keeps every other committed budget — a subset
    must never silently delete the rest of the file."""
    old = _load_budgets(path) if os.path.exists(path) else {}
    kernels = dict(old.get("kernels", {})) if merge else {}
    for name, e in sorted(ledger.items()):
        if "error" in e:
            raise SystemExit(f"--update refused: {name} failed to lower "
                             f"({e['error']})")
        kernels[name] = {
            "shape": e["shape"],
            "flops": e["flops"],
            "bytes_accessed": e["bytes_accessed"],
            "argument_bytes": e["argument_bytes"],
            "output_bytes": e["output_bytes"],
            "temp_bytes": e["temp_bytes"],
        }
    out = {
        "_note": ("XLA cost-model budgets per kernel per canonical shape "
                  "(opendht_tpu/profiling.py KERNEL_SPECS), lowered on "
                  "cpu.  Gated by ci/perf_gate.py in ci/run_ci.sh; "
                  "re-base deliberately with ci/perf_gate.py --update."),
        "platform": "cpu",
        "tolerance": old.get("tolerance", DEFAULT_TOL),
        "soft_tolerance": old.get("soft_tolerance", SOFT_TOL),
        "kernels": kernels,
        "open_bounds": old.get("open_bounds", {}),
        "timing_soft": old.get("timing_soft", {}),
    }
    with open(path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"perf_gate: budgets re-based for {len(kernels)} kernels -> "
          f"{path}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--budgets", default=BUDGETS)
    p.add_argument("--update", action="store_true",
                   help="re-base perf_budgets.json from live lowering "
                        "(deliberate re-baseline; review the diff)")
    p.add_argument("--kernels", default="",
                   help="comma-separated subset (default: all)")
    p.add_argument("--records",
                   default=os.environ.get("OPENDHT_TPU_SMOKE_RECORD_DIR",
                                          ""),
                   help="smoke-record dir for the timing soft-warn pass")
    args = p.parse_args(argv)

    names = [k for k in args.kernels.split(",") if k] or None
    ledger = compute_ledger(names)

    if args.update:
        update_budgets(args.budgets, ledger, merge=bool(names))
        return 0

    if not os.path.exists(args.budgets):
        print(f"perf_gate: {args.budgets} missing — run "
              f"'python ci/perf_gate.py --update' and commit it",
              file=sys.stderr)
        return 1
    budgets = _load_budgets(args.budgets)
    if names:
        budgets = dict(budgets,
                       kernels={k: v for k, v in budgets["kernels"].items()
                                if k in names})

    failures: list = []
    warnings: list = []
    check_costs(budgets, ledger, failures, warnings)
    check_timing(budgets, args.records, warnings)

    for w in warnings:
        print("perf_gate WARN:", w)
    print_open_bounds(budgets)
    if failures:
        print("perf_gate: COST-MODEL REGRESSION vs perf_budgets.json:",
              file=sys.stderr)
        for fmsg in failures:
            print(" -", fmsg, file=sys.stderr)
        print("(if the change is intentional, re-base with "
              "'python ci/perf_gate.py --update' and commit the diff)",
              file=sys.stderr)
        return 1
    print("perf_gate: %d kernel budgets within tolerance (%d soft "
          "warnings)" % (len(budgets.get("kernels", {})), len(warnings)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
