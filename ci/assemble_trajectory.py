"""Assemble the scattered perf record into ONE committed artifact.

The BENCH trajectory (920× → 46× → 121× → 131× → 213× vs the scalar
baseline) lives in five ``BENCH_r*.json`` driver dumps, a dozen
``captures/*.json`` attribution artifacts, and ``TP_SCALING.json`` —
no single file shows the whole curve, which is exactly how a future
regression hides.  This script parses them all into
``PERF_TRAJECTORY.json`` (committed) and prints the README trajectory
table; ``ci/check_docs.py check_trajectory`` enforces BOTH directions:
the committed JSON must equal a fresh assembly of the sources, and the
README's ``<!-- trajectory -->``-tagged table must quote the JSON's
numbers.

Round 1's 127M lookups/s is RECORDED, NOT CLAIMED: it predates the
device-serialized chain-slope methodology (bench.py's docstring — a
tunneled ``block_until_ready`` returned before execution completed and
inflated throughput up to ~100×); the honest curve starts at round 2.
The artifact keeps it with a ``superseded`` note so the methodology
fix itself stays visible in the record.

Usage::

    python ci/assemble_trajectory.py            # rewrite the artifact
    python ci/assemble_trajectory.py --check    # exit 1 on drift
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "PERF_TRAJECTORY.json")


def _ms_per_batch(metric: str):
    m = re.search(r"(\d+(?:\.\d+)?) ?ms/batch", metric)
    return float(m.group(1)) if m else None


def build() -> dict:
    """Pure assembly of the committed sources — deterministic, so the
    docs checker can diff a fresh build against the committed file."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json"))):
        with open(path) as f:
            rec = json.load(f)
        parsed = rec.get("parsed") or {}
        if not parsed:
            continue
        n = rec.get("n")
        entry = {
            "round": n,
            "source": os.path.basename(path),
            "lookups_per_s": parsed.get("value"),
            "unit": parsed.get("unit"),
            "vs_baseline": parsed.get("vs_baseline"),
            "ms_per_batch": _ms_per_batch(parsed.get("metric", "")),
            "metric": parsed.get("metric"),
        }
        if n == 1:
            entry["superseded"] = (
                "pre-chain-slope timing artifact (pipelined dispatch on a "
                "tunneled device, inflated up to ~100x — bench.py "
                "docstring); recorded for methodology history, not part "
                "of the claimed curve")
        rounds.append(entry)

    captures = {}
    for path in sorted(glob.glob(os.path.join(ROOT, "captures", "*.json"))):
        name = os.path.splitext(os.path.basename(path))[0]
        with open(path) as f:
            cap = json.load(f)
        captures[name] = {
            "value": cap.get("value"),
            "unit": cap.get("unit"),
            "metric": (cap.get("metric") or cap.get("name") or "")[:160],
        }

    tp = {}
    tp_path = os.path.join(ROOT, "TP_SCALING.json")
    if os.path.exists(tp_path):
        with open(tp_path) as f:
            tps = json.load(f)
        rows = tps.get("rows") or []
        if rows:
            r0 = rows[0]
            tp = {
                "metric": tps.get("metric"),
                "bytes_per_query_per_hop": r0.get(
                    "bytes_per_local_query_per_hop"),
                "in_loop_collective_sites": r0.get(
                    "collective_sites_in_loop"),
                "geometries": len(rows),
            }

    headline = {}
    bc = os.path.join(ROOT, "bench_capture.json")
    if os.path.exists(bc):
        with open(bc) as f:
            cap = json.load(f)
        headline = {"lookups_per_s": cap.get("value"),
                    "ms_per_batch": cap.get("ms_per_batch"),
                    "rate_range": cap.get("rate_range")}

    return {
        "_note": ("Assembled by ci/assemble_trajectory.py from "
                  "BENCH_r*.json + captures/*.json + TP_SCALING.json; "
                  "README's <!-- trajectory --> table quotes this file "
                  "and ci/check_docs.py enforces both directions."),
        "headline_unit": "lookups/s/chip",
        "rounds": rounds,
        "headline_capture": headline,
        "captures": captures,
        "tp_scaling": tp,
    }


def drift() -> "str | None":
    """None when the committed artifact equals a fresh assembly of its
    sources, else a one-line description — THE single comparison,
    shared by ``--check`` and ``ci/check_docs.py check_trajectory``."""
    if not os.path.exists(OUT):
        return ("PERF_TRAJECTORY.json missing — run "
                "python ci/assemble_trajectory.py")
    with open(OUT) as f:
        committed = json.load(f)
    if committed != build():
        return ("PERF_TRAJECTORY.json drifted from its sources "
                "(BENCH_r*/captures/TP_SCALING) — regenerate with "
                "python ci/assemble_trajectory.py")
    return None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--check", action="store_true",
                   help="exit 1 if the committed artifact drifted from "
                        "a fresh assembly of the sources")
    args = p.parse_args(argv)
    if args.check:
        msg = drift()
        if msg:
            print(msg, file=sys.stderr)
            return 1
        fresh = build()
        print("PERF_TRAJECTORY.json agrees with its sources "
              "(%d rounds, %d captures)"
              % (len(fresh["rounds"]), len(fresh["captures"])))
        return 0
    fresh = build()
    with open(OUT, "w") as f:
        json.dump(fresh, f, indent=1, sort_keys=True)
        f.write("\n")
    print("wrote %s (%d rounds, %d captures)"
          % (OUT, len(fresh["rounds"]), len(fresh["captures"])))
    for r in fresh["rounds"]:
        flag = " (superseded)" if "superseded" in r else ""
        print("  round %d: %.4gM lookups/s, %sx baseline%s"
              % (r["round"], (r["lookups_per_s"] or 0) / 1e6,
                 r["vs_baseline"], flag))
    return 0


if __name__ == "__main__":
    sys.exit(main())
