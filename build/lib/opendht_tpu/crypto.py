"""Public-key crypto layer: identities, signatures, hybrid encryption, x509.

Behavioral port of the reference crypto wrappers (reference:
include/opendht/crypto.h:67-496, src/crypto.cpp) on top of the
``cryptography`` package instead of GnuTLS/nettle:

- ``PrivateKey`` — RSA (default 4096) or EC (SECP521R1 = GnuTLS
  SEC_PARAM_ULTRA, src/crypto.cpp:885-896); ``sign`` = SHA512 signature
  (PKCS#1 v1.5 for RSA, DER ECDSA for EC; src/crypto.cpp:307-321);
  ``decrypt`` undoes the hybrid scheme below (src/crypto.cpp:336-356).
- ``PublicKey`` — ``check_signature``; ``encrypt``: plain RSA PKCS#1 v1.5
  when the data fits one block (≤ keylen/8 − 11), else hybrid
  [RSA(random AES key)][AES-GCM(data)] using the largest AES key that
  fits (src/crypto.cpp:478-543); ``get_id()`` = SHA1 of the DER
  SubjectPublicKeyInfo (the fingerprint the whole DHT keys on).
- ``Certificate`` — x509 chain (cert + issuers), packed as concatenated
  DER like the reference's getPacked chains (src/crypto.cpp:573-600);
  ``generate`` mirrors Certificate::generate (src/crypto.cpp:925-995):
  10-year validity, CN=name, UID=key id hex, random 64-bit serial,
  subject-key-id = key id, CA flags.
- ``RevocationList`` — x509 CRL (src/crypto.cpp:1005-1125).
- ``TrustList`` — trusted-root store with chain + revocation verification
  (crypto.h:468-496).
- ``aes_encrypt/aes_decrypt`` — AES-GCM, layout IV(12)‖ciphertext‖tag(16)
  (src/crypto.cpp:119-191); password variants prefix a 16-byte salt.
- ``stretch_key`` — password KDF: argon2i(t=16, m=64MiB, p=1) → 32 bytes
  → length-selected digest, exactly the reference's stretchKey
  (src/crypto.cpp:193-206), via argon2-cffi (the official phc-winner
  C implementation).  Round-1 used scrypt(n=2^15, r=8, p=1) as a
  stand-in; ``aes_decrypt_password`` still falls back to the scrypt key
  so blobs written by round-1 builds remain readable (legacy path,
  local storage only — never the wire format).

``Identity = (PrivateKey, Certificate)`` as in crypto.h:62.
"""

from __future__ import annotations

import datetime
import secrets
from typing import Optional

from cryptography import x509
from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec, padding, rsa
from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.x509.oid import ExtensionOID, NameOID
import hashlib

from argon2.low_level import hash_secret_raw as _argon2_raw
from argon2.low_level import Type as _Argon2Type

from .infohash import InfoHash, PkId, _digest_for_len
from .utils import DhtException


class CryptoException(DhtException):
    pass


class DecryptError(CryptoException):
    pass


# ------------------------------------------------------------------ symmetric

GCM_IV_SIZE = 12
GCM_DIGEST_SIZE = 16
PASSWORD_SALT_LENGTH = 16
AES_LENGTHS = (128 // 8, 192 // 8, 256 // 8)


def aes_key_size(max_size: int) -> int:
    """Largest AES key length ≤ max_size (src/crypto.cpp:95-105)."""
    best = 0
    for s in AES_LENGTHS:
        if s <= max_size:
            best = s
        else:
            break
    return best


def aes_key_size_good(n: int) -> bool:
    return n in AES_LENGTHS


def aes_encrypt(data: bytes, key: bytes) -> bytes:
    """IV(12) ‖ ciphertext ‖ tag(16)  (src/crypto.cpp:119-137)."""
    if not aes_key_size_good(len(key)):
        raise DecryptError("Wrong key size")
    iv = secrets.token_bytes(GCM_IV_SIZE)
    # AESGCM.encrypt returns ciphertext‖tag, exactly the reference layout
    return iv + AESGCM(key).encrypt(iv, bytes(data), None)


def aes_decrypt(data: bytes, key: bytes) -> bytes:
    if not aes_key_size_good(len(key)):
        raise DecryptError("Wrong key size")
    if len(data) <= GCM_IV_SIZE + GCM_DIGEST_SIZE:
        raise DecryptError("Wrong data size")
    try:
        return AESGCM(key).decrypt(data[:GCM_IV_SIZE], data[GCM_IV_SIZE:], None)
    except Exception as e:
        raise DecryptError("Can't decrypt data") from e


def stretch_key(password: str, salt: Optional[bytes], key_length: int = 32):
    """Password → key.  Returns (key, salt).

    argon2i(t=16, m=64MiB, p=1, out=32) then the length-selected digest,
    byte-compatible with the reference stretchKey
    (src/crypto.cpp:193-206: argon2i_hash_raw(16, 64*1024, 1, ...) then
    hash(res, key_length))."""
    if not salt:
        salt = secrets.token_bytes(PASSWORD_SALT_LENGTH)
    raw = _argon2_raw(password.encode(), salt, time_cost=16,
                      memory_cost=64 * 1024, parallelism=1, hash_len=32,
                      type=_Argon2Type.I)
    return _digest_for_len(raw, key_length), salt


def _stretch_key_scrypt(password: str, salt: bytes, key_length: int = 32):
    """Round-1 legacy KDF (scrypt stand-in), kept so blobs written
    before the argon2i switch stay decryptable."""
    raw = hashlib.scrypt(password.encode(), salt=salt, n=2 ** 15, r=8, p=1,
                         maxmem=64 * 1024 * 1024, dklen=32)
    return _digest_for_len(raw, key_length)


def aes_encrypt_password(data: bytes, password: str) -> bytes:
    key, salt = stretch_key(password, None, 256 // 8)
    return salt + aes_encrypt(data, key)


def aes_decrypt_password(data: bytes, password: str) -> bytes:
    if len(data) <= PASSWORD_SALT_LENGTH:
        raise DecryptError("Wrong data size")
    salt = data[:PASSWORD_SALT_LENGTH]
    key, _ = stretch_key(password, salt, 256 // 8)
    try:
        return aes_decrypt(data[PASSWORD_SALT_LENGTH:], key)
    except DecryptError:
        # legacy: blob may have been written by a round-1 (scrypt) build
        key = _stretch_key_scrypt(password, salt, 256 // 8)
        return aes_decrypt(data[PASSWORD_SALT_LENGTH:], key)


def hash_data(data: bytes, hash_len: int = 64) -> bytes:
    """Digest selected by output length: ≤20 SHA1, ≤32 SHA256, else SHA512
    (src/crypto.cpp:208-227)."""
    return _digest_for_len(bytes(data), hash_len)


# ----------------------------------------------------------------- PublicKey


class PublicKey:
    """Verify + hybrid-encrypt wrapper (crypto.h:67-117).

    Satisfies the owner-key protocol expected by core.value
    (export_der / get_id / check_signature)."""

    __slots__ = ("_pk", "_der")

    def __init__(self, key_or_der):
        if isinstance(key_or_der, (bytes, bytearray, memoryview)):
            der = bytes(key_or_der)
            try:
                self._pk = serialization.load_der_public_key(der)
            except Exception:
                try:
                    self._pk = serialization.load_pem_public_key(der)
                except Exception as e:
                    raise CryptoException("Can't read public key") from e
        else:
            self._pk = key_or_der
        self._der = self._pk.public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo)

    # -- identity
    def export_der(self) -> bytes:
        return self._der

    def export_pem(self) -> bytes:
        return self._pk.public_bytes(
            serialization.Encoding.PEM,
            serialization.PublicFormat.SubjectPublicKeyInfo)

    def get_id(self) -> InfoHash:
        """SHA1 fingerprint of the DER export (crypto.cpp:545-560)."""
        return InfoHash.get(self._der)

    def get_long_id(self) -> PkId:
        """SHA256 fingerprint (crypto.cpp:562-575)."""
        return PkId.get(self._der)

    # -- verify
    def check_signature(self, data: bytes, signature: bytes) -> bool:
        """SHA512 verify; scheme keyed by key type (crypto.cpp:466-477)."""
        try:
            if isinstance(self._pk, rsa.RSAPublicKey):
                self._pk.verify(bytes(signature), bytes(data),
                                padding.PKCS1v15(), hashes.SHA512())
            elif isinstance(self._pk, ec.EllipticCurvePublicKey):
                self._pk.verify(bytes(signature), bytes(data),
                                ec.ECDSA(hashes.SHA512()))
            else:
                return False
            return True
        except InvalidSignature:
            return False
        except Exception:
            return False

    # -- encrypt
    def encrypt(self, data: bytes) -> bytes:
        """Plain RSA block if it fits, else [RSA(aes key)][aes_encrypt(data)]
        (crypto.cpp:494-543)."""
        if not isinstance(self._pk, rsa.RSAPublicKey):
            raise CryptoException("Must be an RSA key")
        data = bytes(data)
        block = self._pk.key_size // 8
        max_block = block - 11
        if len(data) <= max_block:
            return self._pk.encrypt(data, padding.PKCS1v15())
        key_sz = aes_key_size(max_block)
        if key_sz == 0:
            raise CryptoException("Key is not long enough for AES128")
        key = secrets.token_bytes(key_sz)
        return self._pk.encrypt(key, padding.PKCS1v15()) + aes_encrypt(data, key)

    def __eq__(self, other):
        return (hasattr(other, "export_der")
                and self._der == other.export_der())

    def __hash__(self):
        return hash(self._der)

    def __repr__(self):
        return f"PublicKey({self.get_id()})"


# ---------------------------------------------------------------- PrivateKey


class PrivateKey:
    """RSA/EC private key (crypto.h:120-169)."""

    __slots__ = ("_sk",)

    def __init__(self, key_or_bytes, password: str = ""):
        if isinstance(key_or_bytes, (bytes, bytearray, memoryview)):
            raw = bytes(key_or_bytes)
            pw = password.encode() if password else None
            last = None
            for loader in (serialization.load_pem_private_key,
                           serialization.load_der_private_key):
                try:
                    self._sk = loader(raw, password=pw)
                    return
                except Exception as e:
                    last = e
            raise CryptoException(f"Can't load private key: {last}")
        else:
            self._sk = key_or_bytes

    @classmethod
    def generate(cls, key_length: int = 4096) -> "PrivateKey":
        return cls(rsa.generate_private_key(public_exponent=65537,
                                            key_size=key_length))

    @classmethod
    def generate_ec(cls) -> "PrivateKey":
        # GnuTLS SEC_PARAM_ULTRA ⇒ 521-bit curve (crypto.cpp:885-896)
        return cls(ec.generate_private_key(ec.SECP521R1()))

    def public_key(self) -> PublicKey:
        return PublicKey(self._sk.public_key())

    def get_public_key(self) -> PublicKey:
        return self.public_key()

    def sign(self, data: bytes) -> bytes:
        """SHA512 signature (crypto.cpp:307-321)."""
        data = bytes(data)
        if isinstance(self._sk, rsa.RSAPrivateKey):
            return self._sk.sign(data, padding.PKCS1v15(), hashes.SHA512())
        if isinstance(self._sk, ec.EllipticCurvePrivateKey):
            return self._sk.sign(data, ec.ECDSA(hashes.SHA512()))
        raise CryptoException("Can't sign data: unsupported key type")

    def decrypt(self, cipher: bytes) -> bytes:
        """Undo PublicKey.encrypt (crypto.cpp:323-356)."""
        if not isinstance(self._sk, rsa.RSAPrivateKey):
            raise CryptoException("Must be an RSA key")
        cipher = bytes(cipher)
        block = self._sk.key_size // 8
        if len(cipher) < block:
            raise DecryptError("Unexpected cipher length")
        try:
            head = self._sk.decrypt(cipher[:block], padding.PKCS1v15())
        except Exception as e:
            raise DecryptError("Can't decrypt data") from e
        if len(cipher) == block:
            return head
        return aes_decrypt(cipher[block:], head)

    def serialize(self, password: str = "") -> bytes:
        """PKCS#8 PEM, AES-256 password-encrypted when given
        (crypto.cpp:358-380)."""
        enc = (serialization.BestAvailableEncryption(password.encode())
               if password else serialization.NoEncryption())
        return self._sk.private_bytes(serialization.Encoding.PEM,
                                      serialization.PrivateFormat.PKCS8, enc)

    def __repr__(self):
        return f"PrivateKey({self.public_key().get_id()})"


# --------------------------------------------------------------- Certificate

_UID_OID = NameOID.USER_ID
_TEN_YEARS = datetime.timedelta(days=10 * 365)


def _der_cert_chunks(data: bytes):
    """Split concatenated DER certificates by reading ASN.1 TLV lengths."""
    i, n = 0, len(data)
    while i + 4 <= n and data[i] == 0x30:
        l0 = data[i + 1]
        if l0 < 0x80:
            end = i + 2 + l0
        else:
            nlen = l0 & 0x7F
            if nlen == 0 or i + 2 + nlen > n:
                break
            end = i + 2 + nlen + int.from_bytes(data[i + 2:i + 2 + nlen], "big")
        if end > n:
            break
        yield data[i:end]
        i = end


class Certificate:
    """x509 certificate + issuer chain (crypto.h:249-465).

    Packs/unpacks as concatenated DER, leaf first, like the reference's
    getPacked chain export."""

    __slots__ = ("_cert", "issuer", "revocation_lists")

    def __init__(self, cert_or_bytes, issuer: "Certificate | None" = None):
        self.issuer = issuer
        self.revocation_lists: list["RevocationList"] = []
        if isinstance(cert_or_bytes, (bytes, bytearray, memoryview)):
            raw = bytes(cert_or_bytes)
            certs = list(_der_cert_chunks(raw))
            if not certs:
                try:
                    certs = [c.public_bytes(serialization.Encoding.DER)
                             for c in x509.load_pem_x509_certificates(raw)]
                except Exception as e:
                    raise CryptoException("Can't load certificate") from e
            if not certs:
                raise CryptoException("Can't load certificate")
            self._cert = x509.load_der_x509_certificate(certs[0])
            if len(certs) > 1:
                self.issuer = Certificate(b"".join(certs[1:]))
        else:
            self._cert = cert_or_bytes

    # -- chain
    def chain(self):
        c: Optional[Certificate] = self
        while c is not None:
            yield c
            c = c.issuer

    def pack(self) -> bytes:
        return b"".join(c._cert.public_bytes(serialization.Encoding.DER)
                        for c in self.chain())

    def export_pem(self) -> bytes:
        return b"".join(c._cert.public_bytes(serialization.Encoding.PEM)
                        for c in self.chain())

    # -- accessors
    @property
    def x509(self) -> x509.Certificate:
        return self._cert

    def get_public_key(self) -> PublicKey:
        return PublicKey(self._cert.public_key())

    def get_id(self) -> InfoHash:
        return self.get_public_key().get_id()

    def get_long_id(self) -> PkId:
        return self.get_public_key().get_long_id()

    def _name_attr(self, name: x509.Name, oid) -> str:
        attrs = name.get_attributes_for_oid(oid)
        return attrs[0].value if attrs else ""

    def get_name(self) -> str:
        return self._name_attr(self._cert.subject, NameOID.COMMON_NAME)

    def get_uid(self) -> str:
        return self._name_attr(self._cert.subject, _UID_OID)

    def get_issuer_name(self) -> str:
        return self._name_attr(self._cert.issuer, NameOID.COMMON_NAME)

    def get_issuer_uid(self) -> str:
        return self._name_attr(self._cert.issuer, _UID_OID)

    def is_ca(self) -> bool:
        try:
            ext = self._cert.extensions.get_extension_for_oid(
                ExtensionOID.BASIC_CONSTRAINTS)
            return bool(ext.value.ca)
        except x509.ExtensionNotFound:
            return False

    def get_expiration(self) -> datetime.datetime:
        return self._cert.not_valid_after_utc

    # -- verification helpers
    def signed_by(self, issuer: "Certificate") -> bool:
        """Was this cert signed by `issuer`'s key?"""
        try:
            self._cert.verify_directly_issued_by(issuer._cert)
            return True
        except Exception:
            return False

    def __eq__(self, other):
        return (isinstance(other, Certificate)
                and self._cert == other._cert)

    def __hash__(self):
        return hash(self._cert)

    def __repr__(self):
        return f"Certificate({self.get_id()}, CN={self.get_name()!r})"

    # -- generation (Certificate::generate, crypto.cpp:925-995)
    @classmethod
    def generate(cls, key: PrivateKey, name: str = "dhtnode",
                 ca: "Identity | None" = None,
                 is_ca: bool = False) -> "Certificate":
        pk = key.public_key()
        pk_id = pk.get_id()
        subject = x509.Name([
            x509.NameAttribute(NameOID.COMMON_NAME, name),
            x509.NameAttribute(_UID_OID, str(pk_id)),
        ])
        now = datetime.datetime.now(datetime.timezone.utc)
        if ca is not None and ca.first is not None and ca.second is not None:
            if not ca.second.is_ca():
                raise CryptoException("Signing certificate must be CA")
            issuer_name = ca.second._cert.subject
            sign_key = ca.first._sk
            issuer_cert: Optional[Certificate] = ca.second
        else:
            issuer_name = subject
            sign_key = key._sk
            issuer_cert = None
        builder = (x509.CertificateBuilder()
                   .subject_name(subject)
                   .issuer_name(issuer_name)
                   .public_key(pk._pk)
                   .serial_number(secrets.randbits(63) | 1)
                   .not_valid_before(now)
                   .not_valid_after(now + _TEN_YEARS)
                   .add_extension(
                       x509.SubjectKeyIdentifier(bytes(pk_id)), critical=False)
                   .add_extension(
                       x509.BasicConstraints(ca=is_ca, path_length=None),
                       critical=True)
                   .add_extension(
                       x509.KeyUsage(
                           digital_signature=not is_ca,
                           content_commitment=False,
                           key_encipherment=False,
                           data_encipherment=not is_ca,
                           key_agreement=False,
                           key_cert_sign=is_ca,
                           crl_sign=is_ca,
                           encipher_only=False,
                           decipher_only=False),
                       critical=False))
        cert = builder.sign(sign_key, hashes.SHA512())
        return cls(cert, issuer=issuer_cert)


# ------------------------------------------------------------ RevocationList


class RevocationList:
    """x509 CRL wrapper (crypto.h:172-246, crypto.cpp:1005-1125)."""

    def __init__(self, data: Optional[bytes] = None):
        self._crl: Optional[x509.CertificateRevocationList] = None
        self._revoked: dict[int, datetime.datetime] = {}
        self._issuer: Optional[Certificate] = None
        if data is not None:
            self.unpack(bytes(data))

    def unpack(self, data: bytes) -> None:
        try:
            self._crl = x509.load_der_x509_crl(data)
        except Exception:
            try:
                self._crl = x509.load_pem_x509_crl(data)
            except Exception as e:
                raise CryptoException("Can't load CRL") from e
        self._revoked = {r.serial_number: r.revocation_date_utc
                         for r in self._crl}

    def pack(self) -> bytes:
        if self._crl is None:
            raise CryptoException("CRL not signed yet")
        return self._crl.public_bytes(serialization.Encoding.DER)

    def revoke(self, crt: Certificate,
               when: Optional[datetime.datetime] = None) -> None:
        when = when or datetime.datetime.now(datetime.timezone.utc)
        self._revoked[crt._cert.serial_number] = when

    def is_revoked(self, crt: Certificate) -> bool:
        return crt._cert.serial_number in self._revoked

    def sign(self, identity: "Identity",
             validity: datetime.timedelta = datetime.timedelta(days=7)):
        """Build + sign the CRL with the issuer identity
        (RevocationList::sign, crypto.cpp:1138-1170)."""
        key, cert = identity.first, identity.second
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (x509.CertificateRevocationListBuilder()
                   .issuer_name(cert._cert.subject)
                   .last_update(now)
                   .next_update(now + validity))
        for serial, when in self._revoked.items():
            builder = builder.add_revoked_certificate(
                x509.RevokedCertificateBuilder()
                .serial_number(serial)
                .revocation_date(when)
                .build())
        self._crl = builder.sign(key._sk, hashes.SHA512())
        self._issuer = cert

    def is_signed_by(self, issuer: Certificate) -> bool:
        if self._crl is None:
            return False
        try:
            return bool(self._crl.is_signature_valid(
                issuer._cert.public_key()))
        except Exception:
            return False

    def get_issuer_name(self) -> str:
        if self._crl is None:
            return ""
        attrs = self._crl.issuer.get_attributes_for_oid(NameOID.COMMON_NAME)
        return attrs[0].value if attrs else ""


# ------------------------------------------------------------------ Identity


class Identity:
    """(PrivateKey, Certificate) pair (crypto.h:62)."""

    __slots__ = ("first", "second")

    def __init__(self, key: Optional[PrivateKey] = None,
                 cert: Optional[Certificate] = None):
        self.first = key
        self.second = cert

    def __iter__(self):
        yield self.first
        yield self.second

    def __getitem__(self, i):
        return (self.first, self.second)[i]

    def __bool__(self):
        return self.first is not None and self.second is not None

    def __repr__(self):
        return f"Identity({self.second})" if self else "Identity(<empty>)"


def generate_identity(name: str = "dhtnode", ca: Optional[Identity] = None,
                      key_length: int = 4096,
                      is_ca: Optional[bool] = None) -> Identity:
    """generateIdentity (crypto.cpp:899-912): new RSA key + cert, CA-signed
    when a CA identity is given, else self-signed CA."""
    if is_ca is None:
        is_ca = not (ca and ca.first and ca.second)
    key = PrivateKey.generate(key_length)
    cert = Certificate.generate(key, name, ca, is_ca)
    return Identity(key, cert)


def generate_ec_identity(name: str = "dhtnode",
                         ca: Optional[Identity] = None,
                         is_ca: Optional[bool] = None) -> Identity:
    """generateEcIdentity (crypto.cpp:913-924). Note: EC identities can
    sign but not receive encrypted values (encrypt is RSA-only, as in the
    reference)."""
    if is_ca is None:
        is_ca = not (ca and ca.first and ca.second)
    key = PrivateKey.generate_ec()
    cert = Certificate.generate(key, name, ca, is_ca)
    return Identity(key, cert)


# ------------------------------------------------------------------ TrustList


class VerifyResult:
    __slots__ = ("valid", "reason")

    def __init__(self, valid: bool, reason: str = ""):
        self.valid = valid
        self.reason = reason

    def __bool__(self):
        return self.valid

    def __repr__(self):
        return f"VerifyResult({self.valid}, {self.reason!r})"


class TrustList:
    """Trusted-CA store with chain verification + CRLs (crypto.h:468-496)."""

    def __init__(self):
        self._roots: list[Certificate] = []
        self._crls: list[RevocationList] = []

    def add(self, crt: Certificate) -> None:
        for c in crt.chain():
            if c not in self._roots:
                self._roots.append(c)
        for crl in crt.revocation_lists:
            self.add_revocation_list(crl)

    def add_revocation_list(self, crl: RevocationList) -> None:
        self._crls.append(crl)

    def remove(self, crt: Certificate) -> None:
        self._roots = [c for c in self._roots if c != crt]

    def verify(self, crt: Certificate) -> VerifyResult:
        """Walk the presented chain; every link must verify, terminate at a
        trusted root, and no link may be revoked."""
        now = datetime.datetime.now(datetime.timezone.utc)
        chain = list(crt.chain())
        for c in chain:
            for crl in self._crls + c.revocation_lists:
                if crl.is_revoked(c):
                    return VerifyResult(False, "certificate revoked")
            na = c._cert.not_valid_after_utc
            if na < now:
                return VerifyResult(False, "certificate expired")
        # find link into the trust store
        for i, c in enumerate(chain):
            for root in self._roots:
                if c.signed_by(root):
                    for crl in self._crls:
                        if crl.is_revoked(c):
                            return VerifyResult(False, "certificate revoked")
                    # verify the presented chain below the trusted link
                    for j in range(i):
                        if not chain[j].signed_by(chain[j + 1]):
                            return VerifyResult(False, "broken chain")
                    return VerifyResult(True)
            if c in self._roots:
                for j in range(i):
                    if not chain[j].signed_by(chain[j + 1]):
                        return VerifyResult(False, "broken chain")
                return VerifyResult(True)
        return VerifyResult(False, "no trusted issuer")
