"""NodeSet / NodeEntry: sorted collection of discovered nodes
(↔ reference python/opendht.pyx:158-310 — the binding types the cluster
tools iterate while scanning/censusing the network)."""

from __future__ import annotations

from typing import Iterable, Iterator, Optional, Tuple

from .infohash import InfoHash


class NodeEntry:
    """(id, node) pair (opendht.pyx:158-167).  ``node`` is anything with
    an address — a net.node.Node, a SockAddr, or None."""

    __slots__ = ("id", "node")

    def __init__(self, node_id: InfoHash, node=None):
        self.id = InfoHash(node_id)
        self.node = node

    def get_id(self) -> InfoHash:
        return self.id

    def get_node(self):
        return self.node

    def __repr__(self):
        return f"NodeEntry({self.id}, {self.node})"


class NodeSet:
    """Sorted id → node map (opendht.pyx:273-310): insert/extend,
    first/last, iteration in id order."""

    def __init__(self, entries: Optional[Iterable] = None):
        self._nodes: dict = {}
        if entries:
            self.extend(entries)

    def size(self) -> int:
        return len(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)

    def insert(self, entry) -> bool:
        """Insert a NodeEntry, (id, node) tuple, or bare id; returns
        True when the id was new (map-insert semantics)."""
        if isinstance(entry, NodeEntry):
            nid, node = entry.id, entry.node
        elif isinstance(entry, tuple):
            nid, node = InfoHash(entry[0]), entry[1]
        else:
            nid, node = InfoHash(entry), None
        key = bytes(nid)
        if key in self._nodes:          # std::map::insert keeps the first
            return False
        self._nodes[key] = NodeEntry(nid, node)
        return True

    def extend(self, entries: Iterable) -> None:
        for e in entries:
            self.insert(e)

    def first(self) -> InfoHash:
        if not self._nodes:
            raise IndexError("empty NodeSet")
        return self._nodes[min(self._nodes)].id

    def last(self) -> InfoHash:
        if not self._nodes:
            raise IndexError("empty NodeSet")
        return self._nodes[max(self._nodes)].id

    def _sorted(self) -> list:
        return [self._nodes[k] for k in sorted(self._nodes)]

    def __iter__(self) -> Iterator[NodeEntry]:
        return iter(self._sorted())

    def __contains__(self, node_id) -> bool:
        return bytes(InfoHash(node_id)) in self._nodes

    def __str__(self) -> str:
        out = []
        for e in self._sorted():
            addr = getattr(e.node, "addr", e.node)
            out.append("%s %s" % (e.id, addr if addr is not None else ""))
        return "\n".join(out)
