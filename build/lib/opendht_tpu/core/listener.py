"""Listener records (reference src/listener.h).

- :class:`Listener` — a foreign node subscribed to updates of a key
  (held in Storage.listeners, refreshed by repeated listen RPCs).
- :class:`LocalListener` — one local ``listen`` op: query + filter + cb.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

from .value import Filter, Query, Value

#: cb(values, expired) -> bool; returning False unsubscribes
ValueCallback = Callable[[List[Value], bool], bool]


class Listener:
    """Remote listener state {time, query} (listener.h:31-42)."""

    __slots__ = ("time", "query", "sid")

    def __init__(self, t: float, query: Query, sid: int = 0):
        self.time = t
        self.query = query
        self.sid = sid      # the peer's push socket id for value updates

    def refresh(self, t: float, query: Query) -> None:
        self.time = t
        self.query = query


@dataclass
class LocalListener:
    """One local listen op (listener.h:45-51)."""
    query: Optional[Query]
    filter: Optional[Filter]
    get_cb: ValueCallback

    def notify(self, values: List[Value], expired: bool) -> bool:
        """Deliver the filtered batch; False means 'unsubscribe me'.
        Only an explicit ``False`` return unsubscribes — a callback that
        returns None (the usual Python default) stays subscribed."""
        from .value import Filters
        vals = Filters.apply(self.filter, values)
        if not vals:
            return True
        return self.get_cb(vals, expired) is not False
