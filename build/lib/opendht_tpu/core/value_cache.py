"""Per-(node, query) listen-side value cache (reference src/value_cache.h).

Tracks values a remote peer has pushed over a listen subscription, with
created/expiration bookkeeping per value type; emits add/expire events
through one callback ``cb(values, expired)``.  Handles the peer's
refreshed/expired id lists from value-update packets, caps at 4096
values (oldest evicted), and reports the next expiration time so the
owner can schedule an expiry job.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from ..utils import TIME_MAX
from .value import TypeStore, Value

MAX_VALUES = 4096               # value_cache.h:131

#: cb(values, expired)
ValueStateCallback = Callable[[List[Value], bool], None]


@dataclass
class _CacheSlot:
    data: Value
    created: float
    expiration: float


class ValueCache:
    def __init__(self, callback: Optional[ValueStateCallback]):
        self._values: Dict[int, _CacheSlot] = {}
        self._callback = callback

    # -- event entry point (value_cache.h:102-122) -------------------------
    def on_values(self, values: Sequence[Value], refreshed: Sequence[int],
                  expired: Sequence[int], types: TypeStore, now: float) -> float:
        """Apply one update from the peer: new/refreshed full values,
        refreshed ids, expired ids; then sweep expirations.  Returns the
        next expiration time (TIME_MAX if cache empty)."""
        pending: List[tuple[List[Value], bool]] = []
        if values:
            added = self._add_values(values, types, now)
            if added:
                pending.append((added, False))
        for vid in refreshed:
            self._refresh_value(vid, types, now)
        for vid in expired:
            gone = self._expire_value(vid)
            if gone:
                pending.append((gone, True))
        nxt, swept = self._sweep(now)
        if swept:
            pending.append((swept, True))
        cb = self._callback
        if cb:
            for vals, exp in pending:
                cb(vals, exp)
        return nxt

    def expire_values(self, now: float) -> float:
        """Standalone expiry sweep (value_cache.h:56-63)."""
        return self.on_values((), (), (), TypeStore(), now)

    def clear(self) -> None:
        """Flush everything, signalling expiration (value_cache.h:40-54)."""
        vals = [s.data for s in self._values.values()]
        self._values.clear()
        if vals and self._callback:
            self._callback(vals, True)

    def get_values(self) -> List[Value]:
        return [s.data for s in self._values.values()]

    def __len__(self) -> int:
        return len(self._values)

    # -- internals ---------------------------------------------------------
    def _add_values(self, new_values: Sequence[Value], types: TypeStore,
                    now: float) -> List[Value]:
        """(value_cache.h:144-165)"""
        fresh = []
        for v in new_values:
            slot = self._values.get(v.id)
            if slot is None:
                self._values[v.id] = _CacheSlot(
                    v, now, now + types.get_type(v.type).expiration)
                fresh.append(v)
            else:
                slot.created = now
                slot.expiration = now + types.get_type(slot.data.type).expiration
        return fresh

    def _refresh_value(self, vid: int, types: TypeStore, now: float) -> None:
        slot = self._values.get(vid)
        if slot is not None:
            slot.created = now
            slot.expiration = now + types.get_type(slot.data.type).expiration

    def _expire_value(self, vid: int) -> List[Value]:
        slot = self._values.pop(vid, None)
        return [slot.data] if slot is not None else []

    def _sweep(self, now: float) -> tuple[float, List[Value]]:
        """Expire due values; enforce the size cap by dropping oldest
        (value_cache.h:66-99).  Returns (next expiration, dropped)."""
        nxt = TIME_MAX
        dropped: List[Value] = []
        for vid in list(self._values):
            slot = self._values[vid]
            if slot.expiration <= now:
                dropped.append(slot.data)
                del self._values[vid]
            else:
                nxt = min(nxt, slot.expiration)
        while len(self._values) > MAX_VALUES:
            oldest_vid = min(self._values, key=lambda k: self._values[k].created)
            dropped.append(self._values.pop(oldest_vid).data)
        return nxt, dropped
