"""Registered default value types (reference include/opendht/default_types.h
+ src/default_types.cpp).

Each type is a thin serializable payload class plus a registered
:class:`~opendht_tpu.core.value.ValueType` with the reference's id, name,
expiration and store policy:

  1 DhtMessage      service message, 5 min, store iff service non-empty
  2 IpServiceAnnouncement  peer announce, 15 min, stored address is
                    rewritten to the *sender's* address (anti-spoof)
  3 ImMessage       instant message, 5 min (signed)
  4 TrustRequest    certificate trust request, 7 days (encrypted)
  5 IceCandidates   ICE bootstrap blob, 1 min (encrypted)
"""

from __future__ import annotations

import enum
from typing import Optional

from ..infohash import InfoHash
from ..sockaddr import SockAddr
from ..utils import pack_msg, unpack_msg
from .value import Filter, Filters, Value, ValueType


# ------------------------------------------------------------------ payloads
class DhtMessage:
    """Generic service message {service, data} (default_types.h:36-59)."""

    def __init__(self, service: str = "", data: bytes = b""):
        self.service = service
        self.data = bytes(data)

    def pack(self) -> bytes:
        return pack_msg([self.service, self.data])    # MSGPACK_DEFINE array

    @classmethod
    def unpack(cls, data: bytes) -> "DhtMessage":
        service, payload = unpack_msg(data)[:2]
        return cls(str(service), bytes(payload))

    @staticmethod
    def store_policy(key, value: Value, from_id, from_addr) -> bool:
        """Store iff the payload names a service (default_types.cpp:29-38)."""
        try:
            if not DhtMessage.unpack(value.data).service:
                return False
        except Exception:
            pass
        return ValueType.default_store_policy(key, value, from_id, from_addr)

    @staticmethod
    def service_filter(service: str) -> Filter:
        """(default_types.cpp:40-53)"""
        def match(v: Value) -> bool:
            try:
                return DhtMessage.unpack(v.data).service == service
            except Exception:
                return False
        return Filters.chain(Filters.value_type(DHT_MESSAGE_TYPE.id), match)

    def to_value(self, value_id: int = 0) -> Value:
        return Value(self.pack(), type_id=DHT_MESSAGE_TYPE.id, value_id=value_id)


class ImStatus(enum.IntEnum):
    NONE = 0
    TYPING = 1
    RECEIVED = 2
    READ = 3


class ImMessage:
    """Signed instant message (default_types.h:105-132)."""

    def __init__(self, msg_id: int = 0, msg: str = "", date: int = 0,
                 datatype: str = ""):
        self.id = msg_id
        self.msg = msg
        self.date = date
        self.datatype = datatype
        self.status = ImStatus.NONE
        self.from_id: Optional[InfoHash] = None     # signer, set on unpack
        self.to: Optional[InfoHash] = None          # recipient, set on unpack

    def pack(self) -> bytes:
        # MSGPACK_DEFINE_MAP(id, msg, date, status, datatype)
        return pack_msg({"id": self.id, "msg": self.msg, "date": self.date,
                         "status": int(self.status), "datatype": self.datatype})

    @classmethod
    def unpack(cls, data: bytes) -> "ImMessage":
        o = unpack_msg(data)
        m = cls(int(o.get("id", 0)), str(o.get("msg", "")),
                int(o.get("date", 0)), str(o.get("datatype", "")))
        m.status = ImStatus(int(o.get("status", 0)))
        return m

    @classmethod
    def from_value(cls, v: Value) -> "ImMessage":
        m = cls.unpack(v.data)
        m.from_id = v.owner.get_id() if v.owner else None
        m.to = v.recipient
        return m

    def to_value(self, value_id: int = 0) -> Value:
        return Value(self.pack(), type_id=IM_MESSAGE_TYPE.id, value_id=value_id)

    @staticmethod
    def get_filter() -> Filter:
        return lambda v: v.is_signed()


class TrustRequest:
    """Encrypted certificate trust request (default_types.h:134-155)."""

    def __init__(self, service: str = "", payload: bytes = b"", confirm: bool = False):
        self.service = service
        self.payload = bytes(payload)
        self.confirm = confirm

    def pack(self) -> bytes:
        return pack_msg({"service": self.service, "payload": self.payload,
                         "confirm": self.confirm})

    @classmethod
    def unpack(cls, data: bytes) -> "TrustRequest":
        o = unpack_msg(data)
        return cls(str(o.get("service", "")), bytes(o.get("payload", b"")),
                   bool(o.get("confirm", False)))

    def to_value(self, value_id: int = 0) -> Value:
        return Value(self.pack(), type_id=TRUST_REQUEST_TYPE.id, value_id=value_id)

    @staticmethod
    def get_filter() -> Filter:
        return lambda v: v.is_signed() and v.recipient is not None


class IceCandidates:
    """Encrypted ICE bootstrap blob [id, bin] (default_types.h:157-195)."""

    def __init__(self, msg_id: int = 0, ice_data: bytes = b""):
        self.id = msg_id
        self.ice_data = bytes(ice_data)

    def pack(self) -> bytes:
        return pack_msg([self.id, self.ice_data])

    @classmethod
    def unpack(cls, data: bytes) -> "IceCandidates":
        o = unpack_msg(data)
        if not isinstance(o, (list, tuple)) or len(o) < 2:
            raise ValueError("malformed IceCandidates")
        return cls(int(o[0]), bytes(o[1]))

    def to_value(self, value_id: int = 0) -> Value:
        return Value(self.pack(), type_id=ICE_CANDIDATES_TYPE.id, value_id=value_id)

    @staticmethod
    def get_filter() -> Filter:
        return lambda v: v.is_signed() and v.recipient is not None


class IpServiceAnnouncement:
    """Service announcement carrying an ip:port (default_types.h:199-252).
    Wire form: bin(compact sockaddr)."""

    def __init__(self, addr: Optional[SockAddr] = None):
        self.addr = addr or SockAddr()

    @property
    def port(self) -> int:
        return self.addr.port

    def pack(self) -> bytes:
        return pack_msg(self.addr.to_compact())

    @classmethod
    def unpack(cls, data: bytes) -> "IpServiceAnnouncement":
        o = unpack_msg(data)
        if not isinstance(o, (bytes, bytearray)):
            raise ValueError("malformed IpServiceAnnouncement")
        return cls(SockAddr.from_compact(bytes(o)))

    def to_value(self, value_id: int = 0) -> Value:
        return Value(self.pack(), type_id=IP_SERVICE_ANNOUNCEMENT_TYPE.id,
                     value_id=value_id)

    @staticmethod
    def store_policy(key, value: Value, from_id, from_addr: SockAddr) -> bool:
        """Anti-spoof: rewrite the announced address to the sender's
        observed source address, keeping only the announced port; reject
        port 0 (default_types.cpp:68-82).  Mutates ``value.data``."""
        try:
            ann = IpServiceAnnouncement.unpack(value.data)
            if ann.port == 0:
                return False
            rewritten = IpServiceAnnouncement(
                SockAddr(from_addr.ip, ann.port) if from_addr else ann.addr)
            value.data = rewritten.pack()
            value.type = IP_SERVICE_ANNOUNCEMENT_TYPE.id
            return ValueType.default_store_policy(key, value, from_id, from_addr)
        except Exception:
            return False


# --------------------------------------------------------------- type tables
DHT_MESSAGE_TYPE = ValueType(1, "DHT message", 5 * 60.0, DhtMessage.store_policy)
IP_SERVICE_ANNOUNCEMENT_TYPE = ValueType(
    2, "Internet Service Announcement", 15 * 60.0, IpServiceAnnouncement.store_policy)
IM_MESSAGE_TYPE = ValueType(3, "IM message", 5 * 60.0)
TRUST_REQUEST_TYPE = ValueType(4, "Certificate trust request", 7 * 24 * 3600.0)
ICE_CANDIDATES_TYPE = ValueType(5, "ICE candidates", 60.0)

#: types registered on every node (default_types.cpp:85-101)
DEFAULT_TYPES = (ValueType.USER_DATA, DHT_MESSAGE_TYPE, IM_MESSAGE_TYPE,
                 ICE_CANDIDATES_TYPE, TRUST_REQUEST_TYPE)

#: types whose store policy trusts the transport address, not signatures
DEFAULT_INSECURE_TYPES = (IP_SERVICE_ANNOUNCEMENT_TYPE,)
