"""Value & query model — the data plane of the DHT.

Counterpart of reference ``include/opendht/value.h`` + ``src/value.cpp``:

- :class:`Value` — a stored datum with metadata (value.h:134-591).  Wire
  format is three nested msgpack layers, outermost first:
    pack          {"id": u64, "dat": <to_encrypt>}            (value.h:506-511)
    to_encrypt    bin(cypher)  if encrypted, else
                  {"body": <to_sign>, ["sig": bin]}           (value.h:490-503)
    to_sign       {["seq", "owner", ["to"]], "type", "data", ["utype"]}
                                                              (value.h:470-487)
  (keys emitted in exactly that order; dict insertion order + msgpack
  preserves the reference's byte layout).
- :class:`ValueType` / :class:`TypeStore` — per-type expiration and
  store/edit policies (value.h:78-123).
- filters — composable predicates (value.h:150-199).
- remote query language — :class:`Select` (field projection),
  :class:`Where` (field equality), :class:`Query` with the SQL-ish
  string form ``[SELECT $fields$] [WHERE $field$=$value$,...]``
  (value.h:686-918, src/value.cpp:405-472).
- :class:`FieldValueIndex` — projected value for query replies
  (value.h:927-945, src/value.cpp:293-341).
"""

from __future__ import annotations

import enum
import random
from typing import Callable, Iterable, List, Optional, Sequence

from ..infohash import InfoHash
from ..utils import pack_msg

MAX_VALUE_SIZE = 64 * 1024          # value.h:77
TEN_MINUTES = 600.0

#: predicate over Value
Filter = Callable[["Value"], bool]


# --------------------------------------------------------------------- owner
class RawPublicKey:
    """Placeholder owner: holds the DER-encoded public key from the wire
    without parsing it.  The crypto layer subclasses/replaces this with a
    real key object exposing the same protocol: ``export_der()``,
    ``get_id()``, ``check_signature(data, sig)``."""

    __slots__ = ("der",)

    def __init__(self, der: bytes):
        self.der = bytes(der)

    def export_der(self) -> bytes:
        return self.der

    def get_id(self) -> InfoHash:
        """Key fingerprint = digest of the DER export (crypto.cpp:447-456)."""
        return InfoHash.get(self.der)

    def check_signature(self, data: bytes, signature: bytes) -> bool:
        return False    # can't verify without a parsed key

    def __eq__(self, other):
        return isinstance(other, RawPublicKey) and self.der == other.der

    def __hash__(self):
        return hash(self.der)


def _owner_equal(a, b) -> bool:
    if a is None or b is None:
        return a is b
    return a.export_der() == b.export_der()


# ----------------------------------------------------------------- ValueType
class ValueType:
    """Type metadata + storage/edit policies (value.h:78-110).

    ``store_policy(key, value, from_id, from_addr) -> bool`` gates every
    incoming store; ``edit_policy(key, old_value, new_value, from_id,
    from_addr) -> bool`` gates overwrites of an existing (key, value-id).
    Default: store anything sized, never edit."""

    __slots__ = ("id", "name", "expiration", "store_policy", "edit_policy")

    @staticmethod
    def default_store_policy(key, value: "Value", from_id, from_addr) -> bool:
        return value.size() <= MAX_VALUE_SIZE

    @staticmethod
    def default_edit_policy(key, old_value, new_value, from_id, from_addr) -> bool:
        return False

    def __init__(self, type_id: int, name: str, expiration: float = TEN_MINUTES,
                 store_policy=None, edit_policy=None):
        self.id = int(type_id)
        self.name = name
        self.expiration = float(expiration)
        self.store_policy = store_policy or ValueType.default_store_policy
        self.edit_policy = edit_policy or ValueType.default_edit_policy

    def __eq__(self, other):
        return isinstance(other, ValueType) and self.id == other.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return f"ValueType({self.id}, {self.name!r})"


ValueType.USER_DATA = ValueType(0, "USER_DATA")


class TypeStore:
    """Registry of known value types (value.h:112-123); unknown ids fall
    back to USER_DATA."""

    def __init__(self):
        self._types: dict[int, ValueType] = {}

    def register_type(self, vtype: ValueType) -> None:
        self._types[vtype.id] = vtype

    def get_type(self, type_id: int) -> ValueType:
        return self._types.get(type_id, ValueType.USER_DATA)


# --------------------------------------------------------------------- Value
class Value:
    """A datum stored on the DHT (value.h:134-591)."""

    INVALID_ID = 0
    Id = int

    __slots__ = ("id", "owner", "recipient", "type", "data", "user_type",
                 "seq", "signature", "cypher")

    def __init__(self, data: bytes = b"", *, type_id: int = 0,
                 value_id: int = INVALID_ID, user_type: str = ""):
        self.id = value_id
        self.owner = None                       # PublicKey-like or None
        self.recipient: Optional[InfoHash] = None
        self.type = type_id
        self.data = bytes(data)
        self.user_type = user_type
        self.seq = 0
        self.signature = b""
        self.cypher = b""

    # -- predicates --------------------------------------------------------
    def is_encrypted(self) -> bool:
        return len(self.cypher) > 0

    def is_signed(self) -> bool:
        return self.owner is not None and len(self.signature) > 0

    def size(self) -> int:
        """Bytes used by this value (value.cpp:99-102)."""
        return (len(self.cypher) + len(self.data) + len(self.signature)
                + len(self.user_type))

    def check_signature(self) -> bool:
        return (self.is_signed()
                and self.owner.check_signature(self.get_to_sign(), self.signature))

    def sign(self, key) -> None:
        """Sign with a PrivateKey-like object: sets owner to its public key
        and signature over the signed body (value.h:331-336)."""
        if self.is_encrypted():
            raise ValueError("Can't sign encrypted data")
        self.owner = key.public_key()
        self.signature = key.sign(self.get_to_sign())

    def encrypt(self, from_key, to_pk) -> "Value":
        """Sign with ``from_key``, then return a new Value carrying only the
        cypher encrypted to ``to_pk`` (value.h:350-360)."""
        if self.is_encrypted():
            raise ValueError("Data is already encrypted")
        self.recipient = to_pk.get_id()
        self.sign(from_key)
        nv = Value(value_id=self.id)
        nv.cypher = to_pk.encrypt(self.get_to_encrypt())
        return nv

    # -- wire layers (see module docstring) --------------------------------
    def to_sign_obj(self) -> dict:
        """Innermost layer: the signed body (value.h:470-487)."""
        out: dict = {}
        has_owner = self.owner is not None
        if has_owner:
            out["seq"] = self.seq
            out["owner"] = self.owner.export_der()
            if self.recipient:
                out["to"] = bytes(self.recipient)
        out["type"] = self.type
        out["data"] = self.data
        if self.user_type:
            out["utype"] = self.user_type
        return out

    def to_encrypt_obj(self):
        """Middle layer: cypher bin, or {body, [sig]} (value.h:490-503)."""
        if self.is_encrypted():
            return self.cypher
        out: dict = {"body": self.to_sign_obj()}
        if self.is_signed():
            out["sig"] = self.signature
        return out

    def wire_obj(self) -> dict:
        """Outermost layer (value.h:506-511)."""
        return {"id": self.id, "dat": self.to_encrypt_obj()}

    def get_to_sign(self) -> bytes:
        return pack_msg(self.to_sign_obj())

    def get_to_encrypt(self) -> bytes:
        return pack_msg(self.to_encrypt_obj())

    def get_packed(self) -> bytes:
        return pack_msg(self.wire_obj())

    # -- decoding ----------------------------------------------------------
    @classmethod
    def from_wire_obj(cls, obj) -> "Value":
        """Decode the outer layer (src/value.cpp:105-119)."""
        if not isinstance(obj, dict) or "id" not in obj or "dat" not in obj:
            raise ValueError("malformed value: missing id/dat")
        v = cls(value_id=int(obj["id"]))
        v._unpack_body(obj["dat"])
        return v

    def _unpack_body(self, o) -> None:
        """Decode the dat layer (src/value.cpp:122-173)."""
        self.owner = None
        self.recipient = None
        self.cypher = b""
        self.signature = b""
        self.data = b""
        self.type = 0
        if isinstance(o, (bytes, bytearray)):
            self.cypher = bytes(o)
            return
        if not isinstance(o, dict):
            raise ValueError("malformed value body")
        body = o.get("body")
        if not isinstance(body, dict):
            raise ValueError("malformed value: missing body")
        if "data" not in body or "type" not in body:
            raise ValueError("malformed value: missing data/type")
        self.data = bytes(body["data"])
        self.type = int(body["type"])
        self.user_type = str(body.get("utype", ""))
        if "owner" in body:
            if "seq" not in body:
                raise ValueError("signed value missing seq")
            self.seq = int(body["seq"])
            self.owner = RawPublicKey(body["owner"])
            if "to" in body:
                self.recipient = InfoHash(body["to"])
            if "sig" not in o:
                raise ValueError("signed value missing sig")
            self.signature = bytes(o["sig"])

    @classmethod
    def from_packed(cls, data: bytes) -> "Value":
        from ..utils import unpack_msg
        return cls.from_wire_obj(unpack_msg(data))

    # -- field projection (query replies) ----------------------------------
    def pack_fields(self, fields: "Sequence[Field]") -> list:
        """Per-field wire values in the given (sorted) field order
        (value.h:514-539)."""
        out = []
        for f in fields:
            if f == Field.ID:
                out.append(self.id)
            elif f == Field.VALUE_TYPE:
                out.append(self.type)
            elif f == Field.OWNER_PK:
                out.append(self.owner.get_id().data if self.owner else bytes(20))
            elif f == Field.SEQ_NUM:
                out.append(self.seq)
            elif f == Field.USER_TYPE:
                out.append(self.user_type)
        return out

    # -- equality / repr ---------------------------------------------------
    def __eq__(self, other) -> bool:
        """value.h:411-418: id match, then cypher if encrypted else the
        signed tuple."""
        if not isinstance(other, Value):
            return NotImplemented
        if self.id != other.id:
            return False
        if self.is_encrypted() or other.is_encrypted():
            return self.cypher == other.cypher
        return (_owner_equal(self.owner, other.owner)
                and self.seq == other.seq
                and self.signature == other.signature
                and self.data == other.data
                and self.type == other.type
                and self.user_type == other.user_type)

    def __hash__(self):
        return hash((self.id, self.cypher, self.data, self.signature))

    def __repr__(self):
        tag = "encrypted" if self.is_encrypted() else (
            "signed" if self.is_signed() else "plain")
        return (f"Value(id={self.id:016x}, type={self.type}, {tag}, "
                f"{len(self.cypher) or len(self.data)}B)")


def random_value_id(rng: Optional[random.Random] = None) -> int:
    """Non-zero random 64-bit value id (assigned on put when unset,
    dht.cpp:918-922)."""
    r = rng or random
    while True:
        vid = r.getrandbits(64)
        if vid != Value.INVALID_ID:
            return vid


# ------------------------------------------------------------------- filters
class Filters:
    """Composable Value predicates (value.h:150-199).  A falsy/None filter
    means 'accept everything'."""

    @staticmethod
    def all(v: "Value") -> bool:
        return True

    @staticmethod
    def chain(f1: Optional[Filter], f2: Optional[Filter]) -> Optional[Filter]:
        if not f1:
            return f2
        if not f2:
            return f1
        return lambda v: f1(v) and f2(v)

    @staticmethod
    def chain_or(f1: Optional[Filter], f2: Optional[Filter]) -> Filter:
        if not f1 or not f2:
            return Filters.all
        return lambda v: f1(v) or f2(v)

    @staticmethod
    def chain_all(fs: Iterable[Optional[Filter]]) -> Optional[Filter]:
        fset = [f for f in fs if f]
        if not fset:
            return None
        return lambda v: all(f(v) for f in fset)

    @staticmethod
    def apply(f: Optional[Filter], values: Iterable["Value"]) -> List["Value"]:
        return list(values) if not f else [v for v in values if f(v)]

    @staticmethod
    def type_filter(type_id: int) -> Filter:
        """Value::TypeFilter (value.h:187-191)."""
        tid = int(type_id.id) if hasattr(type_id, "id") else int(type_id)
        return lambda v: v.type == tid

    @staticmethod
    def id_filter(vid: int) -> Filter:
        """Value::IdFilter (value.h:181-185)."""
        return lambda v: v.id == vid

    # field filters
    @staticmethod
    def id(vid: int) -> Filter:
        return lambda v: v.id == vid

    @staticmethod
    def value_type(tid: int) -> Filter:
        return lambda v: v.type == tid

    @staticmethod
    def owner(pk_hash: InfoHash) -> Filter:
        return lambda v: v.owner is not None and v.owner.get_id() == pk_hash

    @staticmethod
    def recipient(h: InfoHash) -> Filter:
        return lambda v: v.recipient == h

    @staticmethod
    def seq(s: int) -> Filter:
        return lambda v: v.seq == s

    @staticmethod
    def user_type(ut: str) -> Filter:
        return lambda v: v.user_type == ut


# ------------------------------------------------------------ query language
class Field(enum.IntEnum):
    """Projectable/filterable Value fields (value.h:136-146)."""
    NONE = 0
    ID = 1
    VALUE_TYPE = 2
    OWNER_PK = 3
    SEQ_NUM = 4
    USER_TYPE = 5


_FIELD_NAMES = {
    "id": Field.ID,
    "value_type": Field.VALUE_TYPE,
    "owner_pk": Field.OWNER_PK,
    "seq": Field.SEQ_NUM,
    "user_type": Field.USER_TYPE,
}
_FIELD_STR = {v: k for k, v in _FIELD_NAMES.items()}

QUERY_PARSE_ERROR = "Error parsing query."


class FieldValue:
    """One WHERE restriction: (field, value) where value is an int,
    an InfoHash, or bytes by field kind (value.h:595-677)."""

    __slots__ = ("field", "value")

    def __init__(self, field: Field, value):
        self.field = Field(field)
        if self.field in (Field.ID, Field.VALUE_TYPE, Field.SEQ_NUM):
            self.value = int(value)
        elif self.field == Field.OWNER_PK:
            self.value = value if isinstance(value, InfoHash) else InfoHash(value)
        elif self.field == Field.USER_TYPE:
            self.value = bytes(value) if not isinstance(value, str) else value.encode()
        else:
            self.value = value

    def wire_obj(self) -> dict:
        v = self.value
        if self.field == Field.OWNER_PK:
            v = bytes(v)
        return {"f": int(self.field), "v": v}

    @classmethod
    def from_wire_obj(cls, obj) -> "FieldValue":
        if not isinstance(obj, dict) or "f" not in obj or "v" not in obj:
            raise ValueError("malformed field value")
        return cls(Field(obj["f"]), obj["v"])

    def local_filter(self) -> Filter:
        """The equivalent in-process predicate (src/value.cpp:275-292)."""
        f, v = self.field, self.value
        if f == Field.ID:
            return Filters.id(v)
        if f == Field.VALUE_TYPE:
            return Filters.value_type(v)
        if f == Field.OWNER_PK:
            return Filters.owner(v)
        if f == Field.SEQ_NUM:
            return Filters.seq(v)
        if f == Field.USER_TYPE:
            return Filters.user_type(v.decode() if isinstance(v, bytes) else v)
        return Filters.all

    def __eq__(self, other):
        return (isinstance(other, FieldValue) and self.field == other.field
                and self.value == other.value)

    def __hash__(self):
        return hash((self.field, self.value if not isinstance(self.value, InfoHash)
                     else bytes(self.value)))

    def __repr__(self):
        return f"{_FIELD_STR.get(self.field, '?')}={self.value!r}"


class Select:
    """Field projection of a remote query (value.h:686-730).

    String form: ``SELECT f1,f2,...`` with fields from
    id|value_type|owner_pk|seq|user_type (src/value.cpp:405-428)."""

    def __init__(self, q_str: str = ""):
        self._fields: list[Field] = []
        tokens = q_str.split()
        if tokens and tokens[0].lower() == "select":
            for tok in "".join(tokens[1:]).split(","):
                tok = tok.strip()
                if tok in _FIELD_NAMES:
                    self.field(_FIELD_NAMES[tok])

    def field(self, f: Field) -> "Select":
        if f not in self._fields:
            self._fields.append(Field(f))
        return self

    def get_selection(self) -> list[Field]:
        """Selected fields in canonical (enum) order — matches the
        reference's std::set iteration order used on the wire."""
        return sorted(set(self._fields))

    def empty(self) -> bool:
        return not self._fields

    def wire_obj(self) -> list:
        return [int(f) for f in self._fields]

    @classmethod
    def from_wire_obj(cls, obj) -> "Select":
        s = cls()
        for f in obj:
            s.field(Field(f))
        return s

    def is_satisfied_by(self, other: "Select") -> bool:
        """True if this selection's fields are all explicitly present in
        `other`'s (src/value.cpp:505-511).  Note an *empty* `other`
        (unprojected, full values) does NOT satisfy a non-empty selection:
        projected and full replies have different shapes on the wire, so
        ops are only shared between explicitly-compatible projections —
        same rule as the reference."""
        if not self._fields and other._fields:
            return False
        return all(f in other._fields for f in self._fields)

    def __eq__(self, other):
        return isinstance(other, Select) and self._fields == other._fields

    def __repr__(self):
        if not self._fields:
            return "SELECT *"
        return "SELECT " + ",".join(_FIELD_STR[f] for f in self._fields)


class Where:
    """Conjunction of field-equality restrictions (value.h:738-847).

    String form: ``WHERE f1=v1,f2=v2,...`` (src/value.cpp:430-472)."""

    def __init__(self, q_str: str = ""):
        self.filters: list[FieldValue] = []
        tokens = q_str.split(None, 1)
        if tokens and tokens[0].lower() == "where":
            rest = tokens[1] if len(tokens) > 1 else ""
            for part in rest.split(","):
                part = part.strip()
                if not part:
                    continue
                if "=" not in part:
                    raise ValueError(f"{QUERY_PARSE_ERROR} (WHERE) near: {part}")
                fname, _, vstr = part.partition("=")
                fname, vstr = fname.strip(), vstr.strip()
                if not vstr:
                    continue
                if len(vstr) > 1 and vstr[0] == '"' and vstr[-1] == '"':
                    sval = vstr[1:-1]
                else:
                    sval = vstr

                def as_int() -> int:
                    # Stricter than the reference, which coerces unparsable
                    # numerics to 0 (src/value.cpp:445-452) and so silently
                    # matches id=0; a malformed query should fail loudly.
                    try:
                        return int(sval)
                    except ValueError:
                        raise ValueError(
                            f"{QUERY_PARSE_ERROR} (WHERE) bad number near: {vstr}")

                if fname == "id":
                    self.id(as_int())
                elif fname == "value_type":
                    self.value_type(as_int())
                elif fname == "owner_pk":
                    self.owner(InfoHash(sval))
                elif fname == "seq":
                    self.seq(as_int())
                elif fname == "user_type":
                    self.user_type(sval)
                else:
                    raise ValueError(f"{QUERY_PARSE_ERROR} (WHERE) wrong token near: {fname}")

    def _add(self, fv: FieldValue) -> "Where":
        if fv not in self.filters:
            self.filters.append(fv)
        return self

    def id(self, vid: int) -> "Where":
        return self._add(FieldValue(Field.ID, vid))

    def value_type(self, tid: int) -> "Where":
        return self._add(FieldValue(Field.VALUE_TYPE, tid))

    def owner(self, pk_hash: InfoHash) -> "Where":
        return self._add(FieldValue(Field.OWNER_PK, pk_hash))

    def seq(self, s: int) -> "Where":
        return self._add(FieldValue(Field.SEQ_NUM, s))

    def user_type(self, ut: str) -> "Where":
        return self._add(FieldValue(Field.USER_TYPE, ut))

    def empty(self) -> bool:
        return not self.filters

    def get_filter(self) -> Optional[Filter]:
        if not self.filters:
            return None
        return Filters.chain_all(fv.local_filter() for fv in self.filters)

    def wire_obj(self) -> list:
        return [fv.wire_obj() for fv in self.filters]

    @classmethod
    def from_wire_obj(cls, obj) -> "Where":
        w = cls()
        for o in obj:
            w._add(FieldValue.from_wire_obj(o))
        return w

    def is_satisfied_by(self, other: "Where") -> bool:
        """True if `other`'s restrictions are a subset of this one's —
        i.e. other's (cached) result set is a superset of what this where
        clause selects (src/value.cpp:513-515)."""
        return all(fv in self.filters for fv in other.filters)

    def __eq__(self, o):
        return isinstance(o, Where) and self.filters == o.filters

    def __repr__(self):
        return "WHERE " + ",".join(map(repr, self.filters)) if self.filters else ""


class Query:
    """A remote query: projection + restriction (value.h:851-918).

    String form ``[SELECT $fields$] [WHERE $field$=$value$,...]``; wire
    form ``{"s": <select>, "w": <where>}``."""

    def __init__(self, select: "Select | str | None" = None,
                 where: "Where | None" = None, none: bool = False):
        if isinstance(select, str):
            q_str = select
            lower = q_str.lower()
            pos = lower.find("where")
            if pos < 0:
                pos = len(q_str)
            select = Select(q_str[:pos])
            where = Where(q_str[pos:])
        self.select = select or Select()
        self.where = where or Where()
        self.none = none   # when True, any query satisfies this one

    def is_satisfied_by(self, q: "Query") -> bool:
        """(src/value.cpp:517-519)"""
        return self.none or (self.where.is_satisfied_by(q.where)
                             and self.select.is_satisfied_by(q.select))

    def get_filter(self) -> Optional[Filter]:
        return self.where.get_filter()

    def wire_obj(self) -> dict:
        return {"s": self.select.wire_obj(), "w": self.where.wire_obj()}

    @classmethod
    def from_wire_obj(cls, obj) -> "Query":
        if not isinstance(obj, dict) or "s" not in obj or "w" not in obj:
            raise ValueError("malformed query")
        return cls(Select.from_wire_obj(obj["s"]), Where.from_wire_obj(obj["w"]))

    def __eq__(self, o):
        return (isinstance(o, Query) and self.select == o.select
                and self.where == o.where and self.none == o.none)

    def __hash__(self):
        return hash((tuple(self.select.get_selection()),
                     tuple(self.where.filters and map(repr, self.where.filters) or ()),
                     self.none))

    def __repr__(self):
        return f"Query[{self.select!r} {self.where!r}]"


class FieldValueIndex:
    """Projected view of a Value for a Select — what query replies carry
    instead of whole values (value.h:927-945, src/value.cpp:293-341)."""

    def __init__(self, value: Optional[Value] = None, select: Optional[Select] = None):
        self.index: dict[Field, FieldValue] = {}
        if value is None:
            return
        fields = (select.get_selection() if select and not select.empty()
                  else [Field.ID, Field.VALUE_TYPE, Field.OWNER_PK,
                        Field.SEQ_NUM, Field.USER_TYPE])
        for f in fields:
            if f == Field.ID:
                self.index[f] = FieldValue(f, value.id)
            elif f == Field.VALUE_TYPE:
                self.index[f] = FieldValue(f, value.type)
            elif f == Field.OWNER_PK:
                self.index[f] = FieldValue(
                    f, value.owner.get_id() if value.owner else InfoHash())
            elif f == Field.SEQ_NUM:
                self.index[f] = FieldValue(f, value.seq)
            elif f == Field.USER_TYPE:
                self.index[f] = FieldValue(f, value.user_type)

    def contained_in(self, other: "FieldValueIndex") -> bool:
        """Same fields present with equal values.  Stricter than the
        reference (src/value.cpp:330-341), which checks field presence
        only — value equality is what reply dedup actually needs."""
        if len(self.index) > len(other.index):
            return False
        return all(f in other.index and self.index[f] == other.index[f]
                   for f in self.index)

    def pack_fields(self) -> list:
        """Wire array of field values, canonical field order."""
        out = []
        for f in sorted(self.index):
            fv = self.index[f]
            out.append(bytes(fv.value) if isinstance(fv.value, InfoHash) else fv.value)
        return out

    @classmethod
    def unpack_fields(cls, fields: Sequence[Field], values: Sequence) -> "FieldValueIndex":
        """(src/value.cpp:374-396)"""
        fvi = cls()
        for f, v in zip(sorted(fields), values):
            fvi.index[Field(f)] = FieldValue(Field(f), v)
        return fvi

    def __repr__(self):
        return "Index[" + ",".join(repr(v) for _, v in sorted(self.index.items())) + "]"
