"""L2 data structures: node tables, routing semantics, batched search
engine, value store.  Host code mutates numpy-backed slabs; batched
queries run on device snapshots (see core/table.py for the split)."""
