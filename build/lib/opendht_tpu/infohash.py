"""Host-side 160-bit node/key identifiers (the scalar protocol primitive).

This is the host (per-packet, per-node) counterpart of the batched device
kernels in :mod:`opendht_tpu.ops.ids`.  Semantics match the reference
``Hash<N>`` (reference: include/opendht/infohash.h:61-268):

- ``cmp`` / ``<`` / ``==``  — lexicographic byte order (infohash.h:149-151)
- ``xor_cmp(a, b)``         — which of a, b is XOR-closer to self
  (infohash.h:179-194): first differing byte decides
- ``common_bits(a, b)``     — length of shared bit prefix (infohash.h:154-176)
- ``lowbit``                — index of the lowest set bit, -1 for zero
  (infohash.h:132-143); used for bucket depth computations
- ``get(data)``             — digest of ``data`` sized to the hash length
  (infohash.h:231-236; digest selection by length src/crypto.cpp:208-227:
  20B→SHA1, 32B→SHA256, 64B→SHA512)

The scalar implementations here double as the exactness oracle for the
vectorized kernels (tests/test_ids_ops.py).
"""

from __future__ import annotations

import hashlib
import secrets
from functools import total_ordering


def _digest_for_len(data: bytes, n: int) -> bytes:
    """Digest of `data`, truncated/selected by output length like the
    reference's crypto::hash (src/crypto.cpp:208-227)."""
    if n <= 20:
        h = hashlib.sha1(data).digest()
    elif n <= 32:
        h = hashlib.sha256(data).digest()
    else:
        h = hashlib.sha512(data).digest()
    return h[:n]


@total_ordering
class Hash:
    """Fixed-size big-endian identifier. Subclass and set HASH_LEN."""

    HASH_LEN = 20
    __slots__ = ("_b",)

    def __init__(self, value: "bytes | str | Hash | None" = None):
        n = self.HASH_LEN
        if value is None:
            self._b = bytes(n)
        elif isinstance(value, Hash):
            b = value._b
            # converting across hash widths: truncate or treat-as-too-short,
            # same rules as raw bytes below
            self._b = b if len(b) == n else (b[:n] if len(b) > n else bytes(n))
        elif isinstance(value, (bytes, bytearray, memoryview)):
            b = bytes(value)
            # Reference semantics (infohash.h:73-87): too-short input gives a
            # zero hash; too-long input is truncated.
            self._b = b[:n] if len(b) >= n else bytes(n)
        elif isinstance(value, str):
            s = value.strip()
            if len(s) != 2 * n:
                self._b = bytes(n)
            else:
                try:
                    b = bytes.fromhex(s)
                except ValueError:
                    b = b""
                # fromhex skips internal whitespace; enforce exact width
                self._b = b if len(b) == n else bytes(n)
        else:
            raise TypeError(f"cannot build {type(self).__name__} from {type(value)}")

    # -- basic accessors ---------------------------------------------------
    def __bytes__(self) -> bytes:
        return self._b

    @property
    def data(self) -> bytes:
        return self._b

    def __len__(self) -> int:
        return self.HASH_LEN

    def __getitem__(self, i):
        return self._b[i]

    def __bool__(self) -> bool:
        return self._b != bytes(self.HASH_LEN)

    def __hash__(self) -> int:
        return hash(self._b)

    def __eq__(self, other) -> bool:
        return isinstance(other, Hash) and self._b == other._b

    def __lt__(self, other) -> bool:
        return self._b < other._b

    def __repr__(self) -> str:
        return f"{type(self).__name__}('{self.hex()}')"

    def __str__(self) -> str:
        return self.hex()

    def hex(self) -> str:
        return self._b.hex()

    def to_int(self) -> int:
        return int.from_bytes(self._b, "big")

    def to_float(self) -> float:
        """Fractional position of the id in [0, 1) (infohash.h:212-218)."""
        return self.to_int() / (1 << (8 * self.HASH_LEN))

    @classmethod
    def from_int(cls, v: int) -> "Hash":
        return cls(v.to_bytes(cls.HASH_LEN, "big"))

    # -- the XOR metric ----------------------------------------------------
    @staticmethod
    def cmp(a: "Hash", b: "Hash") -> int:
        """Lexicographic compare, memcmp-style (infohash.h:149-151)."""
        return (a._b > b._b) - (a._b < b._b)

    def xor_cmp(self, a: "Hash", b: "Hash") -> int:
        """-1 if `a` is XOR-closer to self than `b`, 1 if farther, 0 if tied
        (infohash.h:179-194)."""
        s = self._b
        for i in range(self.HASH_LEN):
            if a._b[i] == b._b[i]:
                continue
            x1 = a._b[i] ^ s[i]
            x2 = b._b[i] ^ s[i]
            return -1 if x1 < x2 else 1
        return 0

    @staticmethod
    def common_bits(a: "Hash", b: "Hash") -> int:
        """Number of leading bits shared by a and b (infohash.h:154-176)."""
        n = a.HASH_LEN
        for i in range(n):
            if a._b[i] != b._b[i]:
                x = a._b[i] ^ b._b[i]
                j = 0
                while not (x & 0x80):
                    x = (x << 1) & 0xFF
                    j += 1
                return 8 * i + j
        return 8 * n

    def lowbit(self) -> int:
        """Index (from the MSB, i.e. tree depth) of the lowest set bit, or
        -1 when the id is zero (infohash.h:132-143)."""
        b = self._b
        for i in range(self.HASH_LEN - 1, -1, -1):
            if b[i]:
                byte = b[i]
                j = 7
                while not (byte & (0x80 >> j)):
                    j -= 1
                return 8 * i + j
        return -1

    def get_bit(self, nbit: int) -> bool:
        """Bit `nbit` counting from the MSB (infohash.h:196-202)."""
        return bool((self._b[nbit // 8] >> (7 - nbit % 8)) & 1)

    def set_bit(self, nbit: int, value: bool) -> "Hash":
        """Return a copy with bit `nbit` set/cleared (infohash.h:204-210)."""
        arr = bytearray(self._b)
        mask = 1 << (7 - nbit % 8)
        if value:
            arr[nbit // 8] |= mask
        else:
            arr[nbit // 8] &= ~mask
        return type(self)(bytes(arr))

    def xor(self, other: "Hash") -> "Hash":
        if len(other._b) != self.HASH_LEN:
            raise ValueError(
                f"cannot xor {type(self).__name__} with {len(other._b)}-byte hash"
            )
        return type(self)(bytes(x ^ y for x, y in zip(self._b, other._b)))

    # -- constructors ------------------------------------------------------
    @classmethod
    def get(cls, data: "bytes | str") -> "Hash":
        """Hash arbitrary data down to an id (infohash.h:220-236)."""
        if isinstance(data, str):
            data = data.encode()
        return cls(_digest_for_len(bytes(data), cls.HASH_LEN))

    @classmethod
    def get_random(cls) -> "Hash":
        """Uniformly random id (infohash.h:314-325)."""
        return cls(secrets.token_bytes(cls.HASH_LEN))

    @classmethod
    def zero(cls) -> "Hash":
        return cls()


class InfoHash(Hash):
    """160-bit DHT key / node id (infohash.h:267: ``using InfoHash = Hash<20>``)."""

    HASH_LEN = 20


class PkId(Hash):
    """256-bit public-key id (infohash.h:268-270: ``h256 = Hash<32>``)."""

    HASH_LEN = 32


def random_infohash() -> InfoHash:
    return InfoHash.get_random()
