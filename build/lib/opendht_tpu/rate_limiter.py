"""Sliding-window rate limiter (reference include/opendht/rate_limiter.h:26-48).

Used by the network engine for the global (1600/s) and per-IP (200/s)
ingress quotas.  A deque of admission timestamps; ``limit(now)`` admits
iff fewer than ``quota`` records fall inside the trailing ``period``.
"""

from __future__ import annotations

from collections import deque


class RateLimiter:
    __slots__ = ("quota", "period", "_records")

    def __init__(self, quota: int, period: float = 1.0):
        self.quota = quota
        self.period = period
        self._records: deque[float] = deque()

    def maintain(self, now: float) -> int:
        """Drop outdated records; return current usage (rate_limiter.h:28-34)."""
        limit = now - self.period
        rec = self._records
        while rec and rec[0] < limit:
            rec.popleft()
        return len(rec)

    def limit(self, now: float) -> bool:
        """False if the quota is spent, else record the hit and admit
        (rate_limiter.h:36-42)."""
        if self.maintain(now) >= self.quota:
            return False
        self._records.append(now)
        return True

    def empty(self) -> bool:
        return not self._records
