"""Vectorized 160-bit identifier kernels.

Device-side counterpart of :mod:`opendht_tpu.infohash`.  Ids are stored
as ``uint32[..., 5]`` limb vectors, **big-endian limb order** (limb 0
holds bytes 0..3 of the id, the most significant).  This layout makes
lexicographic byte order == lexicographic limb order, so every ordering
primitive of the reference maps onto 5-limb unsigned compares:

- ``lex_lt / lex_cmp``  ↔ ``Hash::cmp`` (reference include/opendht/infohash.h:149-151)
- ``xor_cmp``           ↔ ``Hash::xorCmp`` (infohash.h:179-194)
- ``common_bits``       ↔ ``Hash::commonBits`` (infohash.h:154-176)
- ``lowbit``            ↔ ``Hash::lowbit`` (infohash.h:132-143)
- ``get_bit``           ↔ ``Hash::getBit`` (infohash.h:196-202)

Why limbs and not bytes: the VPU operates on 32-bit lanes; 5 uint32 ops
per id beat 20 uint8 ops, and 5-operand ``lax.sort`` gives an exact
160-bit lexicographic sort without any wide-integer emulation.

All functions broadcast over leading batch dimensions and are safe to
``jit``/``vmap``/``shard_map``.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

HASH_BYTES = 20
N_LIMBS = 5
ID_BITS = 160

_U32 = jnp.uint32


# ---------------------------------------------------------------------------
# host <-> device representation
# ---------------------------------------------------------------------------

def ids_from_bytes(raw) -> np.ndarray:
    """Pack id bytes into big-endian uint32 limbs.

    `raw`: bytes of length 20*n, or uint8 array [..., 20].
    Returns uint32 [..., 5] (numpy; move to device with jnp.asarray).
    """
    if isinstance(raw, (bytes, bytearray, memoryview)):
        if len(raw) % HASH_BYTES:
            raise ValueError(
                f"id buffer length {len(raw)} is not a multiple of {HASH_BYTES}"
            )
        arr = np.frombuffer(bytes(raw), dtype=np.uint8).reshape(-1, HASH_BYTES)
    else:
        arr = np.asarray(raw, dtype=np.uint8)
    if arr.shape[-1] != HASH_BYTES:
        raise ValueError(f"expected trailing dim {HASH_BYTES}, got {arr.shape}")
    # big-endian: limb = b0<<24 | b1<<16 | b2<<8 | b3
    limbs = arr.reshape(arr.shape[:-1] + (N_LIMBS, 4)).astype(np.uint32)
    return (
        (limbs[..., 0] << 24)
        | (limbs[..., 1] << 16)
        | (limbs[..., 2] << 8)
        | limbs[..., 3]
    )


def ids_to_bytes(ids) -> np.ndarray:
    """Inverse of :func:`ids_from_bytes` → uint8 [..., 20]."""
    ids = np.asarray(ids, dtype=np.uint32)
    out = np.empty(ids.shape[:-1] + (N_LIMBS, 4), dtype=np.uint8)
    out[..., 0] = (ids >> 24) & 0xFF
    out[..., 1] = (ids >> 16) & 0xFF
    out[..., 2] = (ids >> 8) & 0xFF
    out[..., 3] = ids & 0xFF
    return out.reshape(ids.shape[:-1] + (HASH_BYTES,))


def ids_from_hashes(hashes) -> np.ndarray:
    """Pack an iterable of :class:`opendht_tpu.infohash.InfoHash` → uint32 [n, 5]."""
    return ids_from_bytes(b"".join(bytes(h) for h in hashes))


def random_ids(key, n: int):
    """Uniformly random ids, uint32 [n, 5] (↔ InfoHash::getRandom, infohash.h:314-325)."""
    return jax.random.bits(key, (n, N_LIMBS), dtype=jnp.uint32)


# ---------------------------------------------------------------------------
# bit kernels (uint32, fully vectorized)
# ---------------------------------------------------------------------------

def popcount32(x):
    return jax.lax.population_count(x.astype(_U32)).astype(jnp.int32)


def clz32(x):
    """Count leading zeros of each uint32 (32 for x == 0)."""
    return jax.lax.clz(x.astype(_U32)).astype(jnp.int32)


def ctz32(x):
    """Count trailing zeros of each uint32 (32 for x == 0)."""
    x = x.astype(_U32)
    return jnp.where(
        x == 0,
        jnp.int32(32),
        popcount32((~x).astype(_U32) & (x - _U32(1))),
    )


# ---------------------------------------------------------------------------
# ordering / metric kernels
# ---------------------------------------------------------------------------

def xor_ids(a, b):
    """XOR distance limbs: uint32 [..., 5]."""
    return jnp.bitwise_xor(a.astype(_U32), b.astype(_U32))


def _lex_fold(a, b):
    """Returns (lt, eq) booleans for 5-limb lexicographic compare a ? b."""
    a = a.astype(_U32)
    b = b.astype(_U32)
    lt = jnp.zeros(a.shape[:-1], dtype=bool)
    eq = jnp.ones(a.shape[:-1], dtype=bool)
    for i in range(N_LIMBS):
        ai, bi = a[..., i], b[..., i]
        lt = lt | (eq & (ai < bi))
        eq = eq & (ai == bi)
    return lt, eq


def lex_lt(a, b):
    """a < b in lexicographic (byte/limb) order (↔ Hash::operator<)."""
    lt, _ = _lex_fold(a, b)
    return lt


def lex_eq(a, b):
    a = a.astype(_U32)
    b = b.astype(_U32)
    return jnp.all(a == b, axis=-1)


def lex_cmp(a, b):
    """memcmp-style -1/0/1 (↔ Hash::cmp, infohash.h:149-151)."""
    lt, eq = _lex_fold(a, b)
    return jnp.where(eq, 0, jnp.where(lt, -1, 1)).astype(jnp.int32)


def xor_cmp(self_id, a, b):
    """-1 if `a` is XOR-closer to `self_id` than `b`, 1 farther, 0 tied
    (↔ Hash::xorCmp, infohash.h:179-194).  Broadcasts over batch dims."""
    da = xor_ids(a, self_id)
    db = xor_ids(b, self_id)
    return lex_cmp(da, db)


def common_bits(a, b):
    """Length of the shared bit prefix, 0..160 (↔ Hash::commonBits,
    infohash.h:154-176).  int32 [...]."""
    x = xor_ids(a, b)
    out = jnp.full(x.shape[:-1], ID_BITS, dtype=jnp.int32)
    prev_zero = jnp.ones(x.shape[:-1], dtype=bool)
    for i in range(N_LIMBS):
        xi = x[..., i]
        is_first = prev_zero & (xi != 0)
        out = jnp.where(is_first, 32 * i + clz32(xi), out)
        prev_zero = prev_zero & (xi == 0)
    return out


def lowbit(a):
    """Index (tree depth from MSB) of the lowest set bit; -1 when zero
    (↔ Hash::lowbit, infohash.h:132-143).  int32 [...]."""
    a = a.astype(_U32)
    out = jnp.full(a.shape[:-1], -1, dtype=jnp.int32)
    later_zero = jnp.ones(a.shape[:-1], dtype=bool)
    # scan limbs from least-significant (limb 4) upward; take the last
    # nonzero limb in byte order == first nonzero from the bottom.
    for i in range(N_LIMBS - 1, -1, -1):
        ai = a[..., i]
        is_last_nonzero = later_zero & (ai != 0)
        out = jnp.where(is_last_nonzero, 32 * i + 31 - ctz32(ai), out)
        later_zero = later_zero & (ai == 0)
    return out


def get_bit(a, nbit):
    """Bit `nbit` counting from the MSB (↔ Hash::getBit, infohash.h:196-202).
    `nbit` may be a scalar or batched traced int32; broadcasts against the
    ids' batch shape.  Out-of-range indices are clamped to bit 159 (device
    code can't raise; the host InfoHash.get_bit raises IndexError instead)."""
    a = a.astype(_U32)
    nbit = jnp.broadcast_to(
        jnp.asarray(nbit, dtype=jnp.int32), a.shape[:-1]
    )
    nbit = jnp.clip(nbit, 0, ID_BITS - 1)
    limb_idx = nbit // 32
    bit_in_limb = 31 - (nbit % 32)  # from LSB of limb
    limbs = jnp.take_along_axis(a, limb_idx[..., None], axis=-1)[..., 0]
    return ((limbs >> bit_in_limb.astype(_U32)) & _U32(1)).astype(bool)


def set_bit(a, nbit, value):
    """Return ids with bit `nbit` set/cleared (↔ Hash::setBit)."""
    a = a.astype(_U32)
    nbit = jnp.asarray(nbit, dtype=jnp.int32)
    limb_idx = nbit // 32
    mask = (_U32(1) << (31 - (nbit % 32)).astype(_U32))
    limb_sel = jnp.arange(N_LIMBS, dtype=jnp.int32) == limb_idx[..., None]
    v = jnp.asarray(value, dtype=bool)[..., None]
    with_set = a | jnp.where(limb_sel, mask[..., None], _U32(0))
    with_clr = a & ~jnp.where(limb_sel, mask[..., None], _U32(0))
    return jnp.where(v, with_set, with_clr)
