"""L0 device kernels: vectorized 160-bit ID math, XOR-distance top-k,
radix/prefix partitioning.  All functions are pure, jit-friendly, and
operate on uint32 limb matrices (see :mod:`opendht_tpu.ops.ids`)."""

from .ids import (  # noqa: F401
    N_LIMBS,
    ID_BITS,
    ids_from_bytes,
    ids_to_bytes,
    ids_from_hashes,
    xor_ids,
    lex_lt,
    lex_eq,
    lex_cmp,
    xor_cmp,
    common_bits,
    lowbit,
    get_bit,
    set_bit,
    clz32,
    ctz32,
    popcount32,
    random_ids,
)
