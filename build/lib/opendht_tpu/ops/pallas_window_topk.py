"""Fused window top-k selection — the pallas hot path.

Takes the expanded-table rows fetched by one row gather
(ops/sorted_table.py:expand_table — limb-planar [Q, 5·194] windows) and
produces each query's k XOR-closest candidates in one kernel: limb
extraction, XOR distance, and exact 5-limb lexicographic top-k by
progressive-masking min-extraction, all in VMEM.

Why not ``lax.sort``: the 7-operand bitonic sort XLA emits for the
(invalid, d0..d4, index) comparator costs ~18 ms per 131K×192 batch on
a v5e — it moves every payload channel through every sort stage.  Here
selection is k rounds of masked lane-reductions on 2-D vregs
(~50 vector ops per query), and the payloads are only touched k times.

Exactness: the reference orders XOR distances bytewise-lexicographically
(InfoHash::xorCmp, include/opendht/infohash.h:179-194).  Each round
finds the row-wise minimum of limb 0, narrows the candidate mask through
limbs 1..4 (progressive masking — exactly the first-differing-limb
rule), resolves remaining full-160-bit ties by smallest lane, then masks
the winner out.  Invalid lanes (beyond n_valid, or beyond the window)
carry all-MAX distances; a *valid* candidate whose true distance is
all-ones in every limb would tie with them (2^-160 per id — the caller's
``kth_valid`` check may then drop it; accepted and documented).

Outputs are packed into one [Q, 128]-lane row per query (k ≤ 21):
cols [l·k, (l+1)·k) = distance limb l of the winners, cols [5k, 6k) =
the winner's *local* window lane (0..191; 0xFFFFFFFF when the slot had
no valid candidate is NOT signalled here — the caller reconstructs
validity from ``start + local ≥ n_valid``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .ids import N_LIMBS

_EROW = 194          # lanes per limb plane (left nbr + 192 window + right nbr)
_WIN = 192
_U32 = jnp.uint32
_MAX = np.int32(0x7FFFFFFF)   # int32 max == uint32 max in the flipped domain
                              # (numpy scalar: jnp scalars become captured
                              # consts in pallas kernels)

TQ = 32              # query rows per grid step


def _kernel(rows_ref, q_ref, bound_ref, out_ref, *, k):
    # All limb math runs in the sign-flipped int32 domain (u ^ 0x80000000
    # viewed as int32 preserves unsigned order) because Mosaic has no
    # unsigned min-reduction.  The caller pre-flips the query limbs, so
    # rows ^ q_flipped IS the flipped distance; _MAX below is int32 max.
    rows = rows_ref[:, :]                                   # (TQ, 5·194)
    iota = jax.lax.broadcasted_iota(jnp.int32, (TQ, _WIN), 1)
    bound = bound_ref[:, 0:1]                               # (TQ, 1) int32
    valid = iota < bound

    d = []
    for l in range(N_LIMBS):
        w = rows[:, l * _EROW + 1: l * _EROW + 1 + _WIN]    # (TQ, 192)
        dl = w ^ q_ref[:, l:l + 1]
        d.append(jnp.where(valid, dl, _MAX))

    # `rem` tracks not-yet-extracted candidates so an extracted winner can
    # never re-enter through an all-MAX tie once a query's valid
    # candidates are exhausted (wl then hits the _WIN sentinel and the
    # caller marks the slot invalid).
    #
    # Winners accumulate in a (TQ, 128) register block via static one-hot
    # lane selects — per-lane out_ref stores are masked-store roundtrips
    # and dominated the first version of this kernel.
    d0 = d[0]
    rem = valid
    oiota = jax.lax.broadcasted_iota(jnp.int32, (TQ, 128), 1)
    acc = jnp.zeros((TQ, 128), jnp.int32)
    for r in range(k):
        m0 = jnp.min(jnp.where(rem, d0, _MAX), axis=1, keepdims=True)
        t = rem & (d0 == m0)
        ms = [m0]
        for l in range(1, N_LIMBS):
            ml = jnp.min(jnp.where(t, d[l], _MAX), axis=1, keepdims=True)
            t = t & (d[l] == ml)
            ms.append(ml)
        wl = jnp.min(jnp.where(t, iota, jnp.int32(_WIN)), axis=1,
                     keepdims=True)                         # (TQ, 1)
        for l in range(N_LIMBS):
            acc = jnp.where(oiota == l * k + r, ms[l], acc)
        acc = jnp.where(oiota == N_LIMBS * k + r, wl, acc)
        rem = rem & (iota != wl)
    out_ref[:, :] = acc


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def window_select(rows, queries8, bounds, *, k: int = 16,
                  interpret: bool = False):
    """Exact top-k over limb-planar window rows.

    rows:     uint32 [Q, 5·194] from the expand_table row gather
    queries8: uint32 [Q, 8] — query limbs 0..4, lanes 5..7 ignored
    bounds:   int32  [Q, 8] — col 0 = number of valid window lanes
              (n_valid - window start, clipped to [0, 192])
    Returns packed uint32 [Q, 128]; see module docstring for layout.
    Q is padded to a multiple of 32 internally.
    """
    if k * (N_LIMBS + 1) > 128:
        raise ValueError(f"k={k} does not fit the packed 128-lane output")
    Q = rows.shape[0]
    pad = (-Q) % TQ
    if pad:
        rows = jnp.pad(rows, ((0, pad), (0, 0)))
        queries8 = jnp.pad(queries8, ((0, pad), (0, 0)))
        bounds = jnp.pad(bounds, ((0, pad), (0, 0)))
    Qp = Q + pad

    flip = jnp.uint32(0x80000000)
    rows_s = jax.lax.bitcast_convert_type(rows, jnp.int32)
    q_s = jax.lax.bitcast_convert_type(queries8 ^ flip, jnp.int32)

    out = pl.pallas_call(
        functools.partial(_kernel, k=k),
        grid=(Qp // TQ,),
        in_specs=[
            pl.BlockSpec((TQ, rows.shape[1]), lambda i: (i, 0)),
            pl.BlockSpec((TQ, 8), lambda i: (i, 0)),
            pl.BlockSpec((TQ, 8), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((TQ, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Qp, 128), jnp.int32),
        interpret=interpret,
    )(rows_s, q_s, bounds)
    # un-flip the limb columns back to uint32; idx columns pass through
    out_u = jax.lax.bitcast_convert_type(out, _U32)
    limbs = out_u[:Q, :N_LIMBS * k] ^ flip
    idx = out_u[:Q, N_LIMBS * k:]
    return jnp.concatenate([limbs, idx], axis=1)
