"""Pallas TPU kernel: exact lexicographic XOR-distance top-k selection.

The hot op of the framework (SURVEY.md §7: the batched replacement for
``RoutingTable::findClosestNodes`` / ``NodeCache::getCachedNodes``,
reference src/routing_table.cpp:109-150, src/node_cache.cpp:41-74) has
two stages: compute 160-bit XOR distances, then select the k smallest
under **bytewise lexicographic order** (``InfoHash::xorCmp``,
include/opendht/infohash.h:179-194).  The jnp path does the selection
with a 7-key ``lax.sort`` (ops/xor_topk.py) — a bitonic network of
O(W log² W) limb compares per query row.

This kernel replaces the sort with **iterative lexicographic
min-extraction** in VMEM: per extracted rank, five masked VPU min
reductions narrow the candidate mask limb by limb (ties broken by
smallest window position), then the winner is retired from the alive
mask.  Cost is O(k · 5 · W) element ops of pure VPU work per query row —
no sorting network, no MXU, no data-dependent shapes — and the
selection is exact by construction (full 5-limb order, deterministic
tie-break), so it needs no fallback certificate.

Layout (TPU tiling: last dim 128 lanes):

- distances arrive as 5 separate ``[Q, W]`` uint32 limb planes (not
  ``[Q, W, 5]`` — a last dim of 5 would break lane alignment),
- invalid rows as an int32 ``[Q, W]`` plane (nonzero = skip),
- output is an int32 ``[Q, 128]`` plane whose first k lanes hold the
  selected window positions (−1 where fewer than k valid rows exist);
  the caller slices ``[:, :k]``.

Grid: 1-D over query tiles of QT rows; each program owns its rows
end-to-end, so there is no cross-program reduction.  On CPU the same
kernel runs under ``interpret=True`` (tests, and the virtual-net tier).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .ids import N_LIMBS

OUT_LANES = 128          # output plane lane width (≥ any useful k)


def _select_kernel(d0_ref, d1_ref, d2_ref, d3_ref, d4_ref, inv_ref,
                   out_ref, *, k: int):
    # limb planes arrive sign-flipped int32 (Mosaic has no unsigned
    # reductions; x ^ 0x80000000 maps unsigned order onto signed order)
    W = d0_ref.shape[1]
    big = jnp.int32(0x7FFFFFFF)
    d = (d0_ref[...], d1_ref[...], d2_ref[...], d3_ref[...], d4_ref[...])
    alive = inv_ref[...] == 0                             # [QT, W]
    pos = lax.broadcasted_iota(jnp.int32, d[0].shape, 1)  # [QT, W]
    lane = lax.broadcasted_iota(jnp.int32, (d[0].shape[0], OUT_LANES), 1)
    out = jnp.full((d[0].shape[0], OUT_LANES), -1, jnp.int32)
    for kk in range(k):
        # narrow the candidate mask one limb at a time: after limb i the
        # mask holds exactly the alive rows minimal on limbs 0..i
        cand = alive
        for i in range(N_LIMBS):
            di = jnp.where(cand, d[i], big)
            mi = jnp.min(di, axis=1, keepdims=True)
            cand = cand & (d[i] == mi)
        # deterministic tie-break: smallest window position
        j = jnp.min(jnp.where(cand, pos, W), axis=1)      # [QT]
        found = j < W
        out = jnp.where((lane == kk) & found[:, None], j[:, None], out)
        alive = alive & (pos != j[:, None])
    out_ref[...] = out


@functools.partial(jax.jit,
                   static_argnames=("k", "q_tile", "interpret"))
def lex_topk_select(dist, invalid, *, k: int = 8, q_tile: int = 256,
                    interpret: bool = False):
    """Exact lexicographic top-k positions per query row.

    Args:
      dist:    uint32 [Q, W, 5] XOR distances (W ≥ k recommended).
      invalid: int32/bool [Q, W]; nonzero rows are never selected.
      k:       ranks to extract.
      q_tile:  query rows per pallas program.
      interpret: run the kernel in interpreter mode (CPU backends).

    Returns:
      idx int32 [Q, k]: window positions, −1 where < k valid rows.
    """
    Q, W, _ = dist.shape
    inv = invalid.astype(jnp.int32)
    pad_q = (-Q) % q_tile
    if pad_q:
        dist = jnp.concatenate(
            [dist, jnp.zeros((pad_q, W, N_LIMBS), jnp.uint32)], axis=0)
        inv = jnp.concatenate(
            [inv, jnp.ones((pad_q, W), jnp.int32)], axis=0)
    qp = dist.shape[0]
    planes = [lax.bitcast_convert_type(
        dist[:, :, i] ^ jnp.uint32(0x80000000), jnp.int32)
        for i in range(N_LIMBS)]

    grid = (qp // q_tile,)
    in_spec = pl.BlockSpec((q_tile, W), lambda i: (i, 0),
                           memory_space=pl.ANY
                           if interpret else pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_select_kernel, k=k),
        grid=grid,
        in_specs=[in_spec] * (N_LIMBS + 1),
        out_specs=pl.BlockSpec((q_tile, OUT_LANES), lambda i: (i, 0),
                               memory_space=pl.ANY
                               if interpret else pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((qp, OUT_LANES), jnp.int32),
        interpret=interpret,
    )(*planes, inv)
    return out[:Q, :k]


# Backend dispatch lives at the call site (ops/sorted_table.window_topk
# selects pallas-vs-sort and compiled-vs-interpret by backend); this
# module stays a pure kernel.
