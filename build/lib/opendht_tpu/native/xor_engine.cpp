// Host-side XOR-metric engine.
//
// Native implementation of the reference's scalar id kernels and the
// sorted-map outward walk (reference: include/opendht/infohash.h:149-210
// xorCmp/commonBits/cmp; src/node_cache.cpp:41-74 getCachedNodes).  This
// is the host fallback/baseline path of the TPU framework: per-packet
// table ops on small live tables run here, batched/simulated lookups run
// on the device kernels (opendht_tpu/ops/*).
//
// C ABI only (consumed via ctypes).  IDs are 20-byte big-endian rows in
// a contiguous [N, 20] uint8 buffer.
//
// Build: g++ -O3 -shared -fPIC -o libdht_native.so xor_engine.cpp udp_engine.cpp

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <vector>

namespace {
constexpr int HASH_LEN = 20;

inline int cmp_id(const uint8_t* a, const uint8_t* b) {
    return std::memcmp(a, b, HASH_LEN);
}

// which of a,b is XOR-closer to self: <0 a closer, >0 b closer, 0 equal
// (infohash.h:179-194)
inline int xor_cmp(const uint8_t* self, const uint8_t* a, const uint8_t* b) {
    for (int i = 0; i < HASH_LEN; ++i) {
        uint8_t da = a[i] ^ self[i];
        uint8_t db = b[i] ^ self[i];
        if (da != db) return da < db ? -1 : 1;
    }
    return 0;
}

inline int common_bits(const uint8_t* a, const uint8_t* b) {
    for (int i = 0; i < HASH_LEN; ++i) {
        uint8_t x = a[i] ^ b[i];
        if (x) {
            int j = 0;
            while (!(x & 0x80)) { x <<= 1; ++j; }
            return i * 8 + j;
        }
    }
    return HASH_LEN * 8;
}
} // namespace

extern "C" {

int dht_xor_cmp(const uint8_t* self, const uint8_t* a, const uint8_t* b) {
    return xor_cmp(self, a, b);
}

int dht_common_bits(const uint8_t* a, const uint8_t* b) {
    return common_bits(a, b);
}

int dht_cmp(const uint8_t* a, const uint8_t* b) {
    int c = cmp_id(a, b);
    return c < 0 ? -1 : (c > 0 ? 1 : 0);
}

// Lexicographically sort an [N,20] id matrix in place, carrying a
// permutation of original row indices.  perm must hold N int32.
void dht_sort_ids(uint8_t* ids, int32_t* perm, int64_t n) {
    for (int64_t i = 0; i < n; ++i) perm[i] = (int32_t)i;
    // sort the permutation, then apply (avoids moving rows during compare)
    std::sort(perm, perm + n, [ids](int32_t a, int32_t b) {
        return cmp_id(ids + (int64_t)a * HASH_LEN,
                      ids + (int64_t)b * HASH_LEN) < 0;
    });
    // apply permutation out-of-place
    uint8_t* tmp = new uint8_t[(size_t)n * HASH_LEN];
    for (int64_t i = 0; i < n; ++i)
        std::memcpy(tmp + i * HASH_LEN,
                    ids + (int64_t)perm[i] * HASH_LEN, HASH_LEN);
    std::memcpy(ids, tmp, (size_t)n * HASH_LEN);
    delete[] tmp;
}

void dht_scan_closest(const uint8_t* ids, int64_t n,
                      const uint8_t* queries, int64_t nq,
                      int32_t k, int32_t* out);

// First index i in [0,n) with sorted_ids[i] >= q (lower bound).
int64_t dht_lower_bound(const uint8_t* sorted_ids, int64_t n,
                        const uint8_t* q) {
    int64_t lo = 0, hi = n;
    while (lo < hi) {
        int64_t mid = (lo + hi) / 2;
        if (cmp_id(sorted_ids + mid * HASH_LEN, q) < 0) lo = mid + 1;
        else hi = mid;
    }
    return lo;
}

// The reference's NodeCache::getCachedNodes walk (node_cache.cpp:41-74)
// made exact: the reference walks outward from the insertion point
// taking the XOR-closer frontier side directly — a heuristic, since XOR
// distance is not monotone in lexicographic offset.  Here the walk only
// *collects* a `window`-wide candidate set (which, by the common-prefix
// containment property, holds the true top-k whenever window is large
// enough — same argument as the device kernel's certificate,
// ops/sorted_table.py), and an exact insertion-select over the
// candidates picks the k closest.  window < k is clamped to k.
// Writes k int32 sorted-table indices per query into out (row-major
// [nq,k]); -1 padding when fewer than k rows exist.
void dht_sorted_closest(const uint8_t* sorted_ids, int64_t n,
                        const uint8_t* queries, int64_t nq,
                        int32_t k, int32_t window, int32_t* out) {
    if (window < k) window = k;
    std::vector<int64_t> cand((size_t)window);
    for (int64_t qi = 0; qi < nq; ++qi) {
        const uint8_t* q = queries + qi * HASH_LEN;
        int32_t* row = out + qi * k;
        int64_t pos = dht_lower_bound(sorted_ids, n, q);
        int64_t lo = pos - 1, hi = pos;
        int32_t ncand = 0;
        while (ncand < window && (lo >= 0 || hi < n)) {
            bool take_lo;
            if (lo < 0) take_lo = false;
            else if (hi >= n) take_lo = true;
            else take_lo = xor_cmp(q, sorted_ids + lo * HASH_LEN,
                                   sorted_ids + hi * HASH_LEN) <= 0;
            cand[ncand++] = take_lo ? lo-- : hi++;
        }
        // exact k-closest among the candidates (insertion select)
        int32_t got = 0;
        for (int32_t c = 0; c < ncand; ++c) {
            const uint8_t* cid = sorted_ids + cand[c] * HASH_LEN;
            int32_t p = got;
            while (p > 0 && xor_cmp(q, cid, sorted_ids +
                                    (int64_t)row[p - 1] * HASH_LEN) < 0)
                --p;
            if (p < k) {
                int32_t end = got < k ? got : k - 1;
                for (int32_t m = end; m > p; --m) row[m] = row[m - 1];
                row[p] = (int32_t)cand[c];
                if (got < k) ++got;
            }
        }
        for (int32_t g = got; g < k; ++g) row[g] = -1;

        // exactness certificate (same argument as the device kernel,
        // ops/sorted_table.py:134-157): excluded nodes sit beyond the
        // window's edges; the kth result beats them all iff it shares a
        // strictly longer prefix with q than the nearest excluded
        // neighbor on each unexhausted side.  On failure, fall back to
        // the exact full scan for this query.
        bool certified = true;
        if (got == k) {
            int cp_k = common_bits(q, sorted_ids +
                                   (int64_t)row[k - 1] * HASH_LEN);
            if (lo >= 0 &&
                cp_k <= common_bits(q, sorted_ids + lo * HASH_LEN))
                certified = false;
            if (hi < n &&
                cp_k <= common_bits(q, sorted_ids + hi * HASH_LEN))
                certified = false;
        } else if (lo >= 0 || hi < n) {
            certified = false;   // fewer than k found but rows excluded
        }
        if (!certified)
            dht_scan_closest(sorted_ids, n, q, 1, k, row);
    }
}

// Exact full-scan oracle: k XOR-closest rows per query by selection scan
// (O(n·k) per query; used for parity tests and small tables).
void dht_scan_closest(const uint8_t* ids, int64_t n,
                      const uint8_t* queries, int64_t nq,
                      int32_t k, int32_t* out) {
    for (int64_t qi = 0; qi < nq; ++qi) {
        const uint8_t* q = queries + qi * HASH_LEN;
        int32_t* row = out + qi * k;
        int32_t got = 0;
        for (int64_t i = 0; i < n; ++i) {
            const uint8_t* cand = ids + i * HASH_LEN;
            // insertion position among current results
            int32_t p = got;
            while (p > 0 &&
                   xor_cmp(q, cand, ids + (int64_t)row[p - 1] * HASH_LEN) < 0)
                --p;
            if (p < k) {
                int32_t end = got < k ? got : k - 1;
                for (int32_t m = end; m > p; --m) row[m] = row[m - 1];
                row[p] = (int32_t)i;
                if (got < k) ++got;
            }
        }
        for (; got < k; ++got) row[got] = -1;
    }
}

} // extern "C"
