"""Compile + load the native library (g++ → shared object → ctypes).

The build is lazy and cached: sources are hashed, the .so lands in
``$OPENDHT_TPU_CACHE`` (default ``~/.cache/opendht_tpu``), and a rebuild
only happens when the sources change.  No toolchain / failed build ⇒
``get_lib()`` returns None and callers use their Python fallbacks.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger("opendht_tpu.native")

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ("xor_engine.cpp", "udp_engine.cpp")

_lock = threading.Lock()
_lib: "ctypes.CDLL | None" = None
_tried = False


def _cache_dir() -> str:
    d = os.environ.get("OPENDHT_TPU_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "opendht_tpu")
    os.makedirs(d, exist_ok=True)
    return d


def _src_hash() -> str:
    h = hashlib.sha256()
    for name in _SOURCES:
        with open(os.path.join(_SRC_DIR, name), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _build() -> Optional[str]:
    out = os.path.join(_cache_dir(), "libdht_native_%s.so" % _src_hash())
    if os.path.exists(out):
        return out
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", out + ".tmp"] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(out + ".tmp", out)
        return out
    except (subprocess.SubprocessError, OSError) as e:
        detail = getattr(e, "stderr", b"")
        log.warning("native build failed: %s %s", e,
                    detail.decode(errors="replace") if detail else "")
        return None


def _declare(lib: ctypes.CDLL) -> None:
    u8p, i32p = ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.dht_xor_cmp.restype = ctypes.c_int
    lib.dht_xor_cmp.argtypes = [u8p, u8p, u8p]
    lib.dht_common_bits.restype = ctypes.c_int
    lib.dht_common_bits.argtypes = [u8p, u8p]
    lib.dht_cmp.restype = ctypes.c_int
    lib.dht_cmp.argtypes = [u8p, u8p]
    lib.dht_sort_ids.restype = None
    lib.dht_sort_ids.argtypes = [u8p, i32p, ctypes.c_int64]
    lib.dht_lower_bound.restype = ctypes.c_int64
    lib.dht_lower_bound.argtypes = [u8p, ctypes.c_int64, u8p]
    lib.dht_sorted_closest.restype = None
    lib.dht_sorted_closest.argtypes = [u8p, ctypes.c_int64, u8p,
                                       ctypes.c_int64, ctypes.c_int32,
                                       ctypes.c_int32, i32p]
    lib.dht_scan_closest.restype = None
    lib.dht_scan_closest.argtypes = [u8p, ctypes.c_int64, u8p,
                                     ctypes.c_int64, ctypes.c_int32, i32p]
    lib.dht_udp_create.restype = ctypes.c_void_p
    lib.dht_udp_create.argtypes = [ctypes.c_uint16, ctypes.c_uint32,
                                   ctypes.c_uint32, ctypes.c_uint32,
                                   ctypes.c_int32, ctypes.c_int32]
    lib.dht_udp_port.restype = ctypes.c_uint16
    lib.dht_udp_port.argtypes = [ctypes.c_void_p]
    lib.dht_udp_has_v6.restype = ctypes.c_int32
    lib.dht_udp_has_v6.argtypes = [ctypes.c_void_p]
    lib.dht_udp_destroy.restype = None
    lib.dht_udp_destroy.argtypes = [ctypes.c_void_p]
    lib.dht_udp_send.restype = ctypes.c_int
    lib.dht_udp_send.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint32,
                                 u8p, ctypes.c_int32, ctypes.c_uint16]
    lib.dht_udp_poll.restype = ctypes.c_int32
    lib.dht_udp_poll.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64,
                                 ctypes.c_int32, u64p]
    lib.dht_udp_pending.restype = ctypes.c_int32
    lib.dht_udp_pending.argtypes = [ctypes.c_void_p]
    lib.dht_udp_wait.restype = ctypes.c_int32
    lib.dht_udp_wait.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.dht_udp_stats.restype = None
    lib.dht_udp_stats.argtypes = [ctypes.c_void_p, u64p]


def get_lib() -> "ctypes.CDLL | None":
    """The loaded native library, building it on first call; None when
    unavailable (callers fall back to Python)."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
            _declare(lib)
            _lib = lib
        except OSError as e:
            log.warning("native load failed: %s", e)
        return _lib


def available() -> bool:
    return get_lib() is not None
