"""Native (C++) runtime components, consumed via ctypes.

The reference is a C++ library end to end; this package provides the
TPU framework's native host-side pieces (the device compute path stays
JAX/Pallas):

- :func:`xor_cmp` / :func:`common_bits` / :func:`sorted_closest` /
  :func:`scan_closest` — the scalar XOR-metric kernels and the
  sorted-map outward walk (reference include/opendht/infohash.h:149-210,
  src/node_cache.cpp:41-74) for per-packet host ops and honest CPU
  baselines.
- :class:`UdpEngine` — native datagram ingress/egress with a C++
  receiver thread, ring buffer, martian filter, and global/per-IP rate
  limiting (reference src/dhtrunner.cpp:511-608,
  network_engine.h:424,519-523).

The shared library is compiled on first use with g++ into
``~/.cache/opendht_tpu`` (or ``$OPENDHT_TPU_CACHE``); :func:`available`
reports whether it loaded.  Callers must degrade gracefully when it
didn't (pure-Python paths exist everywhere this package is used).
"""

from .build import available, get_lib
from .wrappers import (UdpEngine, common_bits, scan_closest,
                       sorted_closest, sort_ids, xor_cmp)

__all__ = ["available", "get_lib", "xor_cmp", "common_bits", "sort_ids",
           "sorted_closest", "scan_closest", "UdpEngine"]
