"""Pythonic wrappers over the native C ABI (see build.py)."""

from __future__ import annotations

import ctypes
import socket
import struct
from typing import List, Optional, Tuple

import numpy as np

from .build import get_lib

_HASH_LEN = 20


def _u8(buf) -> "ctypes.POINTER(ctypes.c_uint8)":
    return (ctypes.c_uint8 * len(buf)).from_buffer_copy(bytes(buf))


def _lib():
    lib = get_lib()
    if lib is None:
        raise RuntimeError(
            "native library unavailable (no C++ toolchain or build failed); "
            "check opendht_tpu.native.available() before calling")
    return lib


def _rows(arr) -> np.ndarray:
    a = np.ascontiguousarray(np.asarray(arr, dtype=np.uint8))
    if a.ndim != 2 or a.shape[1] != _HASH_LEN:
        raise ValueError("expected [N, 20] uint8 id matrix")
    return a


def xor_cmp(self_id: bytes, a: bytes, b: bytes) -> int:
    """infohash.h:179-194 semantics; requires the native lib."""
    lib = _lib()
    return lib.dht_xor_cmp(_u8(self_id), _u8(a), _u8(b))


def common_bits(a: bytes, b: bytes) -> int:
    lib = _lib()
    return lib.dht_common_bits(_u8(a), _u8(b))


def sort_ids(ids) -> Tuple[np.ndarray, np.ndarray]:
    """Lexicographic sort of an [N,20] id matrix; returns
    (sorted_ids, perm int32[N])."""
    lib = _lib()
    a = _rows(ids).copy()
    perm = np.empty(a.shape[0], dtype=np.int32)
    lib.dht_sort_ids(
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        perm.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        a.shape[0])
    return a, perm


def sorted_closest(sorted_ids, queries, k: int = 8,
                   window: int = 64) -> np.ndarray:
    """Window-collected outward walk + exact select: the reference's
    sorted-map walk (node_cache.cpp:41-74) hardened to exact k-closest
    (window plays the same role as the device kernel's, see
    ops/sorted_table.py).  Returns int32 [Q,k] sorted-table indices,
    -1 padded."""
    lib = _lib()
    t = _rows(sorted_ids)
    q = _rows(queries)
    out = np.empty((q.shape[0], k), dtype=np.int32)
    lib.dht_sorted_closest(
        t.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), t.shape[0],
        q.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), q.shape[0],
        k, window, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out


def scan_closest(ids, queries, k: int = 8) -> np.ndarray:
    """Exact full-scan oracle (insertion scan), int32 [Q,k]."""
    lib = _lib()
    t = _rows(ids)
    q = _rows(queries)
    out = np.empty((q.shape[0], k), dtype=np.int32)
    lib.dht_scan_closest(
        t.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), t.shape[0],
        q.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), q.shape[0],
        k, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out


class UdpEngine:
    """Native dual-stack datagram engine: C++ receiver thread + ring
    buffer + ingress guards over an IPv4 and (optionally) an IPv6-only
    socket on the same port; Python drains packets in batches.

    ↔ reference rcv_thread select loop over both sockets
    (dhtrunner.cpp:511-608) and NetworkEngine ingress rate limits /
    martian filter (network_engine.h:424, network_engine.cpp:339-401).
    """

    _HDR = struct.Struct("<dB16sHH")

    def __init__(self, port: int = 0, *, ring_size: int = 16384,
                 global_rps: int = 1600, per_ip_rps: int = 200,
                 exempt_loopback: bool = True, ipv6: bool = True):
        lib = _lib()
        self._lib = lib
        self._h = lib.dht_udp_create(port, ring_size, global_rps, per_ip_rps,
                                     1 if exempt_loopback else 0,
                                     1 if ipv6 else 0)
        if not self._h:
            raise OSError("could not bind UDP port %d" % port)
        self._owned = True
        self.port = lib.dht_udp_port(self._h)
        self.has_v6 = bool(lib.dht_udp_has_v6(self._h))
        self._buf = (ctypes.c_uint8 * (64 * 1024))()
        self._nbytes = ctypes.c_uint64(0)

    def send(self, data: bytes, addr: Tuple[str, int]) -> int:
        host = addr[0]
        if ":" in host:
            packed = socket.inet_pton(socket.AF_INET6, host)
            fam = 6
        else:
            packed = socket.inet_aton(host)
            fam = 4
        return self._lib.dht_udp_send(self._h, _u8(data), len(data),
                                      _u8(packed.ljust(16, b"\0")), fam,
                                      addr[1])

    def poll(self, max_pkts: int = 256
             ) -> List[Tuple[float, bytes, Tuple[str, int]]]:
        """Drain up to max_pkts received packets as
        (rx_time, data, (host, port)) tuples; host is a textual v4 or
        v6 address."""
        out: List[Tuple[float, bytes, Tuple[str, int]]] = []
        while len(out) < max_pkts:
            n = self._lib.dht_udp_poll(
                self._h, self._buf, len(self._buf),
                max_pkts - len(out), ctypes.byref(self._nbytes))
            if n <= 0:
                break
            raw = bytes(self._buf[:self._nbytes.value])
            off = 0
            for _ in range(n):
                rx_time, fam, a16, port, ln = self._HDR.unpack_from(raw, off)
                off += self._HDR.size
                data = raw[off:off + ln]
                off += ln
                if fam == 6:
                    host = socket.inet_ntop(socket.AF_INET6, a16)
                else:
                    host = socket.inet_ntoa(a16[:4])
                out.append((rx_time, data, (host, port)))
        return out

    def pending(self) -> bool:
        return bool(self._lib.dht_udp_pending(self._h))

    def wait(self, timeout: float = 0.1) -> bool:
        """Block (GIL released) until a packet is pending or timeout;
        returns whether packets are pending."""
        return bool(self._lib.dht_udp_wait(self._h, int(timeout * 1000)))

    def stats(self) -> dict:
        s = (ctypes.c_uint64 * 6)()
        self._lib.dht_udp_stats(self._h, s)
        return {"rx": s[0], "tx": s[1], "dropped_ring": s[2],
                "dropped_rate": s[3], "dropped_martian": s[4],
                "queued": s[5]}

    def close(self) -> None:
        if self._h and self._owned:
            self._lib.dht_udp_destroy(self._h)
            self._h = None

    def detach(self) -> None:
        """Give up ownership without freeing the engine.  Used when a
        receiver thread may still be blocked inside wait()/poll(): a
        destroy would free the Engine under that thread (use-after-free),
        so the owner deliberately leaks it.  ``_h`` stays valid — the
        stuck thread may still be dereferencing it — only the ownership
        flag flips, so close()/__del__ become no-ops."""
        self._owned = False

    def __enter__(self) -> "UdpEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
