// Native UDP datagram engine (dual-stack).
//
// C++ implementation of the runtime's packet ingress/egress — the role
// the reference's rcv_thread + NetworkEngine ingress guards play
// (reference: src/dhtrunner.cpp:511-608 select loop over the v4+v6
// sockets + bounded queue; include/opendht/network_engine.h:424,519-523
// global/per-IP rate limits; src/network_engine.cpp:361-386 martian
// filter).
//
// Design: one engine owns a bound IPv4 socket and (optionally) an
// IPv6-only socket on the same port; one receiver thread polls both and
// timestamps datagrams into a fixed ring buffer.  Python drains the
// ring in batches (one ctypes call for many packets) instead of one
// recvfrom syscall + allocation per packet through the interpreter.
// Rate limiting and martian filtering run natively before a packet ever
// reaches Python.
//
// C ABI only (ctypes).  Addresses cross the ABI as
// (family u8, addr u8[16], port u16) — v4 uses the first 4 addr bytes.

#include <arpa/inet.h>
#include <array>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <netinet/in.h>
#include <poll.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

constexpr int MAX_PACKET = 1500;

double now_s() {
    return std::chrono::duration<double>(
        std::chrono::steady_clock::now().time_since_epoch()).count();
}

// sliding-window quota (reference: include/opendht/rate_limiter.h:26-48)
struct RateWindow {
    std::vector<double> hits;
    size_t quota;
    double period;
    RateWindow(size_t q = 0, double p = 1.0) : quota(q), period(p) {}
    bool limit(double now) {
        if (quota == 0) return true;           // disabled
        while (!hits.empty() && hits.front() < now - period)
            hits.erase(hits.begin());
        if (hits.size() >= quota) return false;
        hits.push_back(now);
        return true;
    }
};

struct Packet {
    double rx_time;
    uint8_t family;                            // 4 or 6
    uint8_t addr[16];                          // v4 in first 4 bytes
    uint16_t port;
    uint16_t len;
    uint8_t data[MAX_PACKET];
};

struct Engine {
    int fd4 = -1;
    int fd6 = -1;                              // <0 when v6 disabled
    uint16_t bound_port = 0;
    std::thread rcv;
    std::atomic<bool> running{false};

    std::vector<Packet> ring;
    size_t head = 0, tail = 0;                 // ring indices
    std::mutex mtx;
    std::condition_variable cv;                // signalled on enqueue

    RateWindow global_limit;
    std::unordered_map<std::string, RateWindow> ip_limits;  // 16-byte key
    size_t per_ip_quota = 0;
    double last_prune = 0.0;
    bool drop_martian = true;
    bool exempt_loopback = true;

    std::atomic<uint64_t> rx_count{0}, dropped_ring{0}, dropped_rate{0},
        dropped_martian{0}, tx_count{0};
};

bool is_martian_v4(const uint8_t* a4, uint16_t port) {
    // (network_engine.cpp:361-386): zero port, 0.0.0.0/8, 224/4
    // multicast; 127/8 is allowed for localhost operation here (the
    // reference drops it only on non-local builds)
    if (port == 0) return true;
    if (a4[0] == 0) return true;
    if (a4[0] >= 224 && a4[0] <= 239) return true;
    return false;
}

bool is_martian_v6(const uint8_t* a, uint16_t port) {
    // (network_engine.cpp:372-383): zero port, multicast ff00::/8,
    // link-local fe80::/10, the unspecified address, v4-mapped
    // ::ffff:0:0/96.  ::1 is allowed for localhost operation.
    if (port == 0) return true;
    if (a[0] == 0xFF) return true;
    if (a[0] == 0xFE && (a[1] & 0xC0) == 0x80) return true;
    static const uint8_t zeros[16] = {0};
    if (std::memcmp(a, zeros, 16) == 0) return true;
    static const uint8_t mapped[12] = {0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                                       0xFF, 0xFF};
    if (std::memcmp(a, mapped, 12) == 0) return true;
    return false;
}

bool is_loopback(uint8_t family, const uint8_t* a) {
    if (family == 4) return a[0] == 127;
    static const uint8_t v6lo[16] = {0, 0, 0, 0, 0, 0, 0, 0,
                                     0, 0, 0, 0, 0, 0, 0, 1};
    return std::memcmp(a, v6lo, 16) == 0;
}

void handle_datagram(Engine* e, uint8_t family, const uint8_t* addr,
                     uint16_t port, const uint8_t* buf, ssize_t n) {
    double now = now_s();
    bool martian = (family == 4) ? is_martian_v4(addr, port)
                                 : is_martian_v6(addr, port);
    if (e->drop_martian && martian) {
        e->dropped_martian++;
        return;
    }
    // loopback traffic is exempt from rate limiting: local clusters
    // legitimately share 127.0.0.1/::1 as the source, and the limits
    // exist for remote floods
    bool loopback = e->exempt_loopback && is_loopback(family, addr);
    {
        std::lock_guard<std::mutex> lk(e->mtx);
        if (!loopback && !e->global_limit.limit(now)) {
            e->dropped_rate++;
            return;
        }
        if (!loopback && e->per_ip_quota) {
            // bound the per-IP map: spoofed-source floods must not grow
            // memory without limit — evict idle windows once the map
            // gets large, at most once per second (an O(n) sweep per
            // packet would itself be the DoS)
            if (e->ip_limits.size() > 4096 && now - e->last_prune > 1.0) {
                e->last_prune = now;
                for (auto it = e->ip_limits.begin();
                     it != e->ip_limits.end();) {
                    auto& w2 = it->second;
                    if (w2.hits.empty() || w2.hits.back() < now - w2.period)
                        it = e->ip_limits.erase(it);
                    else
                        ++it;
                }
            }
            std::string key((const char*)addr, family == 4 ? 4 : 16);
            auto& w = e->ip_limits[key];
            if (w.quota == 0) w = RateWindow(e->per_ip_quota, 1.0);
            if (!w.limit(now)) {
                e->dropped_rate++;
                return;
            }
        }
        size_t next = (e->head + 1) % e->ring.size();
        if (next == e->tail) {                 // ring full → drop oldest
            e->tail = (e->tail + 1) % e->ring.size();
            e->dropped_ring++;
        }
        Packet& p = e->ring[e->head];
        p.rx_time = now;
        p.family = family;
        std::memset(p.addr, 0, sizeof(p.addr));
        std::memcpy(p.addr, addr, family == 4 ? 4 : 16);
        p.port = port;
        p.len = (uint16_t)n;
        std::memcpy(p.data, buf, n);
        e->head = next;
    }
    e->cv.notify_all();
    e->rx_count++;
}

void drain_fd(Engine* e, int fd) {
    for (;;) {
        sockaddr_storage from{};
        socklen_t fl = sizeof(from);
        uint8_t buf[MAX_PACKET];
        ssize_t n = recvfrom(fd, buf, sizeof(buf), MSG_DONTWAIT,
                             (sockaddr*)&from, &fl);
        if (n <= 0) break;
        if (from.ss_family == AF_INET) {
            auto* sin = (sockaddr_in*)&from;
            handle_datagram(e, 4, (const uint8_t*)&sin->sin_addr,
                            ntohs(sin->sin_port), buf, n);
        } else if (from.ss_family == AF_INET6) {
            auto* sin6 = (sockaddr_in6*)&from;
            handle_datagram(e, 6, (const uint8_t*)&sin6->sin6_addr,
                            ntohs(sin6->sin6_port), buf, n);
        }
    }
}

void rcv_loop(Engine* e) {
    struct pollfd pfds[2];
    int nfds = 0;
    pfds[nfds++] = {e->fd4, POLLIN, 0};
    if (e->fd6 >= 0) pfds[nfds++] = {e->fd6, POLLIN, 0};
    while (e->running.load(std::memory_order_relaxed)) {
        int r = poll(pfds, nfds, 100);
        if (r <= 0) continue;
        for (int i = 0; i < nfds; ++i)
            if (pfds[i].revents & POLLIN) drain_fd(e, pfds[i].fd);
    }
}

} // namespace

extern "C" {

// returns an opaque handle, or null on failure.  enable_v6 != 0 also
// binds an IPv6-only socket on the same port (best effort: v6 bind
// failure leaves a v4-only engine — check dht_udp_has_v6).
void* dht_udp_create(uint16_t port, uint32_t ring_size,
                     uint32_t global_rps, uint32_t per_ip_rps,
                     int32_t exempt_loopback, int32_t enable_v6) {
    Engine* e = new Engine();
    e->exempt_loopback = exempt_loopback != 0;
    e->fd4 = socket(AF_INET, SOCK_DGRAM, 0);
    if (e->fd4 < 0) { delete e; return nullptr; }
    int one = 1;
    setsockopt(e->fd4, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(port);
    if (bind(e->fd4, (sockaddr*)&addr, sizeof(addr)) != 0) {
        close(e->fd4);
        delete e;
        return nullptr;
    }
    socklen_t alen = sizeof(addr);
    getsockname(e->fd4, (sockaddr*)&addr, &alen);
    e->bound_port = ntohs(addr.sin_port);

    if (enable_v6) {
        e->fd6 = socket(AF_INET6, SOCK_DGRAM, 0);
        if (e->fd6 >= 0) {
            setsockopt(e->fd6, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
            setsockopt(e->fd6, IPPROTO_IPV6, IPV6_V6ONLY, &one, sizeof(one));
            sockaddr_in6 a6{};
            a6.sin6_family = AF_INET6;
            a6.sin6_addr = in6addr_any;
            a6.sin6_port = htons(e->bound_port);
            if (bind(e->fd6, (sockaddr*)&a6, sizeof(a6)) != 0) {
                close(e->fd6);
                e->fd6 = -1;
            }
        }
    }

    e->ring.resize(ring_size ? ring_size : 16384);
    // defaults mirror network_engine.h:424 (1600 global, 200 per-IP rps)
    e->global_limit = RateWindow(global_rps, 1.0);
    e->per_ip_quota = per_ip_rps;
    e->running = true;
    e->rcv = std::thread(rcv_loop, e);
    return e;
}

uint16_t dht_udp_port(void* h) { return ((Engine*)h)->bound_port; }

int32_t dht_udp_has_v6(void* h) { return ((Engine*)h)->fd6 >= 0 ? 1 : 0; }

void dht_udp_destroy(void* h) {
    Engine* e = (Engine*)h;
    e->running = false;
    if (e->rcv.joinable()) e->rcv.join();
    if (e->fd4 >= 0) close(e->fd4);
    if (e->fd6 >= 0) close(e->fd6);
    delete e;
}

// family 4: addr16's first 4 bytes; family 6: all 16 bytes.
int dht_udp_send(void* h, const uint8_t* data, uint32_t len,
                 const uint8_t* addr16, int32_t family, uint16_t port) {
    Engine* e = (Engine*)h;
    ssize_t n = -1;
    if (family == 4) {
        sockaddr_in to{};
        to.sin_family = AF_INET;
        std::memcpy(&to.sin_addr, addr16, 4);
        to.sin_port = htons(port);
        n = sendto(e->fd4, data, len, 0, (sockaddr*)&to, sizeof(to));
    } else if (family == 6 && e->fd6 >= 0) {
        sockaddr_in6 to{};
        to.sin6_family = AF_INET6;
        std::memcpy(&to.sin6_addr, addr16, 16);
        to.sin6_port = htons(port);
        n = sendto(e->fd6, data, len, 0, (sockaddr*)&to, sizeof(to));
    } else {
        return EAFNOSUPPORT;
    }
    if (n == (ssize_t)len) { e->tx_count++; return 0; }
    return errno ? errno : -1;
}

// Drain up to max_pkts packets.  Layout per packet in out:
//   f64 rx_time | u8 family | u8 addr[16] | u16 port | u16 len | u8 data[len]
// Returns the number of packets written; out_bytes receives bytes used.
int32_t dht_udp_poll(void* h, uint8_t* out, uint64_t out_cap,
                     int32_t max_pkts, uint64_t* out_bytes) {
    Engine* e = (Engine*)h;
    int32_t count = 0;
    uint64_t off = 0;
    std::lock_guard<std::mutex> lk(e->mtx);
    while (count < max_pkts && e->tail != e->head) {
        Packet& p = e->ring[e->tail];
        uint64_t need = 8 + 1 + 16 + 2 + 2 + p.len;
        if (off + need > out_cap) break;
        std::memcpy(out + off, &p.rx_time, 8); off += 8;
        out[off++] = p.family;
        std::memcpy(out + off, p.addr, 16); off += 16;
        std::memcpy(out + off, &p.port, 2); off += 2;
        std::memcpy(out + off, &p.len, 2); off += 2;
        std::memcpy(out + off, p.data, p.len); off += p.len;
        e->tail = (e->tail + 1) % e->ring.size();
        ++count;
    }
    *out_bytes = off;
    return count;
}

// has packets waiting?
int32_t dht_udp_pending(void* h) {
    Engine* e = (Engine*)h;
    std::lock_guard<std::mutex> lk(e->mtx);
    return e->tail != e->head ? 1 : 0;
}

// Block until a packet is pending or timeout_ms elapses; returns 1 if
// pending.  ctypes releases the GIL around the call, so a Python waiter
// thread can sleep here without starving the interpreter.
int32_t dht_udp_wait(void* h, int32_t timeout_ms) {
    Engine* e = (Engine*)h;
    std::unique_lock<std::mutex> lk(e->mtx);
    if (e->tail != e->head) return 1;
    e->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms));
    return e->tail != e->head ? 1 : 0;
}

void dht_udp_stats(void* h, uint64_t* out6) {
    Engine* e = (Engine*)h;
    out6[0] = e->rx_count.load();
    out6[1] = e->tx_count.load();
    out6[2] = e->dropped_ring.load();
    out6[3] = e->dropped_rate.load();
    out6[4] = e->dropped_martian.load();
    std::lock_guard<std::mutex> lk(e->mtx);
    out6[5] = (e->head + e->ring.size() - e->tail) % e->ring.size();
}

} // extern "C"
