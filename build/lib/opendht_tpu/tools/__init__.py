"""CLI tools (↔ reference tools/): dhtnode interactive node/daemon,
dhtchat minimal IM, dhtscanner keyspace census, plus shared argv/identity
helpers (↔ tools/tools_common.h)."""
