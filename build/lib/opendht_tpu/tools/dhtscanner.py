"""dhtscanner: census the network by walking the keyspace
(↔ reference tools/dhtscanner.cpp:40-135: search successive ids spread
over the ring, collecting every node seen in replies)."""

from __future__ import annotations

import socket
import sys
import time

from ..infohash import InfoHash
from .common import make_arg_parser, print_node_info, setup_node


def scan(node, rounds: int = 32, timeout: float = 15.0) -> dict:
    """Issue `rounds` gets at ids evenly spaced over the 160-bit ring;
    harvest the union of nodes from the routing table after each
    (dhtscanner.cpp:52-99 steps a prefix counter the same way)."""
    seen = {}
    for i in range(rounds):
        target = InfoHash.from_int((i << 152) | (1 << 151))
        done = []
        node.get(target, lambda vals: True,
                 lambda ok, nodes: done.append([
                     (n.id, n.addr) for n in nodes or []]))
        t0 = time.monotonic()
        while not done and time.monotonic() - t0 < timeout:
            time.sleep(0.02)
        for nid, addr in (done[0] if done else []):
            seen[nid] = addr
        print("scan %2d/%d: target %s…, %d nodes known"
              % (i + 1, rounds, str(target)[:8], len(seen)))
    return seen


def main(argv=None) -> int:
    p = make_arg_parser("OpenDHT-TPU network scanner")
    p.add_argument("--rounds", type=int, default=32,
                   help="number of keyspace probes")
    args = p.parse_args(argv)
    node = setup_node(args)
    print_node_info(node)
    try:
        # wait for connectivity before scanning (dhtscanner.cpp:109-117)
        from ..runtime.config import NodeStatus
        t0 = time.monotonic()
        while (node.get_status() is not NodeStatus.CONNECTED
               and time.monotonic() - t0 < 30.0):
            time.sleep(0.1)
        seen = scan(node, args.rounds)
        print("\n%d nodes discovered:" % len(seen))
        for nid, addr in sorted(seen.items(), key=lambda kv: str(kv[0])):
            print("  %s  %s" % (nid, addr))
        stats = node.get_node_stats(socket.AF_INET)
        print("network size estimation: %d"
              % stats.get_network_size_estimation())
    finally:
        node.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
