"""Socket address wrapper (reference include/opendht/sockaddr.h).

A small immutable (ip, port, family) value object built on the stdlib
``ipaddress`` module instead of raw ``sockaddr_storage``: family/port
accessors, ``resolve()`` via getaddrinfo (sockaddr.h:91), loopback /
private-range predicates (sockaddr.h:219-224), an ``ip_cmp`` comparator
that ignores the port (sockaddr.h:235), and the compact wire form
(4B/16B address ‖ 2B big-endian port) used in node blobs
(src/network_engine.cpp:1002-1050).
"""

from __future__ import annotations

import ipaddress
import socket
from functools import total_ordering
from typing import Iterable


@total_ordering
class SockAddr:
    __slots__ = ("_ip", "_port")

    def __init__(self, host: "str | bytes | ipaddress._BaseAddress | None" = None,
                 port: int = 0):
        if host is None or host == "":
            self._ip = None
        elif isinstance(host, (bytes, bytearray, memoryview)):
            self._ip = ipaddress.ip_address(bytes(host))
        elif isinstance(host, (ipaddress.IPv4Address, ipaddress.IPv6Address)):
            self._ip = host
        else:
            self._ip = ipaddress.ip_address(host)
        self._port = int(port)

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_tuple(cls, addr: tuple) -> "SockAddr":
        """From an asyncio/socket address tuple (host, port[, flow, scope])."""
        return cls(addr[0], addr[1])

    @classmethod
    def resolve(cls, host: str, service: "str | int | None" = None) -> "list[SockAddr]":
        """All addresses of host:service via getaddrinfo (sockaddr.h:91)."""
        port = int(service) if service not in (None, "") else 0
        out, seen = [], set()
        for *_, sockaddr in socket.getaddrinfo(
                host, port or None, proto=socket.IPPROTO_UDP):
            sa = cls(sockaddr[0], sockaddr[1] or port)
            key = (sa._ip, sa._port)
            if key not in seen:
                seen.add(key)
                out.append(sa)
        return out

    # -- accessors ---------------------------------------------------------
    @property
    def family(self) -> int:
        """AF_INET / AF_INET6 / AF_UNSPEC(0) (sockaddr.h:150-158)."""
        if self._ip is None:
            return socket.AF_UNSPEC
        return socket.AF_INET if self._ip.version == 4 else socket.AF_INET6

    @property
    def port(self) -> int:
        return self._port

    @property
    def host(self) -> str:
        return str(self._ip) if self._ip is not None else ""

    @property
    def ip(self):
        return self._ip

    def with_port(self, port: int) -> "SockAddr":
        return SockAddr(self._ip, port)

    def __bool__(self) -> bool:
        return self._ip is not None

    # -- predicates (sockaddr.h:219-224) -----------------------------------
    def is_loopback(self) -> bool:
        return self._ip is not None and self._ip.is_loopback

    def is_private(self) -> bool:
        """RFC1918/link-local — used by the martian filter."""
        return self._ip is not None and (self._ip.is_private or self._ip.is_link_local)

    def is_unspecified(self) -> bool:
        return self._ip is None or self._ip.is_unspecified

    def is_multicast(self) -> bool:
        return self._ip is not None and self._ip.is_multicast

    def is_global(self) -> bool:
        return self._ip is not None and self._ip.is_global

    # -- ordering / equality ----------------------------------------------
    def _key(self):
        ip = self._ip
        return (0 if ip is None else ip.version,
                b"" if ip is None else ip.packed,
                self._port)

    def __eq__(self, other) -> bool:
        return isinstance(other, SockAddr) and self._key() == other._key()

    def __lt__(self, other) -> bool:
        return self._key() < other._key()

    def __hash__(self) -> int:
        return hash(self._key())

    def ip_cmp(self, other: "SockAddr") -> int:
        """Compare addresses ignoring ports (sockaddr.h:235)."""
        a, b = self._key()[:2], other._key()[:2]
        return -1 if a < b else (1 if a > b else 0)

    def same_ip(self, other: "SockAddr") -> bool:
        return self.ip_cmp(other) == 0

    # -- conversions -------------------------------------------------------
    def to_tuple(self) -> tuple:
        """(host, port) for sendto / asyncio."""
        return (self.host, self._port)

    def to_compact(self) -> bytes:
        """Compact wire form: packed address ‖ 2B big-endian port — the
        payload of n4/n6 node blobs and the 'sa' echo
        (network_engine.cpp:636-645, 1002-1050)."""
        if self._ip is None:
            return b""
        return self._ip.packed + self._port.to_bytes(2, "big")

    @classmethod
    def from_compact(cls, data: bytes) -> "SockAddr":
        if len(data) == 6:
            return cls(bytes(data[:4]), int.from_bytes(data[4:6], "big"))
        if len(data) == 18:
            return cls(bytes(data[:16]), int.from_bytes(data[16:18], "big"))
        raise ValueError(f"bad compact sockaddr length {len(data)}")

    def __repr__(self) -> str:
        if self._ip is None:
            return "SockAddr()"
        if self._ip.version == 6:
            return f"[{self.host}]:{self._port}"
        return f"{self.host}:{self._port}"

    def toString(self) -> str:  # reference-style alias
        return repr(self)


def match_family(addrs: Iterable[SockAddr], family: int) -> "list[SockAddr]":
    return [a for a in addrs if a.family == family]
