"""Logging subsystem (↔ reference include/opendht/log_enable.h:35-190,
include/opendht/log.h:20-82, src/log.cpp).

The reference's ``Logger`` carries three printf-style streams
(ERR/WARN/DEBUG), an optional per-InfoHash filter that silences
everything not about one key, and pluggable sinks (colored console,
file, syslog).  This module provides the same surface on top of the
stdlib ``logging`` machinery the rest of the package already uses:

- :class:`DhtLogger` — e/w/d streams, per-hash filtering
  (``set_filter``), and sink management (``set_sink_console`` /
  ``set_sink_file`` / ``set_sink_syslog``).
- The filter is a ``logging.Filter`` on the sink handler keyed on the
  ``dht_hash`` record attribute, so it applies to *every* record that
  reaches the sink — core runtime logs included, as long as they tag
  records via ``extra={"dht_hash": ...}`` (the e/w/d streams do this
  with their ``h=`` argument).  When a filter is set, untagged records
  are suppressed, matching the reference's "show only this hash" mode.
- Enabling a sink captures the target logger's level/propagate state
  and ``disable()`` restores it, so an embedding application's own
  logging configuration survives.
"""

from __future__ import annotations

import logging
import sys
import time
from typing import Optional

from .infohash import InfoHash

_COLORS = {"ERR": "\x1b[31m", "WARN": "\x1b[33m", "DEBUG": "\x1b[90m"}
_RESET = "\x1b[0m"


class _ColorFormatter(logging.Formatter):
    """Colored console lines (↔ the reference's enableLogging console
    sink with per-level colors, src/log.cpp)."""

    def __init__(self, color: bool):
        super().__init__()
        self.color = color

    def format(self, record: logging.LogRecord) -> str:
        level = {"ERROR": "ERR", "WARNING": "WARN"}.get(
            record.levelname, "DEBUG")
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        line = "[%s] %s: %s" % (ts, level, record.getMessage())
        if self.color:
            return _COLORS.get(level, "") + line + _RESET
        return line


class _HashFilter(logging.Filter):
    """Pass everything when unset; with a hash set, pass only records
    tagged with it (↔ Logger::setFilter, log_enable.h:77-90)."""

    def __init__(self):
        super().__init__()
        self.hash: Optional[InfoHash] = None

    def filter(self, record: logging.LogRecord) -> bool:
        if self.hash is None:
            return True
        tag = getattr(record, "dht_hash", None)
        if tag is None:
            return False
        try:
            return InfoHash(tag) == self.hash
        except Exception:
            return False


class DhtLogger:
    """ERR/WARN/DEBUG streams with per-InfoHash filtering
    (log_enable.h:35-190)."""

    def __init__(self, name: str = "opendht_tpu"):
        self._logger = logging.getLogger(name)
        self._filter = _HashFilter()
        self._handler: Optional[logging.Handler] = None
        self._saved_state: "tuple | None" = None

    # ------------------------------------------------------------- streams
    def _emit(self, level: int, fmt: str, args: tuple, h) -> None:
        extra = {"dht_hash": bytes(InfoHash(h))} if h is not None else None
        self._logger.log(level, fmt, *args, extra=extra)

    def e(self, fmt: str, *args, h=None) -> None:
        self._emit(logging.ERROR, fmt, args, h)

    def w(self, fmt: str, *args, h=None) -> None:
        self._emit(logging.WARNING, fmt, args, h)

    def d(self, fmt: str, *args, h=None) -> None:
        self._emit(logging.DEBUG, fmt, args, h)

    # ------------------------------------------------------------ filtering
    def set_filter(self, h: "InfoHash | None") -> None:
        """Only emit messages tagged with this hash; None clears."""
        self._filter.hash = InfoHash(h) if h else None

    # --------------------------------------------------------------- sinks
    def _swap_handler(self, handler: logging.Handler) -> None:
        if self._saved_state is None:
            # first sink: capture the embedding app's configuration
            self._saved_state = (self._logger.level, self._logger.propagate)
            self._logger.setLevel(logging.DEBUG)
            self._logger.propagate = False
        if self._handler is not None:
            self._logger.removeHandler(self._handler)
            self._handler.close()
        handler.addFilter(self._filter)
        self._handler = handler
        self._logger.addHandler(handler)

    def set_sink_console(self, color: Optional[bool] = None) -> None:
        """(↔ log::enableLogging, log.h:20-40)"""
        if color is None:
            color = sys.stderr.isatty()
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(_ColorFormatter(color))
        self._swap_handler(h)

    def set_sink_file(self, path: str) -> None:
        """(↔ log::enableFileLogging, log.h:42-60)"""
        h = logging.FileHandler(path)
        h.setFormatter(_ColorFormatter(False))
        self._swap_handler(h)

    def set_sink_syslog(self, ident: str = "dhtnode") -> None:
        """(↔ OPENDHT_SYSLOG enableSyslog, log.h:62-82)"""
        from logging.handlers import SysLogHandler
        h = SysLogHandler(address="/dev/log")
        h.setFormatter(logging.Formatter(ident + ": %(message)s"))
        self._swap_handler(h)

    def disable(self) -> None:
        """Detach the sink and restore the logger's prior configuration
        (↔ log::disableLogging)."""
        if self._handler is not None:
            self._logger.removeHandler(self._handler)
            self._handler.close()
            self._handler = None
        if self._saved_state is not None:
            level, propagate = self._saved_state
            self._logger.setLevel(level)
            self._logger.propagate = propagate
            self._saved_state = None
