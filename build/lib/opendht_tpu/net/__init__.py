"""L1 — host network engine: the msgpack wire protocol and request
lifecycle of the DHT (reference src/network_engine.cpp et al.), kept
transport-agnostic: the engine serializes/parses packets and manages
retries/fragmentation/rate limits; actual datagram IO is a callable
injected by the runtime (asyncio UDP, the native C++ engine, or a test
harness wiring two engines back-to-back)."""

from .node import Node, Socket, NODE_GOOD_TIME, NODE_EXPIRE_TIME, MAX_RESPONSE_TIME  # noqa: F401
from .node_cache import NodeCache  # noqa: F401
from .request import Request, RequestState, MAX_ATTEMPT_COUNT  # noqa: F401
from .parsed_message import MessageType, ParsedMessage  # noqa: F401
from .engine import (  # noqa: F401
    DhtProtocolException, EngineCallbacks, NetworkEngine, RequestAnswer,
)
