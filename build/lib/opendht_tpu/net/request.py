"""In-flight RPC state machine (reference src/request.h).

PENDING → COMPLETED (reply matched by tid) | EXPIRED (3 attempts × 1 s
timed out) | CANCELLED.  ``on_expired(req, done)`` fires once with
done=False after the first re-attempt (early hint used to solicit other
candidates) and once with done=True on final expiry."""

from __future__ import annotations

import enum
from typing import TYPE_CHECKING, Callable, Optional

from .node import MAX_RESPONSE_TIME, Node

if TYPE_CHECKING:
    from .parsed_message import MessageType, ParsedMessage

MAX_ATTEMPT_COUNT = 3           # request.h:108

_NEVER = float("-inf")


class RequestState(enum.Enum):
    PENDING = "pending"
    CANCELLED = "cancelled"
    EXPIRED = "expired"
    COMPLETED = "completed"


class Request:
    __slots__ = ("node", "tid", "type", "msg", "on_done", "on_expired",
                 "socket_id", "state", "attempt_count", "start", "last_try",
                 "reply_time")

    def __init__(self, msg_type: "MessageType", tid: int, node: Node,
                 msg: bytes,
                 on_done: Optional[Callable[["Request", "ParsedMessage"], None]],
                 on_expired: Optional[Callable[["Request", bool], None]],
                 socket_id: int = 0):
        self.node = node
        self.tid = tid
        self.type = msg_type
        self.msg = msg
        self.on_done = on_done
        self.on_expired = on_expired
        self.socket_id = socket_id
        self.state = RequestState.PENDING
        self.attempt_count = 0
        self.start = _NEVER
        self.last_try = _NEVER
        self.reply_time = _NEVER

    # -- state predicates --------------------------------------------------
    @property
    def pending(self) -> bool:
        return self.state is RequestState.PENDING

    @property
    def completed(self) -> bool:
        return self.state is RequestState.COMPLETED

    @property
    def expired(self) -> bool:
        return self.state is RequestState.EXPIRED

    @property
    def cancelled(self) -> bool:
        return self.state is RequestState.CANCELLED

    @property
    def over(self) -> bool:
        return not self.pending

    def is_expired(self, now: float) -> bool:
        """All attempts used and the last one timed out (request.h:110-112).
        ``>=``, not ``>``: retries are scheduled at exactly
        last_try + MAX_RESPONSE_TIME, and discrete-event drivers land on
        that instant — strict compare would retry dead nodes forever."""
        return (self.pending
                and now >= self.last_try + MAX_RESPONSE_TIME
                and self.attempt_count >= MAX_ATTEMPT_COUNT)

    # -- transitions (request.h:88-105) ------------------------------------
    def set_expired(self) -> None:
        if self.pending:
            self.state = RequestState.EXPIRED
            if self.on_expired:
                self.on_expired(self, True)
            self._clear()

    def set_done(self, msg: "ParsedMessage") -> None:
        if self.pending:
            self.state = RequestState.COMPLETED
            if self.on_done:
                self.on_done(self, msg)
            self._clear()

    def cancel(self) -> None:
        if self.pending:
            self.state = RequestState.CANCELLED
            self._clear()

    def close_socket(self) -> int:
        sid = self.socket_id
        self.socket_id = 0
        return sid

    def _clear(self) -> None:
        self.on_done = None
        self.on_expired = None
        self.msg = b""

    def state_char(self) -> str:
        return {"pending": "f", "cancelled": "c", "expired": "e",
                "completed": "a"}[self.state.value]
