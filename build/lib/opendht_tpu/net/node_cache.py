"""Node interning cache (reference include/opendht/node_cache.h,
src/node_cache.cpp).

One weakly-referenced :class:`Node` object per (id, family), shared by
every subsystem so liveness updates are seen everywhere.
``get_cached_nodes`` is the scalar XOR-closest scan: walk a
lexicographically-sorted id index outward from ``lower_bound(id)``
choosing the XOR-closer side each step (node_cache.cpp:41-74) — the
same unimodal-window property the batched device kernel exploits
(opendht_tpu/ops/sorted_table.py)."""

from __future__ import annotations

import bisect
import socket as _socket
import weakref
from typing import Dict, List, Optional

from ..infohash import InfoHash
from ..sockaddr import SockAddr
from .node import Node


class _FamilyCache:
    """Sorted weak map InfoHash → Node for one address family."""

    def __init__(self):
        self._map: Dict[bytes, weakref.ref] = {}
        self._keys: List[bytes] = []        # sorted id bytes

    def _drop(self, key: bytes) -> None:
        self._map.pop(key, None)
        i = bisect.bisect_left(self._keys, key)
        if i < len(self._keys) and self._keys[i] == key:
            del self._keys[i]

    def lookup(self, node_id: InfoHash) -> Optional[Node]:
        key = bytes(node_id)
        ref = self._map.get(key)
        if ref is None:
            return None
        node = ref()
        if node is None:
            self._drop(key)
        return node

    def get_node(self, node_id: InfoHash, addr: SockAddr, now: float,
                 confirm: bool, client: bool) -> Node:
        """(node_cache.cpp:100-112): intern; refresh address if confirmed
        or the cached entry is stale."""
        key = bytes(node_id)
        ref = self._map.get(key)
        node = ref() if ref is not None else None
        if node is None:
            node = Node(node_id, addr, client)
            self._map[key] = weakref.ref(node)
            i = bisect.bisect_left(self._keys, key)
            if i >= len(self._keys) or self._keys[i] != key:
                self._keys.insert(i, key)
        elif confirm or node.is_old(now):
            node.update(addr)
        return node

    def closest(self, target: InfoHash, count: int) -> List[Node]:
        """Outward walk from lower_bound, XOR-closer side first
        (node_cache.cpp:41-74)."""
        keys = self._keys
        tkey = bytes(target)
        n = len(keys)
        lo = bisect.bisect_left(keys, tkey) - 1     # just below
        hi = lo + 1                                  # at/above
        out: List[Node] = []
        while len(out) < count and (lo >= 0 or hi < n):
            if lo < 0:
                key = keys[hi]; hi += 1
            elif hi >= n:
                key = keys[lo]; lo -= 1
            elif target.xor_cmp(InfoHash(keys[lo]), InfoHash(keys[hi])) < 0:
                key = keys[lo]; lo -= 1
            else:
                key = keys[hi]; hi += 1
            ref = self._map.get(key)
            node = ref() if ref is not None else None
            if node is not None and not node.expired and not node.is_client:
                out.append(node)
        return out

    def clear_bad(self) -> None:
        for key in list(self._map):
            ref = self._map[key]
            node = ref()
            if node is None:
                self._drop(key)
            else:
                node.reset()

    def set_expired(self) -> None:
        for ref in list(self._map.values()):
            node = ref()
            if node is not None:
                node.set_expired()
        self._map.clear()
        self._keys.clear()

    def __len__(self):
        return len(self._map)


class NodeCache:
    def __init__(self):
        self._cache4 = _FamilyCache()
        self._cache6 = _FamilyCache()

    def _cache(self, family: int) -> _FamilyCache:
        return self._cache6 if family == _socket.AF_INET6 else self._cache4

    def get_node(self, node_id: InfoHash, addr: SockAddr, now: float,
                 confirm: bool, client: bool = False) -> Node:
        """Intern (node_cache.cpp:34-39); anonymous ids get throwaway
        nodes."""
        if not node_id:
            return Node(node_id, addr, client)
        return self._cache(addr.family).get_node(node_id, addr, now, confirm, client)

    def lookup(self, node_id: InfoHash, family: int) -> Optional[Node]:
        return self._cache(family).lookup(node_id)

    def get_cached_nodes(self, target: InfoHash, family: int,
                         count: int) -> List[Node]:
        return self._cache(family).closest(target, count)

    def clear_bad_nodes(self, family: int = 0) -> None:
        """On connectivity change: un-expire everything (node_cache.cpp:76-85)."""
        if family == 0:
            self._cache4.clear_bad()
            self._cache6.clear_bad()
        else:
            self._cache(family).clear_bad()

    def set_expired(self) -> None:
        self._cache4.set_expired()
        self._cache6.set_expired()

    def size(self, family: int) -> int:
        return len(self._cache(family))
