"""Remote-peer state (reference include/opendht/node.h, src/node.cpp).

A :class:`Node` tracks one remote peer: address, last-heard/last-reply
times, liveness classification (good / old / expired), auth-error
strikes, the per-node in-flight request map, listen push sockets, and
the transaction-id generator."""

from __future__ import annotations

import random
from typing import TYPE_CHECKING, Callable, Dict, Optional

from ..infohash import InfoHash
from ..sockaddr import SockAddr

if TYPE_CHECKING:
    from .request import Request
    from .parsed_message import ParsedMessage

NODE_GOOD_TIME = 120 * 60.0      # node.h:148: replied within 2 h
NODE_EXPIRE_TIME = 10 * 60.0     # node.h:151: heard within 10 min
MAX_RESPONSE_TIME = 1.0          # node.h:154: per-attempt timeout
MAX_AUTH_ERRORS = 3              # node.h:158

#: cb(node, parsed_message) — unsolicited data on a listen socket
SocketCb = Callable[["Node", "ParsedMessage"], None]

_NEVER = float("-inf")


class Socket:
    """A per-node channel for unsolicited pushes after a listen
    (node.h:40-45)."""

    __slots__ = ("on_receive",)

    def __init__(self, on_receive: SocketCb):
        self.on_receive = on_receive


class Node:
    def __init__(self, node_id: InfoHash, addr: SockAddr, client: bool = False):
        self.id = node_id
        self.addr = addr
        self.is_client = client
        self.time = _NEVER            # last time heard about
        self.reply_time = _NEVER      # last correct reply
        self.auth_errors = 0
        self.expired = False
        # random initial tid (node.cpp:32-37)
        self._tid = random.randint(1, 0xFFFFFFFF)
        self.requests: Dict[int, "Request"] = {}
        self.sockets: Dict[int, Socket] = {}

    # -- liveness (node.cpp:39-46, node.h:79-92) ---------------------------
    def is_good(self, now: float) -> bool:
        return (not self.expired
                and self.reply_time >= now - NODE_GOOD_TIME
                and self.time >= now - NODE_EXPIRE_TIME)

    def is_old(self, now: float) -> bool:
        return self.time + NODE_EXPIRE_TIME < now

    def is_removable(self, now: float) -> bool:
        return self.expired and self.is_old(now)

    def is_incoming(self) -> bool:
        return self.time > self.reply_time

    def is_pending(self) -> bool:
        return any(r.pending for r in self.requests.values())

    def pending_count(self) -> int:
        return sum(1 for r in self.requests.values() if r.pending)

    @property
    def family(self) -> int:
        return self.addr.family

    # -- auth strikes (node.h:73-77) ---------------------------------------
    def auth_error(self) -> None:
        self.auth_errors += 1
        if self.auth_errors > MAX_AUTH_ERRORS:
            self.set_expired()

    def auth_success(self) -> None:
        self.auth_errors = 0

    # -- request bookkeeping (node.cpp:74-115) -----------------------------
    def requested(self, req: "Request") -> None:
        old = self.requests.get(req.tid)
        if old is not None and old is not req:
            old.set_expired()
        self.requests[req.tid] = req

    def received(self, now: float, req: Optional["Request"] = None) -> None:
        """A message arrived from this node; `req` set if it answers one
        of ours."""
        self.time = now
        self.expired = False
        if req is not None:
            self.reply_time = now
            self.requests.pop(req.tid, None)

    def get_request(self, tid: int) -> Optional["Request"]:
        return self.requests.get(tid)

    def cancel_request(self, req: Optional["Request"]) -> None:
        if req is not None:
            req.cancel()
            self.close_socket(req.close_socket())
            self.requests.pop(req.tid, None)

    def set_expired(self) -> None:
        """(node.cpp:117-126)"""
        self.expired = True
        for r in list(self.requests.values()):
            r.set_expired()
        self.requests.clear()
        self.sockets.clear()

    def reset(self) -> None:
        self.expired = False
        self.reply_time = _NEVER

    def update(self, addr: SockAddr) -> None:
        self.addr = addr

    # -- tids & sockets (node.h:118-142, node.cpp:128-152) -----------------
    def get_new_tid(self) -> int:
        self._tid = (self._tid + 1) & 0xFFFFFFFF
        if self._tid == 0:
            self._tid = 1
        return self._tid

    def open_socket(self, cb: SocketCb) -> int:
        sid = self.get_new_tid()
        self.sockets[sid] = Socket(cb)
        return sid

    def get_socket(self, sid: int) -> Optional[Socket]:
        return self.sockets.get(sid)

    def close_socket(self, sid: int) -> None:
        if sid:
            self.sockets.pop(sid, None)

    def export_node(self) -> dict:
        """{id, addr} for node export/bootstrap (infohash.h:363-382)."""
        return {"id": str(self.id), "addr": self.addr.to_compact()}

    def __repr__(self) -> str:
        return f"{self.id} {self.addr!r}"
