"""REST proxy subsystem.

TPU-native framework's equivalent of the reference's proxy pair
(reference: src/dht_proxy_server.cpp, src/dht_proxy_client.cpp):

- :class:`DhtProxyServer` — an HTTP facade over a running
  :class:`~opendht_tpu.runtime.runner.DhtRunner`, streaming values as
  line-delimited JSON.
- :class:`DhtProxyClient` — a full ``DhtInterface``-shaped backend that
  performs get/put/listen over that REST API instead of UDP, so
  light/NAT-restricted clients can reach the DHT through one proxy node.
"""

from .json_codec import value_to_json, value_from_json
from .server import DhtProxyServer, ServerStats
from .client import DhtProxyClient

__all__ = [
    "value_to_json", "value_from_json",
    "DhtProxyServer", "ServerStats", "DhtProxyClient",
]
