"""Push-notification gateway client (Gorush-shaped).

HTTP port of the reference sender (src/dht_proxy_server.cpp:548-583):
every notification POSTs ``http://<push_server>/api/push`` with

    {"notifications": [{
        "tokens": [<device push token>],
        "platform": 2 | 1,            # android | ios (gorush convention)
        "data": {...},                # e.g. {"key", "to", "token"}
        "priority": "high",
        "time_to_live": 600,
    }]}

The reference fires requests asynchronously (restbed::Http::async) and
ignores the response; here a single daemon worker drains a queue so a
slow or dead gateway never blocks DHT listener callbacks.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import urllib.request

log = logging.getLogger("opendht_tpu.proxy.push")

HTTP_PROTO = "http://"          # proxy.h:27


class GorushPushSender:
    """Fire-and-forget Gorush client; one worker thread, bounded queue."""

    def __init__(self, push_server: str, *, timeout: float = 10.0,
                 max_queue: int = 1024):
        self.push_server = push_server
        self._timeout = timeout
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self.sent = 0
        self.dropped = 0
        self.errors = 0
        self._worker = threading.Thread(target=self._run, name="push-gorush",
                                        daemon=True)
        self._worker.start()

    def notify(self, push_token: str, data: dict,
               is_android: bool = True) -> None:
        """Queue one notification (dht_proxy_server.cpp:548-583 shape)."""
        body = json.dumps({"notifications": [{
            "tokens": [push_token],
            "platform": 2 if is_android else 1,
            "data": data,
            "priority": "high",
            "time_to_live": 600,
        }]}).encode()
        try:
            self._q.put_nowait(body)
        except queue.Full:
            self.dropped += 1

    def join(self, timeout: float = 5.0) -> None:
        """Drain the queue (best-effort) — for tests and shutdown."""
        self._q.put(None)
        self._worker.join(timeout=timeout)

    # ------------------------------------------------------------- internal
    def _run(self) -> None:
        while True:
            body = self._q.get()
            if body is None:
                return
            req = urllib.request.Request(
                HTTP_PROTO + self.push_server + "/api/push", data=body,
                headers={"Content-Type": "application/json", "Accept": "*/*"},
                method="POST")
            try:
                with urllib.request.urlopen(req, timeout=self._timeout):
                    pass
                self.sent += 1
            except Exception as e:
                self.errors += 1
                log.debug("push gateway error: %s", e)
