"""JSON encoding of DHT values for the REST proxy wire format.

Mirrors the reference's JSON layer key-for-key (reference:
src/value.cpp:176-234 ``Value::Value(Json::Value&)`` / ``Value::toJson``):
``id`` is a decimal string, binary fields (``data``, ``sig``, ``cypher``)
are base64, ``owner`` is the owner public key (base64 DER here), ``to``
the recipient hash in hex, plus ``type``, ``seq`` and ``utype``.
"""

from __future__ import annotations

import base64
from typing import Optional

from ..infohash import InfoHash
from ..core.value import Value, RawPublicKey


def _b64(b: bytes) -> str:
    return base64.b64encode(bytes(b)).decode("ascii")


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def value_to_json(v: Value) -> dict:
    """reference: src/value.cpp:211-234."""
    out: dict = {"id": str(v.id)}
    if v.is_encrypted():
        out["cypher"] = _b64(v.cypher)
        return out
    if v.is_signed():
        out["sig"] = _b64(v.signature)
    if v.owner is not None:
        out["seq"] = v.seq
        out["owner"] = _b64(v.owner.export_der())
        if v.recipient:
            out["to"] = v.recipient.hex()
    out["type"] = v.type
    out["data"] = _b64(v.data)
    if v.user_type:
        out["utype"] = v.user_type
    return out


def value_from_json(obj: dict) -> Value:
    """reference: src/value.cpp:176-209."""
    v = Value()
    try:
        v.id = int(obj.get("id", 0))
    except (TypeError, ValueError):
        v.id = 0
    if "cypher" in obj:
        v.cypher = _unb64(obj["cypher"])
        return v
    if "sig" in obj:
        v.signature = _unb64(obj["sig"])
    if "owner" in obj:
        try:
            # parse to a real verifying key right away (the UDP path defers
            # this to SecureDht._parse_owner; REST values may be consumed
            # without a SecureDht in front)
            from .. import crypto
            v.owner = crypto.PublicKey(_unb64(obj["owner"]))
        except Exception:
            try:
                v.owner = RawPublicKey(_unb64(obj["owner"]))
            except Exception:
                v.owner = None
        v.seq = int(obj.get("seq", 0))
        if "to" in obj:
            v.recipient = InfoHash(obj["to"])
    v.type = int(obj.get("type", 0))
    v.data = _unb64(obj.get("data", ""))
    v.user_type = obj.get("utype", "")
    return v


def permanent_deadline(obj: dict, default_timeout: float) -> Optional[float]:
    """Extract the proxy permanent-put flag from a POST body.

    The reference accepts ``permanent: true`` or a nested object carrying
    a push token (src/dht_proxy_server.cpp:505-560).  Returns the relative
    refresh timeout when the put is permanent, else None.
    """
    p = obj.get("permanent")
    if not p:
        return None
    return float(default_timeout)
