"""DhtProxyClient: the DhtInterface over REST instead of UDP.

Behavioral port of the reference proxy client (reference:
src/dht_proxy_client.cpp, include/opendht/dht_proxy_client.h:1-383):

- ``get``  — streaming ``GET /{hash}`` parsing line-delimited JSON
  (:243-314), filter applied client-side.
- ``put``  — ``POST /{hash}``; permanent puts are re-sent periodically so
  the proxy's server-side bookkeeping keeps them alive (:316-437).
- ``listen`` — a background long-poll ``LISTEN /{hash}`` per subscribed
  key with a value cache deduplicating repeats and emitting expirations
  (:465-620); reconnects with backoff while active.
- status — polling ``GET /`` for the proxy's node info (:211-241):
  reachable proxy + known nodes ⇒ Connected.

The client is ``DhtInterface``-shaped: :class:`SecureDht` can wrap it
unchanged (the reference hot-swaps the same way, dhtrunner.cpp:967-975),
which is what gives signed/encrypted puts over REST.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Callable, Dict, List, Optional

from ..infohash import InfoHash
from ..core.value import Value, ValueType, TypeStore, Filters
from ..runtime.config import NodeStatus, NodeStats
from .json_codec import value_to_json, value_from_json

# re-send period for permanent puts; must undercut the server's
# OP_TIMEOUT window (reference: proxy::OP_TIMEOUT − OP_MARGIN).
PUT_REFRESH_PERIOD = 5 * 60.0
STATUS_PERIOD = 15.0
RECONNECT_BACKOFF = 1.0


class _ProxyListen:
    __slots__ = ("key", "cb", "f", "thread", "active", "cache")

    def __init__(self, key: InfoHash, cb, f):
        self.key = key
        self.cb = cb
        self.f = f
        self.thread: Optional[threading.Thread] = None
        self.active = True
        #: value id -> Value already delivered (ValueCache dedup role)
        self.cache: Dict[int, Value] = {}


class DhtProxyClient:
    """REST backend with the Dht surface (dht_proxy_client.h:60-383)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8080, *,
                 client_id: str = "", timeout: float = 30.0):
        self.host = host
        self.port = int(port)
        self.client_id = client_id
        self.timeout = timeout
        self.types = TypeStore()
        self._id = InfoHash.get_random()
        self._lock = threading.Lock()
        self._listen_token = 1
        self._listens: Dict[int, _ProxyListen] = {}
        #: (key, value id) -> (key, Value) for permanent re-puts (:316-437)
        self._puts: Dict[tuple, tuple] = {}
        self._running = True
        self._status = NodeStatus.CONNECTING
        self._maint = threading.Thread(target=self._maintenance_loop,
                                       name="proxy-client", daemon=True)
        self._maint.start()

    # ------------------------------------------------------------ transport
    def _conn(self) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)

    def _request_json(self, method: str, path: str,
                      body: Optional[dict] = None) -> Optional[dict]:
        try:
            c = self._conn()
            payload = json.dumps(body).encode() if body is not None else None
            c.request(method, path, body=payload,
                      headers={"Content-Type": "application/json"}
                      if payload else {})
            r = c.getresponse()
            data = r.read()
            c.close()
            if r.status >= 400:
                return None
            return json.loads(data.decode() or "{}")
        except Exception:
            return None

    def _stream_lines(self, method: str, path: str,
                      line_cb: Callable[[dict], bool],
                      idle_timeout: Optional[float] = None) -> bool:
        """Issue a streaming request, invoking ``line_cb`` per JSON line.
        Returns True when the stream ended cleanly."""
        c = None
        try:
            c = self._conn()
            if idle_timeout is not None:
                c.timeout = idle_timeout
            c.request(method, path)
            r = c.getresponse()
            if r.status >= 400:
                return False
            buf = b""
            while True:
                chunk = r.read1(65536) if hasattr(r, "read1") else r.read(4096)
                if not chunk:
                    return True
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    try:
                        obj = json.loads(line.decode())
                    except Exception:
                        continue
                    if not line_cb(obj):
                        return True
        except Exception:
            return False
        finally:
            if c is not None:
                try:
                    c.close()
                except Exception:
                    pass

    # ------------------------------------------------------------ identity
    def get_id(self) -> InfoHash:
        return self._id

    def get_node_id(self) -> InfoHash:
        return self._id

    def register_type(self, vt: ValueType) -> None:
        self.types.register_type(vt)

    def is_running(self, af: int = 0) -> bool:
        return self._running

    # ------------------------------------------------------------------ ops
    def get(self, key: InfoHash, get_cb=None, done_cb=None,
            f=None, where=None) -> None:
        """Async streaming GET (dht_proxy_client.cpp:243-314)."""
        flt = f
        if where is not None:
            try:
                flt = Filters.chain(f, where.get_filter())
            except Exception:
                pass

        def run():
            seen: List[Value] = []

            def on_line(obj) -> bool:
                try:
                    v = value_from_json(obj)
                except Exception:
                    return True
                if flt is not None and not flt(v):
                    return True
                if any(s == v for s in seen):
                    return True
                seen.append(v)
                if get_cb is not None:
                    return bool(get_cb([v]))
                return True

            ok = self._stream_lines("GET", "/" + key.hex(), on_line)
            if done_cb:
                done_cb(ok, [])

        threading.Thread(target=run, daemon=True).start()

    def get_sync(self, key: InfoHash, timeout: Optional[float] = 30.0,
                 f=None, where=None) -> List[Value]:
        ev = threading.Event()
        out: List[Value] = []
        self.get(key, lambda vs: out.extend(vs) or True,
                 lambda ok, ns: ev.set(), f, where)
        ev.wait(timeout)
        return out

    def query(self, key: InfoHash, query_cb, done_cb=None, q=None) -> None:
        """Client-side query: full get then project fields locally —
        the proxy protocol has no field-query verb (the reference sends
        the whole value too, dht_proxy_client.cpp:243-259)."""
        fields = getattr(getattr(q, "select", None), "get_selection",
                         lambda: [])()

        def gcb(values: List[Value]) -> bool:
            if q is not None and getattr(q, "where", None) is not None:
                wf = q.where.get_filter()
                values = [v for v in values if wf is None or wf(v)]
            if not values:
                return True
            if fields:
                return bool(query_cb([v.pack_fields(fields)
                                      for v in values]))
            return bool(query_cb(values))

        self.get(key, gcb, done_cb)

    def put(self, key: InfoHash, value: Value, done_cb=None,
            created: Optional[float] = None, permanent: bool = False) -> None:
        """POST, with periodic re-send when permanent
        (dht_proxy_client.cpp:316-437)."""
        if value.id == Value.INVALID_ID:
            from ..core.value import random_value_id
            value.id = random_value_id()

        def run():
            body = value_to_json(value)
            if permanent:
                body["permanent"] = True
            res = self._request_json("POST", "/" + key.hex(), body)
            if permanent and res is not None:
                with self._lock:
                    self._puts[(key, value.id)] = (key, value)
            if done_cb:
                done_cb(res is not None, [])

        threading.Thread(target=run, daemon=True).start()

    def cancel_put(self, key: InfoHash, vid: int) -> bool:
        with self._lock:
            return self._puts.pop((key, vid), None) is not None

    def get_put(self, key: InfoHash, vid: Optional[int] = None):
        """Announced-value lookup (↔ DhtProxyClient::getPut): returns the
        tracked permanent put for (key, vid), a list for the key when
        ``vid`` is None, else None."""
        with self._lock:
            if vid is not None:
                rec = self._puts.get((key, vid))
                return rec[1] if rec else None
            return [v for (k, _vid), (_k, v) in self._puts.items()
                    if k == key]

    def listen(self, key: InfoHash, cb, f=None, where=None) -> int:
        """Long-poll LISTEN (dht_proxy_client.cpp:465-620)."""
        flt = f
        if where is not None:
            try:
                flt = Filters.chain(f, where.get_filter())
            except Exception:
                pass
        with self._lock:
            token = self._listen_token
            self._listen_token += 1
            rec = _ProxyListen(key, cb, flt)
            self._listens[token] = rec

        def run():
            while rec.active and self._running:
                def on_line(obj) -> bool:
                    if not rec.active:
                        return False
                    if "t" in obj and "id" not in obj:
                        return True            # heartbeat
                    try:
                        v = value_from_json(obj)
                    except Exception:
                        return True
                    if rec.f is not None and not rec.f(v):
                        return True
                    expired = bool(obj.get("expired"))
                    if expired:
                        rec.cache.pop(v.id, None)
                        return bool(rec.cb([v], True))
                    known = rec.cache.get(v.id)
                    if known is not None and known == v:
                        return True            # dedup on reconnect replay
                    rec.cache[v.id] = v
                    return bool(rec.cb([v], False))

                self._stream_lines("LISTEN", "/" + key.hex(), on_line,
                                   idle_timeout=max(self.timeout, 30.0))
                if rec.active and self._running:
                    time.sleep(RECONNECT_BACKOFF)

        rec.thread = threading.Thread(target=run, daemon=True)
        rec.thread.start()
        return token

    def cancel_listen(self, key: InfoHash, token) -> bool:
        with self._lock:
            rec = self._listens.pop(token, None)
        if rec is None:
            return False
        rec.active = False
        return True

    # ------------------------------------------------------ push (SUBSCRIBE)
    def subscribe(self, key: InfoHash, *, push_token: str = "",
                  platform: str = "android",
                  token: int = 0) -> Optional[dict]:
        """Register for push notifications (dht_proxy_client.cpp:622-700).
        Requires a ``client_id``; ``push_token``/``platform``/``token``
        are the gateway fields the reference sends (body "key",
        "platform", "token" — dht_proxy_server.cpp:404-412)."""
        if not self.client_id:
            return None
        body = {"client_id": self.client_id}
        if push_token:
            body["key"] = push_token
            body["platform"] = platform
        if token:
            body["token"] = token
        return self._request_json("SUBSCRIBE", "/" + key.hex(), body)

    def unsubscribe(self, key: InfoHash) -> Optional[dict]:
        if not self.client_id:
            return None
        return self._request_json("UNSUBSCRIBE", "/" + key.hex(),
                                  {"client_id": self.client_id})

    # ----------------------------------------------------------- inspection
    def get_status(self, af: int = 0) -> NodeStatus:
        return self._status

    def get_proxy_info(self) -> Optional[dict]:
        return self._request_json("GET", "/")

    def get_nodes_stats(self, af: int = 0) -> NodeStats:
        import socket as _s
        info = self.get_proxy_info() or {}
        fam = info.get("ipv6" if af == _s.AF_INET6 else "ipv4", {}) or {}
        st = NodeStats()
        st.good_nodes = int(fam.get("good", 0))
        st.dubious_nodes = int(fam.get("dubious", 0))
        st.searches = int(fam.get("searches", 0))
        st.table_depth = int(fam.get("table_depth", 0))
        return st

    # ---------------------------------------------------------- maintenance
    def _maintenance_loop(self) -> None:
        """Status poll + permanent-put refresh
        (dht_proxy_client.cpp:211-241, :316-437)."""
        last_refresh = time.monotonic()
        while self._running:
            info = self.get_proxy_info()
            if info is None:
                self._status = NodeStatus.DISCONNECTED
            else:
                known = 0
                for fam in ("ipv4", "ipv6"):
                    stats = info.get(fam, {}) or {}
                    known += (int(stats.get("good", 0))
                              + int(stats.get("dubious", 0)))
                self._status = (NodeStatus.CONNECTED if known > 0
                                else NodeStatus.CONNECTING)
            now = time.monotonic()
            if now - last_refresh >= PUT_REFRESH_PERIOD:
                last_refresh = now
                with self._lock:
                    puts = list(self._puts.values())
                for key, value in puts:
                    body = value_to_json(value)
                    body["permanent"] = True
                    self._request_json("POST", "/" + key.hex(), body)
            t0 = time.monotonic()
            while self._running and time.monotonic() - t0 < STATUS_PERIOD:
                time.sleep(0.2)

    def shutdown(self, cb=None) -> None:
        if cb:
            cb()

    def join(self) -> None:
        self._running = False
        with self._lock:
            listens = list(self._listens.values())
            self._listens.clear()
        for rec in listens:
            rec.active = False

    # parity with Dht's periodic-driven surface: nothing to pump — all
    # client I/O lives on its own threads (the reference pumps its own
    # Scheduler the same way, dht_proxy_client.cpp:211+).
    def periodic(self, data, from_addr, now: Optional[float] = None) -> float:
        return time.monotonic() + 10.0
