"""Distributed indexation over the DHT (reference: include/opendht/indexation)."""

from .pht import Cache, IndexEntry, Pht, Prefix  # noqa: F401
