"""pingpong: two-node round-trip latency probe
(↔ reference python/tools/pingpong.py — the minimal wire-level latency
utility of the cluster toolkit).

Two in-process nodes bounce a value back and forth via put/listen;
prints per-round-trip wall-clock stats.  Usage::

    python -m opendht_tpu.testing.pingpong [-n ROUNDS]
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="OpenDHT-TPU ping-pong")
    p.add_argument("-n", "--rounds", type=int, default=20)
    p.add_argument("-b", "--bootstrap", default="",
                   help="optional external bootstrap host[:port] "
                        "(default: private two-node network)")
    args = p.parse_args(argv)

    from ..tools.common import force_cpu_jax
    force_cpu_jax()

    from ..core.value import Value
    import random as _random

    from ..infohash import InfoHash
    from ..runtime.config import NodeStatus
    from ..runtime.runner import DhtRunner
    from ..tools.common import parse_bootstrap
    from .scenarios import LatencyStats

    # per-run key suffix: stale values from a previous run against the
    # same external network must not satisfy this run's rounds
    run_tag = "%016x" % _random.getrandbits(64)
    ping_key = InfoHash.get("pingpong:ping:" + run_tag)
    pong_key = InfoHash.get("pingpong:pong:" + run_tag)

    a, b = DhtRunner(), DhtRunner()
    a.run(0)
    b.run(0)
    bs = parse_bootstrap(args.bootstrap)
    if bs:
        a.bootstrap(*bs)
        b.bootstrap(*bs)
    else:
        b.bootstrap("127.0.0.1", a.get_bound_port())
    deadline = time.monotonic() + 30.0
    while ((a.get_status() is not NodeStatus.CONNECTED
            or b.get_status() is not NodeStatus.CONNECTED)
           and time.monotonic() < deadline):
        time.sleep(0.05)

    # the ponger echoes every ping id it hears
    def pong(values, expired):
        if not expired:
            for v in values:
                b.put(pong_key, Value(v.data, value_id=v.id))
        return True

    b.listen(ping_key, pong)

    got = threading.Event()
    latest = {}

    def on_pong(values, expired):
        if not expired:
            for v in values:
                latest[v.id] = True
                got.set()
        return True

    a.listen(pong_key, on_pong)
    time.sleep(1.0)

    stats = LatencyStats()
    for i in range(args.rounds):
        vid = _random.getrandbits(48) | 1
        got.clear()
        t0 = time.monotonic()
        a.put(ping_key, Value(b"ping", value_id=vid))
        while vid not in latest and time.monotonic() - t0 < 10.0:
            got.wait(0.01)
            got.clear()
        if vid in latest:
            stats.add(time.monotonic() - t0)
    a.join()
    b.join()
    print(json.dumps({"test": "pingpong", "rounds": args.rounds,
                      **stats.summary()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
