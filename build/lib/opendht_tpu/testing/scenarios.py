"""Scenario suites over the virtual network
(↔ reference python/tools/dht/tests.py).

- :class:`PerformanceTest` — repeated random-hash ``get`` rounds with
  latency statistics and optional cluster replacement between rounds
  (↔ PerformanceTest._getsTimesTest, tests.py:866-948), and the
  node-kill *delete* test (↔ _delete, tests.py:951-995).
- :class:`PersistenceTest` — value survival under churn with
  ``maintain_storage`` republication (↔ PersistenceTest
  delete/replace/mult_time, tests.py:440-829).

All scenarios run on :class:`VirtualNet`'s virtual clock, so hours of
protocol time (republish sweeps, expiry) cost milliseconds, and results
are deterministic per seed.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import List, Optional

from ..core.value import Value
from ..infohash import InfoHash
from ..runtime.config import Config
from .virtual_net import VirtualNet


@dataclass
class LatencyStats:
    """sum/mean/std/min/max like the reference prints
    (dht/tests.py:930-948)."""
    samples: List[float] = field(default_factory=list)

    def add(self, dt: float) -> None:
        self.samples.append(dt)

    @property
    def mean(self) -> float:
        return sum(self.samples) / len(self.samples) if self.samples else 0.0

    @property
    def std(self) -> float:
        if len(self.samples) < 2:
            return 0.0
        m = self.mean
        return math.sqrt(sum((x - m) ** 2 for x in self.samples)
                         / (len(self.samples) - 1))

    def summary(self) -> dict:
        s = self.samples
        return {"count": len(s), "sum": sum(s), "mean": self.mean,
                "std": self.std, "min": min(s) if s else 0.0,
                "max": max(s) if s else 0.0}


def build_net(num_nodes: int, *, delay: float = 0.005, loss: float = 0.0,
              seed: int = 42, config: Optional[Config] = None,
              settle: float = 20.0) -> VirtualNet:
    """Spin up a connected N-node virtual network."""
    net = VirtualNet(delay=delay, loss=loss, seed=seed)
    nodes = [net.add_node(config) for _ in range(num_nodes)]
    net.bootstrap_all(nodes[0])
    net.run(max_time=settle, until=net.all_connected)
    return net


class PerformanceTest:
    """(↔ PerformanceTest, dht/tests.py:831-995)"""

    def __init__(self, net: VirtualNet, *, seed: int = 7):
        self.net = net
        self.rng = random.Random(seed)

    def gets_times(self, rounds: int = 10, gets_per_round: int = 50,
                   replace: int = 0, config: Optional[Config] = None
                   ) -> LatencyStats:
        """`gets_per_round` random-hash gets per round × `rounds`,
        measured in *virtual* seconds; optionally replace `replace`
        nodes between rounds (↔ _getsTimesTest, tests.py:866-948)."""
        stats = LatencyStats()
        nodes = list(self.net.nodes.values())
        seed_node = nodes[0]
        for _ in range(rounds):
            for _ in range(gets_per_round):
                src = self.rng.choice(list(self.net.nodes.values()))
                target = InfoHash.get_random()
                done = []
                t0 = self.net.clock
                src.get(target, lambda vs: True,
                        lambda ok, ns: done.append(ok))
                self.net.run(max_time=30.0, until=lambda: bool(done))
                stats.add(self.net.clock - t0)
            if replace:
                self.net.replace_cluster(replace, seed_node, config)
                self.net.run(max_time=20.0, until=self.net.all_connected)
        return stats

    def delete_test(self, *, payload: bytes = b"perf-delete"
                    ) -> "tuple[bool, int]":
        """Kill every node hosting a value at once, then check whether
        the network still serves it (↔ _delete, tests.py:951-995).
        Returns (survived, holders_killed)."""
        key = InfoHash.get("delete-test-key")
        nodes = list(self.net.nodes.values())
        done = []
        nodes[-1].put(key, Value(payload), lambda ok, ns: done.append(ok))
        self.net.run(max_time=30.0, until=lambda: bool(done))
        holders = self.net.storers_of(key)
        for h in holders:
            self.net.remove_node(h)
        alive = [d for d in self.net.nodes.values()]
        if not alive:
            return False, len(holders)
        got: List[Value] = []
        fin = []
        alive[0].get(key, lambda vs: got.extend(vs) or True,
                     lambda ok, ns: fin.append(ok))
        self.net.run(max_time=30.0, until=lambda: bool(fin))
        return any(v.data == payload for v in got), len(holders)


class PersistenceTest:
    """Value survival under churn (↔ PersistenceTest,
    dht/tests.py:440-829).  Requires nodes built with
    ``Config(maintain_storage=True)`` for republication."""

    def __init__(self, net: VirtualNet, *, seed: int = 11):
        self.net = net
        self.rng = random.Random(seed)

    def churn_survival(self, *, kills: int = 4, between: float = 700.0,
                       payload: bytes = b"persist-me",
                       config: Optional[Config] = None) -> bool:
        """Permanent-put a value, then kill one holder at a time with
        `between` virtual seconds in between so the putter's refresh
        cycle and maintain_storage republication can restore the replica
        set, replacing each victim with a fresh node
        (↔ PersistenceTest.replace/mult_time, tests.py:600-829).

        The put must be permanent: plain values expire after their type
        TTL (10 min) by design, so multi-TTL churn windows would lose
        them regardless of churn (value.h:77 semantics).
        """
        key = InfoHash.get("persistence-key")
        nodes = list(self.net.nodes.values())
        seed_node, putter = nodes[0], nodes[-1]
        done = []
        putter.put(key, Value(payload), lambda ok, ns: done.append(ok),
                   permanent=True)
        self.net.run(max_time=30.0, until=lambda: bool(done))
        for _ in range(kills):
            holders = [d for d in self.net.storers_of(key)
                       if d is not seed_node and d is not putter]
            if not holders:
                break
            victim = self.rng.choice(holders)
            self.net.remove_node(victim)
            fresh = self.net.add_node(config)
            self.net.bootstrap_node(fresh, seed_node)
            self.net.settle(between)      # let republication run
        got: List[Value] = []
        fin = []
        # probe from a node that holds nothing locally (and isn't the
        # putter) so the check exercises network replication, not the
        # probe's own store
        storers = set(map(id, self.net.storers_of(key)))
        candidates = [d for d in self.net.nodes.values()
                      if d is not putter and id(d) not in storers]
        if not candidates:
            fresh = self.net.add_node(config)
            self.net.bootstrap_node(fresh, seed_node)
            self.net.settle(10.0)
            candidates = [fresh]
        probe = self.rng.choice(candidates)
        probe.get(key, lambda vs: got.extend(vs) or True,
                  lambda ok, ns: fin.append(ok))
        self.net.run(max_time=30.0, until=lambda: bool(fin))
        return any(v.data == payload for v in got)
