"""network_monitor: continuous end-to-end put→listen health probe.

Analog of the reference monitor (reference python/tools/
network_monitor.py:26-83): two local nodes bootstrap to the monitored
network; node1 listens on N keys, node2 puts a fresh random value on
every key each period, and the monitor reports how long the full
put→propagate→listen round trip takes.  A timeout exits non-zero so the
tool can drive alerting.

Differences from the reference: ``--rounds`` bounds the loop (0 = run
forever like the reference) and ``--local`` spins up a private two-node
network instead of joining a public bootstrap, so the tool is runnable
in sealed environments and tests.

Usage::

    python -m opendht_tpu.testing.network_monitor --local -n 4 --rounds 3
    python -m opendht_tpu.testing.network_monitor -b host:port -p 60
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from datetime import datetime

from ..infohash import InfoHash
from ..core.value import Value
from ..runtime.config import NodeStatus
from ..runtime.runner import DhtRunner


class Monitor:
    def __init__(self, bootstrap: "tuple[str, int] | None", num_ops: int,
                 timeout: float):
        self.timeout = timeout
        self.node1 = DhtRunner()
        self.node2 = DhtRunner()
        self.node1.run(0)
        self.node2.run(0)
        self._local = None
        if bootstrap is None:
            # private network: node1 doubles as the bootstrap
            self.node2.bootstrap("127.0.0.1", self.node1.get_bound_port())
        else:
            host, port = bootstrap
            self.node1.bootstrap(host, port)
            self.node2.bootstrap(host, port)
        self.keys = [InfoHash.get_random() for _ in range(num_ops)]
        self.pending: dict = {}          # key-hex -> expected Value
        self._cv = threading.Condition()
        for key in self.keys:
            self.node1.listen(key, self._make_cb(key))

    def _make_cb(self, key: InfoHash):
        kstr = key.hex()

        def cb(values, expired):
            if expired:
                return True
            with self._cv:
                exp = self.pending.get(kstr)
                if exp is not None and any(v.id == exp.id for v in values):
                    self.pending.pop(kstr, None)
                    self._cv.notify_all()
            return True
        return cb

    def wait_connected(self, timeout: float = 30.0) -> bool:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if (self.node1.get_status() is NodeStatus.CONNECTED
                    and self.node2.get_status() is NodeStatus.CONNECTED):
                return True
            time.sleep(0.1)
        return False

    def run_test(self) -> float:
        """One round: put a fresh value on every key, wait until every
        listener heard its value.  Returns elapsed seconds; raises
        TimeoutError on expiry (reference monitor exits 1)."""
        start = time.monotonic()
        with self._cv:
            for i, key in enumerate(self.keys):
                val = Value(InfoHash.get_random().hex().encode(),
                            value_id=int(start * 1000) * 1000 + i + 1)
                self.pending[key.hex()] = val
                self.node2.put(key, val, lambda ok, nodes: None)
            while self.pending:
                remaining = self.timeout - (time.monotonic() - start)
                if remaining <= 0 or not self._cv.wait(timeout=remaining):
                    missing = list(self.pending)
                    self.pending.clear()
                    raise TimeoutError("no listen callback for %d keys: %s"
                                       % (len(missing), missing[:4]))
        return time.monotonic() - start

    def close(self) -> None:
        self.node1.join()
        self.node2.join()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="monitor a DHT network with periodic put->listen probes")
    p.add_argument("-b", "--bootstrap",
                   help="bootstrap address host:port (default: private net)")
    p.add_argument("-n", "--num-ops", type=int, default=8,
                   help="concurrent keys probed per round")
    p.add_argument("-p", "--period", type=float, default=60.0,
                   help="seconds between rounds")
    p.add_argument("-t", "--timeout", type=float, default=15.0,
                   help="per-round timeout")
    p.add_argument("--rounds", type=int, default=0,
                   help="stop after N rounds (0 = forever)")
    p.add_argument("--local", action="store_true",
                   help="run against a private 2-node network")
    args = p.parse_args(argv)

    bootstrap = None
    if args.bootstrap and not args.local:
        host, _, port = args.bootstrap.partition(":")
        bootstrap = (host, int(port or 4222))

    mon = Monitor(bootstrap, args.num_ops, args.timeout)
    try:
        if not mon.wait_connected():
            print("monitor: nodes failed to connect", file=sys.stderr)
            return 1
        next_test = time.monotonic()
        done_rounds = 0
        while args.rounds == 0 or done_rounds < args.rounds:
            try:
                dt = mon.run_test()
            except TimeoutError as e:
                print("Test timeout !", e, file=sys.stderr)
                return 1
            print(datetime.now().strftime("%Y-%m-%d %H:%M:%S"),
                  "Test completed successfully in", round(dt, 3))
            done_rounds += 1
            if args.rounds and done_rounds >= args.rounds:
                break
            next_test += args.period
            now = time.monotonic()
            if next_test > now:
                time.sleep(next_test - now)
    finally:
        mon.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
