"""dhtcluster: run and control a resizable cluster of live DHT nodes.

Analog of the reference cluster driver (reference python/tools/
dhtcluster.py:29-270): a ``NodeCluster`` manages N in-process DhtRunner
nodes (launch/end/resize, aggregate message stats), and ``ClusterShell``
is a cmd.Cmd REPL with the reference's commands (node, resize, ll, ls,
log, exit).  Service mode runs headless under SIGTERM/SIGINT handlers.

All nodes bind 127.0.0.1 with OS-assigned ports and bootstrap off the
first node, so a multi-hundred-node cluster runs in one process with no
interface configuration (the reference binds an interface IP and a port
range; netifaces-style interface selection has no analog here).

Usage::

    python -m opendht_tpu.testing.dhtcluster -n 16            # REPL
    python -m opendht_tpu.testing.dhtcluster -n 16 -s         # service
"""

from __future__ import annotations

import argparse
import cmd
import signal
import sys
import time

from ..runtime.runner import DhtRunner

MAX_NODES = 500                  # reference dhtcluster.py:106


class NodeCluster:
    """A resizable set of live local nodes (dhtcluster.py:29-128)."""

    def __init__(self, port: int = 0):
        self.nodes: list[DhtRunner] = []
        self.port = port            # 0 = OS-assigned per node

    # -- lifecycle ---------------------------------------------------------
    def launch_node(self) -> DhtRunner:
        n = DhtRunner()
        n.run(self.port if not self.nodes else 0)
        if self.nodes:
            n.bootstrap("127.0.0.1", self.nodes[0].get_bound_port())
        self.nodes.append(n)
        return n

    def end_node(self) -> bool:
        if not self.nodes:
            return False
        self.nodes.pop().join()
        return True

    def resize(self, n: int) -> None:
        n = max(0, min(n, MAX_NODES))
        while len(self.nodes) < n:
            self.launch_node()
            time.sleep(0.01)
        while len(self.nodes) > n:
            self.end_node()

    def close(self) -> None:
        self.resize(0)

    # -- access ------------------------------------------------------------
    def front(self):
        return self.nodes[0] if self.nodes else None

    def get(self, i: int):
        return self.nodes[i] if 0 <= i < len(self.nodes) else None

    def get_node_info_by_id(self, node_id):
        for n in self.nodes:
            if n.get_node_id() == node_id:
                return n
        return None

    def get_message_stats(self) -> list:
        """[n_nodes, sum of per-node engine counters]
        (dhtcluster.py:122-128)."""
        totals = None
        for n in self.nodes:
            s = n.get_node_message_stats()
            totals = s if totals is None else [a + b
                                               for a, b in zip(totals, s)]
        return [len(self.nodes)] + (totals or [])


class ClusterShell(cmd.Cmd):
    """dhtcluster.py:130-192."""

    intro = ("Welcome to the OpenDHT-TPU node cluster control. "
             "Type help or ? to list commands.\n")
    prompt = ">> "

    def __init__(self, network: NodeCluster, stdout=None, stdin=None):
        super().__init__(stdout=stdout, stdin=stdin)
        if stdin is not None:
            self.use_rawinput = False
        self.net = network
        self.node = None
        self.node_num = 0

    def _print(self, *args):
        print(*args, file=self.stdout)

    def do_exit(self, arg):
        """Stop the cluster and exit."""
        self.close()
        return True

    do_EOF = do_exit

    def do_node(self, arg):
        """node [N]: select node N (1-based) or deselect."""
        if not arg:
            self.node, self.node_num = None, 0
            self.prompt = ">> "
            return
        try:
            num = int(arg)
        except ValueError:
            self._print("Invalid node number:", arg)
            return
        node = self.net.get(num - 1)
        if node is None:
            self._print("Invalid node number:", num,
                        "(accepted: 1-%d)" % len(self.net.nodes))
        else:
            self.node, self.node_num = node, num
            self.prompt = "(%d) >> " % num

    def do_resize(self, arg):
        """resize N: grow/shrink the cluster to N nodes."""
        if not arg:
            return
        try:
            self.net.resize(int(arg))
        except Exception as e:
            self._print("Can't resize:", e)
        # a shrink may have joined the selected node — deselect it so
        # later commands don't act on a dead runner
        if self.node is not None and self.node not in self.net.nodes:
            self._print("(selected node %d was removed)" % self.node_num)
            self.node, self.node_num = None, 0
            self.prompt = ">> "

    def do_ll(self, arg):
        """Selected node id, or cluster size."""
        if self.node:
            self._print("Node", self.node.get_node_id().hex())
        else:
            self._print(len(self.net.nodes), "nodes running.")

    def do_ls(self, arg):
        """Searches log of the selected node."""
        if self.node:
            self._print(self.node.get_searches_log())
        else:
            self._print("No node selected.")

    def do_stats(self, arg):
        """Aggregate message statistics over the cluster."""
        self._print(self.net.get_message_stats())

    def do_log(self, arg):
        """Toggle logging on the selected node."""
        if self.node:
            self._print("(log toggling is a no-op here: use the module "
                        "logger, opendht_tpu.log.setup_logging)")

    def close(self):
        if self.net is not None:
            self.net.close()
            self.net = None


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Create a dht network of -n nodes")
    p.add_argument("-n", "--node-num", type=int, default=32,
                   help="number of dht nodes to run")
    p.add_argument("-p", "--port", type=int, default=0,
                   help="port for the first (bootstrap) node")
    p.add_argument("-s", "--service", action="store_true",
                   help="service mode (headless, stop on SIGTERM/SIGINT)")
    args = p.parse_args(argv)

    net = NodeCluster(port=args.port)
    stop = []

    def quit_signal(signum, frame):
        stop.append(signum)

    try:
        if args.service:
            signal.signal(signal.SIGTERM, quit_signal)
            signal.signal(signal.SIGINT, quit_signal)
            net.resize(args.node_num)
            print("%d nodes running (bootstrap 127.0.0.1:%d)"
                  % (len(net.nodes), net.front().get_bound_port()))
            while not stop:
                time.sleep(0.5)
        else:
            net.resize(args.node_num)
            ClusterShell(net).cmdloop()
    finally:
        net.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
