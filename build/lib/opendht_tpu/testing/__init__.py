"""Multi-node test/bench harness (↔ reference python/tools/dht/*).

Two backends:

- :class:`VirtualNet` — deterministic in-process virtual UDP network
  over ``Dht`` cores with a virtual clock (replaces the reference's
  netns + netem tier, virtual_network_builder.py).
- :class:`DhtNetwork` — N real ``DhtRunner`` nodes on localhost UDP
  (the reference's in-namespace node cluster, dht/network.py:283-436).

Scenario suites (↔ dht/tests.py): :class:`PerformanceTest` (gets latency
histograms, node-kill delete test), :class:`PersistenceTest` (value
survival under churn).  CLI driver: ``python -m
opendht_tpu.testing.benchmark`` (↔ benchmark.py).
"""

from .virtual_net import VirtualNet
from .network import DhtNetwork
from .scenarios import PerformanceTest, PersistenceTest, LatencyStats

__all__ = ["VirtualNet", "DhtNetwork", "PerformanceTest",
           "PersistenceTest", "LatencyStats"]
