"""In-process virtual UDP network for driving whole Dht nodes.

The tier-3 analogue of the reference's netns cluster harness
(python/tools/dht/network.py, virtual_network_builder.py) with no real
sockets: every node's injected ``send_fn`` enqueues datagrams on a
shared event queue, a virtual clock advances to the next packet arrival
or scheduler wakeup, and delivery calls the destination's
``periodic(data, from_addr)``.  Deterministic, immune to wall-clock
flakiness, and able to jump hours of protocol time (token rotation,
value expiry) in milliseconds.  Optional per-packet loss and delay play
the role of netem (benchmark.py -l/-d).
"""

from __future__ import annotations

import heapq
import itertools
import random
from typing import Callable, Dict, List, Optional

from opendht_tpu.runtime import Config, Dht
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr
from opendht_tpu.utils import TIME_MAX


class VirtualNet:
    def __init__(self, *, delay: float = 0.01, jitter: float = 0.0,
                 loss: float = 0.0, seed: int = 42):
        self.clock = 0.0
        self.delay = delay
        self.jitter = jitter
        self.loss = loss
        self.rng = random.Random(seed)
        self.nodes: Dict[tuple, Dht] = {}
        self._queue: list = []          # (arrival, seq, data, src, dst_key)
        self._seq = itertools.count()
        self._next_port = 20000
        self.dropped = 0

    # ------------------------------------------------------------- topology
    def add_node(self, config: Optional[Config] = None,
                 host: str = "127.0.0.1", port: Optional[int] = None) -> Dht:
        if port is None:
            port = self._next_port
            self._next_port += 1
        addr = SockAddr(host, port)
        key = (addr.host, addr.port)

        def send_fn(data: bytes, dest: SockAddr, _src=addr) -> int:
            if self.loss and self.rng.random() < self.loss:
                self.dropped += 1
                return 0
            arrival = self.clock + self.delay + \
                (self.rng.random() * self.jitter if self.jitter else 0.0)
            heapq.heappush(self._queue, (arrival, next(self._seq), data,
                                         _src, (dest.host, dest.port)))
            return 0

        dht = Dht(send_fn, config, Scheduler(clock=lambda: self.clock),
                  has_v6=False)
        dht.bound_addr = addr
        self.nodes[key] = dht
        return dht

    def bootstrap_node(self, dht: Dht, seed_node: Dht) -> None:
        """Point one node at the seed and ping it (↔ the runner's
        bootstrap thread, reference src/dhtrunner.cpp:819-875)."""
        dht.insert_node(seed_node.myid, seed_node.bound_addr)
        dht.ping_node(seed_node.bound_addr)

    def remove_node(self, dht: Dht) -> None:
        """Kill a node: it stops receiving and its scheduler stops running
        (↔ DhtNetworkSubProcess node shutdown, reference
        python/tools/dht/network.py:377-436)."""
        key = (dht.bound_addr.host, dht.bound_addr.port)
        self.nodes.pop(key, None)

    def replace_cluster(self, count: int, seed_node: Dht,
                        config: Optional[Config] = None) -> List[Dht]:
        """Kill ``count`` random nodes (never the seed) and start as many
        fresh ones bootstrapped at the seed (↔ the reference's cluster
        replacement during PerformanceTest rounds, dht/tests.py:905-910)."""
        candidates = [d for d in self.nodes.values() if d is not seed_node]
        victims = self.rng.sample(candidates, min(count, len(candidates)))
        for v in victims:
            self.remove_node(v)
        fresh = []
        for _ in victims:
            d = self.add_node(config)
            self.bootstrap_node(d, seed_node)
            fresh.append(d)
        return fresh

    def storers_of(self, key) -> List[Dht]:
        """Nodes currently holding values for ``key`` locally."""
        return [d for d in self.nodes.values() if d.get_local(key)]

    def bootstrap_all(self, seed_node: Dht) -> None:
        """Point every other node at the seed and ping it (↔ the runner's
        bootstrap thread, reference src/dhtrunner.cpp:819-875)."""
        for dht in self.nodes.values():
            if dht is not seed_node:
                self.bootstrap_node(dht, seed_node)

    # ------------------------------------------------------------ event loop
    def _next_event_time(self) -> float:
        t = self._queue[0][0] if self._queue else TIME_MAX
        for dht in self.nodes.values():
            t = min(t, dht.scheduler.next_job_time())
        return t

    def run(self, max_time: float = 30.0,
            until: Optional[Callable[[], bool]] = None,
            max_events: int = 1_000_000) -> bool:
        """Advance virtual time; returns True as soon as `until()` holds."""
        deadline = self.clock + max_time
        for _ in range(max_events):
            if until is not None and until():
                return True
            t = self._next_event_time()
            if t > deadline:
                self.clock = deadline
                break
            self.clock = max(self.clock, t)
            # deliver all packets due now
            while self._queue and self._queue[0][0] <= self.clock:
                _, _, data, src, dst_key = heapq.heappop(self._queue)
                dst = self.nodes.get(dst_key)
                if dst is not None:
                    dst.periodic(data, src)
            # run due scheduler jobs everywhere
            for dht in self.nodes.values():
                if dht.scheduler.next_job_time() <= self.clock:
                    dht.periodic(None, None)
        return until() if until is not None else False

    def settle(self, seconds: float) -> None:
        """Run with no exit condition for `seconds` of virtual time."""
        self.run(max_time=seconds, until=None)

    # ------------------------------------------------------------- helpers
    def connected_count(self) -> int:
        from opendht_tpu.runtime import NodeStatus
        return sum(1 for d in self.nodes.values()
                   if d.get_status() is NodeStatus.CONNECTED)

    def all_connected(self) -> bool:
        return self.connected_count() == len(self.nodes)
