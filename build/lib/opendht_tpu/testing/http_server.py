"""http_server: minimal HTTP control interface over one DHT node.

Analog of the reference tool (reference python/tools/http_server.py:
26-78, a twisted app): GET /<key>?id=..&user_type=.. runs a filtered
``get`` and returns ``{"<vid hex>": {"base64": ...}}``; POST /<key> with
``data`` (or ``base64``) + optional ``id``/``user_type`` form fields
puts a value.  Keys are a 40-hex infohash or any string (hashed with
InfoHash.get, like the reference).  Built on the stdlib HTTP server —
twisted is not a dependency here.

This is the *census/ops* helper; the full REST facade with streaming,
listen and push lives in opendht_tpu.proxy.

Usage::

    python -m opendht_tpu.testing.http_server -p 0 -hp 8080 \
        -b host:port
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..core.value import Value, Where
from ..infohash import InfoHash
from ..runtime.runner import DhtRunner

WHERE_FIELDS = ("id", "user_type", "value_type", "owner", "seq")


def _key_of(path_part: str) -> InfoHash:
    """40-hex → literal infohash, else hash the string
    (http_server.py:36,59)."""
    if len(path_part) == 40:
        try:
            return InfoHash(bytes.fromhex(path_part))
        except ValueError:
            pass
    return InfoHash.get(path_part)


def make_handler(node: DhtRunner):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.0"

        def log_message(self, fmt, *args):
            pass

        def _json(self, obj, code: int = 200) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            u = urlparse(self.path)
            uri = u.path.lstrip("/")
            args = parse_qs(u.query)
            h = _key_of(uri)
            # build 'WHERE k=v,...' from whitelisted query params
            # (http_server.py:38-41); the reference's 'owner' param is
            # the Where grammar's 'owner_pk'
            clauses = ",".join(
                "%s=%s" % ("owner_pk" if k == "owner" else k, v[0])
                for k, v in args.items() if k in WHERE_FIELDS and v)
            try:
                where = Where("WHERE " + clauses) if clauses else None
            except ValueError as e:
                self._json({"error": str(e)}, code=400)
                return
            values = node.get_sync(h, where=where) or []
            self._json({"%x" % v.id:
                        {"base64": base64.b64encode(v.data).decode()}
                        for v in values})

        def do_POST(self):
            u = urlparse(self.path)
            uri = u.path.lstrip("/")
            ln = int(self.headers.get("Content-Length", 0))
            args = parse_qs(self.rfile.read(ln).decode())
            data = args.get("data", [None])[0]
            data = data.encode() if data is not None else None
            if not data and "base64" in args:
                data = base64.b64decode(args["base64"][0])
            try:
                vid = int(args.get("id", ["0"])[0])
            except ValueError:
                vid = 0
            user_type = args.get("user_type", [""])[0]
            if not data:
                self._json({"success": False,
                            "error": "no data parameter"}, code=400)
                return
            v = Value(data, value_id=vid, user_type=user_type)
            ok = node.put_sync(_key_of(uri), v, timeout=30.0)
            self._json({"success": bool(ok)})

    return Handler


class DhtHttpServer:
    """Bind the HTTP control interface to a running node."""

    def __init__(self, node: DhtRunner, http_port: int = 8080,
                 address: str = "127.0.0.1"):
        self.node = node
        self._httpd = ThreadingHTTPServer((address, http_port),
                                          make_handler(node))
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dht-http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Launch a DHT node with an HTTP control interface")
    p.add_argument("-p", "--port", type=int, default=0,
                   help="DHT port to bind")
    p.add_argument("-hp", "--http-port", type=int, default=8080)
    p.add_argument("-b", "--bootstrap", help="bootstrap address host:port")
    args = p.parse_args(argv)

    node = DhtRunner()
    node.run(args.port)
    if args.bootstrap:
        host, _, port = args.bootstrap.partition(":")
        node.bootstrap(host, int(port or 4222))
    srv = DhtHttpServer(node, args.http_port)
    print("dht node %s on udp port %d, http port %d"
          % (node.get_node_id().hex()[:16], node.get_bound_port(), srv.port))
    try:
        import time
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
        node.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
