"""scanner: adaptive keyspace crawl with node-ring + geo summaries.

Analog of the reference crawler (reference python/tools/
scanner.py:118-166): starting from prefix 0 at depth 0, every completed
``get`` reply drives the descent — the crawl splits deeper wherever
replies show more shared prefix bits (``commonBits(first, last) + 6``,
capped at depth 8), so dense keyspace regions get proportionally more
probes.  Discovered nodes accumulate in a NodeSet; per-IP aggregation,
unit-circle ring coordinates (id → angle, scanner.py:180-184) and a
geo summary are reported at the end.

The reference resolves locations by downloading MaxMind GeoIP databases
and plots live matplotlib/Basemap maps; this environment has no egress,
so geolocation is a pluggable resolver (default: an offline classifier
that labels loopback/private/global per RFC 6890) and the "map" is a
JSON summary on stdout.  Pass a real resolver callable for actual
GeoIP lookups.

Usage::

    python -m opendht_tpu.testing.scanner -b 127.0.0.1:4222
    python -m opendht_tpu.testing.scanner --local 8   # self-made network
"""

from __future__ import annotations

import argparse
import ipaddress
import json
import math
import sys
import threading
import time

from ..infohash import InfoHash
from ..nodeset import NodeSet
from ..runtime.config import NodeStatus
from ..runtime.runner import DhtRunner

MAX_DEPTH = 8                    # scanner.py:143


def offline_geo(ip: str) -> dict:
    """Offline stand-in for the GeoIP record: RFC 6890 class labels."""
    try:
        a = ipaddress.ip_address(ip)
    except ValueError:
        return {"class": "invalid"}
    if a.is_loopback:
        cls = "loopback"
    elif a.is_private:
        cls = "private"
    elif a.is_multicast:
        cls = "multicast"
    else:
        cls = "global"
    return {"class": cls, "v": a.version}


class Scanner:
    """Concurrent adaptive crawl of the full keyspace
    (scanner.py:118-150: step / stepdone / nextstep)."""

    def __init__(self, node: DhtRunner, geo=offline_geo,
                 max_depth: int = MAX_DEPTH):
        self.node = node
        self.geo = geo
        self.max_depth = max_depth
        self.all_nodes = NodeSet()
        self.ip4s: dict = {}
        self.ip6s: dict = {}
        self.probes = 0
        self._inflight = 0
        self._cv = threading.Condition()

    def scan(self, timeout: float = 120.0) -> None:
        # start from 00..01, not the zero hash: peers reject a get for a
        # null infohash (GET_NO_INFOHASH, src/dht.cpp:2140) — the
        # reference seeds the same way (scanner.py:277-279 setBit(159,1))
        with self._cv:
            self._step(InfoHash.zero().set_bit(159, True), 0)
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(timeout=min(remaining, 0.5))

    # ------------------------------------------------------------- crawl
    def _step(self, cur_h: InfoHash, cur_depth: int) -> None:
        """Probe one keyspace arc; replies may split it deeper
        (scanner.py:118-128)."""
        self._inflight += 1
        self.probes += 1
        self.node.get(cur_h, lambda values: True,
                      lambda ok, nodes: self._step_done(cur_h, cur_depth,
                                                        ok, nodes))

    def _step_done(self, cur_h, cur_depth, ok, nodes) -> None:
        with self._cv:
            try:
                if nodes:
                    self._append_nodes(nodes)
                    common = 0
                    if len(nodes) > 1:
                        s = NodeSet()
                        s.extend(nodes)
                        common = InfoHash.common_bits(s.first(), s.last())
                    depth = min(self.max_depth, common + 6)
                    # split the remaining arc one level per gained bit
                    # (scanner.py:139-148)
                    if cur_depth < depth:
                        for b in range(cur_depth, depth):
                            new_h = cur_h.set_bit(b, True)
                            self._step(new_h, b + 1)
            finally:
                self._inflight -= 1
                self._cv.notify_all()

    # ----------------------------------------------------------- harvest
    def _append_nodes(self, nodes) -> None:
        for n in nodes:
            nid = getattr(n, "id", n)
            if self.all_nodes.insert((nid, n)):
                addr = getattr(n, "addr", None)
                ip = getattr(addr, "host", "") or ""
                bucket = self.ip6s if ":" in ip else self.ip4s
                if ip in bucket:
                    bucket[ip]["nodes"] += 1
                else:
                    bucket[ip] = {"nodes": 1, "geo": self.geo(ip)}

    # ----------------------------------------------------------- reports
    def ring_points(self) -> list:
        """Unit-circle coordinates of every node id
        (scanner.py:180-184: angle = 2π · id.toFloat())."""
        pts = []
        for entry in self.all_nodes:
            a = 2.0 * math.pi * entry.get_id().to_float()
            pts.append({"id": entry.get_id().hex()[:16],
                        "x": math.cos(a), "y": math.sin(a)})
        return pts

    def summary(self) -> dict:
        geo_counts: dict = {}
        for bucket in (self.ip4s, self.ip6s):
            for rec in bucket.values():
                cls = rec["geo"].get("class", "unknown")
                geo_counts[cls] = geo_counts.get(cls, 0) + 1
        return {
            "probes": self.probes,
            "nodes": len(self.all_nodes),
            "ip4s": len(self.ip4s),
            "ip6s": len(self.ip6s),
            "geo": geo_counts,
            "ring": self.ring_points(),
        }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="crawl a DHT network and summarize nodes/locations")
    p.add_argument("-b", "--bootstrap",
                   help="bootstrap address host:port")
    p.add_argument("--local", type=int, default=0, metavar="N",
                   help="spin up a private N-node network and scan it")
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--max-depth", type=int, default=MAX_DEPTH)
    args = p.parse_args(argv)

    cluster = None
    scanner_node = DhtRunner()
    scanner_node.run(0)
    try:
        if args.local:
            from .dhtcluster import NodeCluster
            cluster = NodeCluster()
            cluster.resize(args.local)
            scanner_node.bootstrap("127.0.0.1",
                                   cluster.front().get_bound_port())
        elif args.bootstrap:
            host, _, port = args.bootstrap.partition(":")
            scanner_node.bootstrap(host, int(port or 4222))
        else:
            p.error("need -b or --local")

        t0 = time.monotonic()
        while (scanner_node.get_status() is not NodeStatus.CONNECTED
               and time.monotonic() - t0 < 30.0):
            time.sleep(0.1)

        sc = Scanner(scanner_node, max_depth=args.max_depth)
        sc.scan(timeout=args.timeout)
        print(json.dumps(sc.summary()))
    finally:
        scanner_node.join()
        if cluster is not None:
            cluster.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
