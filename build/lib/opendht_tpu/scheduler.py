"""Time-ordered job scheduler — the single-threaded runtime driver.

Counterpart of the reference ``Scheduler`` (include/opendht/scheduler.h:37-122):
every periodic behavior in the network engine and DHT core is a job keyed
by a time point; ``run()`` executes everything due and reports the next
wakeup so the owning loop can sleep exactly that long.

Python-idiomatic design: a heapq of (time, seq, Job) entries with lazy
deletion — ``cancel``/``edit`` just drop the callable, and stale heap
entries are skipped when popped (the reference reschedules by re-emplacing
into a multimap, same effect).
"""

from __future__ import annotations

import heapq
import itertools
import time as _time
from typing import Callable, Optional

from .utils import TIME_MAX


class Job:
    """A scheduled callable. ``cancel()`` clears it (scheduler.h:41-44).
    ``time`` tracks the pending fire time (None once popped/parked) so
    callers can compare against an intended reschedule."""

    __slots__ = ("func", "time")

    def __init__(self, func: Optional[Callable[[], None]]):
        self.func = func
        self.time: Optional[float] = None

    def cancel(self) -> None:
        self.func = None

    @property
    def cancelled(self) -> bool:
        return self.func is None


class Scheduler:
    def __init__(self, clock: Callable[[], float] = _time.monotonic):
        self._clock = clock
        self._now = clock()
        self._heap: list[tuple[float, int, Job]] = []
        self._seq = itertools.count()

    # -- queue ops ---------------------------------------------------------
    def add(self, t: float, func: Callable[[], None]) -> Job:
        """Schedule ``func`` at time ``t``; returns the Job handle
        (scheduler.h:53-58). t == TIME_MAX means 'parked': the job exists
        but is not queued."""
        job = Job(func)
        if t != TIME_MAX:
            job.time = t
            heapq.heappush(self._heap, (t, next(self._seq), job))
        return job

    def queue(self, job: Job, t: float) -> None:
        """Re-enqueue an existing job at ``t`` (scheduler.h:60-63)."""
        if t != TIME_MAX:
            job.time = t
            heapq.heappush(self._heap, (t, next(self._seq), job))

    def edit(self, job: Optional[Job], t: float) -> Optional[Job]:
        """Reschedule: cancel the old entry, return a fresh Job at ``t``
        (scheduler.h:70-80 — the reference also invalidates the old
        shared_ptr's callable and re-adds)."""
        if job is None:
            return None
        func = job.func
        job.func = None
        job.time = None
        return self.add(t, func) if func is not None else None

    # -- execution ---------------------------------------------------------
    def run(self) -> float:
        """Run all jobs due as of now; return next wakeup time
        (scheduler.h:87-106).  Jobs scheduled for a time strictly after the
        synced 'now' are left for the next run, so a job that reschedules
        itself for 'now + d' cannot starve the loop."""
        self.sync_time()
        heap = self._heap
        # Snapshot the due entries first: a job that re-adds itself for
        # "now" during this sweep waits for the next run() instead of
        # spinning the loop (the reference relies on real time advancing
        # for the same guarantee, scheduler.h:90-95).
        due = []
        while heap and heap[0][0] <= self._now:
            t, _, job = heapq.heappop(heap)
            job.time = None
            due.append((t, job))
        try:
            while due:
                _, job = due.pop(0)
                func = job.func
                if func is not None:
                    func()
        finally:
            # If a job raised, the not-yet-run due jobs go back on the
            # heap instead of being silently lost with the local list.
            for t, job in due:
                heapq.heappush(heap, (t, next(self._seq), job))
        return self.next_job_time()

    def next_job_time(self) -> float:
        heap = self._heap
        while heap and heap[0][2].cancelled:
            heapq.heappop(heap)
        return heap[0][0] if heap else TIME_MAX

    # -- time reference ----------------------------------------------------
    def time(self) -> float:
        """The common synchronized time reference (scheduler.h:116)."""
        return self._now

    def sync_time(self) -> float:
        self._now = self._clock()
        return self._now

    def __len__(self) -> int:
        return sum(1 for *_, j in self._heap if not j.cancelled)
