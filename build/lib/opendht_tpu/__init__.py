"""opendht_tpu — a TPU-native distributed hash table framework.

A ground-up re-design of the capabilities of OpenDHT (reference:
``Dale-M/opendht`` @ /root/reference, surveyed in SURVEY.md): a Kademlia
DHT with ``get/put/listen/query`` value store, signed/encrypted values,
write tokens, a REST proxy and a Python-first API — with the routing
core re-architected as batched JAX/XLA kernels over HBM-resident
node-ID matrices instead of scalar per-search loops.

Package layout (mirrors the reference's layer map, SURVEY.md §1):

- ``ops``        L0 device kernels: 160-bit ID math, XOR top-k (lax + pallas),
                 sorted-table window lookup, radix partition
- ``core``       L2 data structures: node table, routing, batched search, storage, values
- ``net``        L1 host network engine: msgpack wire protocol, request lifecycle
- ``native``     C++ host runtime: XOR engine + UDP datagram engine (ctypes)
- ``crypto``     L0/L3 identities, sign/encrypt (SecureDht overlay)
- ``runtime``    L4 Dht core + DhtRunner façade + scheduler
- ``parallel``   multi-chip sharded tables (jax.sharding Mesh + shard_map)
- ``proxy``      REST proxy server/client
- ``indexation`` PHT (prefix hash tree) distributed index
- ``tools``      dhtnode / dhtchat / dhtscanner CLI equivalents
- ``testing``    cluster harness: virtual-clock network, scenario suites, benchmark
- ``log``        Logger with per-hash filter and console/file/syslog sinks
"""

__version__ = "0.1.0"

from .infohash import InfoHash, PkId, random_infohash  # noqa: F401
from .core.value import Value, ValueType, Query, Select, Where, Filters  # noqa: F401
from .runtime.config import Config, NodeStats, NodeStatus, SecureDhtConfig  # noqa: F401
from .runtime.runner import DhtRunner, RunnerConfig  # noqa: F401
from .crypto import (  # noqa: F401
    Certificate, Identity, PrivateKey, PublicKey, RevocationList, TrustList,
    VerifyResult, generate_identity, generate_ec_identity,
)
from .sockaddr import SockAddr  # noqa: F401
from .net.node import Node  # noqa: F401
from .nodeset import NodeEntry, NodeSet  # noqa: F401
from .indexation.pht import IndexEntry as IndexValue, Pht  # noqa: F401

#: binding-compat aliases (↔ python/opendht.pyx names)
DhtConfig = Config
#: DhtRunner.listen returns this token handle (a Future resolving to the
#: runner-level token — pass it back to cancel_listen)
import concurrent.futures as _futures
ListenToken = _futures.Future
