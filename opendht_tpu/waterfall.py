"""Per-op latency waterfall: always-on stage profiler + OPEN-bound
tracker (round 19).

Ten rounds of observability report one opaque number per op — a host
wall-clock around ``block_until_ready`` (``dht_op_seconds``).  This
module decomposes where those milliseconds actually go, continuously
and at <1% overhead (captures/waterfall_overhead.json), the
Google-Wide-Profiling posture: a Dapper-style trace says *which* op was
slow, the always-on stage profiler says *why*.

**Stages** (one labeled histogram family, ``dht_stage_seconds{stage=}``):

- ``queue_wait`` — admission → wave pickup, off the round-12 enqueue
  stamp (``_Entry.t_wall``); the continuous-batching coalesce tax.
- ``cache_probe`` — the round-16 hot-cache XOR-compare launch + serve
  window at the head of every wave.
- ``device_compile`` — the FIRST timed launch per (family, k) group
  shape: XLA compilation rides that call, and folding it into the
  serving device stage would poison the p99 forever.  Split host-side
  by first-launch tracking — the kernels themselves are untouched.
- ``dispatch`` — the host-side async-dispatch cost of a wave, measured
  AT LAUNCH (round 22; the ``find_closest_nodes_launch`` call itself).
- ``device_wait`` — the blocking wait actually paid when results are
  used (``BatchedResolve.consume``), measured AT CONSUME.  For
  ``ingest_pipeline_depth=1`` this collapses to the old timed
  launch→block span of ``find_closest_nodes_batched``; at depth 2+ the
  wave's host-overlap window (launch → drain pump) is deliberately NOT
  device cost — it shows as the ``dht.search.wave`` span's wall
  duration, the ``dht_ingest_pipeline_inflight`` gauge (+ windowed
  ``_peak``) and, since round 22, the pipeline observatory's device
  lane (``pipeline_observatory.py``).  ``device_launch`` is a
  one-release alias of ``device_wait`` (:data:`STAGE_ALIASES`) so
  existing ``dhtmon --max-stage`` invocations keep matching.
- ``scatter_back`` — results materialized → each op's scatter callback
  returned (result fan-out + trace recording).
- ``rpc_wait`` — network hop RTTs off the round-4 per-hop spans
  (``net/request.py`` completion; overlaps the device stages, so it is
  excluded from the per-op sum pin below).

Hot buckets carry **exemplars**: each observation under a sampled trace
stamps its bucket with the op's trace id
(:meth:`~opendht_tpu.telemetry.Histogram.observe` ``exemplar=``), so a
p99 bucket links directly to a reconstructable trace via the round-9
assembler (``testing/trace_assembler.assemble_trace``).

**Per-op records**: a bounded ring of ``{kind, trace_id, stages{...},
end_to_end}`` dicts, one per wave-carried op.  The decomposition's
contract — stage sum ≈ end-to-end wall-clock (admission → scatter
returned) within tolerance — is pinned in tests/test_waterfall.py; the
unattributed remainder is the wave-assembly glue (grouping loop, metric
writes), all host-side.

**SLIs**: :meth:`StageProfiler.stage_budget` derives a windowed
worst-stage p95/budget ratio feeding the round-14 health engine as the
degrade-only ``stage_budget`` signal (a slow stage is an efficiency
problem, not a liveness one).

**OPEN-bound tracking**: :class:`OpenBoundTracker` continuously
compares achieved wave p50 / occupancy / churny-static ratio against
the six ``open: true`` entries of perf_budgets.json (ROADMAP item 7)
and exports ``dht_open_bound{key=, status=}`` gauges.  On a real
accelerator it drops a ready-to-commit settling record into
``$OPENDHT_TPU_SMOKE_RECORD_DIR`` (status="candidate"); a CPU run
exercises the same record path with status="unsettled", so the
machinery is CI-tested long before a chip sees it.

Surfaces: proxy ``GET /profile`` (+ ``?fmt=folded`` flamegraph stacks),
the ``profile`` REPL cmd, a ``waterfall`` section in ``dhtscanner
--json``, ``dhtmon --max-stage STAGE=SEC``, and — because the round-17
recorder samples every registry family — stage frames ride the history
ring and appear in black-box bundles automatically.

Import-light (stdlib + telemetry/tracing spine only at module import);
the profiler is process-global like the registry it feeds
(:func:`get_profiler`), so per-node cardinality remains the embedder's
concern — same documented aggregation rule as telemetry.py.
"""

from __future__ import annotations

import json
import os
import threading
import time as _time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from . import telemetry

__all__ = [
    "STAGES", "STAGE_ALIASES", "DEFAULT_STAGE_BUDGETS", "WaterfallConfig",
    "StageProfiler", "OpenBoundTracker", "get_profiler",
]

#: the waterfall stages, in serving-path order (rpc_wait overlaps the
#: device stages — it is a parallel plane, not a pipeline step).
#: Round 22 split the old overlapped ``device_launch`` into
#: ``dispatch`` (host-side async-dispatch cost, measured AT LAUNCH)
#: and ``device_wait`` (the blocking wait actually paid at consume) —
#: at depth >= 2 the two happen pumps apart, and folding them into one
#: stage made in-flight device time reappear as queue_wait.
STAGES = ("queue_wait", "cache_probe", "device_compile", "dispatch",
          "device_wait", "scatter_back", "rpc_wait")

#: one-release compatibility aliases (round 22): old stage name →
#: canonical stage.  ``observe("device_launch", ...)`` and ``dhtmon
#: --max-stage device_launch=...`` keep working against the
#: ``device_wait`` histogram; snapshots mirror the entry under both
#: keys with an ``alias_of`` marker.  Scheduled for removal next
#: release — switch invocations to ``device_wait``.
STAGE_ALIASES = {"device_launch": "device_wait"}

#: per-stage latency budgets (seconds) the ``stage_budget`` health
#: signal and ``dhtmon --max-stage`` default to: generous CPU-safe
#: ceilings — a stage sitting at its budget's p95 is *degraded*, at 2x
#: *unhealthy-grade* (but the signal is degrade-only in the verdict)
DEFAULT_STAGE_BUDGETS = {
    "queue_wait": 0.020,      # 10x the default ingest deadline knob
    "cache_probe": 0.050,
    "device_compile": 120.0,  # one-time XLA lowering, not a serving SLI
    "dispatch": 0.050,        # host async-dispatch share of a wave
    "device_wait": 0.250,
    "scatter_back": 0.050,
    "rpc_wait": 3.5,          # 3 attempts x 1 s + slack (request.py)
}

#: minimum new observations inside a budget window before the signal
#: reports (one slow wave at boot is not a trend)
_BUDGET_MIN_EVENTS = 4


@dataclass
class WaterfallConfig:
    """Knob surface (``runtime.config.Config.waterfall``)."""

    #: master switch: False stops stage observation and per-op records
    #: (results are identical either way — the profiler only observes)
    enabled: bool = True
    #: bounded per-op record ring (the sum≈end-to-end evidence)
    op_ring: int = 256
    #: per-stage budget overrides (seconds) merged over
    #: :data:`DEFAULT_STAGE_BUDGETS`
    budgets: dict = field(default_factory=dict)
    #: seconds between OPEN-bound tracker refreshes on the node
    #: scheduler; 0 disables the tracker tick
    open_bound_period: float = 5.0


class StageProfiler:
    """Always-on per-stage latency aggregator (see module docstring).

    One instance per process (:func:`get_profiler`); every hook is a
    cached-handle histogram observe — cheap enough for the per-RPC and
    per-wave hot paths."""

    def __init__(self, cfg: Optional[WaterfallConfig] = None,
                 reg: Optional[telemetry.MetricsRegistry] = None):
        self.cfg = cfg or WaterfallConfig()
        self._reg = reg or telemetry.get_registry()
        self.enabled = self.cfg.enabled
        self._h = {s: self._reg.histogram("dht_stage_seconds", stage=s)
                   for s in STAGES}
        # aliases map to the SAME Histogram object: an old-name observe
        # or a direct _h["device_launch"] access lands in the canonical
        # series — nothing double-counts, nothing goes dark
        for old, new in STAGE_ALIASES.items():
            self._h[old] = self._h[new]
        self._ops: deque = deque(maxlen=max(1, self.cfg.op_ring))
        self._compiled: set = set()       # (af, k) groups already launched
        self.budgets = dict(DEFAULT_STAGE_BUDGETS)
        self.budgets.update(self._resolve_budget_aliases(self.cfg.budgets))
        # budget-window baselines: stage -> (count, sum, {bucket: n})
        self._win_prev: Dict[str, tuple] = {}
        self._lock = threading.Lock()
        self._publish_budgets()

    @staticmethod
    def _resolve_budget_aliases(budgets: Optional[dict]) -> dict:
        """Config budget overrides keyed by an aliased stage name apply
        to the canonical stage (one-release compatibility)."""
        return {STAGE_ALIASES.get(k, k): v for k, v in (budgets or {}).items()}

    def _publish_budgets(self) -> None:
        """Stage budgets as ``dht_stage_budget_seconds{stage=}`` gauges
        on the profiler's registry, so every scrape carries the
        thresholds next to the achieved ``dht_stage_seconds``
        distributions (a dashboard plots p95 vs budget without repo
        access)."""
        for stage, sec in self.budgets.items():
            self._reg.gauge("dht_stage_budget_seconds", stage=stage).set(sec)

    def configure(self, cfg: WaterfallConfig) -> None:
        """Re-apply a node's config to the process-global profiler
        (the documented aggregation rule: last node wins, like the
        shared registry)."""
        self.cfg = cfg
        self.enabled = cfg.enabled
        self.budgets = dict(DEFAULT_STAGE_BUDGETS)
        self.budgets.update(self._resolve_budget_aliases(cfg.budgets))
        if self._ops.maxlen != max(1, cfg.op_ring):
            self._ops = deque(self._ops, maxlen=max(1, cfg.op_ring))
        self._publish_budgets()

    # ------------------------------------------------------------ observes
    def observe(self, stage: str, seconds: float,
                exemplar: Optional[str] = None) -> None:
        """One stage sample; ``exemplar`` is the op's 32-hex trace id
        (stamped on the landing bucket so a hot bucket links to a
        reconstructable trace)."""
        if not self.enabled:
            return
        self._h[stage].observe(seconds, exemplar=exemplar)

    def first_launch(self, key) -> bool:
        """True exactly once per launch-group shape ``key`` — the
        compile-vs-execute split: the first timed launch of a group
        carries XLA lowering and lands in ``device_compile``."""
        if key in self._compiled:
            return False
        with self._lock:
            if key in self._compiled:
                return False
            self._compiled.add(key)
            return True

    def record_op(self, kind: str, stages: Dict[str, float],
                  end_to_end: float,
                  trace_id: Optional[str] = None) -> None:
        """Append one per-op decomposition record to the bounded ring."""
        if not self.enabled:
            return
        self._ops.append({
            "kind": kind,
            "trace_id": trace_id,
            "stages": stages,
            "end_to_end": end_to_end,
            "t": _time.time(),
        })

    def ops(self) -> List[dict]:
        return list(self._ops)

    # ---------------------------------------------------------------- SLIs
    def stage_budget(self) -> Optional[float]:
        """Windowed worst-stage p95/budget ratio — the degrade-only
        ``stage_budget`` health signal's value.  Each call diffs the
        stage histograms against the previous call's baselines (the
        health tick cadence IS the window), so the signal tracks
        current behavior, not boot history.  None (unknown) when no
        stage accrued :data:`_BUDGET_MIN_EVENTS` new samples —
        ``device_compile`` is excluded (one-time cost, budgeted but
        not a serving trend)."""
        worst = None
        with self._lock:
            for stage in STAGES:
                if stage == "device_compile":
                    continue
                cur = self._h[stage].raw()
                prev = self._win_prev.get(stage, (0, 0.0, {}))
                self._win_prev[stage] = cur
                dcount = cur[0] - prev[0]
                if dcount < _BUDGET_MIN_EVENTS:
                    continue
                db = {i: c - prev[2].get(i, 0)
                      for i, c in cur[2].items()
                      if c - prev[2].get(i, 0) > 0}
                p95 = telemetry.quantile_from_buckets(
                    sorted(db.items()), dcount, 0.95)
                ratio = p95 / self.budgets[stage]
                if worst is None or ratio > worst:
                    worst = ratio
        return worst

    # -------------------------------------------------------------- export
    def snapshot(self) -> dict:
        """JSON-able waterfall: per-stage count/sum/p50/p95/p99 with
        bucket exemplars, the budgets, and the recent per-op records —
        what ``GET /profile``, the REPL ``profile`` cmd and the
        scanner's ``waterfall`` section all serve."""
        stages = {}
        for s in STAGES:
            h = self._h[s]
            d = h.to_dict()
            d["p50"] = h.quantile(0.50)
            d["p95"] = h.quantile(0.95)
            d["p99"] = h.quantile(0.99)
            stages[s] = d
        # one-release alias mirror: readers keyed on the old name see
        # the canonical stage's data, marked so they can migrate
        for old, new in STAGE_ALIASES.items():
            stages[old] = dict(stages[new], alias_of=new)
        return {
            "enabled": self.enabled,
            "stages": stages,
            "budgets": dict(self.budgets),
            "ops": self.ops(),
        }

    def folded(self) -> str:
        """Flamegraph-shaped folded stacks (``stack weight`` lines,
        weight = cumulative stage microseconds): feed straight into
        ``flamegraph.pl`` / speedscope.  The op root frame carries the
        end-to-end sums so the stage children visually subdivide it."""
        lines = []
        for s in STAGES:
            h = self._h[s]
            us = int(h.sum * 1e6)
            if us > 0:
                lines.append("dht;op;%s %d" % (s, us))
        return "\n".join(lines) + ("\n" if lines else "")


# ===================================================== OPEN-bound tracker
#: keys the tracker serves — exactly the six ``open: true`` entries of
#: perf_budgets.json (ROADMAP item 7); asserted at load so a renamed
#: budget entry fails loudly instead of silently going untracked
OPEN_BOUND_KEYS = (
    "cache_flood_p50", "churny_static_ratio", "ingest_wave_occupancy",
    "listener_wave_1m", "maintenance_sweep_config4", "shard_wave_10m",
    "wave_p50_ms_1024",
)


def _repo_budgets_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "perf_budgets.json")


def _agg_quantile(series: dict, q: float, want: Optional[dict] = None):
    """Quantile over the merged buckets of every label series of one
    histogram family (optionally filtered to series whose labels
    contain ``want``); None when nothing matched or nothing observed."""
    total = 0
    acc: Dict[int, int] = {}
    for key, h in series.items():
        if want and any(dict(key).get(k) != v for k, v in want.items()):
            continue
        c, _s, b = h.raw()
        total += c
        for i, n in b.items():
            acc[i] = acc.get(i, 0) + n
    if total <= 0:
        return None
    return telemetry.quantile_from_buckets(sorted(acc.items()), total, q)


class OpenBoundTracker:
    """Live comparison of achieved serving metrics against the six
    ``open: true`` accelerator bounds (see module docstring).

    ``status`` is decided once per process from the jax backend:
    ``"unsettled"`` off-accelerator (the measurement exists but cannot
    settle the bound), ``"candidate"`` on a real accelerator (the
    settling record is ready to commit) — fixed per run so the gauge's
    label set never churns."""

    def __init__(self, reg: Optional[telemetry.MetricsRegistry] = None,
                 budgets_path: Optional[str] = None):
        self._reg = reg or telemetry.get_registry()
        self._job = None
        self._sched = None
        self.period = 5.0
        path = budgets_path or _repo_budgets_path()
        self.bounds: Dict[str, dict] = {}
        try:
            with open(path) as fh:
                doc = json.load(fh)
            self.bounds = {k: v for k, v in
                           (doc.get("open_bounds") or {}).items()
                           if v.get("open")}
        except Exception:
            pass                    # no budgets file: tracker degrades
        self.platform = self._detect_platform()
        self.status = ("unsettled" if self.platform == "cpu"
                       else "candidate")
        self._g = {k: self._reg.gauge("dht_open_bound", key=k,
                                      status=self.status)
                   for k in self.bounds}
        self._last: Dict[str, Optional[float]] = {}

    @staticmethod
    def _detect_platform() -> str:
        try:
            import jax
            return str(jax.default_backend())
        except Exception:
            return "cpu"

    # -------------------------------------------------------- measurements
    def _measure(self, key: str) -> Optional[float]:
        """The bound's live measurement off the registry (None =
        nothing observed yet); units follow the budget entry's metric
        text — milliseconds for the p50 bounds, a ratio for
        churny_static_ratio, a mean for ingest_wave_occupancy."""
        reg = self._reg
        if key == "wave_p50_ms_1024":
            p = _agg_quantile(reg.series("dht_search_wave_seconds"), 0.5,
                              {"mode": "single"})
            return None if p is None else p * 1e3
        if key == "shard_wave_10m":
            p = _agg_quantile(reg.series("dht_search_wave_seconds"), 0.5,
                              {"mode": "tp"})
            return None if p is None else p * 1e3
        if key == "maintenance_sweep_config4":
            p = _agg_quantile(reg.series("dht_maintenance_sweep_seconds"),
                              0.5)
            return None if p is None else p * 1e3
        if key == "churny_static_ratio":
            static = _agg_quantile(reg.series("dht_search_wave_seconds"),
                                   0.5)
            churn = _agg_quantile(reg.series("dht_churn_lookup_seconds"),
                                  0.5)
            if static is None or churn is None or static <= 0:
                return None
            # the budget's ratio is churny/static THROUGHPUT >= 0.6,
            # i.e. static p50 latency / churny p50 latency
            return static / churn
        if key == "ingest_wave_occupancy":
            # round 22: prefer the pipeline observatory's MEASURED
            # device-occupancy gauge (fraction of wall clock with >= 1
            # wave in flight, windowed on the history cadence) — the
            # bound tracks live utilization now, not a settling command
            # alone.  -1 is the gauge's "unknown" sentinel; fall back
            # to the wave-width histogram mean until it goes live.
            for _k, g in reg.series("dht_pipeline_occupancy").items():
                if g.value >= 0.0:
                    return float(g.value)
            occ = None
            for _k, h in reg.series("dht_ingest_wave_occupancy").items():
                c, s, _b = h.raw()
                if c > 0:
                    occ = s / c
            return occ
        if key == "cache_flood_p50":
            p = _agg_quantile(reg.series("dht_op_seconds"), 0.5,
                              {"op": "get"})
            return None if p is None else p * 1e3
        if key == "listener_wave_1m":
            # round 24: the batched listener-match launch latency —
            # the bound claims one wave's stored puts matched against
            # a million-listener device table in single-digit ms
            p = _agg_quantile(reg.series("dht_listener_match_seconds"),
                              0.5)
            return None if p is None else p * 1e3
        return None

    def refresh(self) -> dict:
        """Recompute every bound's measurement and push the
        ``dht_open_bound{key=, status=}`` gauges (-1 = no measurement
        available yet — gauges have no 'unknown', so the sentinel keeps
        the series live from boot)."""
        out = {}
        for key in self.bounds:
            v = self._measure(key)
            self._last[key] = v
            self._g[key].set(-1.0 if v is None else v)
            out[key] = {
                "status": self.status,
                "value": v,
                "metric": self.bounds[key].get("metric", ""),
                "target": self.bounds[key].get("target", ""),
            }
        return out

    def snapshot(self) -> dict:
        return {
            "platform": self.platform,
            "status": self.status,
            "period": self.period,
            "bounds": self.refresh(),
        }

    # ----------------------------------------------------- settling record
    def write_record(self, record_dir: Optional[str] = None) -> Optional[str]:
        """Drop the settling record into ``$OPENDHT_TPU_SMOKE_RECORD_DIR``
        (or ``record_dir``): one JSON doc per process with every bound
        that has a live measurement.  On an accelerator this is the
        ready-to-commit evidence ROADMAP item 7 asks for; a CPU run
        writes the identical shape with status="unsettled" so CI
        exercises the path continuously.  Returns the path (None when
        no dir is configured or nothing measured yet)."""
        d = record_dir or os.environ.get("OPENDHT_TPU_SMOKE_RECORD_DIR")
        if not d or not self.bounds:
            return None
        measured = {k: v for k, v in self._last.items() if v is not None}
        if not measured:
            return None
        doc = {
            "name": "open_bounds",
            "platform": self.platform,
            "status": self.status,
            "time": _time.time(),
            "bounds": {
                k: {"value": measured[k],
                    "metric": self.bounds[k].get("metric", ""),
                    "settle": self.bounds[k].get("settle", ""),
                    "status": self.status}
                for k in sorted(measured)
            },
        }
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, "open_bounds.json")
            with open(path, "w") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
                fh.write("\n")
            return path
        except OSError:
            return None

    # ----------------------------------------------------------- scheduling
    def attach(self, scheduler, period: Optional[float] = None) -> None:
        """Periodic refresh on the node scheduler (the same thread as
        every other observatory tick); also re-drops the settling
        record so the freshest measurements are what a smoke harvest
        collects."""
        if period is not None:
            self.period = period
        if self.period <= 0 or self._job is not None or not self.bounds:
            return
        self._sched = scheduler
        self._job = scheduler.add(scheduler.time() + self.period,
                                  self._tick)

    def _tick(self) -> None:
        try:
            self.refresh()
            self.write_record()
        finally:
            self._job = self._sched.add(
                self._sched.time() + self.period, self._tick)


_global_profiler: Optional[StageProfiler] = None
_profiler_lock = threading.Lock()


def get_profiler() -> StageProfiler:
    """The process-global stage profiler every layer feeds by default
    (the waterfall analogue of ``telemetry.get_registry``)."""
    global _global_profiler
    if _global_profiler is None:
        with _profiler_lock:
            if _global_profiler is None:
                _global_profiler = StageProfiler()
    return _global_profiler
