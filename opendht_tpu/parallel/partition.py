"""Declarative sharding rules for mesh-placed DHT state.

Large-model JAX codebases place parameter-sized state with one pattern
(SNIPPETS.md retrieved three instances of it): a list of **regex
partition rules** matched against the /-joined names of a state pytree
yields a pytree of :class:`~jax.sharding.PartitionSpec`, which turns
into per-leaf :class:`~jax.sharding.NamedSharding` **shard/gather
functions** — host arrays go straight to their device slices (no
replicated staging copy), device arrays reshard in place, and loop
bodies pin intermediates with ``with_sharding_constraint``.  This
module is that layer for the DHT's table state, replacing the
hand-rolled per-entry ``jnp.asarray`` + ``device_put`` placement that
``parallel/sharded.py`` grew one function at a time.

The named state it exists for is :func:`shard_table_state`'s pytree —
the row-sharded sorted table that scales the iterative search engine
past one chip's HBM (ROADMAP item 1):

``sorted_ids``   uint32 [N, 5]        ``P('t', None)`` — each ``t``
                 shard owns one contiguous range of the global sorted
                 order (the Kademlia analog: a node owns the contiguous
                 XOR neighborhood around its id, PARITY.md).
``local_lut``    int32 [n_t, 2^lb+1]  ``P('t', None)`` — per-shard
                 positioning LUT over the shard's own rows, built once
                 (the old layout re-derived it inside every launch).
``block_lut``    int32 [2^bb+1]       replicated — the GLOBAL prefix
                 LUT, assembled as ONE one-shot psum of the per-shard
                 LUTs at table-build time.  Entry p of a shard's LUT is
                 its local count of valid rows with prefix < p, and the
                 global count is the sum, so the replicated table is
                 bit-identical to ``build_prefix_lut`` over the whole
                 id set.  This is what removes the per-hop block-edge
                 psum from the engine's steady-state round: reply-block
                 edges become two LOCAL reads, and the round's only
                 collective is the reply-row merge
                 (``sharded.build_tp_lookup``).
``n_valid``      int32 scalar         replicated.

Rules are matched first-hit in order; every leaf must match (the
catch-all ``.*`` → replicated rule closes the list, as in the
reference pattern).  Scalars and 0-d leaves never partition.
"""

from __future__ import annotations

import functools
import re
from typing import NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax moved shard_map out of experimental AND (separately, later)
# renamed check_rep → check_vma; the two changes don't coincide, so the
# kwarg is chosen by the resolved function's own signature rather than
# by where it lives (a mid-window release has top-level jax.shard_map
# that still takes check_rep).  Resolved once here; parallel/sharded.py
# imports the resolved pair so every shard_map builder in the package
# is version-agnostic.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:                                     # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map
import inspect as _inspect
try:
    _sm_params = _inspect.signature(shard_map).parameters
except (TypeError, ValueError):           # C-level/odd callables
    _sm_params = {}
SHARD_MAP_KW = ({"check_vma": False} if "check_vma" in _sm_params
                else {"check_rep": False} if "check_rep" in _sm_params
                else {})


def tree_paths(tree):
    """Pytree of '/'-joined string names, one per leaf (dict keys and
    sequence indices), the name space the partition rules match."""
    paths_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    names = []
    for path, _leaf in paths_leaves:
        parts = []
        for entry in path:
            key = getattr(entry, "key", getattr(entry, "idx",
                                                getattr(entry, "name", None)))
            parts.append(str(key))
        names.append("/".join(parts))
    treedef = jax.tree_util.tree_structure(tree)
    return jax.tree_util.tree_unflatten(treedef, names)


def match_partition_rules(rules, tree):
    """Pytree of PartitionSpec from ``rules``: an ordered list of
    ``(regex, PartitionSpec)`` searched against each leaf's /-joined
    name — the declarative placement pattern of large-model JAX
    codebases (SNIPPETS.md).  Scalar leaves are never partitioned;
    a leaf matching no rule is an error (close rule lists with
    ``(".*", P())``)."""
    def spec_of(name, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()                        # never partition scalars
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(f"no partition rule matches leaf {name!r} "
                         f"(shape {shape}) — add a rule or a catch-all")
    return jax.tree_util.tree_map(spec_of, tree_paths(tree), tree)


def make_shard_and_gather_fns(mesh: Mesh, partition_specs):
    """Per-leaf (shard_fns, gather_fns) pytrees from a PartitionSpec
    pytree.

    A shard fn places ONE leaf under its NamedSharding: host (numpy)
    arrays are ``device_put`` **directly to the sharding** — each
    device receives only its slice, never a replicated staging copy
    (the transient 2× HBM spike of ``jnp.asarray`` + re-placement that
    ``dp_simulate_lookups`` used to pay); committed device arrays
    reshard via a jitted identity pinned by ``out_shardings``.  A
    gather fn is the inverse: one jitted identity to the fully
    replicated spec, returned as numpy.
    """
    shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), partition_specs,
        is_leaf=lambda x: isinstance(x, P))
    is_ns = lambda x: isinstance(x, NamedSharding)     # noqa: E731
    return (jax.tree_util.tree_map(_shard_fn_for, shardings, is_leaf=is_ns),
            jax.tree_util.tree_map(_gather_fn_for, shardings, is_leaf=is_ns))


@functools.lru_cache(maxsize=256)
def _shard_fn_for(sharding: NamedSharding):
    """Placement fn for one NamedSharding (memoized — repeated waves
    reuse one compiled reshard identity per sharding)."""
    @functools.partial(jax.jit, out_shardings=sharding)
    def _reshard(x):
        return jnp.asarray(x)

    def shard_fn(x):
        if getattr(x, "sharding", None) == sharding:
            return x                          # already placed
        if isinstance(x, (np.ndarray, np.generic)) or np.isscalar(x):
            return jax.device_put(x, sharding)
        return _reshard(x)
    return shard_fn


@functools.lru_cache(maxsize=256)
def _gather_fn_for(sharding: NamedSharding):
    rep = NamedSharding(sharding.mesh, P())

    @functools.partial(jax.jit, out_shardings=rep)
    def _gather(x):
        return jnp.asarray(x)

    def gather_fn(x):
        return np.asarray(_gather(x))
    return gather_fn


def shard_put(mesh: Mesh, tree, rules):
    """Place a whole named pytree by rule match — the one-call form the
    ``parallel/sharded.py`` entry points use."""
    specs = match_partition_rules(rules, tree)
    shard_fns, _ = make_shard_and_gather_fns(mesh, specs)
    return jax.tree_util.tree_map(lambda fn, x: fn(x), shard_fns, tree)


def constrain(tree, mesh: Mesh, rules):
    """``with_sharding_constraint`` every leaf of a named pytree to its
    rule-matched spec — for use INSIDE jitted bodies (the dp engine's
    query-axis pin), where placement is a compiler constraint rather
    than a transfer."""
    specs = match_partition_rules(rules, tree)
    return jax.tree_util.tree_map(
        lambda x, spec: lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec)),
        tree, specs)


# --------------------------------------------------------------------------
# The DHT table-state rules.  First match wins; names are the keys of
# the pytrees the parallel/ entry points build.
# --------------------------------------------------------------------------

#: row-sharded table state (the t axis owns rows; see module docstring)
TABLE_AXIS_RULES = (
    (r"sorted_ids$|^ids$|^table$|expanded$", P("t", None)),
    (r"local_lut$", P("t", None)),
    (r"block_lut$", P()),
    # load-aware reshard geometry (ISSUE-17): per-shard (base, width)
    # row ranges of a traffic-weighted split — a [t, 2] int32 operand,
    # one row per shard, so boundary moves are data, never a recompile
    (r"shard_rows$", P("t", None)),
    # `valid$` also covers the sketch twin's `sketch_valid` mask
    (r"perm$|valid$|n_local$|last_reply$", P("t")),
    # keyspace sketch traffic (ISSUE-10): the wave's observed ids split
    # over the table axis — each shard builds a partial sketch, one
    # psum pair merges (sharded.py sharded_sketch_update)
    (r"sketch_ids$", P("t", None)),
    # hot-cache probe traffic (ISSUE-11): the wave's probe targets
    # split over the table axis — the tiny [C, 5] cache table rides
    # replicated, each shard XOR-compares its target rows locally
    # (sharded.py sharded_cache_probe; fully data-parallel, no
    # collective)
    (r"probe_ids$", P("t", None)),
    (r"targets$|queries$", P("q", None)),
    (r".*", P()),
)

#: data-parallel engine state (table replicated, queries over the
#: whole mesh) — dp_simulate_lookups
DP_AXIS_RULES = (
    (r"targets$|queries$", P(("q", "t"), None)),
    (r".*", P()),
)


class TableState(NamedTuple):
    """A row-sharded sorted table, placed once and reused across waves
    (:func:`shard_table_state`).  ``arrays`` is the named pytree whose
    leaves sit under :data:`TABLE_AXIS_RULES`; the ints are the static
    geometry ``sharded.build_tp_lookup`` compiles against."""
    arrays: dict
    shard_n: int
    lut_bits: int
    block_bits: int
    #: interior row boundaries of a load-aware split (None = uniform
    #: N/t rows per shard).  When set, ``arrays`` carries a
    #: ``shard_rows`` [t, 2] operand and ``shard_n`` is the rounded-up
    #: per-shard row CAPACITY, not the uniform width.
    boundaries: Optional[tuple] = None

    @property
    def sorted_ids(self):
        return self.arrays["sorted_ids"]

    def table_bytes_per_shard(self) -> int:
        """Resident sorted-table bytes on ONE device — the N/t·5·4 B
        figure the per-shard HBM budget bounds (benchmarks/
        exp_shard_r13.py; ci/run_ci.sh asserts it on the 8-device
        mesh)."""
        return self.shard_n * self.sorted_ids.shape[1] * 4


@functools.lru_cache(maxsize=16)
def _build_state_luts(mesh: Mesh, shard_n: int, lut_bits: int,
                      block_bits: int):
    from ..ops.sorted_table import build_prefix_lut

    def local(sorted_shard, n_valid):
        ti = lax.axis_index("t")
        n_local = jnp.clip(jnp.asarray(n_valid, jnp.int32)
                           - ti.astype(jnp.int32) * shard_n, 0, shard_n)
        lut = build_prefix_lut(sorted_shard, n_local, bits=lut_bits)
        part = (lut if block_bits == lut_bits else
                build_prefix_lut(sorted_shard, n_local, bits=block_bits))
        # entry p of each shard's LUT counts LOCAL valid rows with
        # prefix < p; the sum over shards is the global count — ONE
        # one-shot psum yields the replicated global prefix LUT,
        # bit-identical to build_prefix_lut over the whole table
        block_lut = lax.psum(part, "t")
        return lut[None], block_lut

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P("t", None), P()),
        out_specs=(P("t", None), P()),
        **SHARD_MAP_KW,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=16)
def _build_state_luts_weighted(mesh: Mesh, lut_bits: int, block_bits: int):
    """Weighted-split twin of :func:`_build_state_luts`: each shard's
    valid width comes from its ``shard_rows`` row instead of the
    uniform ``n - ti*shard_n`` clip.  Because the (base, width) ranges
    PARTITION the valid rows exactly, the psum of per-shard prefix LUTs
    is still bit-identical to ``build_prefix_lut`` over the whole
    table — the exactness argument never depended on equal widths."""
    from ..ops.sorted_table import build_prefix_lut

    def local(sorted_shard, shard_rows):
        n_local = shard_rows[0, 1]
        lut = build_prefix_lut(sorted_shard, n_local, bits=lut_bits)
        part = (lut if block_bits == lut_bits else
                build_prefix_lut(sorted_shard, n_local, bits=block_bits))
        block_lut = lax.psum(part, "t")
        return lut[None], block_lut

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P("t", None), P("t", None)),
        out_specs=(P("t", None), P()),
        **SHARD_MAP_KW,
    )
    return jax.jit(fn)


#: weighted shard capacities round up to a multiple of this, so a
#: boundary nudge reuses the compiled kernels instead of recompiling
#: for every new max-width
RESHARD_ALIGN = 256


def shard_table_state(mesh: Mesh, sorted_ids, n_valid, *,
                      lut_bits: Optional[int] = None,
                      block_bits: Optional[int] = None,
                      boundaries=None) -> TableState:
    """Split a GLOBALLY sorted id table over the mesh ``t`` axis and
    derive its lookup state — built ONCE per table, reused across every
    wave (``tp_simulate_lookups(..., state=)``).

    Row count must divide ``mesh.shape['t']`` (pad with invalid rows
    via :func:`~opendht_tpu.parallel.sharded.pad_to_multiple`; pad rows
    land on the LAST shard since padding appends past the valid
    prefix).  Placement goes through :data:`TABLE_AXIS_RULES` — a host
    array is sliced straight onto its owners.  ``lut_bits`` sizes the
    per-shard positioning LUT (default ``default_lut_bits(shard_n)``);
    ``block_bits`` the replicated global block LUT (default
    ``default_lut_bits(N)`` — it must match the single-device engine's
    width for bit-identity, core/search.py ``_lut_block_bounds``).

    ``boundaries`` (ISSUE-17, load-aware resharding) is an optional
    sequence of ``t-1`` interior row indices into the VALID prefix of
    the sorted order (:func:`solve_shard_boundaries`).  Shard ``i``
    then owns rows ``[b_i, b_{i+1})`` — still contiguous in the global
    sort, just not equal-width.  Because ``P('t', None)`` placement
    needs equal chunks per device, the weighted layout is physically
    realized as a REARRANGED equal-capacity table: each shard's rows
    are copied to the start of a ``shard_cap``-row slab (capacity =
    max width rounded up to :data:`RESHARD_ALIGN`), and a ``shard_rows``
    [t, 2] operand carries each shard's (base, width).  Reshard is row
    movement + LUT rebuild — never a re-sort."""
    from ..ops.sorted_table import default_lut_bits
    N = sorted_ids.shape[0]
    n_t = mesh.shape["t"]
    if boundaries is not None:
        return _shard_table_state_weighted(
            mesh, sorted_ids, n_valid, boundaries,
            lut_bits=lut_bits, block_bits=block_bits)
    if N % n_t:
        raise ValueError(f"table rows ({N}) not divisible by t={n_t}; "
                         f"pad with invalid rows via pad_to_multiple")
    shard_n = N // n_t
    lb = lut_bits or default_lut_bits(shard_n)
    bb = block_bits or default_lut_bits(N)
    # normalize dtype BEFORE placement: the kernels are uint32-limb
    # programs, and an int64 table silently produces wrong lookups
    if hasattr(sorted_ids, "sharding"):
        if sorted_ids.dtype != jnp.uint32:
            sorted_ids = sorted_ids.astype(jnp.uint32)
    else:
        sorted_ids = np.asarray(sorted_ids, np.uint32)
    placed = shard_put(mesh, {"sorted_ids": sorted_ids}, TABLE_AXIS_RULES)
    nv = jnp.asarray(n_valid, jnp.int32)
    local_lut, block_lut = _build_state_luts(mesh, shard_n, lb, bb)(
        placed["sorted_ids"], nv)
    return TableState(
        arrays={"sorted_ids": placed["sorted_ids"], "local_lut": local_lut,
                "block_lut": block_lut, "n_valid": nv},
        shard_n=shard_n, lut_bits=lb, block_bits=bb)


def _shard_table_state_weighted(mesh: Mesh, sorted_ids, n_valid, boundaries,
                                *, lut_bits=None, block_bits=None):
    from ..ops.sorted_table import default_lut_bits
    N = int(sorted_ids.shape[0])
    n_t = int(mesh.shape["t"])
    n = int(n_valid)
    ids_host = np.asarray(sorted_ids, np.uint32)
    b = np.asarray(boundaries, np.int64).reshape(-1)
    if b.shape[0] != n_t - 1:
        raise ValueError(f"expected {n_t - 1} interior boundaries for "
                         f"t={n_t}, got {b.shape[0]}")
    bounds = np.concatenate([[0], np.clip(b, 0, n), [n]])
    bounds = np.maximum.accumulate(bounds)
    widths = np.diff(bounds)
    shard_cap = int(-(-max(int(widths.max()), 1) // RESHARD_ALIGN)
                    * RESHARD_ALIGN)
    ids_re = np.zeros((n_t * shard_cap, ids_host.shape[1]), np.uint32)
    for i in range(n_t):
        w = int(widths[i])
        ids_re[i * shard_cap:i * shard_cap + w] = (
            ids_host[int(bounds[i]):int(bounds[i + 1])])
    shard_rows = np.stack([bounds[:-1], widths], axis=1).astype(np.int32)
    lb = lut_bits or default_lut_bits(shard_cap)
    # block width stays keyed to the ORIGINAL table size: bit-identity
    # with the single-device engine requires the same global LUT shape
    # regardless of how the rows are cut
    bb = block_bits or default_lut_bits(N)
    placed = shard_put(mesh, {"sorted_ids": ids_re,
                              "shard_rows": shard_rows}, TABLE_AXIS_RULES)
    nv = jnp.asarray(n, jnp.int32)
    local_lut, block_lut = _build_state_luts_weighted(mesh, lb, bb)(
        placed["sorted_ids"], placed["shard_rows"])
    return TableState(
        arrays={"sorted_ids": placed["sorted_ids"], "local_lut": local_lut,
                "block_lut": block_lut, "n_valid": nv,
                "shard_rows": placed["shard_rows"]},
        shard_n=shard_cap, lut_bits=lb, block_bits=bb,
        boundaries=tuple(int(x) for x in bounds[1:-1]))


# --------------------------------------------------------------------------
# Load-aware boundary solver (ISSUE-17).  Pure numpy — it runs on the
# node scheduler thread per rebalance tick, not on device.
# --------------------------------------------------------------------------

def _blend_bin_weights(meas, loads, load_weight):
    """Per-bin weight: ``(1-λ)·rows/R + λ·loads/L``.  λ clips to
    [0, 1]; a cold table (zero observed load) forces λ=0 so the solve
    degrades to the row-uniform split."""
    meas = np.asarray(meas, np.float64).reshape(-1)
    if loads is None:
        loads = np.zeros_like(meas)
    else:
        loads = np.asarray(loads, np.float64).reshape(-1)
    if loads.shape != meas.shape:
        raise ValueError(f"bin shapes differ: {meas.shape} vs {loads.shape}")
    lam = min(max(float(load_weight), 0.0), 1.0)
    L = float(loads.sum())
    R = float(meas.sum())
    if L <= 0.0:
        lam = 0.0
    w = np.zeros_like(meas)
    if R > 0.0 and lam < 1.0:
        w += (1.0 - lam) * meas / R
    if lam > 0.0:
        w += lam * np.clip(loads, 0.0, None) / L
    return w


def _solve_crossings(w, t):
    """Interior equal-weight crossings of a per-bin weight profile.

    Returns ``t-1`` pairs ``(bin, frac)``: crossing ``i`` sits at
    fraction ``frac ∈ (0, 1]`` through ``bin`` — the first point where
    cumulative weight reaches ``i/t`` of the total (weight is treated
    as uniform WITHIN a bin, the same assumption ``keyspace.fold_bins``
    makes when apportioning a straddled bin by overlap)."""
    w = np.asarray(w, np.float64)
    cumw = np.concatenate([[0.0], np.cumsum(w)])
    W = float(cumw[-1])
    out = []
    for i in range(1, int(t)):
        if W <= 0.0:
            out.append((0, 0.0))
            continue
        T = W * i / float(t)
        # first e with cumw[e] >= T; e >= 1 since cumw[0] = 0 < T
        e = int(np.searchsorted(cumw, T, side="left"))
        e = min(max(e, 1), len(w))
        bin_ = e - 1
        frac = (T - cumw[bin_]) / w[bin_] if w[bin_] > 0.0 else 1.0
        out.append((bin_, float(min(max(frac, 0.0), 1.0))))
    return out


def solve_shard_boundaries(bin_rows, bin_loads, t, *, load_weight=1.0):
    """Traffic-weighted split points, snapped to real row boundaries.

    ``bin_rows[b]`` counts the sorted table's valid rows whose top id
    byte is ``b`` (the same 256-bin space as the keyspace observatory's
    load histogram ``bin_loads``).  Returns ``t-1`` nondecreasing row
    indices in ``[0, n]``: boundary ``i`` is the SMALLEST row count r
    such that the blended weight of rows ``[0, r)`` reaches ``i/t`` of
    the total — each shard ``[b_i, b_{i+1})`` then carries ~equal
    weighted traffic.  With ``load_weight=0`` (or a cold histogram)
    this is the row-uniform split ``ceil(i·n/t)``."""
    bin_rows = np.asarray(bin_rows, np.int64).reshape(-1)
    n = int(bin_rows.sum())
    w = _blend_bin_weights(bin_rows, bin_loads, load_weight)
    row_start = np.concatenate([[0], np.cumsum(bin_rows)])
    out = np.zeros(int(t) - 1, np.int64)
    for i, (b, frac) in enumerate(_solve_crossings(w, t)):
        r_b = int(bin_rows[b]) if b < bin_rows.shape[0] else 0
        # within-bin row offset: smallest j with j/r_b >= frac (uniform
        # weight within the bin ⇒ weight of j rows is frac·w_b at
        # j = frac·r_b); the tiny eps keeps exact multiples from
        # rounding up a row
        j = int(np.ceil(frac * r_b - 1e-9)) if r_b > 0 else 0
        out[i] = int(row_start[b]) + min(max(j, 0), r_b)
    out = np.clip(out, 0, n)
    return np.maximum.accumulate(out)


def solve_shard_edges(bin_loads, t, *, load_weight=1.0, bin_rows=None):
    """Fractional-bin-coordinate form of the solve, for VIRTUAL
    attribution (no live mesh): returns ``t-1`` nondecreasing floats in
    ``[0, bins]``, directly consumable by ``keyspace.fold_bins``.  The
    cold measure defaults to a uniform ring (ones per bin), so a cold
    table yields exactly ``keyspace.bin_edges_uniform(t)``."""
    bin_loads = np.asarray(bin_loads, np.float64).reshape(-1)
    meas = (np.ones_like(bin_loads) if bin_rows is None
            else np.asarray(bin_rows, np.float64).reshape(-1))
    w = _blend_bin_weights(meas, bin_loads, load_weight)
    if float(w.sum()) <= 0.0:
        bins = bin_loads.shape[0]
        return np.asarray([bins * i / float(t) for i in range(1, int(t))],
                          np.float64)
    edges = np.asarray([b + frac for b, frac in _solve_crossings(w, t)],
                       np.float64)
    return np.maximum.accumulate(edges)
