"""Multi-chip scale-out: sharded node tables + collective top-k merge."""

from .sharded import (  # noqa: F401
    make_mesh,
    pad_to_multiple,
    sharded_xor_topk,
    sharded_sort_table,
    sharded_expand_table,
    sharded_window_lookup,
    sharded_lookup,
    sharded_maintenance_sweep,
    dp_simulate_lookups,
    tp_simulate_lookups,
)
