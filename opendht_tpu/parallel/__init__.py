"""Multi-chip scale-out: sharded node tables + collective top-k merge,
placed by the declarative partition-rule layer (partition.py)."""

from .partition import (  # noqa: F401
    match_partition_rules,
    make_shard_and_gather_fns,
    shard_put,
    constrain,
    shard_table_state,
    TableState,
    TABLE_AXIS_RULES,
    DP_AXIS_RULES,
)
from .sharded import (  # noqa: F401
    make_mesh,
    pad_to_multiple,
    sharded_xor_topk,
    sharded_sort_table,
    sharded_expand_table,
    sharded_window_lookup,
    sharded_lookup,
    sharded_maintenance_sweep,
    dp_simulate_lookups,
    tp_simulate_lookups,
    build_tp_lookup,
)
