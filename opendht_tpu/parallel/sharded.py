"""Sharded node tables over a device mesh with ICI top-k merge.

The reference scales by adding independent peers over UDP (its NCCL/MPI
analog is the bespoke msgpack engine, src/network_engine.cpp).  The TPU
build scales a *single logical node table* past one chip's HBM instead:

- mesh axis ``t`` (table-parallel): the [N, 5] id matrix is sharded by
  rows across devices; every device scans only its shard.
- mesh axis ``q`` (query/data-parallel): the query batch is sharded;
  each device answers its slice of queries.

One lookup = per-shard exact top-k (a local HBM scan or sorted-window
lookup) followed by an ``all_gather`` of the per-shard winners over the
``t`` axis and one [Q_local, n_t·k]-row lexicographic re-sort.  The
merge is exact: the global top-k is always a subset of the union of
per-shard top-ks.  Collectives ride ICI when the mesh maps to one pod
slice; nothing here assumes host locality, so the same code runs on a
DCN-spanning mesh.

Placement is DECLARATIVE (round 13): every entry point places its
operands by regex partition rules over a named state pytree
(``partition.match_partition_rules`` → per-leaf ``NamedSharding``
shard fns, the standard large-model JAX pattern), and the iterative
engine's table state — sorted rows, per-shard positioning LUT, the
replicated global block LUT, validity — is built ONCE by
``partition.shard_table_state`` and reused across waves.  Each ``t``
shard holds ~N/t rows (plus the 4·2^bb-byte block LUT); nothing
table-sized is replicated, so the servable id set scales linearly in
mesh size.  The steady-state search round costs exactly ONE in-loop
collective — the reply-row merge psum, O(queries·k) bytes — because
reply-block edges read the replicated global LUT locally instead of
psumming per-shard edge counts every hop (TP_SCALING.json).

Compiled programs are cached per (mesh, k, tile/window, shard size) —
repeated calls with the same geometry reuse one XLA executable.

All entry points run on any ``jax.sharding.Mesh`` — including a virtual
CPU mesh (``--xla_force_host_platform_device_count``) — which is how the
tests and the driver's ``dryrun_multichip`` exercise multi-chip paths
without multi-chip hardware.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# the shard_map version shim (experimental move + check_rep→check_vma
# rename) lives with the declarative placement layer now; both names
# are re-exported here for the callers that grew up against them
from .partition import (shard_map as _shard_map, SHARD_MAP_KW as _SM_KW,
                        TABLE_AXIS_RULES, DP_AXIS_RULES, TableState,
                        shard_put, shard_table_state)

from ..ops.ids import N_LIMBS
from ..ops.xor_topk import xor_topk, select_topk, mask_invalid
from ..ops.sorted_table import (sort_table, window_topk, build_prefix_lut,
                                default_lut_bits, expand_table, expanded_topk,
                                _EROW)
from ..core.search import (simulate_lookups, _lookup_engine,
                           _guarded_lower_bound, _lut_block_bounds,
                           TARGET_NODES, ALPHA, SEARCH_NODES)

_U32 = jnp.uint32


def make_mesh(n_devices: Optional[int] = None, *, q: Optional[int] = None,
              t: Optional[int] = None) -> Mesh:
    """Build a 2-D (q=data/query, t=table) mesh over the first
    ``n_devices`` devices.  Default split: t gets the larger factor
    (table rows dominate memory; queries are cheap to replicate)."""
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if q is None and t is None:
        # largest power-of-two factor ≤ sqrt for q, rest for t
        q = 1
        while q * 2 <= n_devices // (q * 2) and n_devices % (q * 4) == 0:
            q *= 2
        t = n_devices // q
    elif q is None:
        q = n_devices // t
    elif t is None:
        t = n_devices // q
    if q * t != n_devices:
        raise ValueError(f"mesh {q}x{t} != {n_devices} devices")
    arr = np.asarray(devs[:n_devices]).reshape(q, t)
    return Mesh(arr, ("q", "t"))


def pad_to_multiple(arr: np.ndarray, m: int, axis: int = 0, fill=0):
    """Pad `arr` along `axis` to a multiple of `m`.  Returns (padded, n)."""
    n = arr.shape[axis]
    pad = (-n) % m
    if pad == 0:
        return arr, n
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    return np.pad(arr, widths, constant_values=fill), n


def _as_operand(x, dtype=None):
    """Normalize one entry-point operand for declarative placement:
    host data becomes a (cast) numpy array — ``partition``'s shard fns
    then ``device_put`` it straight to its shards, never a replicated
    staging copy — while an already-committed jax array is cast in
    place and resharded by the jitted identity."""
    if hasattr(x, "sharding"):
        return x if dtype is None or x.dtype == dtype else x.astype(dtype)
    return np.asarray(x, dtype)


def _gather_and_merge(dist, gidx, n_t, k):
    """all_gather per-shard winners over ``t`` and re-select the top-k."""
    all_dist = lax.all_gather(dist, "t")                # [n_t, Qs, k, 5]
    all_idx = lax.all_gather(gidx, "t")                 # [n_t, Qs, k]
    Qs = dist.shape[0]
    cd = jnp.moveaxis(all_dist, 0, 1).reshape(Qs, n_t * k, N_LIMBS)
    ci = jnp.moveaxis(all_idx, 0, 1).reshape(Qs, n_t * k)
    d, i, inv = select_topk(cd, ci, (ci < 0).astype(jnp.int32), k)
    return mask_invalid(d, i, inv)


@functools.lru_cache(maxsize=64)
def _build_sharded_xor_topk(mesh: Mesh, k: int, tile: int, shard_n: int):
    n_t = mesh.shape["t"]

    def local(q, tbl, val):
        ti = lax.axis_index("t")
        dist, idx = xor_topk(q, tbl, k=k, tile=tile, valid=val)
        gidx = jnp.where(idx >= 0, idx + ti * shard_n, -1)
        return _gather_and_merge(dist, gidx, n_t, k)

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(P("q", None), P("t", None), P("t")),
        out_specs=(P("q", None, None), P("q", None)),
        **_SM_KW,
    )
    return jax.jit(fn)


def sharded_xor_topk(mesh: Mesh, queries, table, *, k: int = 8,
                     tile: int = 4096, valid=None):
    """Exact k XOR-closest over a row-sharded table (full-scan path).

    queries: uint32 [Q, 5], Q divisible by mesh.shape['q'].
    table:   uint32 [N, 5], N divisible by mesh.shape['t'] (pad with
             `valid=False` rows via :func:`pad_to_multiple`).
    valid:   bool [N] or None.

    Returns (dist [Q, k, 5], idx [Q, k] int32 global row indices, -1 pad),
    laid out sharded over ``q`` / replicated over ``t``.
    """
    N = table.shape[0]
    shard_n = N // mesh.shape["t"]
    if valid is None:
        valid = jnp.ones((N,), dtype=bool)
    fn = _build_sharded_xor_topk(mesh, k, min(tile, shard_n), shard_n)
    ops = shard_put(mesh, {"queries": _as_operand(queries, np.uint32),
                           "table": _as_operand(table, np.uint32),
                           "valid": _as_operand(valid, bool)},
                    TABLE_AXIS_RULES)
    return fn(ops["queries"], ops["table"], ops["valid"])


@functools.lru_cache(maxsize=8)
def _build_sharded_sort(mesh: Mesh):
    def local(tbl, val):
        sorted_ids, perm, n_valid = sort_table(tbl, val)
        return sorted_ids, perm, jnp.asarray(n_valid, jnp.int32)[None]

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(P("t", None), P("t")),
        out_specs=(P("t", None), P("t"), P("t")),
        **_SM_KW,
    )
    return jax.jit(fn)


def sharded_sort_table(mesh: Mesh, table, valid=None):
    """Sort each table shard locally (rows stay on their device; no
    collectives).  Returns (sorted_ids [N,5], perm [N], n_valid [n_t]) —
    all sharded over ``t`` — to feed repeated
    :func:`sharded_window_lookup` calls, so a stable table is sorted once
    and amortized across query batches (mirroring the single-device
    sort_table / window_topk split in ops/sorted_table.py)."""
    N = table.shape[0]
    if valid is None:
        valid = jnp.ones((N,), dtype=bool)
    fn = _build_sharded_sort(mesh)
    ops = shard_put(mesh, {"table": _as_operand(table, np.uint32),
                           "valid": _as_operand(valid, bool)},
                    TABLE_AXIS_RULES)
    return fn(ops["table"], ops["valid"])


@functools.lru_cache(maxsize=8)
def _build_sharded_expand(mesh: Mesh, bits: int):
    def local(sorted_ids, n_valid_shard):
        expanded = expand_table(sorted_ids)
        lut = build_prefix_lut(sorted_ids, n_valid_shard[0], bits=bits)
        return expanded, lut[None]

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(P("t", None), P("t")),
        out_specs=(P("t", None), P("t", None)),
        **_SM_KW,
    )
    return jax.jit(fn)


def sharded_expand_table(mesh: Mesh, sorted_ids, n_valid, *, bits: int = 16):
    """Build each shard's expanded window-row table and prefix LUT
    locally (no collectives) from :func:`sharded_sort_table` output.
    Returns (expanded [n_t·NB, 970] sharded over ``t``,
    lut [n_t, 2^bits+1] sharded over ``t``) to feed the expanded fast
    path of :func:`sharded_window_lookup`."""
    fn = _build_sharded_expand(mesh, bits)
    return fn(jnp.asarray(sorted_ids, _U32), jnp.asarray(n_valid, jnp.int32))


@functools.lru_cache(maxsize=64)
def _build_sharded_window_lookup(mesh: Mesh, k: int, window: int,
                                 shard_n: int, use_expanded: bool):
    n_t = mesh.shape["t"]

    def local(q, sorted_ids, perm, n_valid_shard, expanded, lut):
        ti = lax.axis_index("t")
        n_valid = n_valid_shard[0]
        if use_expanded:
            dist, sidx, cert = expanded_topk(sorted_ids, expanded, n_valid,
                                             q, k=k, lut=lut[0])
        else:
            dist, sidx, cert = window_topk(sorted_ids, n_valid, q, k=k,
                                           window=window)

        # Certificate fallback: when any row in this shard's batch is
        # uncertified, rerun the whole shard through the exact scan and
        # keep the certified window rows.  lax.cond keeps the common
        # (all-certified) path free of the O(shard_n) scan — but the
        # branch's buffers are still ALLOCATED, and a 4096-row tile
        # sorts [Q, 4104]x7 u32 temps (~7.5 GB at Q=65536), which OOMs
        # alongside a 64M-id shard's 5 GB of resident tables.  Huge
        # shards take a small tile: the branch only ever executes on
        # adversarial id distributions, so its throughput is secondary
        # to it being allocatable.
        def exact(_):
            fb_tile = min(4096 if shard_n <= 8_000_000 else 512, shard_n)
            d2, i2 = xor_topk(q, sorted_ids, k=k, tile=fb_tile,
                              valid=jnp.arange(shard_n) < n_valid)
            keep = cert[:, None]
            return (jnp.where(keep[..., None], dist, d2),
                    jnp.where(keep, sidx, i2))

        def fast(_):
            return dist, sidx

        dist2, sidx2 = lax.cond(jnp.all(cert), fast, exact, operand=None)
        rows = jnp.where(sidx2 >= 0,
                         jnp.take(perm, jnp.clip(sidx2, 0, shard_n - 1)), -1)
        gidx = jnp.where(rows >= 0, rows + ti * shard_n, -1)
        return _gather_and_merge(dist2, gidx, n_t, k)

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(P("q", None), P("t", None), P("t"), P("t"),
                  P("t", None), P("t", None)),
        out_specs=(P("q", None, None), P("q", None)),
        **_SM_KW,
    )
    return jax.jit(fn)


def sharded_window_lookup(mesh: Mesh, queries, sorted_ids, perm, n_valid, *,
                          k: int = 8, window: int = 128, expanded=None,
                          lut=None):
    """Exact k XOR-closest over a pre-sorted row-sharded table — the
    repeated-lookup fast path.  Takes the output of
    :func:`sharded_sort_table`; each shard answers with its local window
    top-k (per-query exactness certificate; uncertified batches fall back
    to the shard-local full scan), then the per-shard winners are
    all_gather-merged over ``t``.

    Pass ``expanded``/``lut`` from :func:`sharded_expand_table` to use
    the expanded row-gather fast path per shard (the headline-bench
    kernel) instead of the per-element window gather.

    Same contract as :func:`sharded_xor_topk`: returns
    (dist [Q, k, 5], idx [Q, k]) where idx are **global original-table
    row indices** (-1 padding), sharded over ``q``.
    """
    N = sorted_ids.shape[0]
    n_t = mesh.shape["t"]
    shard_n = N // n_t
    use_expanded = expanded is not None
    if not use_expanded:
        # placeholder operands keep one shard_map signature for both paths
        expanded = jnp.zeros((n_t, N_LIMBS * _EROW), _U32)
        lut = jnp.zeros((n_t, 2), jnp.int32)
    fn = _build_sharded_window_lookup(mesh, k, min(window, shard_n), shard_n,
                                      use_expanded)
    ops = shard_put(mesh, {"queries": _as_operand(queries, np.uint32),
                           "sorted_ids": _as_operand(sorted_ids, np.uint32),
                           "perm": _as_operand(perm, np.int32),
                           "n_valid": _as_operand(n_valid, np.int32),
                           "expanded": _as_operand(expanded, np.uint32),
                           "local_lut": _as_operand(lut, np.int32)},
                    TABLE_AXIS_RULES)
    return fn(ops["queries"], ops["sorted_ids"], ops["perm"],
              ops["n_valid"], ops["expanded"], ops["local_lut"])


def sharded_lookup(mesh: Mesh, queries, table, *, k: int = 8,
                   window: int = 128, valid=None):
    """One-shot convenience: :func:`sharded_sort_table` +
    :func:`sharded_window_lookup`.  Callers with a stable table and many
    query batches should hold the sorted form and call
    ``sharded_window_lookup`` directly to amortize the sort."""
    sorted_ids, perm, n_valid = sharded_sort_table(mesh, table, valid)
    return sharded_window_lookup(mesh, queries, sorted_ids, perm, n_valid,
                                 k=k, window=window)


@functools.lru_cache(maxsize=16)
def build_tp_lookup(mesh: Mesh, shard_n: int, q_total: int, k: int,
                    alpha: int, search_nodes: int, max_hops: int,
                    state_limbs: int = N_LIMBS, weighted: bool = False):
    """Compile the table-sharded iterative lookup for one geometry.

    Returns a jitted ``fn(sorted_ids, local_lut, block_lut, n_valid,
    targets, seed)`` over the row-sharded table state a single
    ``partition.shard_table_state`` call builds and places (sorted
    rows + per-shard positioning LUT P('t', None), replicated global
    block LUT, ``targets`` P('q', None)).  Public so honest benchmarks
    can wrap the callable in a serialized rep chain
    (``bench.chain_slope``) instead of wall-timing dispatches —
    :func:`tp_simulate_lookups` is the convenience entry that builds
    and places the state per call.

    The steady-state round costs exactly ONE collective: the fused
    reply-row merge psum (O(queries·k) bytes).  Reply-block edges —
    one whole psum site per hop in the round-12 layout — are now two
    LOCAL reads of the replicated global block LUT, which
    ``shard_table_state`` assembled with a single one-shot psum of the
    per-shard LUTs at table-build time (entry p of a shard's LUT is
    its local count of valid rows with prefix < p; the sum over shards
    is the global count, so the values are bit-identical to the
    per-hop psum they replace).
    """
    q_local = q_total // mesh.shape["q"]

    def local(*op):
        if weighted:
            # load-aware layout (ISSUE-17): each shard owns rows
            # [base, base+width) of the global sorted order, carried as
            # DATA in the [1, 2] shard_rows slice — the kernel text is
            # identical for every boundary placement, so a hot swap
            # never recompiles.  shard_n is the per-shard row CAPACITY
            # (rows beyond the width are zero padding).
            (sorted_shard, local_lut, block_lut, n_valid, shard_rows,
             targets_local, seed) = op
            base = shard_rows[0, 0]
            n_local = shard_rows[0, 1]
            n = jnp.asarray(n_valid, jnp.int32)
        else:
            (sorted_shard, local_lut, block_lut, n_valid, targets_local,
             seed) = op
            ti = lax.axis_index("t")
            base = (ti * shard_n).astype(jnp.int32)
            n = jnp.asarray(n_valid, jnp.int32)
            n_local = jnp.clip(n - base, 0, shard_n)
        local_lower = _guarded_lower_bound(sorted_shard, n_local,
                                           local_lut[0])
        sorted_t = sorted_shard.T                        # [5, shard_n]

        def lower(flat):
            # global lower bound = Σ_shards (local rows < q): each
            # shard's local lower-bound index IS that count, and the
            # global sorted order is the in-order concatenation of
            # shard ranges — one [M]-int32 psum over the table axis.
            # Called ONCE per wave (the pre-loop target positioning),
            # never inside the hop loop.
            return lax.psum(local_lower(flat), "t")

        def block_bounds(t0, prefix_len):
            # ZERO collectives: the block LUT is the replicated GLOBAL
            # prefix LUT (built once per table — shard_table_state), so
            # both edges are plain local gathers.  Values are the exact
            # Σ-of-per-shard-counts the round-12 in-loop psum computed,
            # hence bit-identical to the single-device engine at the
            # same block width (default_lut_bits(N), never the shard
            # size — a shard-sized width would make the clamp depth,
            # and hence the reply stream, vary with the mesh split).
            return _lut_block_bounds(block_lut, t0, prefix_len)

        def gather_planar(rows, limbs=N_LIMBS):
            # distributed row fetch: the owning shard contributes the
            # row's limbs, every other shard zeros — psum reassembles.
            # Rows are pre-clipped to [0, n) by the engine; -1 (absent)
            # rows land out of range on every shard and come back 0,
            # masked by the engine exactly like the unsharded garbage.
            # With the round-6 fused engine this runs ONCE per round
            # (the α·k reply fetch): the per-round 1-limb peer fetch's
            # psum site is gone — the engine reads the carried
            # candidate distance instead (core/search.py).
            flat = (rows - base).reshape(-1)
            # ownership test: weighted shards own exactly n_local rows
            # (the [b_i, b_{i+1}) ranges partition the valid prefix);
            # the uniform test keeps the static width — equivalent for
            # valid rows, and it leaves the uniform program unchanged
            ok = (flat >= 0) & (flat < (n_local if weighted else shard_n))
            g = jnp.take(sorted_t[:limbs], jnp.clip(flat, 0, shard_n - 1),
                         axis=1)
            g = jnp.where(ok[None, :], g, _U32(0))
            g = lax.psum(g, "t")
            return [g[l].reshape(rows.shape) for l in range(limbs)]

        q_index = (lax.axis_index("q").astype(jnp.int32) * q_local
                   + jnp.arange(q_local, dtype=jnp.int32))
        return _lookup_engine(gather_planar, lower, n, targets_local,
                              q_index, q_total, seed.astype(_U32),
                              k=k, alpha=alpha, search_nodes=search_nodes,
                              max_hops=max_hops, state_limbs=state_limbs,
                              block_bounds=block_bounds)

    in_specs = ((P("t", None), P("t", None), P(), P(), P("t", None),
                 P("q", None), P()) if weighted else
                (P("t", None), P("t", None), P(), P(), P("q", None), P()))
    fn = _shard_map(
        local, mesh=mesh,
        in_specs=in_specs,
        out_specs={"nodes": P("q", None), "dist": P("q", None, None),
                   "hops": P("q"), "converged": P("q")},
        **_SM_KW,
    )
    return jax.jit(fn)


def tp_simulate_lookups(mesh: Mesh, sorted_ids=None, n_valid=None,
                        targets=None, *, seed: int = 0, k: int = TARGET_NODES,
                        alpha: int = ALPHA, search_nodes: int = SEARCH_NODES,
                        max_hops: int = 48, state_limbs: int = N_LIMBS,
                        state: "TableState | None" = None):
    """Iterative lookups with the sorted table ROW-SHARDED over ``t`` —
    the multi-chip north star: tables larger than one chip's HBM are
    searched iteratively, not just scanned (10M+ ids spread across the
    mesh, benchmarks/exp_shard_r13.py).

    ``sorted_ids`` must be GLOBALLY sorted (one :func:`sort_table` /
    host sort over the whole id set); each ``t``-shard then owns one
    contiguous range of the global sorted order — the Kademlia analog
    of a node owning the contiguous XOR neighborhood around its id
    (PARITY.md "t-sharded table").  That contiguity is what makes the
    distributed primitives cheap:

    - positioning (once per wave): global lower_bound = ONE psum of
      per-shard local counts;
    - reply-block edges (per hop): two LOCAL reads of the replicated
      global block LUT — ZERO collectives (see
      :func:`build_tp_lookup`);
    - row fetch (per hop): owner-shard gather + ONE psum — the round's
      only in-loop collective, O(queries·k) bytes, never O(table).

    Search state is sharded over ``q`` and replicated over ``t``
    (deterministic identical compute per t-rank, like the merge
    re-sort in :func:`sharded_window_lookup`).  Results are
    BIT-IDENTICAL to :func:`~opendht_tpu.core.search.simulate_lookups`
    on the same table (the reply hash is seeded by global query
    identity) — asserted in tests/test_sharded.py.

    Callers serving a stable table should pass ``state=`` from
    :func:`~opendht_tpu.parallel.partition.shard_table_state` (built
    once, reused across waves — the sorted rows and positioning LUTs
    then never re-place or re-derive per call); the raw
    ``sorted_ids``/``n_valid`` form builds a state pytree on the fly.

    targets [Q, 5]: Q divisible by mesh.shape['q']; N divisible by
    mesh.shape['t'] (pad via :func:`pad_to_multiple` — pad rows land
    on the LAST shard).  Ref: the loop being scaled is searchStep,
    /root/reference/src/dht.cpp:561-654.
    """
    if state is None:
        if sorted_ids is None or n_valid is None:
            raise ValueError("pass either (sorted_ids, n_valid) or state=")
        state = shard_table_state(mesh, sorted_ids, n_valid)
    if targets is None:
        raise ValueError("targets are required")
    Q = targets.shape[0]
    if Q % mesh.shape["q"]:
        raise ValueError(f"targets ({Q}) not divisible by q axis "
                         f"{mesh.shape['q']}")
    a = state.arrays
    weighted = "shard_rows" in a
    fn = build_tp_lookup(mesh, state.shard_n, Q, k, alpha, search_nodes,
                         max_hops, state_limbs, weighted)
    targets = shard_put(mesh, {"targets": _as_operand(targets, np.uint32)},
                        TABLE_AXIS_RULES)["targets"]
    if weighted:
        args = (a["sorted_ids"], a["local_lut"], a["block_lut"],
                a["n_valid"], a["shard_rows"], targets,
                jnp.asarray(seed, jnp.int32))
    else:
        args = (a["sorted_ids"], a["local_lut"], a["block_lut"],
                a["n_valid"], targets, jnp.asarray(seed, jnp.int32))
    from .. import telemetry
    reg = telemetry.get_registry()
    if not reg.enabled:
        return fn(*args)
    # same host-side envelope as the single-device entry (core/search.py
    # simulate_lookups): the traced computation is untouched, the span
    # blocks and the wave/hops series land under mode="tp" — and via
    # record_wave the distributed tracer gets the mode="tp" wave/round
    # spans too (ISSUE-4), so a sharded lookup shows up in the same
    # Chrome/Perfetto timeline as the single-device one
    with reg.span("dht_search_wave_seconds", record=False) as sp:
        out = fn(*args)
        jax.block_until_ready(out)
    from ..core.search import record_wave
    record_wave(out, sp.elapsed, Q, mode="tp",
                mesh_t=mesh.shape["t"])
    return out


@functools.lru_cache(maxsize=8)
def _build_sharded_maintenance(mesh: Mesh):
    from ..ops import radix

    def local(self_id, ids, valid, last_reply, now, age, key):
        # per-shard [160, N_s] compare-and-reduce, then one collective
        # per statistic: occupancy sums (int32 — exact) and last-reply
        # maxes (max of per-shard maxes — exact) over the table axis
        counts = lax.psum(radix.bucket_counts(self_id, ids, valid), "t")
        last = lax.pmax(
            radix.bucket_last_seen(self_id, ids, valid, last_reply), "t")
        stale = (counts > 0) & (last < now - age)
        # refresh ids depend only on (self_id, key) — replicated compute,
        # bit-identical to the single-device radix call (same key, same
        # shape => same threefry stream)
        targets = radix.random_id_in_bucket(
            self_id, jnp.arange(radix.ID_BITS, dtype=jnp.int32), key)
        return counts, last, stale, targets

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(P(), P("t", None), P("t"), P("t"), P(), P(), P()),
        out_specs=(P(), P(), P(), P(None, None)),
        **_SM_KW,
    )
    return jax.jit(fn)


def sharded_maintenance_sweep(mesh: Mesh, self_id, ids, valid, last_reply,
                              now, age, key):
    """tp twin of :func:`opendht_tpu.ops.radix.maintenance_sweep` (round
    10): the fused bucket-maintenance pass — occupancy + per-bucket
    last-reply staleness (never-replied ⇒ stale from birth) + a refresh
    target per bucket — over an [N, 5] id matrix ROW-SHARDED across the
    ``t`` axis, so tables past one chip's HBM sweep in one launch.

    Per shard the [160, N_s] compare-and-reduce runs locally; the only
    ICI traffic is one [160]-int32 psum (occupancy) and one [160]-float
    pmax (staleness) — O(buckets), never O(table).  Results are
    BIT-IDENTICAL to the single-device kernel on the same inputs
    (integer sums and maxes are exact under resharding; asserted in
    tests/test_sharded.py).

    ids: uint32 [N, 5] with N divisible by mesh.shape['t'] (pad with
    ``valid=False`` rows via :func:`pad_to_multiple`).  Returns
    (counts [160] int32, last [160], stale [160] bool,
    targets [160, 5] uint32), all replicated.
    """
    N = ids.shape[0]
    if N % mesh.shape["t"]:
        raise ValueError(f"table rows ({N}) not divisible by "
                         f"t={mesh.shape['t']}; pad via pad_to_multiple")
    if valid is None:
        valid = jnp.ones((N,), bool)
    fn = _build_sharded_maintenance(mesh)
    ops = shard_put(mesh, {"ids": _as_operand(ids, np.uint32),
                           "valid": _as_operand(valid, bool),
                           "last_reply": _as_operand(last_reply, np.float32)},
                    TABLE_AXIS_RULES)
    from .. import telemetry
    reg = telemetry.get_registry()
    reg.counter("dht_maintenance_sweeps_total", mode="tp").inc()
    with reg.span("dht_maintenance_sweep_seconds", mode="tp"):
        out = fn(jnp.asarray(self_id, _U32), ops["ids"], ops["valid"],
                 ops["last_reply"], jnp.asarray(now), jnp.asarray(age), key)
        jax.block_until_ready(out)
    return out


@functools.lru_cache(maxsize=8)
def _build_sharded_sketch(mesh: Mesh, depth: int, width: int):
    from ..ops.sketch import BIN_BITS, hash_columns

    def local(sketch, hist, ids, valid):
        # each shard scatter-adds its slice of the observed ids into a
        # ZERO partial sketch/histogram; ONE psum pair merges the
        # partials onto the replicated running state.  Integer adds
        # are associative and exact, so the merged result is
        # bit-identical to the single-device ops.sketch.sketch_update
        # over the same ids (tests/test_keyspace.py).  Pad rows carry
        # weight 0 — they touch cells but add nothing.
        w = valid.astype(jnp.int32)
        cols = hash_columns(ids, depth, width)            # [Qs, depth]
        rows = jnp.broadcast_to(jnp.arange(depth, dtype=jnp.int32),
                                cols.shape)
        part = jnp.zeros_like(sketch).at[
            rows.reshape(-1), cols.reshape(-1)].add(
            jnp.repeat(w, depth))
        bins = (ids[:, 0] >> _U32(32 - BIN_BITS)).astype(jnp.int32)
        ph = jnp.zeros_like(hist).at[bins].add(w)
        return sketch + lax.psum(part, "t"), hist + lax.psum(ph, "t")

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), P("t", None), P("t")),
        out_specs=(P(), P()),
        **_SM_KW,
    )
    return jax.jit(fn)


def sharded_sketch_update(mesh: Mesh, sketch, hist, ids):
    """tp twin of :func:`opendht_tpu.ops.sketch.sketch_update`
    (ISSUE-10): the wave's observed ids ROW-SPLIT over the ``t`` axis,
    each shard building a partial count-min sketch + top-8-bit
    histogram locally, merged with ONE psum pair — O(depth·width +
    bins) int32 wire, independent of the wave width.  Ragged widths
    pad with weight-0 rows (``pad_to_multiple``), so any Q works.

    Returns the updated replicated ``(sketch, hist)``, BIT-IDENTICAL
    to the single-device update over the same ids (integer adds are
    exact under resharding; pinned in tests/test_keyspace.py)."""
    ids = np.asarray(ids, np.uint32).reshape(-1, N_LIMBS)
    n_t = mesh.shape["t"]
    padded, n = pad_to_multiple(ids, n_t)
    valid = np.arange(padded.shape[0]) < n
    fn = _build_sharded_sketch(mesh, int(sketch.shape[0]),
                               int(sketch.shape[1]))
    ops = shard_put(mesh, {"sketch_ids": padded,
                           "sketch_valid": valid}, TABLE_AXIS_RULES)
    return fn(jnp.asarray(sketch, jnp.int32), jnp.asarray(hist, jnp.int32),
              ops["sketch_ids"], ops["sketch_valid"])


@functools.lru_cache(maxsize=8)
def _build_sharded_cache_probe(mesh: Mesh, capacity: int):
    def local(cache_ids, valid, targets):
        # each shard XOR-compares ITS slice of the wave's targets
        # against the replicated [C, 5] cache table — all-limb equality
        # == XOR distance exactly zero, the ops/cache_probe.py compare,
        # fully data-parallel (no collective: outputs stay t-split and
        # the caller gathers)
        t = targets.astype(_U32)
        c = cache_ids.astype(_U32)
        eq = jnp.all(t[:, None, :] == c[None, :, :], axis=-1) \
            & valid[None, :]
        hit = jnp.any(eq, axis=1)
        slot = jnp.where(hit, jnp.argmax(eq, axis=1).astype(jnp.int32),
                         jnp.int32(-1))
        return hit, slot

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), P("t", None)),
        out_specs=(P("t"), P("t")),
        **_SM_KW,
    )
    return jax.jit(fn)


def sharded_cache_probe(mesh: Mesh, cache_ids, valid, targets):
    """tp twin of :func:`opendht_tpu.ops.cache_probe.cache_probe`
    (ISSUE-11): the wave's probe targets ROW-SPLIT over the ``t`` axis
    against the replicated cache table, each shard answering its slice
    locally — zero collectives (membership is per-target), so the twin
    costs exactly the single-device compare divided by t.  Ragged
    widths pad (pad rows' answers are sliced off host-side), so any Q
    works.

    Returns host ``(hit [Q] bool, slot [Q] int32)``, BIT-IDENTICAL to
    the single-device probe over the same targets (pinned in
    tests/test_hotcache.py)."""
    t_np = np.asarray(targets, np.uint32).reshape(-1, N_LIMBS)
    n_t = mesh.shape["t"]
    padded, n = pad_to_multiple(t_np, n_t)
    fn = _build_sharded_cache_probe(mesh, int(cache_ids.shape[0]))
    ops = shard_put(mesh, {"probe_ids": padded}, TABLE_AXIS_RULES)
    hit, slot = fn(jnp.asarray(cache_ids, _U32),
                   jnp.asarray(np.asarray(valid, bool)),
                   ops["probe_ids"])
    return np.asarray(hit)[:n], np.asarray(slot)[:n]


@functools.lru_cache(maxsize=8)
def _build_sharded_listener_match(mesh: Mesh, capacity: int):
    def local(table_ids, valid, stored):
        # each shard XOR-compares ITS slice of the wave's stored-put
        # keys against the replicated [L, 5] listener table — the
        # ops/listener_match.py compare, fully data-parallel
        # (membership is per-stored-key: no collective; outputs stay
        # t-split and the caller gathers)
        s = stored.astype(_U32)
        t = table_ids.astype(_U32)
        eq = jnp.all(s[:, None, :] == t[None, :, :], axis=-1) \
            & valid[None, :]
        hit = jnp.any(eq, axis=1)
        slot = jnp.where(hit, jnp.argmax(eq, axis=1).astype(jnp.int32),
                         jnp.int32(-1))
        return hit, slot

    fn = _shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), P("t", None)),
        out_specs=(P("t"), P("t")),
        **_SM_KW,
    )
    return jax.jit(fn)


def sharded_listener_match(mesh: Mesh, table_ids, valid, stored):
    """tp twin of :func:`opendht_tpu.ops.listener_match.listener_match`
    (ISSUE-20): the wave's stored-put keys ROW-SPLIT over the ``t``
    axis against the replicated listener table, each shard answering
    its slice locally — zero collectives (membership is per-key), so
    the twin costs exactly the single-device compare divided by t.
    Ragged widths pad (pad rows' answers are sliced off host-side), so
    any S works.

    Returns host ``(hit [S] bool, slot [S] int32)``, BIT-IDENTICAL to
    the single-device match over the same keys (pinned in
    tests/test_listener.py at t∈{2,4})."""
    s_np = np.asarray(stored, np.uint32).reshape(-1, N_LIMBS)
    n_t = mesh.shape["t"]
    padded, n = pad_to_multiple(s_np, n_t)
    fn = _build_sharded_listener_match(mesh, int(table_ids.shape[0]))
    ops = shard_put(mesh, {"probe_ids": padded}, TABLE_AXIS_RULES)
    hit, slot = fn(jnp.asarray(table_ids, _U32),
                   jnp.asarray(np.asarray(valid, bool)),
                   ops["probe_ids"])
    return np.asarray(hit)[:n], np.asarray(slot)[:n]


@functools.lru_cache(maxsize=8)
def _dp_lut_builder(mesh: Mesh, bits: int):
    """Build the dp engine's prefix LUT FROM THE PLACED (replicated)
    table, with the output pinned replicated by
    ``with_sharding_constraint`` — no default-device build followed by
    a re-placement copy."""
    rep = NamedSharding(mesh, P(None))

    def fn(sorted_ids, n_valid):
        lut = build_prefix_lut(sorted_ids, n_valid, bits=bits)
        return lax.with_sharding_constraint(lut, rep)
    return jax.jit(fn)


def dp_simulate_lookups(mesh: Mesh, sorted_ids, n_valid, targets, **kw):
    """Data-parallel batched iterative lookups: targets sharded over the
    whole mesh (both axes), sorted table replicated.  The per-step merge
    sort, window binary search, and while_loop all partition trivially
    along the query axis — XLA inserts no cross-device collectives in
    steady state, so scaling is linear in chips.

    Placement goes through the declarative rule layer
    (``partition.DP_AXIS_RULES``): a host table is ``device_put``
    straight to its replicated sharding — the old ``jnp.asarray`` +
    re-place sequence staged a full extra copy on the default device
    first, a transient 2× HBM spike at exactly the table sizes this
    path serves.  Callers with a stable table should pass ``lut=``
    (built once via ``ops.sorted_table.build_prefix_lut``) so repeated
    waves skip the rebuild; when absent the LUT is derived from the
    PLACED table under one jit whose output is constrained replicated,
    never built on the default device and copied."""
    placed = shard_put(mesh, {"targets": _as_operand(targets, np.uint32),
                              "sorted_ids": _as_operand(sorted_ids,
                                                        np.uint32)},
                       DP_AXIS_RULES)
    targets = placed["targets"]
    sorted_ids = placed["sorted_ids"]
    if kw.get("lut") is None:
        kw["lut"] = _dp_lut_builder(
            mesh, default_lut_bits(sorted_ids.shape[0]))(
                sorted_ids, jnp.asarray(n_valid, jnp.int32))
    return simulate_lookups(sorted_ids, n_valid, targets, **kw)
