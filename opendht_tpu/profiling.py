"""Kernel cost ledger: what every shipped kernel costs, by construction.

Five rounds of kernel work (920× → 46× → 121× → 131× → 213× vs the
scalar baseline, PERF_TRAJECTORY.json) are protected by wall-clock
smokes only — and wall-clock on shared CPU runners is noise.  The
device-side costs XLA itself computes are not: for a fixed kernel at a
fixed shape, the lowered executable's ``cost_analysis()`` (flops, bytes
accessed) and ``memory_analysis()`` (argument/output/temp bytes) are
DETERMINISTIC on a given XLA version, platform-portable in meaning, and
move exactly when someone changes what the kernel does.  This module
turns them into the third observability pillar next to the PR-3 metrics
spine and the PR-4 trace spans:

- :data:`KERNEL_SPECS` — every shipped jitted entry point
  (``find_closest_nodes_batched``'s device program, ``expanded_topk``,
  ``fused_gather_planar``, ``packed_churn_merge``,
  ``churn_lookup_topk``, ``maintenance_sweep``, the round-fused
  ``simulate_lookups`` engine, and the ``parallel/sharded.py`` tp
  twins) pinned at one CANONICAL SHAPE each, small enough to lower in
  seconds on the CI CPU.
- :class:`KernelLedger` — lowers each spec once per process, captures
  the XLA cost model + memory footprint, optionally pairs it with a
  measured per-launch device time (one blocking canonical launch
  through the PR-3 ``span()`` envelope), and derives ROOFLINE
  attribution against the per-platform peaks table
  (:data:`PLATFORM_PEAKS`): achieved bytes/s and flops/s as a % of
  peak, and which bound dominates.
- Export everywhere the spine already reaches: ``dht_kernel_*``
  gauges in the registry (→ ``DhtRunner.get_metrics()`` JSON and the
  proxy's Prometheus ``GET /stats``), the ``kernels`` REPL command in
  tools/dhtnode.py, the ``kernels`` section of ``dhtscanner --json``,
  and per-wave device-cost attributes folded onto the PR-4
  ``dht.search.wave`` trace spans (:func:`wave_attrs`).
- The gate: ``ci/perf_gate.py`` diffs this ledger against the
  committed ``perf_budgets.json`` — a refactor that doubles a kernel's
  HBM bytes/query fails CI deterministically, no accelerator needed.

The ledger NEVER touches the hot path: it lowers *separate* canonical-
shape instances of each kernel (the shipping calls and their compiled
executables are untouched — kernels are pinned bit-identical with the
ledger enabled in tests/test_profiling.py), computes once per process,
and costs a dict lookup thereafter.  ``captures/ledger_overhead.json``
(benchmarks/exp_ledger_r11.py, the exp_trace_r9 paired-delta
methodology) quantifies the on-cost of the one hot-path-adjacent hook
(:func:`wave_attrs` inside ``record_wave``).

Like the reference exposing ``Dht::getNodesStats``/``dumpTables`` as a
product surface, the ledger is introspection-first: compute is lazy and
opt-in (``OPENDHT_TPU_LEDGER=1`` arms it for serving processes; the
REPL/scanner/CI arm it explicitly), so minimal containers without the
jax wheel still import this module (stdlib-only at import time, same
rule as telemetry.py).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

__all__ = [
    "KERNEL_SPECS", "PLATFORM_PEAKS", "KernelLedger", "get_ledger",
    "ledger_computed", "maybe_export", "wave_attrs", "ingest_wave_attrs",
]

# --------------------------------------------------------------------------
# Per-platform peaks for roofline attribution.  Matched by substring on
# jax's device_kind (first) then platform name.  These are ATTRIBUTION
# DENOMINATORS, not claims: the committed budgets gate the cost model
# (deterministic), never the roofline % (which inherits wall-clock
# noise and these nominal peaks).  The cpu row is deliberately coarse —
# a shared CI runner has no stable peak; its roofline output is labeled
# indicative.  TPU rows are the published per-chip numbers.
# --------------------------------------------------------------------------
PLATFORM_PEAKS = {
    # device_kind/platform substring -> peaks (per chip)
    "v5e":  {"flops_per_s": 197e12, "hbm_bytes_per_s": 819e9,
             "note": "TPU v5e: 197 TFLOP/s bf16, 819 GB/s HBM"},
    "v5p":  {"flops_per_s": 459e12, "hbm_bytes_per_s": 2765e9,
             "note": "TPU v5p: 459 TFLOP/s bf16, 2765 GB/s HBM"},
    "v4":   {"flops_per_s": 275e12, "hbm_bytes_per_s": 1228e9,
             "note": "TPU v4: 275 TFLOP/s bf16, 1228 GB/s HBM"},
    "tpu":  {"flops_per_s": 197e12, "hbm_bytes_per_s": 819e9,
             "note": "unrecognized TPU generation: v5e numbers assumed"},
    "cpu":  {"flops_per_s": 2e11, "hbm_bytes_per_s": 2e10,
             "note": "nominal shared-runner core (indicative only)"},
    "gpu":  {"flops_per_s": 312e12, "hbm_bytes_per_s": 2039e9,
             "note": "A100-class default (indicative)"},
}


_PEAKS_MEMO: "list | None" = None


def platform_peaks(device=None) -> dict:
    """Peaks row for the default (or given) jax device; the matched key
    rides along as ``peak_key`` so exports say which row they used.
    The default-device row is memoized — :func:`wave_attrs` sits on the
    record_wave path and must not re-query the jax backend per wave."""
    global _PEAKS_MEMO
    if device is None and _PEAKS_MEMO is not None:
        return dict(_PEAKS_MEMO[0])
    import jax
    if device is None:
        device = jax.devices()[0]
        _PEAKS_MEMO = [_match_peaks(device)]
        return dict(_PEAKS_MEMO[0])
    return _match_peaks(device)


def _match_peaks(device) -> dict:
    kind = (getattr(device, "device_kind", "") or "").lower()
    plat = (getattr(device, "platform", "") or "").lower()
    for key, row in PLATFORM_PEAKS.items():
        if key in kind:
            return dict(row, peak_key=key)
    for key, row in PLATFORM_PEAKS.items():
        if key in plat:
            return dict(row, peak_key=key)
    return dict(PLATFORM_PEAKS["cpu"], peak_key="cpu")


# --------------------------------------------------------------------------
# Canonical kernel specs.  Each builder returns (lowerable, args, kwargs,
# shape) where ``lowerable`` is a jitted callable supporting
# ``.lower(*args, **kwargs)``.  Shapes are SMALL ON PURPOSE: the ledger
# must lower on the tier-1 CI CPU in seconds, and the XLA cost model is
# what's gated — absolute size only rescales it.  The shape dict is part
# of the budget key: perf_gate refuses to compare entries whose shapes
# drifted (a silent shape change would otherwise masquerade as a cost
# change, or hide one).
# --------------------------------------------------------------------------

_CANON = {
    "N": 4096,          # base table rows
    "Q": 256,           # query batch
    "K": 8,             # protocol k (routing_table.h:26)
    "D": 512,           # churn delta-slab rows
    "GATHER_M": 2048,   # fused-gather row-vector width
    "R": 24,            # alpha*k reply rows per query (alpha=3)
    "W": 256,           # simulate_lookups wave width
    "INGEST_Q": 64,     # wave-builder fill target (config.ingest_fill_target)
    "INGEST_K": 14,     # refill k (live_search.SEARCH_NODES)
}


def _canonical_table(n: int, seed: int = 11):
    import jax
    import jax.numpy as jnp
    from .ops.sorted_table import (sort_table, expand_table,
                                   build_prefix_lut, default_lut_bits)
    ids = jax.random.bits(jax.random.PRNGKey(seed), (n, 5), dtype=jnp.uint32)
    sorted_ids, _perm, n_valid = sort_table(ids)
    expanded = expand_table(sorted_ids)
    lut = build_prefix_lut(sorted_ids, n_valid, bits=default_lut_bits(n))
    return sorted_ids, expanded, n_valid, lut


def _queries(q: int, seed: int = 12):
    import jax
    import jax.numpy as jnp
    return jax.random.bits(jax.random.PRNGKey(seed), (q, 5),
                           dtype=jnp.uint32)


def _spec_find_closest():
    """The SHIPPING find_closest device program — lookup_topk's
    device-resolved path (expanded window kernel + the lax.cond exact
    fallback branch), exactly what ``NodeTable.find_closest`` →
    ``runtime/dht.py find_closest_nodes_batched`` launches per wave."""
    import jax
    from .ops.sorted_table import lookup_topk
    s, e, nv, lut = _canonical_table(_CANON["N"])
    q = _queries(_CANON["Q"])

    def fn(s, e, nv, q, lut):
        return lookup_topk(s, nv, q, k=_CANON["K"], lut=lut, expanded=e)
    return (jax.jit(fn), (s, e, nv, q, lut), {},
            {"N": _CANON["N"], "Q": _CANON["Q"], "k": _CANON["K"]})


def _spec_wave_builder():
    """The ingest wave builder's canonical coalesced launch (round 12,
    runtime/wave_builder.py): ``lookup_topk`` at the fill target
    Q=64 refill targets × k=SEARCH_NODES=14 — the [Q] wave a fully
    coalesced pump of live get/put/listen refills dispatches, vs the
    Q=1 padded launch each op used to pay.  Budgeted from day one so a
    refactor can't silently fatten the new hot path's device program
    (the ISSUE-7 tentpole's cost-gate requirement).

    Round 20 note: the wave pipeline's buffer donation
    (``ops.sorted_table._donating_lookup_topk``) is a runtime-only,
    CPU-gated alias of the same jitted program — the lowered HLO this
    budget pins is unchanged, so no re-base was needed when the
    builder went async (the launch signature and canonical shape are
    identical; donation only marks the query arg's buffer reusable)."""
    import jax
    from .ops.sorted_table import lookup_topk
    s, e, nv, lut = _canonical_table(_CANON["N"])
    q = _queries(_CANON["INGEST_Q"], seed=24)

    def fn(s, e, nv, q, lut):
        return lookup_topk(s, nv, q, k=_CANON["INGEST_K"], lut=lut,
                           expanded=e)
    return (jax.jit(fn), (s, e, nv, q, lut), {},
            {"N": _CANON["N"], "Q": _CANON["INGEST_Q"],
             "k": _CANON["INGEST_K"]})


def _spec_sketch_update():
    """The keyspace observatory's per-wave launch (round 15,
    ops/sketch.py): one batched scatter-add of the ingest fill target
    Q=64 ids into the [depth=4, width=2048] count-min sketch + the
    256-bin top-8-bit keyspace histogram — budgeted from day one so
    the observability layer's only hot-path device work can't silently
    fatten (the ISSUE-10 cost-gate requirement)."""
    import jax
    import jax.numpy as jnp
    from .ops.sketch import BINS, SKETCH_DEPTH, SKETCH_WIDTH, sketch_update
    sketch = jnp.zeros((SKETCH_DEPTH, SKETCH_WIDTH), jnp.int32)
    hist = jnp.zeros((BINS,), jnp.int32)
    ids = _queries(_CANON["INGEST_Q"], seed=26)

    def fn(sketch, hist, ids):
        return sketch_update(sketch, hist, ids)
    return (jax.jit(fn), (sketch, hist, ids), {},
            {"Q": _CANON["INGEST_Q"], "depth": SKETCH_DEPTH,
             "width": SKETCH_WIDTH, "bins": BINS})


def _spec_cache_probe():
    """The hot-cache membership probe (round 16, ops/cache_probe.py):
    one batched XOR-compare of the ingest fill target Q=64 wave
    targets against the default-capacity [64, 5] cache id table — the
    launch ``runtime/wave_builder.py _serve_cached`` runs BEFORE every
    lookup launch, budgeted from day one so the fast path's only new
    device work can't silently fatten (the ISSUE-11 cost-gate
    requirement)."""
    import jax
    import jax.numpy as jnp
    from .ops.cache_probe import CACHE_CAPACITY, cache_probe
    cache_ids = _queries(CACHE_CAPACITY, seed=27)
    valid = jnp.ones((CACHE_CAPACITY,), bool)
    targets = _queries(_CANON["INGEST_Q"], seed=28)

    def fn(cache_ids, valid, targets):
        return cache_probe(cache_ids, valid, targets)
    return (jax.jit(fn), (cache_ids, valid, targets), {},
            {"Q": _CANON["INGEST_Q"], "C": CACHE_CAPACITY})


def _spec_listener_match():
    """The listener-table membership match (round 24,
    ops/listener_match.py): one batched XOR-compare of the ingest fill
    target S=64 stored-put keys against the default-capacity [1024, 5]
    listener id table — the launch ``runtime/dht.py
    flush_listener_wave`` runs once per ingest wave to drive coalesced
    listen/push delivery, budgeted from day one so the delivery path's
    only device work can't silently fatten (the ISSUE-20 cost-gate
    requirement)."""
    import jax
    import jax.numpy as jnp
    from .ops.listener_match import LISTENER_CAPACITY, listener_match
    table_ids = _queries(LISTENER_CAPACITY, seed=29)
    valid = jnp.ones((LISTENER_CAPACITY,), bool)
    stored = _queries(_CANON["INGEST_Q"], seed=30)

    def fn(table_ids, valid, stored):
        return listener_match(table_ids, valid, stored)
    return (jax.jit(fn), (table_ids, valid, stored), {},
            {"S": _CANON["INGEST_Q"], "L": LISTENER_CAPACITY})


def _spec_swarm_step():
    """The chaos swarm stepper's one-launch-per-tick device program
    (round 18, ops/swarm.py): churn draws + partition-aware analytic
    occupancy refresh + the vmapped PR-5 maintenance_sweep over the
    rotating sample + poison admission/decay + the closest-R republish
    re-resolve, at the canonical S=4096-node / M=16-sample / K=32-key
    shape — budgeted from day one so the robustness workload
    generator's only hot launch can't silently fatten (the ISSUE-13
    cost-gate requirement)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from .ops import swarm
    S, M, K, G = 4096, 16, 32, 2
    state = {k: jnp.asarray(v)
             for k, v in swarm.init_swarm(33, S, K, n_groups=G).items()}
    args = (state, np.float32(1.0), np.float32(0.05), np.float32(0.05),
            np.float32(0.1), np.float32(1.0), np.float32(5.0),
            jnp.ones((G, G), bool), True,
            jnp.zeros((S,), bool), np.int32(4), True,
            jnp.arange(M, dtype=jnp.int32),
            jax.random.bits(jax.random.PRNGKey(34), (S, 3), jnp.uint32),
            jax.random.bits(jax.random.PRNGKey(35), (K,), jnp.uint32))
    return (jax.jit(swarm._swarm_step_impl), args, {},
            {"S": S, "M": M, "K": K, "G": G})


def _spec_expanded_topk():
    """The window kernel alone (headline bench core, fast3 select)."""
    from .ops.sorted_table import expanded_topk
    s, e, nv, lut = _canonical_table(_CANON["N"])
    q = _queries(_CANON["Q"])
    return (expanded_topk, (s, e, nv, q),
            {"k": _CANON["K"], "select": "fast3", "lut": lut},
            {"N": _CANON["N"], "Q": _CANON["Q"], "k": _CANON["K"],
             "select": "fast3"})


def _spec_fused_gather():
    """The round-fused [W·α·k] reply gather (ops/sorted_table.py
    fused_gather_planar) — the iterative round's only table access."""
    import jax
    import jax.numpy as jnp
    from .ops.sorted_table import fused_gather_planar
    s, _e, _nv, _lut = _canonical_table(_CANON["N"])
    st = s.T
    rows = (jax.random.bits(jax.random.PRNGKey(13),
                            (_CANON["GATHER_M"], _CANON["R"]),
                            dtype=jnp.uint32)
            % jnp.uint32(_CANON["N"])).astype(jnp.int32)

    def fn(st, rows):
        return fused_gather_planar(st, rows, 5)
    return (jax.jit(fn), (st, rows), {},
            {"N": _CANON["N"], "M": _CANON["GATHER_M"], "R": _CANON["R"],
             "limbs": 5})


def _spec_packed_merge():
    """The lane-packed churn merge at the TPU pack width P=16 (the
    128-lane padding-tax amortizer) — budgeted at pack=16 on every
    platform so the packed kernel's cost is pinned even though cpu
    resolves merge_pack='auto' to 1."""
    import functools
    import jax
    import jax.numpy as jnp
    from .ops.sorted_table import packed_churn_merge
    Q, K = _CANON["Q"], _CANON["K"]
    key = jax.random.PRNGKey(14)
    ks = jax.random.split(key, 4)
    m_dist = tuple(jax.random.bits(ks[i], (Q, K), dtype=jnp.uint32)
                   for i in range(2))
    d_dist = tuple(jax.random.bits(ks[i + 2], (Q, K), dtype=jnp.uint32)
                   for i in range(2))
    m_idx = (jnp.arange(Q * K, dtype=jnp.int32).reshape(Q, K)
             % jnp.int32(_CANON["N"]))
    d_idx = (jnp.arange(Q * K, dtype=jnp.int32).reshape(Q, K)
             % jnp.int32(_CANON["D"]))
    fn = functools.partial(packed_churn_merge, k=K, nl=2, pack=16)
    return (jax.jit(lambda a, b, c, d: fn(a, b, c, d, _CANON["N"])),
            (m_dist, m_idx, d_dist, d_idx), {},
            {"Q": Q, "k": K, "nl": 2, "pack": 16})


def _spec_churn_lookup():
    """The full churn lookup (base ∪ delta, tombstones, packed merge) —
    the kernel behind ``ChurnView.lookup``."""
    import jax.numpy as jnp
    from .ops.sorted_table import churn_lookup_topk
    s, e, nv, lut = _canonical_table(_CANON["N"])
    ds, de, dnv, dlut = _canonical_table(_CANON["D"], seed=15)
    tomb = jnp.zeros((-(-_CANON["N"] // 32),), jnp.uint32)
    q = _queries(_CANON["Q"])
    return (churn_lookup_topk, (s, e, nv, tomb, ds, de, dnv, q, lut, dlut),
            {"k": _CANON["K"], "select": "fast3", "merge_pack": 16},
            {"N": _CANON["N"], "D": _CANON["D"], "Q": _CANON["Q"],
             "k": _CANON["K"], "select": "fast3", "merge_pack": 16})


def _spec_maintenance_sweep():
    """The fused [160, N] bucket-maintenance pass (ops/radix.py)."""
    import jax
    import jax.numpy as jnp
    from .ops.radix import maintenance_sweep
    N = _CANON["N"]
    ids = jax.random.bits(jax.random.PRNGKey(16), (N, 5), dtype=jnp.uint32)
    self_id = jax.random.bits(jax.random.PRNGKey(17), (5,), dtype=jnp.uint32)
    valid = jnp.ones((N,), bool)
    last = jnp.full((N,), 100.0, jnp.float32)
    key = jax.random.PRNGKey(18)
    return (maintenance_sweep,
            (self_id, ids, valid, last, jnp.float32(700.0),
             jnp.float32(600.0), key),
            {}, {"N": N, "buckets": 160})


def _spec_simulate_lookups():
    """The ROUND-FUSED iterative search engine (core/search.py) at the
    config-3 parameterization (alpha=3, k=8, state_limbs=2).  XLA's
    cost model counts a ``while_loop`` body ONCE (trip counts are
    dynamic), so this entry's flops/bytes approximate bootstrap + one
    steady-state round — which is exactly the per-round unit the
    wave-latency bound and :func:`wave_attrs` want."""
    from .core.search import _simulate_lookups_jit
    s, _e, nv, lut = _canonical_table(_CANON["N"])
    t = _queries(_CANON["W"], seed=19)
    return (_simulate_lookups_jit, (s, nv, t),
            {"alpha": 3, "k": _CANON["K"], "lut": lut, "state_limbs": 2},
            {"N": _CANON["N"], "W": _CANON["W"], "alpha": 3,
             "k": _CANON["K"], "state_limbs": 2})


def _spec_tp_simulate_lookups():
    """The table-sharded engine twin (parallel/sharded.py
    build_tp_lookup) on a 1×1 mesh — the same shard_map program CI's
    8-device step runs, lowered at the smallest geometry so the budget
    is computable on any host.  Collective sites still appear in the
    lowering (psum over a 1-ary axis), so a refactor that adds an
    in-loop collective moves this entry.  Round 13: the operands are
    the row-sharded table state a ``partition.shard_table_state`` call
    builds ONCE — sorted rows, per-shard positioning LUT, replicated
    global block LUT — so ``argument_bytes`` now pins the per-device
    resident footprint of the canonical t-sharded lookup (table bytes
    = N/t·5·4 B per shard; a refactor that re-replicates rows or moves
    a LUT rebuild back into the launch moves this entry's
    argument_bytes/bytes_accessed and fails the gate)."""
    from jax.sharding import Mesh
    import numpy as np
    import jax
    from .parallel.partition import shard_table_state
    from .parallel.sharded import build_tp_lookup
    import jax.numpy as jnp
    s, _e, nv, _lut = _canonical_table(_CANON["N"])
    t = _queries(_CANON["W"], seed=20)
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("q", "t"))
    state = shard_table_state(mesh, s, nv)
    fn = build_tp_lookup(mesh, state.shard_n, _CANON["W"], _CANON["K"], 3,
                         14, 48, state_limbs=2)
    a = state.arrays
    return (fn, (a["sorted_ids"], a["local_lut"], a["block_lut"],
                 a["n_valid"], t, jnp.int32(0)), {},
            {"N": _CANON["N"], "W": _CANON["W"], "mesh": "1x1",
             "k": _CANON["K"], "state_limbs": 2,
             "layout": "row-sharded-state"})


def _spec_sharded_window_lookup():
    """The per-shard windowed top-k + ONE cross-shard merge kernel
    (parallel/sharded.py sharded_window_lookup, round-13 declarative
    layout) on a 1×1 mesh — the one-shot resolve path the ingest wave
    builder launches when a resolve mesh is configured
    (runtime/config.py resolve_mesh_t)."""
    from jax.sharding import Mesh
    import numpy as np
    import jax
    import jax.numpy as jnp
    from .ops.sorted_table import _EROW
    from .ops.ids import N_LIMBS
    from .parallel.sharded import _build_sharded_window_lookup
    s, _e, nv, _lut = _canonical_table(_CANON["N"])
    q = _queries(_CANON["Q"], seed=25)
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("q", "t"))
    fn = _build_sharded_window_lookup(mesh, _CANON["K"], 128, _CANON["N"],
                                      False)
    perm = jnp.arange(_CANON["N"], dtype=jnp.int32)
    expanded = jnp.zeros((1, N_LIMBS * _EROW), jnp.uint32)
    lut = jnp.zeros((1, 2), jnp.int32)
    return (fn, (q, s, perm, jnp.asarray(nv, jnp.int32)[None], expanded,
                 lut), {},
            {"N": _CANON["N"], "Q": _CANON["Q"], "k": _CANON["K"],
             "mesh": "1x1", "window": 128})


def _spec_sharded_maintenance():
    """The tp maintenance-sweep twin on a 1×1 mesh (one [160] psum +
    one [160] pmax — the O(buckets) wire contract)."""
    from jax.sharding import Mesh
    import numpy as np
    import jax
    import jax.numpy as jnp
    from .parallel.sharded import _build_sharded_maintenance
    N = _CANON["N"]
    ids = jax.random.bits(jax.random.PRNGKey(21), (N, 5), dtype=jnp.uint32)
    self_id = jax.random.bits(jax.random.PRNGKey(22), (5,), dtype=jnp.uint32)
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("q", "t"))
    fn = _build_sharded_maintenance(mesh)
    return (fn,
            (self_id, ids, jnp.ones((N,), bool),
             jnp.full((N,), 100.0, jnp.float32), jnp.float32(700.0),
             jnp.float32(600.0), jax.random.PRNGKey(23)),
            {}, {"N": N, "mesh": "1x1", "buckets": 160})


def _spec_reshard_state_build():
    """The reshard hot-swap's device cost (ISSUE-17): the weighted
    per-shard LUT rebuild (parallel/partition.py
    _build_state_luts_weighted — per-shard prefix LUT + one psum for
    the replicated global block LUT) on a 1×1 mesh, the only launch a
    boundary swap adds (row movement is a host copy; there is never a
    re-sort).  Budgeted so a refactor that turns the swap into a table
    re-sort or fattens the rebuild's HBM traffic fails the gate."""
    from jax.sharding import Mesh
    import numpy as np
    import jax
    import jax.numpy as jnp
    from .ops.sorted_table import default_lut_bits
    from .parallel import partition
    s, _e, nv, _lut = _canonical_table(_CANON["N"])
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("q", "t"))
    n = int(nv)
    cap = int(-(-_CANON["N"] // partition.RESHARD_ALIGN)
              * partition.RESHARD_ALIGN)
    ids_re = np.zeros((cap, 5), np.uint32)
    ids_re[:_CANON["N"]] = np.asarray(s, np.uint32)
    shard_rows = np.asarray([[0, n]], np.int32)
    placed = partition.shard_put(
        mesh, {"sorted_ids": ids_re, "shard_rows": shard_rows},
        partition.TABLE_AXIS_RULES)
    fn = partition._build_state_luts_weighted(
        mesh, default_lut_bits(cap), default_lut_bits(_CANON["N"]))
    return (fn, (placed["sorted_ids"], placed["shard_rows"]), {},
            {"N": _CANON["N"], "cap": cap, "mesh": "1x1",
             "layout": "weighted"})


#: name -> (builder, paired live telemetry series or None).  The series
#: is the PR-3 histogram that times the SHIPPING launches of the same
#: kernel, so exports can put the live p50 next to the canonical cost.
KERNEL_SPECS = {
    "find_closest_nodes_batched": (_spec_find_closest, None),
    "wave_builder_lookup": (_spec_wave_builder, "dht_ingest_wave_seconds"),
    "sketch_update": (_spec_sketch_update, None),
    "cache_probe": (_spec_cache_probe, None),
    "listener_match": (_spec_listener_match, "dht_listener_match_seconds"),
    "swarm_step": (_spec_swarm_step, None),
    "expanded_topk": (_spec_expanded_topk, None),
    "fused_gather_planar": (_spec_fused_gather, None),
    "packed_churn_merge": (_spec_packed_merge, None),
    "churn_lookup_topk": (_spec_churn_lookup, "dht_churn_lookup_seconds"),
    "maintenance_sweep": (
        _spec_maintenance_sweep, "dht_maintenance_sweep_seconds"),
    "simulate_lookups": (
        _spec_simulate_lookups, 'dht_search_wave_seconds{mode="single"}'),
    "tp_simulate_lookups": (
        _spec_tp_simulate_lookups, 'dht_search_wave_seconds{mode="tp"}'),
    "sharded_window_lookup": (
        _spec_sharded_window_lookup, None),
    "reshard_state_build": (
        _spec_reshard_state_build, "dht_reshard_swap_seconds"),
    "sharded_maintenance_sweep": (
        _spec_sharded_maintenance,
        'dht_maintenance_sweep_seconds{mode="tp"}'),
}


def _cost_dict(compiled) -> dict:
    """Normalize ``compiled.cost_analysis()`` across jax versions (a
    dict on new jax, a 1-list of dicts on older) to one flat dict."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


class KernelLedger:
    """Per-process cost ledger over :data:`KERNEL_SPECS`.

    ``compute()`` lowers + compiles each canonical spec once and caches
    the entry; ``measure()`` additionally times one blocking canonical
    launch per kernel and fills the roofline fields.  Thread-safe; all
    jax work happens inside the compute/measure calls, never at import.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}
        self._exported = False
        #: master switch consulted by :meth:`computed` (and hence by the
        #: record_wave hook): False restores the exact not-computed
        #: hot-path behavior without dropping the cached entries — the
        #: off-arm of the overhead driver and a kill switch for
        #: latency-critical embeddings
        self.enabled = True

    # ------------------------------------------------------------- compute
    def compute(self, kernels: Optional[List[str]] = None,
                force: bool = False) -> Dict[str, dict]:
        """Lower + compile the named kernels (default: all) and return
        ``{name: entry}``.  Entries carry the XLA cost model
        (``flops``, ``bytes_accessed``), the memory footprint
        (``argument_bytes``/``output_bytes``/``temp_bytes`` and their
        sum ``hbm_bytes``, the device-resident peak the launch needs),
        the canonical ``shape``, and the lowering platform.  Specs that
        fail to build (e.g. no jax wheel) record an ``error`` entry
        instead of raising — the ledger is introspection, it must never
        take a serving process down."""
        import jax
        names = list(KERNEL_SPECS) if kernels is None else list(kernels)
        for name in names:
            if name not in KERNEL_SPECS:
                raise KeyError(f"unknown ledger kernel {name!r} — "
                               f"registered: {sorted(KERNEL_SPECS)}")
            with self._lock:
                if name in self._entries and not force:
                    continue
            builder, series = KERNEL_SPECS[name]
            try:
                fn, args, kwargs, shape = builder()
                lowered = fn.lower(*args, **kwargs)
                compiled = lowered.compile()
                cost = _cost_dict(compiled)
                mem = compiled.memory_analysis()
                entry = {
                    "kernel": name,
                    "shape": shape,
                    "flops": float(cost.get("flops", 0.0)),
                    "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
                    "argument_bytes": int(
                        getattr(mem, "argument_size_in_bytes", 0) or 0),
                    "output_bytes": int(
                        getattr(mem, "output_size_in_bytes", 0) or 0),
                    "temp_bytes": int(
                        getattr(mem, "temp_size_in_bytes", 0) or 0),
                    "platform": jax.devices()[0].platform,
                    "series": series,
                }
                entry["hbm_bytes"] = (entry["argument_bytes"]
                                      + entry["output_bytes"]
                                      + entry["temp_bytes"])
                # entries hold NUMBERS only — no callable, no device
                # buffers: a serving process that computed the ledger
                # (OPENDHT_TPU_LEDGER=1) must not pin the canonical
                # tables in HBM for its lifetime, and compute()'s
                # return must stay json.dumps-able.  measure() rebuilds
                # its launches from the spec builder instead.
                del fn, args, kwargs, lowered, compiled
                with self._lock:
                    self._entries[name] = entry
            except Exception as e:                  # pragma: no cover
                with self._lock:
                    self._entries[name] = {
                        "kernel": name, "error": str(e)[:300],
                        "series": series,
                    }
        with self._lock:
            return {n: dict(self._entries[n]) for n in names
                    if n in self._entries}

    def measure(self, kernels: Optional[List[str]] = None,
                reps: int = 3) -> Dict[str, dict]:
        """One warmed, blocked canonical launch per kernel (min of
        ``reps``) through the PR-3 span envelope, then the roofline
        attribution: achieved bytes/s and flops/s over the platform
        peaks (%), and which bound dominates.  Wall-clock — honest on a
        quiet chip, indicative on shared CPU (the gate never reads
        it)."""
        import time as _time
        import jax
        self.compute(kernels)
        names = list(KERNEL_SPECS) if kernels is None else list(kernels)
        peaks = platform_peaks()
        for name in names:
            with self._lock:
                entry = self._entries.get(name)
                bad = not entry or "error" in entry
            if bad:
                continue
            try:
                # rebuild the canonical launch from the spec (compute()
                # deliberately keeps no callables/buffers alive)
                fn, args, kwargs, _shape = KERNEL_SPECS[name][0]()
                jax.block_until_ready(fn(*args, **kwargs))      # warm
                best = None
                for _ in range(max(1, reps)):
                    t0 = _time.perf_counter()
                    jax.block_until_ready(fn(*args, **kwargs))
                    dt = _time.perf_counter() - t0
                    best = dt if best is None else min(best, dt)
                fields = {"measured_s": best,
                          "roofline": self.roofline(name, best, peaks)}
            except Exception as e:                  # pragma: no cover
                fields = {"measure_error": str(e)[:300]}
            # one locked update: export/snapshot iterate + copy these
            # dicts under the same lock, so a concurrent GET /stats
            # scrape never sees a torn entry
            with self._lock:
                if name in self._entries:
                    self._entries[name].update(fields)
        with self._lock:
            return {n: self._public(self._entries[n]) for n in names
                    if n in self._entries}

    def roofline(self, name: str, elapsed_s: float,
                 peaks: Optional[dict] = None) -> dict:
        """Roofline attribution of one measured launch: the cost
        model's bytes/flops over ``elapsed_s`` as a fraction of the
        platform peaks.  ``bound`` names the larger fraction — the
        resource the kernel is actually pushing on."""
        entry = self._entries.get(name)
        if not entry or "error" in entry or elapsed_s <= 0:
            return {}
        if peaks is None:
            peaks = platform_peaks()
        bps = entry["bytes_accessed"] / elapsed_s
        fps = entry["flops"] / elapsed_s
        hbm_pct = 100.0 * bps / peaks["hbm_bytes_per_s"]
        flops_pct = 100.0 * fps / peaks["flops_per_s"]
        return {
            "hbm_pct_of_peak": round(hbm_pct, 3),
            "flops_pct_of_peak": round(flops_pct, 4),
            "bound": "memory" if hbm_pct >= flops_pct else "compute",
            "peak_key": peaks.get("peak_key", "?"),
            "peak_note": peaks.get("note", ""),
        }

    # -------------------------------------------------------------- export
    @staticmethod
    def _public(entry: dict) -> dict:
        return {k: v for k, v in entry.items() if not k.startswith("_")}

    def computed(self) -> bool:
        if not self.enabled:
            return False
        with self._lock:
            return bool(self._entries)

    def clear(self) -> None:
        """Drop every cached entry (tests; also the 'off' arm of the
        overhead driver)."""
        with self._lock:
            self._entries.clear()
            self._exported = False

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able {kernel: entry} of everything computed so far,
        with the paired live-series p50 folded in when the registry has
        observed that histogram (canonical cost next to shipping
        latency — the REPL/scanner table)."""
        from . import telemetry
        with self._lock:
            out = {n: self._public(e) for n, e in self._entries.items()}
        hists = telemetry.get_registry().snapshot()["histograms"]
        for e in out.values():
            s = e.get("series")
            if s and s in hists:
                e["live_p50_s"] = hists[s]["p50"]
                e["live_count"] = hists[s]["count"]
        return out

    def export_to_registry(self, reg=None) -> int:
        """Publish the computed entries as ``dht_kernel_*{kernel=}``
        gauges on the unified registry — flops, bytes accessed, the
        HBM footprint split, and (when measured) device seconds +
        roofline % — so `get_metrics()` JSON and the proxy's
        Prometheus ``/stats`` carry the ledger with zero extra
        plumbing.  Returns the number of kernels exported."""
        from . import telemetry
        if reg is None:
            reg = telemetry.get_registry()
        with self._lock:
            entries = [self._public(e) for e in self._entries.values()
                       if "error" not in e]
        for e in entries:
            k = e["kernel"]
            reg.gauge("dht_kernel_flops", kernel=k).set(e["flops"])
            reg.gauge("dht_kernel_bytes_accessed", kernel=k).set(
                e["bytes_accessed"])
            reg.gauge("dht_kernel_hbm_bytes", kernel=k).set(e["hbm_bytes"])
            reg.gauge("dht_kernel_temp_bytes", kernel=k).set(
                e["temp_bytes"])
            if "measured_s" in e:
                reg.gauge("dht_kernel_device_seconds", kernel=k).set(
                    e["measured_s"])
                rl = e.get("roofline") or {}
                if rl:
                    reg.gauge("dht_kernel_roofline_hbm_pct", kernel=k).set(
                        rl["hbm_pct_of_peak"])
                    reg.gauge("dht_kernel_roofline_flops_pct",
                              kernel=k).set(rl["flops_pct_of_peak"])
        with self._lock:
            self._exported = True
        return len(entries)

    # ----------------------------------------------------- trace-span hook
    def wave_cost(self, wave_width: int, rounds: int,
                  mode: str = "single", mesh_t: int = 1) -> dict:
        """Cost-model estimate for one LIVE wave, scaled from the
        matching canonical engine entry — ``simulate_lookups`` for
        single-device waves, ``tp_simulate_lookups`` (the shard_map
        program with its collectives, lowered on a 1×1 mesh) for
        ``mode="tp"``: every op in the round body is Q-row batched, so
        flops/bytes scale linearly in wave width, and XLA counts the
        while-loop body once, so the canonical entry ≈ bootstrap + one
        round (its own docstring) — est = canonical × (width / W_c) ×
        rounds.  An APPROXIMATION by construction, and the attrs name
        the entry it came from (for tp the 1×1-mesh base means the
        estimate is whole-program, not per-shard — a larger mesh
        divides the table traffic per chip).  Pure dict math — safe on
        the record_wave path (measured by
        captures/ledger_overhead.json)."""
        src = ("tp_simulate_lookups" if mode == "tp"
               else "simulate_lookups")
        entry = self._entries.get(src)
        if not entry or "error" in entry or rounds <= 0:
            return {}
        w_c = entry["shape"]["W"]
        scale = (wave_width / float(w_c)) * rounds
        # t-sharded waves (round 13): the canonical tp entry lowers on
        # a 1x1 mesh, so its table traffic is whole-table; on a real
        # t-way split each device scans ~1/t of the rows, so the
        # PER-DEVICE estimate divides by mesh_t.  Approximate by
        # construction (the O(queries·k) collective bytes don't divide)
        # and labeled as such in the cost_model string.
        t = max(1, int(mesh_t))
        attrs = {
            "est_device_bytes": int(entry["bytes_accessed"] * scale / t),
            "est_device_flops": int(entry["flops"] * scale / t),
            "cost_model": "%s xla-body-once x width/%d x rounds"
                          % (src, w_c),
        }
        if t > 1:
            attrs["cost_model"] += " / t=%d (row-sharded)" % t
            attrs["table_shard_t"] = t
        return attrs

    def ingest_wave_cost(self, occupancy: int, mesh_t: int = 1) -> dict:
        """Cost-model estimate for one LIVE ingest wave, scaled from
        the canonical coalesced-launch entry (``wave_builder_lookup``)
        by occupancy, with per-device table traffic divided by
        ``mesh_t`` when the resolve actually ran against the t-sharded
        table (round 13).  Approximate by construction (the
        cross-shard merge bytes don't divide); same entry-access
        discipline as :meth:`wave_cost` — pure dict math, safe on the
        wave-scatter path."""
        entry = self._entries.get("wave_builder_lookup")
        if not entry or "error" in entry:
            return {}
        t = max(1, int(mesh_t))
        scale = occupancy / float(entry["shape"]["Q"]) / t
        return {
            "est_device_bytes": int(entry["bytes_accessed"] * scale),
            "cost_model": "wave_builder_lookup x occupancy/%d%s"
                          % (entry["shape"]["Q"],
                             " / t=%d (row-sharded)" % t if t > 1 else ""),
        }


_ledger = KernelLedger()


def get_ledger() -> KernelLedger:
    """The process-global ledger every export surface reads."""
    return _ledger


def ledger_computed() -> bool:
    return _ledger.computed()


def maybe_export(reg=None) -> int:
    """Export hook for ``DhtRunner.get_metrics()`` / the proxy scrape:
    publishes the ledger IF it has been computed, and computes it first
    when ``OPENDHT_TPU_LEDGER=1`` arms eager mode (serving processes
    that want the series on every scrape without an explicit REPL/CI
    nudge).  Never raises; returns kernels exported (0 = ledger off).

    The round-19 ``dht_stage_budget_seconds{stage=}`` gauges do NOT
    ride this hook: the stage profiler publishes them on its own
    registry at construction/configure time (waterfall.py), so a
    ledger-off process still pays nothing here on a scrape."""
    try:
        if not _ledger.computed():
            if os.environ.get("OPENDHT_TPU_LEDGER", "") not in (
                    "1", "true", "on"):
                return 0
            _ledger.compute()
        return _ledger.export_to_registry(reg)
    except Exception:
        return 0


def ingest_wave_attrs(occupancy: int, mesh_t: int = 1) -> dict:
    """Device-cost attributes for an ingest ``dht.search.wave`` span
    (runtime/wave_builder.py) — thin module-level hook over
    :meth:`KernelLedger.ingest_wave_cost`, gated exactly like
    :func:`wave_attrs`: empty dict (a cached-flag check) until the
    ledger is computed."""
    if not _ledger.computed():
        return {}
    return _ledger.ingest_wave_cost(occupancy, mesh_t)


def wave_attrs(wave_width: int, rounds: int, elapsed_s: float,
               mode: str = "single", mesh_t: int = 1) -> dict:
    """Device-cost attributes for a ``dht.search.wave`` trace span
    (core/search.py record_wave; the tp twin passes ``mode="tp"`` and
    its mesh's ``t`` extent so the estimate comes from the sharded
    program's entry with per-device table traffic scaled by 1/t): the
    scaled cost-model estimate plus the achieved HBM fraction over the
    platform peak when the wave's host-measured elapsed is known.
    Empty dict (and ~zero cost) until someone computes the ledger —
    the hot path only ever pays a dict lookup."""
    if not _ledger.computed():
        return {}
    attrs = _ledger.wave_cost(wave_width, rounds, mode, mesh_t)
    if attrs and elapsed_s > 0:
        try:
            peaks = platform_peaks()
            attrs["est_hbm_pct_of_peak"] = round(
                100.0 * (attrs["est_device_bytes"] / elapsed_s)
                / peaks["hbm_bytes_per_s"], 3)
            attrs["peak_key"] = peaks.get("peak_key", "?")
        except Exception:
            pass
    return attrs
