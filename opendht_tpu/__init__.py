"""opendht_tpu — a TPU-native distributed hash table framework.

A ground-up re-design of the capabilities of OpenDHT (reference:
``Dale-M/opendht`` @ /root/reference, surveyed in SURVEY.md): a Kademlia
DHT with ``get/put/listen/query`` value store, signed/encrypted values,
write tokens, a REST proxy and a Python-first API — with the routing
core re-architected as batched JAX/XLA kernels over HBM-resident
node-ID matrices instead of scalar per-search loops.

Package layout (mirrors the reference's layer map, SURVEY.md §1):

- ``ops``        L0 device kernels: 160-bit ID math, XOR top-k (lax + pallas),
                 sorted-table window lookup, radix partition
- ``core``       L2 data structures: node table, routing, batched search, storage, values
- ``net``        L1 host network engine: msgpack wire protocol, request lifecycle
- ``native``     C++ host runtime: XOR engine + UDP datagram engine (ctypes)
- ``crypto``     L0/L3 identities, sign/encrypt (SecureDht overlay)
- ``runtime``    L4 Dht core + DhtRunner façade + scheduler
- ``parallel``   multi-chip sharded tables (jax.sharding Mesh + shard_map)
- ``proxy``      REST proxy server/client
- ``indexation`` PHT (prefix hash tree) distributed index
- ``tools``      dhtnode / dhtchat / dhtscanner CLI equivalents
- ``testing``    cluster harness: virtual-clock network, scenario suites, benchmark
- ``log``        Logger with per-hash filter and console/file/syslog sinks
- ``telemetry``  unified metrics spine: counters/gauges/histograms + span
                 timers, exported as JSON (``DhtRunner.get_metrics``) and
                 Prometheus text (proxy ``GET /stats``)
"""

__version__ = "0.1.0"

from . import telemetry  # noqa: F401  (stdlib-only; safe to import eagerly)
from .infohash import InfoHash, PkId, random_infohash  # noqa: F401
from .core.value import Value, ValueType, Query, Select, Where, Filters  # noqa: F401
from .runtime.config import Config, NodeStats, NodeStatus, SecureDhtConfig  # noqa: F401
from .sockaddr import SockAddr  # noqa: F401
from .net.node import Node  # noqa: F401
from .nodeset import NodeEntry, NodeSet  # noqa: F401
from .indexation.pht import IndexEntry as IndexValue, Pht  # noqa: F401

# The crypto-backed surface (DhtRunner + the identity/certificate types)
# resolves LAZILY (PEP 562): it is the only part of the package that
# needs the ``cryptography`` wheel, and an eager import here used to
# poison every `import opendht_tpu` — including the pure device-kernel
# paths (ops/, core/, parallel/) — on hosts without it.  With
# ``cryptography`` installed nothing changes (first attribute access
# imports and caches the real object); without it, only touching these
# names raises, with the kernels and the sharded engine fully usable.
_LAZY_EXPORTS = {
    name: ".runtime.runner" for name in ("DhtRunner", "RunnerConfig")
}
_LAZY_EXPORTS.update({
    name: ".crypto" for name in (
        "Certificate", "Identity", "PrivateKey", "PublicKey",
        "RevocationList", "TrustList", "VerifyResult",
        "generate_identity", "generate_ec_identity",
    )
})


def __getattr__(name):
    mod = _LAZY_EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    try:
        value = getattr(importlib.import_module(mod, __name__), name)
    except ModuleNotFoundError as e:
        # AttributeError (chained from the real cause) — NOT the bare
        # ModuleNotFoundError: hasattr()/dir()-driven introspection
        # (help, pydoc, inspect.getmembers) must degrade softly on a
        # crypto-less host, while `from opendht_tpu import DhtRunner`
        # still raises ImportError (the import machinery converts the
        # AttributeError) and direct access still names the missing
        # wheel.
        raise AttributeError(
            f"opendht_tpu.{name} requires the optional '{e.name}' package "
            f"(the device kernels, search engine, and parallel/ sharding "
            f"work without it)") from e
    globals()[name] = value              # cache: __getattr__ runs once
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY_EXPORTS))


# Star imports materialize every __all__ name, so on a host without
# ``cryptography`` a `from opendht_tpu import *` raises — exactly what
# the fully-eager module did; the laziness win is that plain
# `import opendht_tpu` (and every non-crypto submodule) now works there.
__all__ = [
    "InfoHash", "PkId", "random_infohash",
    "Value", "ValueType", "Query", "Select", "Where", "Filters",
    "Config", "NodeStats", "NodeStatus", "SecureDhtConfig",
    "SockAddr", "Node", "NodeEntry", "NodeSet", "IndexValue", "Pht",
    "DhtConfig", "ListenToken",
] + sorted(_LAZY_EXPORTS)

#: binding-compat aliases (↔ python/opendht.pyx names)
DhtConfig = Config
#: DhtRunner.listen returns this token handle (a Future resolving to the
#: runner-level token — pass it back to cancel_listen)
import concurrent.futures as _futures
ListenToken = _futures.Future
