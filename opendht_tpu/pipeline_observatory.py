"""Pipeline utilization observatory: lane timelines over the async wave plane.

Round 22.  PR 16's depth-N async pipeline broke the waterfall's
sequential-stage model — stages overlap, so sum-of-stages no longer
equals wall clock and in-flight device time reappears as ``queue_wait``.
This module answers the question the stage histograms no longer can:
*is the device busy, and if not, whose fault is the gap?*

Design (Dapper-style causality applied to the Orca-style pipeline):

- ``WaveBuilder`` reports per-wave lifecycle **edges** — fill_start,
  fill_done/dispatch, device_done, scatter_done — and the observatory
  folds them into a bounded, lane-structured timeline (``fill`` /
  ``device`` / ``drain`` lanes, same ring discipline as the PR-4 flight
  recorder).  A wave's three lane intervals partition its wall-clock
  span exactly: fill = [fill_start, dispatch], device = [dispatch,
  device_done], drain = [device_done, scatter_done].
- **Device occupancy** is counted at busy/idle transitions: the device
  lane is busy while >= 1 wave is between dispatch and device_done.
  Cumulative busy seconds feed a windowed occupancy gauge
  (``dht_pipeline_occupancy``), with window checkpoints pushed on the
  PR-12 history-ring frame cadence.
- **Bubble attribution**: every device-idle gap is classified at the
  idle->busy edge into exactly one cause and observed into
  ``dht_pipeline_bubble_seconds{cause=}``.  Because busy seconds are
  counted on the complementary edges, Σ(busy) + Σ(attributed bubbles)
  equals the observed window — the accounting is conservative and
  closed, and tests pin it against a host-side scalar oracle.
- **Overlap efficiency**: Σ(per-wave serial spans) over the union wall
  span of the retained timeline.  1.0 means depth-1 serial behaviour;
  >1.0 is measured fill∥device overlap — the always-on successor to
  ``captures/pipeline_overlap.json``'s one-shot evidence.

Everything here is host-side bookkeeping around the launch/consume
edges; device kernels are untouched and remain bit-identical with the
observatory on (pinned by tests and the r21 overhead driver).
"""

from __future__ import annotations

import threading
import time as _time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional, Tuple

from . import telemetry
from . import tracing

__all__ = [
    "BUBBLE_CAUSES",
    "PipelineObservatoryConfig",
    "PipelineObservatory",
]

# Every device-idle gap is attributed to exactly one of these causes.
# Order matters twice: classification priority (first match wins among
# the flag-driven causes) and the index published by the top-cause
# gauge ``dht_pipeline_bubble_top_cause``.
BUBBLE_CAUSES = (
    "queue_empty",        # nothing submitted: idle because there was no work
    "fill_slow",          # work arrived but batching/deadline held the wave open
    "drain_backpressure", # pipeline full: fire blocked on draining an old wave
    "launch_retry",       # a launch/consume failure forced a requeue round-trip
    "reshard_swap",       # table generation changed between waves (hot swap)
    "cache_served",       # the whole wave was served from cache; device skipped
)

# Causes that indicate the serving plane is *starved* while work exists.
# queue_empty and cache_served are healthy idleness and never degrade
# the occupancy-collapse health signal.
STARVED_CAUSES = ("fill_slow", "drain_backpressure", "launch_retry", "reshard_swap")


@dataclass
class PipelineObservatoryConfig:
    """Tuning for the pipeline utilization observatory.

    Defaults keep the plane always-on: the per-edge cost is a few dict
    ops under a lock (no syscalls, no allocation beyond the ring slot),
    bounded <1% on the 8192-wave round (``captures/pipeutil_overhead.json``).
    """

    # Master switch.  Off => every hook is a cheap early return and the
    # occupancy gauge stays at -1 (unknown).
    enabled: bool = True
    # Closed wave records retained for overlap/lane export (flight-ring
    # discipline: bounded deque, oldest evicted first).
    ring: int = 512
    # Occupancy gauge window.  Checkpoints are pushed on the history
    # frame cadence; with no history attached the gauge degrades to
    # lifetime occupancy.
    window_s: float = 60.0
    # Bound on retained window checkpoints (one per history frame).
    checkpoints: int = 256


class _Wave:
    """One wave's lifecycle record (open until scatter_done)."""

    __slots__ = (
        "seq", "t_fill", "t_dispatch", "t_avail", "t_done",
        "n", "af", "k", "slot", "gen", "cause", "trace", "span", "cached",
    )

    def __init__(self, seq: int, t_fill: float, t_dispatch: float,
                 n: int, af: int, k: int, slot: int, gen: int,
                 cause: Optional[str]) -> None:
        self.seq = seq
        self.t_fill = t_fill
        self.t_dispatch = t_dispatch
        self.t_avail = -1.0
        self.t_done = -1.0
        self.n = n
        self.af = af
        self.k = k
        self.slot = slot
        self.gen = gen
        self.cause = cause       # bubble cause attributed at this dispatch edge
        self.trace = ""          # dht.search.wave trace id (hex), linked at close
        self.span = ""
        self.cached = False


class PipelineObservatory:
    """Concurrency-aware utilization plane over the wave pipeline.

    Thread-safety: edges arrive from the DHT maintenance thread while
    snapshots/exports are read from proxy handler threads — one lock
    guards all mutable state.  Edge methods are O(1); the overlap sweep
    is O(ring) and only runs at snapshot/frame cadence.
    """

    def __init__(self, config: Optional[PipelineObservatoryConfig] = None,
                 registry: Optional[telemetry.MetricsRegistry] = None,
                 clock: Callable[[], float] = _time.time) -> None:
        self.config = config or PipelineObservatoryConfig()
        self.enabled = bool(self.config.enabled)
        self._clock = clock
        self._lock = threading.Lock()

        self._seq = 0
        self._open: Dict[int, _Wave] = {}
        self._ring: Deque[_Wave] = deque(maxlen=max(1, int(self.config.ring)))

        # Device-lane busy/idle transition accounting.
        self._t0: Optional[float] = None        # first observed edge
        self._device_n = 0                      # waves between dispatch and device_done
        self._busy_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._cum_busy = 0.0                    # closed busy seconds
        self._cum_bubble: Dict[str, float] = {c: 0.0 for c in BUBBLE_CAUSES}
        self._bubble_n: Dict[str, int] = {c: 0 for c in BUBBLE_CAUSES}

        # Idle-gap cause flags, set between an idle edge and the next
        # dispatch; cleared once the gap is attributed.
        self._flag_retry = False
        self._flag_backpressure = False
        self._flag_cache = False
        self._last_gen: Optional[int] = None
        # fill_start of the wave currently batching (queue went 0 -> 1).
        self._fill_start: Optional[float] = None

        # Occupancy window checkpoints: (wall_t, cum_busy_at_t), pushed
        # on the history frame cadence (PR-12 ring).
        self._ckpts: Deque[Tuple[float, float]] = deque(
            maxlen=max(2, int(self.config.checkpoints)))

        # Occupancy-collapse window baseline (stage_budget-style diff).
        self._collapse_prev: Optional[Tuple[float, float, int]] = None

        reg = registry if registry is not None else telemetry.get_registry()
        self._m_occ = reg.gauge("dht_pipeline_occupancy")
        self._m_occ.set(-1.0)  # unknown until a window closes
        self._m_busy_total = reg.counter("dht_pipeline_device_busy_seconds_total")
        self._m_overlap = reg.gauge("dht_pipeline_overlap_ratio")
        self._m_overlap.set(-1.0)
        self._m_top_cause = reg.gauge("dht_pipeline_bubble_top_cause")
        self._m_top_cause.set(-1.0)
        self._m_bubble = {
            c: reg.histogram("dht_pipeline_bubble_seconds", cause=c)
            for c in BUBBLE_CAUSES
        }
        self._m_waves = reg.counter("dht_pipeline_waves_total")

    # ------------------------------------------------------------------
    # lifecycle edges (called by WaveBuilder; all O(1))

    def note_fill_start(self, t: Optional[float] = None) -> None:
        """Pending queue went 0 -> 1: a new wave starts batching."""
        if not self.enabled:
            return
        t = self._clock() if t is None else t
        with self._lock:
            if self._fill_start is None:
                self._fill_start = t
            if self._t0 is None:
                self._t0 = t
                self._idle_since = t

    def take_fill(self, t_pick: float) -> Optional[float]:
        """Fill done: the builder picked up the pending batch.

        Returns the fill_start edge for this wave group (or None when
        the observatory is off / no fill edge was seen) and re-arms for
        the next wave.
        """
        if not self.enabled:
            return None
        with self._lock:
            t_fill = self._fill_start
            self._fill_start = None
            return t_fill

    def note_backpressure(self) -> None:
        """Fire blocked on draining a full pipeline before launching."""
        if not self.enabled:
            return
        with self._lock:
            self._flag_backpressure = True

    def note_launch_retry(self) -> None:
        """A launch or consume failure forced a requeue round-trip."""
        if not self.enabled:
            return
        with self._lock:
            self._flag_retry = True

    def note_cache_served(self, t_fill: Optional[float], n: int) -> None:
        """An entire wave was served from cache; the device was skipped.

        Recorded as a fill-only wave in the ring (device/drain lanes
        empty) and flags the current idle gap as ``cache_served``.
        """
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            self._flag_cache = True
            if self._t0 is None:
                self._t0 = t_fill if t_fill is not None else now
                self._idle_since = self._t0
            self._seq += 1
            w = _Wave(self._seq, t_fill if t_fill is not None else now,
                      now, n, 0, 0, -1, self._last_gen or 0, None)
            w.t_avail = now
            w.t_done = now
            w.cached = True
            self._ring.append(w)

    def on_dispatch(self, t_fill: Optional[float], t_dispatch: float,
                    n: int, af: int, k: int, slot: int, gen: int) -> int:
        """Wave dispatched to the device.  Returns the wave's seq.

        When the device lane was idle, the idle gap [idle_since,
        t_dispatch] is attributed to exactly one bubble cause here —
        the complementary edge to busy accounting, which keeps
        Σ(busy) + Σ(bubbles) == observed window.
        """
        if not self.enabled:
            return -1
        with self._lock:
            if self._t0 is None:
                self._t0 = t_fill if t_fill is not None else t_dispatch
                self._idle_since = self._t0
            cause: Optional[str] = None
            if self._device_n == 0:
                idle0 = self._idle_since if self._idle_since is not None else t_dispatch
                gap = t_dispatch - idle0
                if gap > 0.0:
                    cause = self._classify_locked(t_fill, idle0, t_dispatch, gen)
                    self._cum_bubble[cause] += gap
                    self._bubble_n[cause] += 1
                    self._m_bubble[cause].observe(gap)
                    self._refresh_top_cause_locked()
                self._busy_since = t_dispatch
                self._idle_since = None
                self._flag_retry = False
                self._flag_backpressure = False
                self._flag_cache = False
            self._device_n += 1
            self._last_gen = gen
            self._seq += 1
            seq = self._seq
            self._open[seq] = _Wave(seq, t_fill if t_fill is not None else t_dispatch,
                                    t_dispatch, n, af, k, slot, gen, cause)
            self._m_waves.inc()
            return seq

    def on_device_done(self, seq: int, t_avail: float) -> None:
        """Device results available for wave ``seq`` (consume returned)."""
        if not self.enabled or seq < 0:
            return
        with self._lock:
            w = self._open.get(seq)
            if w is not None:
                w.t_avail = t_avail
            if self._device_n > 0:
                self._device_n -= 1
                if self._device_n == 0 and self._busy_since is not None:
                    busy = max(0.0, t_avail - self._busy_since)
                    self._cum_busy += busy
                    self._m_busy_total.inc(busy)
                    self._busy_since = None
                    self._idle_since = t_avail
                    self._update_occupancy_gauge_locked(t_avail)

    def on_scatter_done(self, seq: int, t_done: float,
                        trace: str = "", span: str = "") -> None:
        """Results scattered back (or the wave abandoned): closes the
        wave's lane slices.  Failure paths must reach here too so the
        timeline never leaks an orphan open interval."""
        if not self.enabled or seq < 0:
            return
        with self._lock:
            w = self._open.pop(seq, None)
            if w is None:
                return
            if w.t_avail < 0.0:
                # Device edge never reported (abandoned mid-flight):
                # close conservatively at the scatter edge.
                w.t_avail = t_done
            w.t_done = max(t_done, w.t_avail)
            if trace:
                w.trace = trace
            if span:
                w.span = span
            self._ring.append(w)

    # ------------------------------------------------------------------
    # classification

    def _classify_locked(self, t_fill: Optional[float], idle0: float,
                         t_dispatch: float, gen: int) -> str:
        # Priority: explicit pipeline events first, then the fill-edge
        # geometry splits "no work" from "work batching too slowly".
        if self._flag_retry:
            return "launch_retry"
        if self._last_gen is not None and gen != self._last_gen:
            return "reshard_swap"
        if self._flag_backpressure:
            return "drain_backpressure"
        if self._flag_cache:
            return "cache_served"
        if t_fill is not None and t_fill < t_dispatch:
            # Gap = empty part [idle0, fill_start] + fill part
            # [fill_start, dispatch]; the dominant share names it.
            fill_part = t_dispatch - max(t_fill, idle0)
            empty_part = max(t_fill, idle0) - idle0
            return "fill_slow" if fill_part >= empty_part else "queue_empty"
        return "queue_empty"

    def _refresh_top_cause_locked(self) -> None:
        top, top_s = -1, 0.0
        for i, c in enumerate(BUBBLE_CAUSES):
            if self._cum_bubble[c] > top_s:
                top, top_s = i, self._cum_bubble[c]
        self._m_top_cause.set(float(top))

    # ------------------------------------------------------------------
    # derived signals

    def _cum_busy_at_locked(self, now: float) -> float:
        busy = self._cum_busy
        if self._busy_since is not None:
            busy += max(0.0, now - self._busy_since)
        return busy

    def occupancy(self, now: Optional[float] = None) -> Optional[float]:
        """Windowed device occupancy in [0, 1]; None while unknown."""
        if not self.enabled:
            return None
        now = self._clock() if now is None else now
        with self._lock:
            return self._occupancy_locked(now)

    def _occupancy_locked(self, now: float) -> Optional[float]:
        if self._t0 is None:
            return None
        target = now - float(self.config.window_s)
        base_t, base_busy = self._t0, 0.0
        for t, b in self._ckpts:
            if t <= target:
                base_t, base_busy = t, b
            else:
                break
        span = now - base_t
        if span <= 0.0:
            return None
        occ = (self._cum_busy_at_locked(now) - base_busy) / span
        return min(1.0, max(0.0, occ))

    def _update_occupancy_gauge_locked(self, now: float) -> None:
        occ = self._occupancy_locked(now)
        if occ is not None:
            self._m_occ.set(occ)

    def on_frame(self, now: Optional[float] = None) -> None:
        """History-ring frame hook: push an occupancy window checkpoint
        and refresh the windowed gauges (PR-12 cadence)."""
        if not self.enabled:
            return
        now = self._clock() if now is None else now
        with self._lock:
            if self._t0 is None:
                return
            self._ckpts.append((now, self._cum_busy_at_locked(now)))
            self._update_occupancy_gauge_locked(now)
            self._update_overlap_gauge_locked()

    def collapse(self) -> Optional[float]:
        """Degrade-only occupancy-collapse signal for the health engine.

        Windowed fraction of wall clock lost to *starved* bubbles
        (fill_slow / drain_backpressure / launch_retry / reshard_swap —
        queue_empty and cache_served are healthy idleness).  None when
        the window saw no pipeline activity (unknown, never degrades).
        """
        if not self.enabled:
            return None
        now = self._clock()
        with self._lock:
            starved = sum(self._cum_bubble[c] for c in STARVED_CAUSES)
            waves = int(self._m_waves.value)
            prev = self._collapse_prev
            self._collapse_prev = (now, starved, waves)
            if prev is None:
                return None
            dt = now - prev[0]
            if dt <= 0.0:
                return None
            d_starved = starved - prev[1]
            d_waves = waves - prev[2]
            if d_waves == 0 and d_starved <= 0.0:
                return None  # quiet window: unknown, not healthy-by-default
            return min(1.0, max(0.0, d_starved / dt))

    # ------------------------------------------------------------------
    # accounting / snapshot / export

    def account(self, now: Optional[float] = None) -> dict:
        """Closed busy/bubble ledger.  On an idle-free load, measured
        through the last idle edge, busy + bubbles == span (the oracle
        the tests pin)."""
        now = self._clock() if now is None else now
        with self._lock:
            # Close the ledger at the last attributed edge: the current
            # idle tail (if any) has not been classified yet.
            until = now if self._busy_since is not None else (
                self._idle_since if self._idle_since is not None else now)
            busy = self._cum_busy_at_locked(until)
            bubbles = dict(self._cum_bubble)
            span = (until - self._t0) if self._t0 is not None else 0.0
            return {
                "t0": self._t0,
                "until": until,
                "span_s": max(0.0, span),
                "busy_s": busy,
                "bubble_s": bubbles,
                "bubble_n": dict(self._bubble_n),
                "attributed_s": busy + sum(bubbles.values()),
                "open_waves": len(self._open),
            }

    def _update_overlap_gauge_locked(self) -> None:
        ratio = self._overlap_locked()
        self._m_overlap.set(ratio if ratio is not None else -1.0)

    def _overlap_locked(self) -> Optional[float]:
        """Σ(per-wave serial spans) / union wall span over the ring.
        1.0 == depth-1 serial; >1.0 is measured lane overlap."""
        spans = [(w.t_fill, w.t_done) for w in self._ring
                 if w.t_done >= 0.0 and w.t_done > w.t_fill]
        if not spans:
            return None
        spans.sort()
        serial = sum(t1 - t0 for t0, t1 in spans)
        union = 0.0
        cur0, cur1 = spans[0]
        for t0, t1 in spans[1:]:
            if t0 > cur1:
                union += cur1 - cur0
                cur0, cur1 = t0, t1
            else:
                cur1 = max(cur1, t1)
        union += cur1 - cur0
        if union <= 0.0:
            return None
        return serial / union

    def snapshot(self, now: Optional[float] = None) -> dict:
        """JSON-safe utilization snapshot (served on ``GET /pipeline``)."""
        if not self.enabled:
            return {"enabled": False}
        now = self._clock() if now is None else now
        with self._lock:
            occ = self._occupancy_locked(now)
            self._update_occupancy_gauge_locked(now)
            overlap = self._overlap_locked()
            self._m_overlap.set(overlap if overlap is not None else -1.0)
            top = -1
            top_s = 0.0
            for i, c in enumerate(BUBBLE_CAUSES):
                if self._cum_bubble[c] > top_s:
                    top, top_s = i, self._cum_bubble[c]
            return {
                "enabled": True,
                "occupancy": occ if occ is not None else -1.0,
                "window_s": float(self.config.window_s),
                "busy_seconds_total": self._cum_busy_at_locked(now),
                "waves_total": int(self._m_waves.value),
                "inflight_device": self._device_n,
                "open_waves": len(self._open),
                "overlap_ratio": overlap if overlap is not None else -1.0,
                "bubbles": {
                    c: {"seconds": self._cum_bubble[c], "count": self._bubble_n[c]}
                    for c in BUBBLE_CAUSES
                },
                "top_bubble_cause": BUBBLE_CAUSES[top] if top >= 0 else None,
                "ring": len(self._ring),
                "ring_cap": int(self._ring.maxlen or 0),
            }

    def lane_records(self) -> List[dict]:
        """Tracer-shaped records for the retained waves, one synthetic
        node per lane so ``tracing.to_chrome_trace`` renders one pid
        per lane with waves as slices, linked to their
        ``dht.search.wave`` spans via args."""
        with self._lock:
            waves = list(self._ring)
        out: List[dict] = []
        for w in waves:
            if w.t_done < 0.0:
                continue
            link = {"wave_seq": w.seq, "af": w.af, "k": w.k,
                    "pipeline_slot": w.slot, "reshard_gen": w.gen,
                    "entries": w.n}
            if w.trace:
                link["wave_trace_id"] = w.trace
            if w.span:
                link["wave_span_id"] = w.span
            if w.cause:
                link["bubble_cause"] = w.cause
            if w.cached:
                link["cache_served"] = True
            lanes = (("lane:fill", w.t_fill, w.t_dispatch),
                     ("lane:device", w.t_dispatch, w.t_avail),
                     ("lane:drain", w.t_avail, w.t_done))
            for li, (lane, t0, t1) in enumerate(lanes):
                if t1 < t0:
                    continue
                if w.cached and lane != "lane:fill":
                    continue  # cache-served waves never touched device/drain
                out.append({
                    "name": "wave %d" % w.seq,
                    "start": t0,
                    "dur": max(0.0, t1 - t0),
                    "trace_id": w.trace or ("%032x" % (w.seq & ((1 << 128) - 1))),
                    "span_id": "%016x" % (((w.seq << 2) | li) & ((1 << 64) - 1)),
                    "attrs": dict(link, lane=lane.split(":", 1)[1]),
                    "node": lane,
                })
        return out

    def chrome_trace(self) -> dict:
        """Perfetto/chrome://tracing lane export (``GET /pipeline?fmt=trace``)."""
        return tracing.to_chrome_trace(records=self.lane_records())
