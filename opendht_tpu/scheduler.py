"""Time-ordered job scheduler — the single-threaded runtime driver.

Counterpart of the reference ``Scheduler`` (include/opendht/scheduler.h:37-122):
every periodic behavior in the network engine and DHT core is a job keyed
by a time point; ``run()`` executes everything due and reports the next
wakeup so the owning loop can sleep exactly that long.

Python-idiomatic design: a heapq of (time, seq, Job) entries with lazy
deletion — ``cancel``/``edit`` just drop the callable, and stale heap
entries are skipped when popped (the reference reschedules by re-emplacing
into a multimap, same effect).  Lazy deletion alone lets a cancel-heavy
workload (listen churn, request retries racing replies) grow the heap
without bound, so the scheduler counts its stale entries (exposed as the
``dht_scheduler_stale_entries`` gauge) and compacts the heap in place
once more than half of a non-trivial heap is dead.

Telemetry (one gauge store / histogram observe per ``run()``, handles
cached at construction): ``dht_scheduler_queue_depth`` /
``dht_scheduler_stale_entries`` gauges, ``dht_scheduler_tick_lag_seconds``
(how late the due job at the head fired — the ISSUE-3 tick-lag surface)
and ``dht_scheduler_heap_compactions_total``.  Multiple schedulers in
one process share the series (last writer wins on the gauges).
"""

from __future__ import annotations

import heapq
import itertools
import time as _time
from typing import Callable, Optional

from . import telemetry
from .utils import TIME_MAX

#: compaction policy: rebuild when the heap is beyond this size AND more
#: than half of it is cancelled entries
_COMPACT_MIN = 64


class Job:
    """A scheduled callable. ``cancel()`` clears it (scheduler.h:41-44).
    ``time`` tracks the pending fire time (None once popped/parked) so
    callers can compare against an intended reschedule."""

    __slots__ = ("func", "time", "_sched")

    def __init__(self, func: Optional[Callable[[], None]]):
        self.func = func
        self.time: Optional[float] = None
        self._sched: "Scheduler | None" = None

    def cancel(self) -> None:
        if self.func is not None:
            self.func = None
            # tell the owning scheduler its heap entry went stale so the
            # lazy-deletion debt is observable (and compactable)
            if self._sched is not None and self.time is not None:
                self._sched._note_stale()

    @property
    def cancelled(self) -> bool:
        return self.func is None


class Scheduler:
    def __init__(self, clock: Callable[[], float] = _time.monotonic):
        self._clock = clock
        self._now = clock()
        self._heap: list[tuple[float, int, Job]] = []
        self._seq = itertools.count()
        self._stale = 0                 # cancelled entries still heaped
        reg = telemetry.get_registry()
        self._m_depth = reg.gauge("dht_scheduler_queue_depth")
        self._m_stale = reg.gauge("dht_scheduler_stale_entries")
        self._m_lag = reg.histogram("dht_scheduler_tick_lag_seconds")
        self._m_compactions = reg.counter(
            "dht_scheduler_heap_compactions_total")

    # -- stale accounting --------------------------------------------------
    def _note_stale(self) -> None:
        self._stale += 1

    def _note_popped(self, job: Job) -> None:
        """A heap entry left the heap; if its job was cancelled it was
        part of the stale debt.  Clamped: a job double-queued via
        ``queue()`` owns several entries but counts one stale — the
        periodic compaction re-zeroes the count exactly."""
        if job.cancelled and self._stale > 0:
            self._stale -= 1

    @property
    def stale_entries(self) -> int:
        """Cancelled jobs still occupying heap slots (lazy deletion)."""
        return self._stale

    def _maybe_compact(self) -> None:
        """Drop cancelled entries in place when they dominate the heap —
        bounds heap growth under cancel-heavy workloads (the regression
        tests pin this; see ISSUE-3 satellite)."""
        heap = self._heap
        if len(heap) > _COMPACT_MIN and 2 * self._stale > len(heap):
            dropped = len(heap) - sum(1 for e in heap
                                      if not e[2].cancelled)
            self._heap = [e for e in heap if not e[2].cancelled]
            heapq.heapify(self._heap)
            self._stale = 0
            self._m_compactions.inc()
            # flight recorder (ISSUE-4): compactions are a first-class
            # postmortem signal alongside the counter
            from . import tracing
            tr = tracing.get_tracer()
            if tr.enabled:
                tr.event("scheduler_compaction", dropped=dropped,
                         kept=len(self._heap))

    # -- queue ops ---------------------------------------------------------
    def add(self, t: float, func: Callable[[], None]) -> Job:
        """Schedule ``func`` at time ``t``; returns the Job handle
        (scheduler.h:53-58). t == TIME_MAX means 'parked': the job exists
        but is not queued."""
        job = Job(func)
        job._sched = self
        if t != TIME_MAX:
            job.time = t
            heapq.heappush(self._heap, (t, next(self._seq), job))
        return job

    def queue(self, job: Job, t: float) -> None:
        """Re-enqueue an existing job at ``t`` (scheduler.h:60-63)."""
        if t != TIME_MAX:
            job._sched = self
            job.time = t
            heapq.heappush(self._heap, (t, next(self._seq), job))

    def edit(self, job: Optional[Job], t: float) -> Optional[Job]:
        """Reschedule: cancel the old entry, return a fresh Job at ``t``
        (scheduler.h:70-80 — the reference also invalidates the old
        shared_ptr's callable and re-adds)."""
        if job is None:
            return None
        func = job.func
        if func is not None and job.time is not None:
            self._note_stale()      # the old heap entry is now dead weight
        job.func = None
        job.time = None
        return self.add(t, func) if func is not None else None

    # -- execution ---------------------------------------------------------
    def run(self) -> float:
        """Run all jobs due as of now; return next wakeup time
        (scheduler.h:87-106).  Jobs scheduled for a time strictly after the
        synced 'now' are left for the next run, so a job that reschedules
        itself for 'now + d' cannot starve the loop."""
        self.sync_time()
        heap = self._heap
        # drop cancelled heads first so the lag observation below never
        # reports lateness for a job that was never going to fire
        while heap and heap[0][2].cancelled:
            self._note_popped(heap[0][2])
            heapq.heappop(heap)
        if heap and heap[0][0] <= self._now:
            # tick lag: how late the head job fires relative to its
            # requested time point (scheduler health under load)
            self._m_lag.observe(self._now - heap[0][0])
        # Snapshot the due entries first: a job that re-adds itself for
        # "now" during this sweep waits for the next run() instead of
        # spinning the loop (the reference relies on real time advancing
        # for the same guarantee, scheduler.h:90-95).
        due = []
        while heap and heap[0][0] <= self._now:
            t, _, job = heapq.heappop(heap)
            self._note_popped(job)
            job.time = None
            due.append((t, job))
        try:
            while due:
                _, job = due.pop(0)
                func = job.func
                if func is not None:
                    func()
        finally:
            # If a job raised, the not-yet-run due jobs go back on the
            # heap instead of being silently lost with the local list.
            for t, job in due:
                heapq.heappush(heap, (t, next(self._seq), job))
                if job.cancelled:
                    self._stale += 1
        self._maybe_compact()
        self._m_depth.set(len(self._heap))
        self._m_stale.set(self._stale)
        return self.next_job_time()

    def next_job_time(self) -> float:
        heap = self._heap
        while heap and heap[0][2].cancelled:
            self._note_popped(heap[0][2])
            heapq.heappop(heap)
        return heap[0][0] if heap else TIME_MAX

    # -- time reference ----------------------------------------------------
    def time(self) -> float:
        """The common synchronized time reference (scheduler.h:116)."""
        return self._now

    def sync_time(self) -> float:
        self._now = self._clock()
        return self._now

    def __len__(self) -> int:
        return sum(1 for *_, j in self._heap if not j.cancelled)
