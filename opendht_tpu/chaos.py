"""Adversarial chaos plane: declarative fault plans + one injection seam.

The resilience of the reference lives in its network engine and
routing-table maintenance (request expiry ``request.h:108-112``,
blacklists, bucket refresh), but until this round every harness here
only ever *measured* clean networks: the virtual net topped out at
uniform loss+delay, and the real-UDP clusters ran loss-free loopback.
This module is the missing half of ROADMAP item 5 — the part that
*produces* the adversarial scenarios the round-9/12 observability stack
(SLO verdicts, replica-coverage probe, black-box bundles, cluster
timeline) is already able to judge:

- :class:`FaultPlan` — a declarative script of timed :class:`Phase`\\ s:
  per-link packet loss / duplication / reordering / extra delay
  (:class:`LinkRule`, asymmetric by default), asymmetric partitions
  with healing (:class:`Partition` — a phase ends, the partition
  heals), join/leave storms (:class:`Storm`) and eclipse/sybil-style
  routing-table poisoning (:class:`Poison`).
- :class:`FaultInjector` — the ONE injection seam every harness
  shares.  ``fate(src, dst, now)`` folds the active phases into a
  per-packet :class:`Fate` (drop / duplicate / extra delay) with a
  seeded RNG, so the same plan drives

  * the in-process virtual net (``testing/virtual_net.py`` send path),
  * the real-UDP cluster harness (``testing/network.py
    DhtNetwork.arm`` installs per-engine hooks), and
  * the live engine — ``net/engine.py`` consults an optional
    ``fault_hook`` in its send path, ``None`` by default and guarded
    by ``Config.chaos_enabled``; with no plan armed the send path is
    byte-identical to pre-chaos builds (pinned in tests/test_chaos.py).

- the :class:`Storm` / :class:`Poison` phases additionally parameterize
  the device-resident swarm stepper (``ops/swarm.py``), which advances
  tens of thousands of simulated nodes through the same plan.

Import-light by design (stdlib + the telemetry spine): the plan and
injector run in minimal containers, in the virtual net's discrete-event
loop, and on the live engine's send path without touching jax.

Reference mapping: the reference's adversarial tier is the netns
cluster harness (``python/tools/dht/network.py``,
``virtual_network_builder.py``) — veth pairs + netem qdiscs scripted
from a shell.  A :class:`FaultPlan` is that scripting surface made
declarative and deterministic, and the injector replaces the qdisc.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Tuple

from . import telemetry

__all__ = [
    "LinkRule", "Partition", "Storm", "Poison", "Phase", "FaultPlan",
    "Fate", "FaultInjector", "arm_dht", "arm_engine", "disarm_engine",
]

#: wildcard group matching any endpoint
ANY = "*"

_PASS = None                      # fate sentinel: deliver unchanged


# ============================================================ plan grammar
@dataclass
class LinkRule:
    """Per-link netem: applies to packets src-group → dst-group.

    Asymmetric by default (matches one direction); ``symmetric=True``
    applies the same treatment to the reverse direction too.  ``loss``/
    ``dup``/``reorder`` are per-packet probabilities; a reordered
    packet is held ``reorder_delay`` extra seconds so later packets
    overtake it (delivery is then no longer send-ordered); ``delay`` +
    uniform ``jitter`` add latency to every matched packet."""
    name: str = "link"
    src: str = ANY
    dst: str = ANY
    loss: float = 0.0
    dup: float = 0.0
    reorder: float = 0.0
    reorder_delay: float = 0.05
    delay: float = 0.0
    jitter: float = 0.0
    symmetric: bool = False

    def matches(self, src_group: str, dst_group: str) -> bool:
        fwd = (self.src in (ANY, src_group)
               and self.dst in (ANY, dst_group))
        if fwd or not self.symmetric:
            return fwd
        return (self.src in (ANY, dst_group)
                and self.dst in (ANY, src_group))


@dataclass
class Partition:
    """Directed group-to-group blocks; heals when its phase ends.

    ``block=[("a", "b")]`` drops a→b only (an *asymmetric* partition —
    b still reaches a); ``symmetric=True`` blocks both directions of
    every listed pair."""
    block: List[Tuple[str, str]] = field(default_factory=list)
    symmetric: bool = False

    def blocks(self, src_group: str, dst_group: str) -> bool:
        for a, b in self.block:
            if (src_group, dst_group) == (a, b):
                return True
            if self.symmetric and (src_group, dst_group) == (b, a):
                return True
        return False


@dataclass
class Storm:
    """Join/leave churn rates (per node per tick / per storm step)."""
    leave_rate: float = 0.0
    join_rate: float = 0.0


@dataclass
class Poison:
    """Eclipse/sybil pressure on one victim group: attacker-controlled
    ids flood the victims' buckets from few source addresses.  The
    swarm stepper admits at most the FREE slots per bucket (the routing
    table's full-bucket admission rule, src/routing_table.cpp:204-262);
    the live sybil test drives the same shape through the wire."""
    victim: str = "victim"
    per_bucket: int = 8        # attacker entries attempted per bucket
    source_addrs: int = 2      # distinct source addresses used


@dataclass
class Phase:
    """One timed window of faults, ``[start, start+duration)`` seconds
    from arming.  ``duration=None`` = open-ended."""
    name: str
    start: float = 0.0
    duration: Optional[float] = None
    rules: List[LinkRule] = field(default_factory=list)
    partition: Optional[Partition] = None
    storm: Optional[Storm] = None
    poison: Optional[Poison] = None

    def active(self, rel: float) -> bool:
        if rel < self.start:
            return False
        return self.duration is None or rel < self.start + self.duration


class FaultPlan:
    """An ordered script of :class:`Phase` windows plus the group
    membership the link rules and partitions refer to.

    ``membership`` maps an endpoint key (whatever the harness uses —
    ``(host, port)`` tuples here) to a group name; unmapped endpoints
    are in group ``"*"`` and only match wildcard rules."""

    def __init__(self, phases: List[Phase], *,
                 membership: Optional[Dict[object, str]] = None,
                 seed: int = 1337):
        self.phases = list(phases)
        self.membership: Dict[object, str] = dict(membership or {})
        self.seed = seed

    def group_of(self, key) -> str:
        return self.membership.get(key, ANY)

    def phases_at(self, rel: float) -> List[Phase]:
        return [p for p in self.phases if p.active(rel)]

    def storm_at(self, rel: float) -> Optional[Storm]:
        for p in self.phases_at(rel):
            if p.storm is not None:
                return p.storm
        return None

    def poison_at(self, rel: float) -> Optional[Poison]:
        for p in self.phases_at(rel):
            if p.poison is not None:
                return p.poison
        return None

    def partitions_at(self, rel: float) -> List[Tuple[str, Partition]]:
        return [(p.name, p.partition) for p in self.phases_at(rel)
                if p.partition is not None]

    def end_time(self) -> Optional[float]:
        """Relative time after which no phase is active (None if any
        phase is open-ended)."""
        end = 0.0
        for p in self.phases:
            if p.duration is None:
                return None
            end = max(end, p.start + p.duration)
        return end


# ========================================================== injection seam
class Fate(NamedTuple):
    """Per-packet verdict from the injector."""
    drop: bool = False
    dup: int = 0               # extra copies to send
    delay: float = 0.0         # extra seconds to hold the packet
    rule: Optional[str] = None  # attribution for per-rule accounting

    @property
    def touched(self) -> bool:
        return self.drop or self.dup > 0 or self.delay > 0.0


_PASS_FATE = Fate()


class FaultInjector:
    """The shared per-packet decision engine.

    One injector serves a whole harness: every send path calls
    ``fate(src_key, dst_key, now)`` and applies the verdict.  Seeded
    (``plan.seed``) so a scripted storm replays identically in the
    single-threaded harnesses (virtual net, swarm stepper); on a
    real-UDP cluster, where every node's loop thread shares the one
    injector, ``fate`` is serialized by a lock — counts stay exact,
    but the cross-thread draw interleaving is scheduling-dependent, so
    only the virtual tiers carry the replay guarantee.  Per-rule
    counters (``counts[rule][action]``) split the harness's drop
    accounting, mirrored on the telemetry spine as
    ``dht_chaos_injected_total{action=,rule=}``."""

    def __init__(self, plan: FaultPlan, *, registry=None):
        self.plan = plan
        self.rng = random.Random(plan.seed)
        self.t0: Optional[float] = None
        self.counts: Dict[str, Dict[str, int]] = {}
        self._reg = registry
        self._metric_cache: Dict[tuple, object] = {}
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def arm(self, now: float) -> None:
        self.t0 = now

    def disarm(self) -> None:
        self.t0 = None

    @property
    def armed(self) -> bool:
        return self.t0 is not None

    def rel(self, now: float) -> float:
        return now - (self.t0 or 0.0)

    # -- accounting --------------------------------------------------------
    def _count(self, rule: str, action: str) -> None:
        self.counts.setdefault(rule, {}).setdefault(action, 0)
        self.counts[rule][action] += 1
        m = self._metric_cache.get((rule, action))
        if m is None:
            reg = self._reg or telemetry.get_registry()
            m = reg.counter("dht_chaos_injected_total",
                            action=action, rule=rule)
            self._metric_cache[(rule, action)] = m
        m.inc()

    def dropped_by_rule(self) -> Dict[str, int]:
        return {r: c.get("dropped", 0) for r, c in self.counts.items()
                if c.get("dropped")}

    # -- the verdict -------------------------------------------------------
    def fate(self, src_key, dst_key, now: float) -> Fate:
        """Fold every active phase into one verdict.  Partition blocks
        win outright; link rules then accumulate loss/dup/reorder/delay
        (first matching loss draw drops; delays add).  Serialized: one
        injector is shared by every engine loop thread of a real-UDP
        cluster."""
        if self.t0 is None:
            return _PASS_FATE
        with self._lock:
            if self.t0 is None:          # disarmed while we waited
                return _PASS_FATE
            return self._fate_locked(src_key, dst_key, now)

    def _fate_locked(self, src_key, dst_key, now: float) -> Fate:
        rel = now - self.t0
        sg = self.plan.group_of(src_key)
        dg = self.plan.group_of(dst_key)
        delay = 0.0
        dup = 0
        tag = None
        delay_tag = None       # the rule whose delay/jitter applied
        for phase in self.plan.phases_at(rel):
            if phase.partition is not None \
                    and phase.partition.blocks(sg, dg):
                self._count("partition:%s" % phase.name, "dropped")
                return Fate(drop=True, rule="partition:%s" % phase.name)
            for rule in phase.rules:
                if not rule.matches(sg, dg):
                    continue
                if rule.loss and self.rng.random() < rule.loss:
                    self._count(rule.name, "dropped")
                    return Fate(drop=True, rule=rule.name)
                if rule.delay or rule.jitter:
                    delay += rule.delay + (
                        self.rng.random() * rule.jitter if rule.jitter
                        else 0.0)
                    delay_tag = delay_tag or rule.name
                    tag = tag or rule.name
                if rule.reorder and self.rng.random() < rule.reorder:
                    delay += rule.reorder_delay
                    self._count(rule.name, "reordered")
                    tag = rule.name
                if rule.dup and self.rng.random() < rule.dup:
                    dup += 1
                    self._count(rule.name, "dup")
                    tag = rule.name
        if dup == 0 and delay == 0.0:
            return _PASS_FATE
        # "delayed" attributes only to delay/jitter rules — a
        # reorder-only hold is already counted as "reordered"
        if delay_tag is not None:
            self._count(delay_tag, "delayed")
        return Fate(drop=False, dup=dup, delay=delay, rule=tag)


# ======================================================= live-engine arming
def arm_engine(engine, injector: FaultInjector, src_key) -> None:
    """Install the injector on one :class:`~opendht_tpu.net.engine.
    NetworkEngine`'s send path.  The hook returns True when it consumed
    the packet (drop, or rescheduled with extra delay); duplicates are
    sent inline before the original.  Delayed packets replay through
    the engine's own scheduler, so ordering faults stay on the node's
    loop thread."""
    def send_quiet(data: bytes, addr) -> None:
        # mirror engine._send's contract: a send never raises (the
        # socket may error under flood or close during shutdown while
        # a delayed replay is still queued on the scheduler)
        try:
            engine._send_fn(data, addr)
        except OSError:
            pass

    def hook(data: bytes, addr) -> bool:
        now = engine.scheduler.time()
        fate = injector.fate(src_key, (addr.host, addr.port), now)
        if fate.drop:
            return True
        for _ in range(fate.dup):
            send_quiet(data, addr)
        if fate.delay > 0.0:
            engine.scheduler.add(
                now + fate.delay,
                lambda d=data, a=addr: send_quiet(d, a))
            return True
        return False

    engine.fault_hook = hook


def disarm_engine(engine) -> None:
    engine.fault_hook = None


def arm_dht(dht, injector: FaultInjector, *, src_key=None,
            force: bool = False) -> None:
    """Arm a live node's engine.  Guarded: a production node must opt
    in via ``Config.chaos_enabled`` (off by default — with the hook
    unarmed the send path is byte-identical to pre-chaos builds);
    test harnesses that own their nodes pass ``force=True``.

    ``src_key`` is the node's own endpoint key for group membership
    lookups.  Only the virtual net's Dht objects carry ``bound_addr``;
    a runner-owned live node MUST pass its ``("host", port)`` key
    explicitly or it joins the wildcard group and group-scoped rules
    and partitions silently never match it (a warning is logged)."""
    import logging
    if not force and not getattr(dht.config, "chaos_enabled", False):
        raise RuntimeError(
            "refusing to arm a fault plan on a node without "
            "Config.chaos_enabled (pass force=True from an owning "
            "test harness)")
    key = src_key
    if key is None:
        ba = getattr(dht, "bound_addr", None)
        if ba is not None:
            key = (ba.host, ba.port)
        elif injector.plan.membership:
            logging.getLogger("opendht_tpu.chaos").warning(
                "arm_dht: no src_key and no bound_addr — the node "
                "joins the wildcard group; group-scoped rules and "
                "partitions will not match its egress")
    arm_engine(dht.engine, injector, key)


def disarm_dht(dht) -> None:
    disarm_engine(dht.engine)
