"""Load-aware resharding: the rebalance tick that closes the loop from
the keyspace observatory's imbalance gauge to traffic-weighted shard
boundaries (ISSUE-17; ROADMAP item 2).

The reference DHT balances load structurally — each node owns the XOR
neighborhood around its id (src/dht.cpp searchStep ownership) — so a
hot key only ever burdens the k nodes nearest it.  Our t-sharded table
(parallel/partition.py) splits the sorted id space into uniform ~N/t
row slices, which a Zipf-skewed workload defeats: most wave traffic
lands on one shard.  The observatory already measures exactly this
(``dht_shard_imbalance`` = max/mean of the per-shard loads folded from
its 256-bin histogram); this module acts on it.

One :class:`Resharder` rides the node scheduler (period
``ReshardConfig.period``).  Each tick:

1. reads the current windowed imbalance from the observatory,
2. runs it through the shared sustain latch
   (:func:`health.sustain_latch` — the PR-9 hysteresis rule, with a
   ``recover_ratio`` band so oscillation around the threshold does not
   restart the clock), corroborated against the history ring's frame
   samples over the sustain window (windowed evidence, not instants),
3. when the imbalance has exceeded ``rebalance_threshold`` for a full
   ``sustain`` window AND the ``min_interval`` cooldown since the last
   swap has passed, solves new boundaries from the observatory's load
   histogram (parallel/partition.py ``solve_shard_edges``, blended
   with row counts by ``rebalance_load_weight``) and installs a new
   :class:`ReshardLayout` generation.

Installing a layout is ONE attribute write on the DHT loop thread —
and because the loop is single-threaded, that write lands strictly
between wave launches.  The serving path (core/table.py
``Snapshot._shard_state``) keys its placed-operand cache on
``layout.gen``: the next wave rebuilds the sharded state at the new
boundaries (row movement + per-shard LUT rebuild — never a re-sort),
while waves already in flight keep the operands and perm map their
launch captured (PendingLookup finalize closures), so every lookup
before, during and after the swap is bit-identical to the
single-device engine.

Every skip is reason-labeled (``dht_reshard_skips_total{reason=}``:
below-threshold / hysteresis / cooldown / disabled / error) so the
chaos-smoke proof — a transient burst shorter than the sustain window
causes ZERO swaps — is observable, not inferred.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time as _time
from typing import Callable, NamedTuple, Optional, Tuple

import numpy as np

from . import telemetry, tracing
from .health import sustain_latch

log = logging.getLogger("opendht.reshard")

_IMB_GAUGE = "dht_shard_imbalance"


@dataclasses.dataclass
class ReshardConfig:
    """Knobs for the rebalance tick (``Config.reshard``)."""
    #: master switch — disabled ticks count skips with reason=disabled
    enabled: bool = True
    #: tick period on the node scheduler, seconds (<= 0 never ticks)
    period: float = 5.0
    #: windowed max/mean imbalance that arms the trigger (the same
    #: quantity ``dht_shard_imbalance`` exports)
    rebalance_threshold: float = 2.0
    #: seconds the imbalance must stay above threshold before a swap —
    #: a transient republish burst shorter than this causes zero swaps
    sustain: float = 15.0
    #: cooldown between swaps, seconds (anti-thrash)
    min_interval: float = 60.0
    #: blend of load vs row counts in the boundary solve: 1.0 = pure
    #: equal-traffic, 0.0 = equal-rows.  The default keeps a 10% row
    #: floor so a pathological histogram cannot starve a shard of rows
    #: (and bounds the weighted layout's per-shard capacity).
    rebalance_load_weight: float = 0.9
    #: hysteresis release band for the sustain latch: once armed, the
    #: imbalance must fall below threshold·recover_ratio to reset the
    #: clock (health.py SLO latch idiom)
    recover_ratio: float = 0.8


class ReshardLayout(NamedTuple):
    """One installed boundary generation.  ``bin_loads`` is the 256-bin
    load histogram the solve ran on — the serving path re-derives ROW
    boundaries from it per snapshot (raw row offsets go stale across
    table rebuilds), cached by ``gen``."""
    gen: int
    t: int
    #: interior fractional bin edges (len t-1) — virtual attribution
    #: and post-swap refold
    edges: Tuple[float, ...]
    #: the solver input (np.int64 [256], frozen at swap time)
    bin_loads: np.ndarray
    load_weight: float


class Resharder:
    """The rebalance state machine (see module docstring).

    ``shard_t`` is a zero-arg callable returning the live resolve-mesh
    ``t`` (0/1 = no physical sharding — the layout then drives VIRTUAL
    attribution at the observatory's ``virtual_shards`` split, same
    semantics as its uniform virtual fold).  ``on_swap(layout)`` is
    called inside the swap span with the new layout BEFORE it is
    installed — the Dht hook uses it to eagerly warm the snapshot's
    weighted shard state so the next wave doesn't pay the rebuild.
    """

    def __init__(self, cfg: Optional[ReshardConfig] = None, *,
                 node: str = "",
                 keyspace=None,
                 shard_t: Optional[Callable[[], int]] = None,
                 on_swap: Optional[Callable] = None,
                 clock: Callable[[], float] = _time.monotonic):
        self.cfg = cfg or ReshardConfig()
        self.node = node
        self.keyspace = keyspace
        self.shard_t = shard_t
        self.on_swap = on_swap
        self.clock = clock
        self.history = None               # wired by the runner post-build
        self._lock = threading.Lock()
        self._labels = {"node": node} if node else {}
        self._layout: Optional[ReshardLayout] = None
        self._gen = 0
        self._above_since: Optional[float] = None
        self._last_swap: Optional[float] = None
        self._last_mode = ""
        self._post_imbalance: Optional[float] = None
        self._ticks = 0
        self._swaps = 0
        self._skips: dict = {}
        self._job = None
        self._sched = None

    # ------------------------------------------------------------ wiring
    def attach(self, scheduler) -> None:
        """Arm the periodic tick on the node scheduler (same pattern as
        the observatory/history ticks — jobs serialize with wave
        launches on the DHT loop, which is what makes the swap's
        attribute write 'between waves' by construction)."""
        if not self.cfg.enabled or self.cfg.period <= 0:
            return
        self._sched = scheduler
        self._job = scheduler.add(scheduler.time() + self.cfg.period,
                                  self._tick_job)

    def _tick_job(self) -> None:
        try:
            self.tick()
        finally:
            self._job = self._sched.add(
                self._sched.time() + self.cfg.period, self._tick_job)

    def set_history(self, history) -> None:
        """Late-bind the history ring (the runner builds it AFTER the
        Dht); the sustain check then reads windowed frame evidence in
        addition to its own latch."""
        self.history = history

    # ----------------------------------------------------------- reading
    @property
    def layout(self) -> Optional[ReshardLayout]:
        return self._layout

    def _skip(self, reason: str) -> None:
        with self._lock:
            self._skips[reason] = self._skips.get(reason, 0) + 1
        telemetry.get_registry().counter(
            "dht_reshard_skips_total", reason=reason, **self._labels).inc()

    def _windowed_imbalance(self, now: float) -> Optional[float]:
        """Min imbalance over the history ring's frame samples in the
        sustain window — frames record a gauge only when it CHANGED
        (delta encoding), so an empty scan means 'no counter-evidence'
        (None), not 'balanced'.  A -1 sample (unknown) counts as
        counter-evidence: an unknown instant inside the window breaks
        the sustained-overload claim."""
        h = self.history
        if h is None or not getattr(h, "enabled", False):
            return None
        try:
            frames = h.frames(now - self.cfg.sustain, now)
        except Exception:
            return None
        vals = []
        for f in frames:
            g = f.get("gauges") or {}
            for k, v in g.items():
                if k == _IMB_GAUGE or k.startswith(_IMB_GAUGE + "{"):
                    vals.append(float(v))
        return min(vals) if vals else None

    # -------------------------------------------------------------- tick
    def tick(self) -> dict:
        """One rebalance pass; returns the action taken (for tests and
        the REPL)."""
        reg = telemetry.get_registry()
        reg.counter("dht_reshard_ticks_total", **self._labels).inc()
        with self._lock:
            self._ticks += 1
        now = self.clock()
        if not self.cfg.enabled:
            self._skip("disabled")
            return {"action": "skip", "reason": "disabled"}
        ks = self.keyspace
        imb = ks.imbalance() if ks is not None else None
        thr = float(self.cfg.rebalance_threshold)
        self._above_since = sustain_latch(
            self._above_since, now, imb, thr, float(self.cfg.recover_ratio))
        if imb is None or imb <= thr:
            # includes the hysteresis band: latched but currently under
            # threshold — the clock holds, the trigger doesn't fire
            self._skip("below-threshold")
            return {"action": "skip", "reason": "below-threshold",
                    "imbalance": imb}
        if self._above_since is None \
                or (now - self._above_since) < float(self.cfg.sustain):
            self._skip("hysteresis")
            return {"action": "skip", "reason": "hysteresis",
                    "imbalance": imb,
                    "sustained": (0.0 if self._above_since is None
                                  else now - self._above_since)}
        wmin = self._windowed_imbalance(now)
        if wmin is not None and wmin <= thr:
            # frame evidence contradicts the latch: somewhere inside
            # the window the imbalance dipped below threshold (or went
            # unknown) — not a sustained overload
            self._skip("hysteresis")
            return {"action": "skip", "reason": "hysteresis",
                    "imbalance": imb, "window_min": wmin}
        if self._last_swap is not None \
                and (now - self._last_swap) < float(self.cfg.min_interval):
            self._skip("cooldown")
            return {"action": "skip", "reason": "cooldown",
                    "imbalance": imb}
        return self._swap(now, imb)

    # -------------------------------------------------------------- swap
    def _swap(self, now: float, imb_before: Optional[float]) -> dict:
        from .parallel.partition import solve_shard_edges
        from .keyspace import fold_bins, _imbalance
        cfg = self.cfg
        ks = self.keyspace
        t_phys = 0
        if self.shard_t is not None:
            try:
                t_phys = int(self.shard_t() or 0)
            except Exception:
                t_phys = 0
        virtual = t_phys <= 1
        t = t_phys if not virtual else max(
            2, int(getattr(getattr(ks, "cfg", None), "virtual_shards", 2)))
        loads = (ks.hist_window() if ks is not None
                 else np.zeros(256, np.int64))
        lam = float(cfg.rebalance_load_weight)
        edges = solve_shard_edges(loads, t, load_weight=lam)
        layout = ReshardLayout(
            gen=self._gen + 1, t=t,
            edges=tuple(float(e) for e in edges),
            bin_loads=np.asarray(loads, np.int64), load_weight=lam)
        reg = telemetry.get_registry()
        tr = tracing.get_tracer()
        mode = "virtual" if virtual else "physical"
        try:
            with reg.span("dht_reshard_swap_seconds", **self._labels), \
                    tr.span("reshard_swap", node=self.node,
                            gen=layout.gen, t=t, mode=mode):
                if self.on_swap is not None:
                    info = self.on_swap(layout) or {}
                    mode = info.get("mode", mode)
                # the installation: one attribute write, between waves
                self._layout = layout
                self._gen = layout.gen
        except Exception:
            log.exception("reshard swap failed; keeping layout gen=%d",
                          self._gen)
            self._skip("error")
            return {"action": "skip", "reason": "error"}
        self._last_swap = now
        self._above_since = None          # attribution restarts clean
        self._last_mode = mode
        # post-swap imbalance: the SAME histogram refolded at the new
        # edges — what the gauge will converge to once traffic continues
        post = _imbalance(fold_bins(loads, list(layout.edges)))
        self._post_imbalance = post
        reg.gauge("dht_reshard_post_imbalance", **self._labels).set(
            -1.0 if post is None else post)
        reg.gauge("dht_reshard_gen", **self._labels).set(layout.gen)
        with self._lock:
            self._swaps += 1
        reg.counter("dht_reshard_swaps_total", mode=mode,
                    **self._labels).inc()
        tr.event("reshard_swap", node=self.node, gen=layout.gen, t=t,
                 mode=mode,
                 imbalance_before=(-1.0 if imb_before is None
                                   else round(float(imb_before), 4)),
                 imbalance_after=(-1.0 if post is None
                                  else round(float(post), 4)))
        log.info("reshard swap gen=%d t=%d mode=%s imbalance %.3f -> %s",
                 layout.gen, t, mode,
                 -1.0 if imb_before is None else imb_before,
                 "?" if post is None else "%.3f" % post)
        return {"action": "swap", "gen": layout.gen, "t": t, "mode": mode,
                "imbalance_before": imb_before, "imbalance_after": post}

    # ---------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """JSON-able state — the ``reshard`` REPL command, the scanner
        section and the proxy accessor."""
        with self._lock:
            ticks, swaps = self._ticks, self._swaps
            skips = dict(self._skips)
        lay = self._layout
        now = self.clock()
        return {
            "enabled": bool(self.cfg.enabled),
            "gen": self._gen,
            "mode": self._last_mode,
            "threshold": float(self.cfg.rebalance_threshold),
            "sustain": float(self.cfg.sustain),
            "min_interval": float(self.cfg.min_interval),
            "load_weight": float(self.cfg.rebalance_load_weight),
            "ticks": ticks,
            "swaps": swaps,
            "skips": skips,
            "latched_s": (None if self._above_since is None
                          else round(now - self._above_since, 3)),
            "last_swap_age_s": (None if self._last_swap is None
                                else round(now - self._last_swap, 3)),
            "post_imbalance": self._post_imbalance,
            "layout": (None if lay is None else {
                "gen": lay.gen, "t": lay.t,
                "edges": [round(e, 4) for e in lay.edges],
            }),
        }
