"""Distributed tracing + flight recorder (ISSUE-4 tentpole).

PR 3's metrics answer "how fast is the system"; this module answers
"why did THIS lookup take 150 ms".  Dapper-style (Sigelman et al.,
2010) request-scoped tracing over the multi-hop DHT pipeline:

- :class:`TraceContext` — (trace_id 128b, span_id 64b, flags) minted
  per operation; head-based sampling: the root decides, the flag rides
  the wire, children obey.
- :class:`Tracer` — records finished spans AND structured
  flight-recorder events into ONE bounded ring (``deque(maxlen=N)``,
  oldest evicted, O(1) append).  The ring is the TPU-native analogue of
  the reference's postmortem surfaces (``Dht::dumpTables`` /
  ``getNodesStats``, src/dht.cpp:1424-1444): every node keeps the last
  N request state transitions, timeouts, rate-limit drops, compactions
  and churn swaps, dumpable at any time (``trace``/``dump`` in
  tools/dhtnode.py, ``GET /trace`` on the proxy).
- Wire propagation: the context serializes as ONE optional top-level
  msgpack key (:data:`TRACE_WIRE_KEY`) on query packets —
  ``{"i": 16B trace id, "s": 8B parent span id, "f": flags}``.  Old
  parsers ignore unknown top-level keys (proven by
  tests/test_wire_fuzz.py + tools/compat_check.py), and
  :func:`decode_wire` is strictly bounded: any malformed or hostile
  oversized blob decodes to ``None``, never raises, never echoes.
- Export three ways: ``DhtRunner.get_trace(trace_id)`` (JSON span
  list), :func:`to_chrome_trace` (Chrome trace-event / Perfetto
  ``ph:"X"`` with pid=node, tid=op), and the cross-node assembler in
  testing/trace_assembler.py that reconstructs one lookup's full span
  tree from every cluster node's ring.

Host-side only, like the telemetry spine: spans wrap the SAME
uninstrumented jitted engines (core/search.py records the wave/round
spans from the already-measured envelope elapsed — the compiled
computation is untouched, kernels bit-identical with tracing on,
pinned in tests/test_tracing.py).

Sampling knobs: default always-on (tests, debugging).  Production
paths rate-limit new roots via :meth:`Tracer.set_sample_rate` or the
``OPENDHT_TPU_TRACE_RATE`` env var (roots per second; unsampled ops
cost one contextvar read and emit no wire bytes).  ``Tracer.enabled =
False`` turns every hook into a single attribute check.

Import-light by design (stdlib only) so net/scheduler layers keep
working in minimal containers.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

__all__ = [
    "TRACE_WIRE_KEY", "TraceContext", "Span", "Tracer", "activate",
    "current", "decode_wire", "get_tracer", "run_with", "to_chrome_trace",
]

#: the optional top-level msgpack key carrying the context on queries
TRACE_WIRE_KEY = "tr"

FLAG_SAMPLED = 1

_rng = random.Random()          # ids need uniqueness, not secrecy


def _new_id(bits: int) -> int:
    return _rng.getrandbits(bits) or 1


class TraceContext:
    """Immutable (trace_id, span_id, flags) triple.  ``span_id`` is the
    id of the span that OWNS this context — a child span parents to it."""

    __slots__ = ("trace_id", "span_id", "flags")

    def __init__(self, trace_id: int, span_id: int,
                 flags: int = FLAG_SAMPLED):
        self.trace_id = trace_id
        self.span_id = span_id
        self.flags = flags

    @property
    def sampled(self) -> bool:
        return bool(self.flags & FLAG_SAMPLED)

    @classmethod
    def new_root(cls, sampled: bool = True) -> "TraceContext":
        return cls(_new_id(128), _new_id(64),
                   FLAG_SAMPLED if sampled else 0)

    def child(self) -> "TraceContext":
        """Same trace, fresh span id, flags inherited."""
        return TraceContext(self.trace_id, _new_id(64), self.flags)

    @property
    def trace_hex(self) -> str:
        return "%032x" % self.trace_id

    @property
    def span_hex(self) -> str:
        return "%016x" % self.span_id

    def to_wire(self) -> dict:
        return {"i": self.trace_id.to_bytes(16, "big"),
                "s": self.span_id.to_bytes(8, "big"),
                "f": self.flags & 0xFF}

    def __repr__(self):
        return "TraceContext(%s/%s f=%d)" % (self.trace_hex, self.span_hex,
                                             self.flags)


def decode_wire(obj) -> Optional[TraceContext]:
    """Bounded decode of the wire key — ``None`` on ANYTHING that is not
    exactly the expected shape (wrong type, wrong lengths, hostile
    oversized blobs).  Never raises: the ingress path calls this on
    attacker-controlled bytes."""
    try:
        if not isinstance(obj, dict) or len(obj) > 8:
            return None
        i, s = obj.get("i"), obj.get("s")
        if not isinstance(i, (bytes, bytearray)) or len(i) != 16:
            return None
        if not isinstance(s, (bytes, bytearray)) or len(s) != 8:
            return None
        f = obj.get("f", FLAG_SAMPLED)
        if not isinstance(f, int):
            return None
        tid = int.from_bytes(bytes(i), "big")
        sid = int.from_bytes(bytes(s), "big")
        if not tid or not sid:
            return None
        return TraceContext(tid, sid, f & 0xFF)
    except Exception:
        return None


# ------------------------------------------------------------- ambient ctx
_CURRENT: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("opendht_tpu_trace_ctx", default=None)


def current() -> Optional[TraceContext]:
    """The ambient trace context of this task/thread (or None)."""
    return _CURRENT.get()


def current_trace_hex() -> Optional[str]:
    """The ambient trace id as its canonical 32-hex form, or None —
    the exemplar stamp (round 19): waterfall stage observations made
    under a sampled op link their histogram bucket to a trace the
    round-9 assembler can reconstruct.  One contextvar read + one
    format on the sampled path; a single None-check otherwise."""
    ctx = _CURRENT.get()
    return ctx.trace_hex if ctx is not None else None


class activate:
    """``with tracing.activate(ctx): ...`` — sets the ambient context
    for the block (including to None: a search step must not inherit a
    foreign op's context from whatever ran before it)."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self):
        self._token = _CURRENT.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _CURRENT.reset(self._token)


def run_with(ctx: Optional[TraceContext], fn):
    """Call ``fn()`` under ``ctx`` as the ambient context (no-op wrapper
    when ctx is None — the unsampled fast path adds one ``is None``)."""
    if ctx is None:
        return fn()
    token = _CURRENT.set(ctx)
    try:
        return fn()
    finally:
        _CURRENT.reset(token)


# ------------------------------------------------------------------- spans
class Span:
    """Active recording handle; records into the ring on :meth:`end`.
    Usable as a context manager (activates its context for the block)."""

    __slots__ = ("_tracer", "name", "kind", "ctx", "parent_id", "node",
                 "start", "attrs", "_t0", "_ended", "_token")

    def __init__(self, tracer: "Tracer", name: str, ctx: TraceContext,
                 parent_id: Optional[int], kind: str, node: str,
                 attrs: dict):
        self._tracer = tracer
        self.name = name
        self.kind = kind
        self.ctx = ctx
        self.parent_id = parent_id
        self.node = node
        self.attrs = attrs
        self.start = time.time()
        self._t0 = time.perf_counter()
        self._ended = False
        self._token = None

    def __bool__(self) -> bool:
        return True

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def end(self) -> None:
        if self._ended:
            return
        self._ended = True
        self._tracer._append_span(
            self.name, self.ctx, self.parent_id, self.kind, self.node,
            self.start, time.perf_counter() - self._t0, self.attrs)

    def __enter__(self) -> "Span":
        self._token = _CURRENT.set(self.ctx)
        return self

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        self.end()


class _NoopSpan:
    """Shared do-nothing span: every hook stays unconditional at the
    call site while the disabled/unsampled path costs ~nothing."""

    __slots__ = ()
    ctx = None
    parent_id = None

    def __bool__(self) -> bool:
        return False

    def set(self, **attrs) -> "_NoopSpan":
        return self

    def end(self) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class _RateSampler:
    """Token bucket: admit at most ``per_sec`` new root traces per
    second (burst = one second's budget)."""

    def __init__(self, per_sec: float):
        self.per_sec = float(per_sec)
        self._tokens = self.per_sec           # rate 0 = sample nothing
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def __call__(self) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self._tokens
                               + (now - self._last) * self.per_sec,
                               max(self.per_sec, 1.0))
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


class Tracer:
    """Span recorder + flight recorder over one bounded ring."""

    def __init__(self, capacity: int = 8192, node: str = ""):
        self.capacity = int(capacity)
        self.node = node
        #: master switch: False turns every hook into one attribute read
        self.enabled = True
        # deque(maxlen): bounded memory, oldest-evicted, O(1) append
        # (append is atomic under the GIL; the lock guards snapshots)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._sampler = None            # None = always sample new roots

    # ------------------------------------------------------------ sampling
    def set_sample_rate(self, per_sec: "float | None") -> None:
        """Head-based sampling budget for NEW root traces (child spans
        always follow their parent's flag).  ``None`` = always-on."""
        self._sampler = None if per_sec is None else _RateSampler(per_sec)

    def set_sampler(self, fn) -> None:
        """Custom root sampler: callable returning bool (None resets)."""
        self._sampler = fn

    def _sample_root(self) -> bool:
        s = self._sampler
        return True if s is None else bool(s())

    # ------------------------------------------------------------- spans
    def span(self, name: str, *, parent: Optional[TraceContext] = None,
             kind: str = "internal", node: Optional[str] = None,
             **attrs) -> "Span | _NoopSpan":
        """Open a span.  ``parent=None`` starts a new root (consults the
        head sampler); an unsampled parent or a disabled tracer returns
        the shared no-op span."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is None:
            if not self._sample_root():
                return NOOP_SPAN
            ctx = TraceContext.new_root()
            parent_id = None
        else:
            if not parent.sampled:
                return NOOP_SPAN
            ctx = parent.child()
            parent_id = parent.span_id
        return Span(self, name, ctx, parent_id, kind,
                    node if node is not None else self.node, attrs)

    def record(self, name: str, start: float, dur: float, *,
               parent: Optional[TraceContext] = None,
               kind: str = "internal", node: Optional[str] = None,
               **attrs) -> Optional[TraceContext]:
        """Retro-record a span whose timing is already known (the search
        envelope measures first, records after).  Returns the new span's
        context (for parenting children) or None when not sampled."""
        if not self.enabled:
            return None
        if parent is None:
            if not self._sample_root():
                return None
            ctx = TraceContext.new_root()
            parent_id = None
        else:
            if not parent.sampled:
                return None
            ctx = parent.child()
            parent_id = parent.span_id
        self._append_span(name, ctx, parent_id, kind,
                          node if node is not None else self.node,
                          start, dur, attrs)
        return ctx

    def _append_span(self, name: str, ctx: TraceContext,
                     parent_id: Optional[int], kind: str, node: str,
                     start: float, dur: float, attrs: dict) -> None:
        self._ring.append({
            "seq": next(self._seq),
            "trace_id": ctx.trace_hex,
            "span_id": ctx.span_hex,
            "parent_id": ("%016x" % parent_id) if parent_id else None,
            "name": name,
            "kind": kind,
            "node": node,
            "start": start,
            "dur": max(float(dur), 0.0),
            "attrs": attrs,
        })

    # ---------------------------------------------------- flight recorder
    def event(self, name: str, *, node: Optional[str] = None,
              **attrs) -> None:
        """Record one structured flight-recorder event (request state
        transitions, timeouts, rate-limit drops, compactions, churn
        swaps).  Always-on while the tracer is enabled — events are not
        sampled; the bounded ring is the budget."""
        if not self.enabled:
            return
        self._ring.append({
            "seq": next(self._seq),
            "ev": name,
            "t": time.time(),
            "node": node if node is not None else self.node,
            "attrs": attrs,
        })

    # ------------------------------------------------------------- export
    def records(self) -> List[dict]:
        """Consistent snapshot of the whole ring (spans + events)."""
        with self._lock:
            return list(self._ring)

    def spans(self, trace_id=None) -> List[dict]:
        """Finished spans, optionally filtered to one trace.
        ``trace_id`` accepts an int, a (up to) 32-hex string, or a
        TraceContext.  A MALFORMED id (non-hex, oversized — see
        :func:`_trace_hex`) matches nothing: the caller asked for one
        trace, so a bogus id must return ``[]``, never the whole
        ring."""
        out = [r for r in self.records() if "ev" not in r]
        if trace_id is None:
            return out
        want = _trace_hex(trace_id)
        if want is None:
            return []
        return [r for r in out if r["trace_id"] == want]

    def events(self, limit: Optional[int] = None,
               name: Optional[str] = None) -> List[dict]:
        """Flight events, optionally name-filtered (substring match,
        e.g. ``"health"`` keeps ``health_transition``)."""
        out = [r for r in self.records()
               if "ev" in r and (name is None or name in r["ev"])]
        return out[-limit:] if limit else out

    def dump(self, name: Optional[str] = None) -> dict:
        """The full flight-recorder dump (↔ ``Dht::dumpTables`` as a
        structured artifact): node tag, capacity, every retained span
        and event.  ``name`` filters spans AND events by name
        substring at dump time — a read-side projection only: the ring
        and its eviction order are untouched (ISSUE-9 satellite)."""
        recs = self.records()
        if name is not None:
            recs = [r for r in recs if name in r.get("ev", r.get("name", ""))]
        return {
            "node": self.node,
            "capacity": self.capacity,
            "spans": [r for r in recs if "ev" not in r],
            "events": [r for r in recs if "ev" in r],
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def _trace_hex(trace_id) -> Optional[str]:
    """Normalize a trace id to its canonical 32-hex form; ``None`` for
    anything MALFORMED (non-hex characters, > 32 hex digits, empty) —
    the distinction the proxy's ``GET /trace/<id>`` route needs: a
    bogus id is a 400, a well-formed unknown id is an empty span list
    (ISSUE-10 satellite; the old normalization char-stripped ``0``/
    ``x`` and silently truncated, so both cases looked identical)."""
    if trace_id is None:
        return None
    if isinstance(trace_id, TraceContext):
        return trace_id.trace_hex
    if isinstance(trace_id, int):
        return "%032x" % (trace_id & ((1 << 128) - 1))
    s = str(trace_id).strip().lower()
    if s.startswith("0x"):
        s = s[2:]
    # charset check, NOT int(s, 16): Python's int() accepts digit-group
    # underscores and sign prefixes, so 'a_b'/'+ab'/'-1' would pass as
    # well-formed (review finding)
    if not s or len(s) > 32 or any(c not in "0123456789abcdef" for c in s):
        return None
    return s.rjust(32, "0")


# ------------------------------------------------------ chrome trace export
def to_chrome_trace(records: Optional[Iterable[dict]] = None,
                    tracer: Optional[Tracer] = None) -> dict:
    """Chrome trace-event JSON (Perfetto-loadable): spans as ``ph:"X"``
    complete events with pid = node (one process per DHT node, named
    via ``process_name`` metadata) and tid = op (named via
    ``thread_name``), ``ts``/``dur`` in microseconds; flight-recorder
    events as ``ph:"i"`` instants.  ``json.dump`` the result into a
    ``.json`` and load it in ``ui.perfetto.dev`` / ``chrome://tracing``."""
    if records is None:
        records = (tracer or get_tracer()).records()
    events: List[dict] = []
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}

    def pid_of(node: str) -> int:
        pid = pids.get(node)
        if pid is None:
            pid = pids[node] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": node or "dht-node"}})
        return pid

    def tid_of(pid: int, op: str) -> int:
        key = (pid, op)
        tid = tids.get(key)
        if tid is None:
            tid = tids[key] = sum(1 for k in tids if k[0] == pid) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": op}})
        return tid

    for r in records:
        if "ev" in r:
            events.append({
                "ph": "i", "s": "p", "name": r["ev"],
                "pid": pid_of(r.get("node", "")), "tid": 0,
                "ts": r["t"] * 1e6,
                "args": dict(r.get("attrs", {})),
            })
        else:
            pid = pid_of(r.get("node", ""))
            args: Dict[str, Any] = {
                "trace_id": r["trace_id"], "span_id": r["span_id"],
            }
            if r.get("parent_id"):
                args["parent_id"] = r["parent_id"]
            args.update(r.get("attrs", {}))
            events.append({
                "ph": "X", "name": r["name"],
                "cat": r.get("kind", "internal"),
                "pid": pid, "tid": tid_of(pid, r["name"]),
                "ts": r["start"] * 1e6, "dur": r["dur"] * 1e6,
                "args": args,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# --------------------------------------------------------- global instance
def _default_capacity() -> int:
    try:
        return max(int(os.environ.get("OPENDHT_TPU_TRACE_RING", "8192")), 16)
    except ValueError:
        return 8192


_global_tracer = Tracer(capacity=_default_capacity())
_rate_env = os.environ.get("OPENDHT_TPU_TRACE_RATE", "")
if _rate_env:
    try:
        _global_tracer.set_sample_rate(float(_rate_env))
    except ValueError:
        pass


def get_tracer() -> Tracer:
    """The process-global tracer every layer feeds by default.  A
    multi-node test process shares one ring; spans carry a per-node tag
    so the cross-node assembler groups correctly either way."""
    return _global_tracer
