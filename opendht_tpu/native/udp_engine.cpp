// Native UDP datagram engine.
//
// C++ implementation of the runtime's packet ingress/egress — the role
// the reference's rcv_thread + NetworkEngine ingress guards play
// (reference: src/dhtrunner.cpp:511-608 select loop + bounded queue;
// include/opendht/network_engine.h:424,519-523 global/per-IP rate
// limits; src/network_engine.cpp:361-386 martian filter).
//
// Design: one engine owns a bound UDP socket and a receiver thread that
// timestamps datagrams into a fixed ring buffer.  Python drains the
// ring in batches (one ctypes call for many packets) instead of one
// recvfrom syscall + allocation per packet through the interpreter.
// Rate limiting and martian filtering run natively before a packet ever
// reaches Python.
//
// C ABI only (ctypes).  Addresses cross the ABI as (ipv4 u32, port u16)
// pairs — the engine is v4; a v6 twin can reuse the ring/limiter.

#include <arpa/inet.h>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

constexpr int MAX_PACKET = 1500;

double now_s() {
    return std::chrono::duration<double>(
        std::chrono::steady_clock::now().time_since_epoch()).count();
}

// sliding-window quota (reference: include/opendht/rate_limiter.h:26-48)
struct RateWindow {
    std::vector<double> hits;
    size_t quota;
    double period;
    RateWindow(size_t q = 0, double p = 1.0) : quota(q), period(p) {}
    bool limit(double now) {
        if (quota == 0) return true;           // disabled
        while (!hits.empty() && hits.front() < now - period)
            hits.erase(hits.begin());
        if (hits.size() >= quota) return false;
        hits.push_back(now);
        return true;
    }
};

struct Packet {
    double rx_time;
    uint32_t ip;
    uint16_t port;
    uint16_t len;
    uint8_t data[MAX_PACKET];
};

struct Engine {
    int fd = -1;
    uint16_t bound_port = 0;
    std::thread rcv;
    std::atomic<bool> running{false};

    std::vector<Packet> ring;
    size_t head = 0, tail = 0;                 // ring indices
    std::mutex mtx;
    std::condition_variable cv;                // signalled on enqueue

    RateWindow global_limit;
    std::unordered_map<uint32_t, RateWindow> ip_limits;
    size_t per_ip_quota = 0;
    double last_prune = 0.0;
    bool drop_martian = true;
    bool exempt_loopback = true;

    std::atomic<uint64_t> rx_count{0}, dropped_ring{0}, dropped_rate{0},
        dropped_martian{0}, tx_count{0};
};

bool is_martian_v4(uint32_t ip_host_order, uint16_t port) {
    // (network_engine.cpp:361-386): zero port, 0.0.0.0/8, 224/4 multicast,
    // 127/8 is allowed for localhost operation here (the reference drops
    // it only on non-local builds)
    if (port == 0) return true;
    uint8_t a = ip_host_order >> 24;
    if (a == 0) return true;
    if (a >= 224 && a <= 239) return true;
    return false;
}

void rcv_loop(Engine* e) {
    struct pollfd pfd { e->fd, POLLIN, 0 };
    while (e->running.load(std::memory_order_relaxed)) {
        int r = poll(&pfd, 1, 100);
        if (r <= 0) continue;
        for (;;) {
            sockaddr_in from{};
            socklen_t fl = sizeof(from);
            uint8_t buf[MAX_PACKET];
            ssize_t n = recvfrom(e->fd, buf, sizeof(buf), MSG_DONTWAIT,
                                 (sockaddr*)&from, &fl);
            if (n <= 0) break;
            double now = now_s();
            uint32_t ip = ntohl(from.sin_addr.s_addr);
            uint16_t port = ntohs(from.sin_port);
            if (e->drop_martian && is_martian_v4(ip, port)) {
                e->dropped_martian++;
                continue;
            }
            // loopback traffic is exempt from rate limiting: local
            // clusters legitimately share 127.0.0.1 as the source, and
            // the limits exist for remote floods
            bool loopback = e->exempt_loopback && (ip >> 24) == 127;
            {
                std::lock_guard<std::mutex> lk(e->mtx);
                if (!loopback && !e->global_limit.limit(now)) {
                    e->dropped_rate++;
                    continue;
                }
                if (!loopback && e->per_ip_quota) {
                    // bound the per-IP map: spoofed-source floods must not
                    // grow memory without limit — evict idle windows once
                    // the map gets large, at most once per second (an O(n)
                    // sweep per packet would itself be the DoS)
                    if (e->ip_limits.size() > 4096 &&
                        now - e->last_prune > 1.0) {
                        e->last_prune = now;
                        for (auto it = e->ip_limits.begin();
                             it != e->ip_limits.end();) {
                            auto& w2 = it->second;
                            if (w2.hits.empty() ||
                                w2.hits.back() < now - w2.period)
                                it = e->ip_limits.erase(it);
                            else
                                ++it;
                        }
                    }
                    auto& w = e->ip_limits[ip];
                    if (w.quota == 0) w = RateWindow(e->per_ip_quota, 1.0);
                    if (!w.limit(now)) {
                        e->dropped_rate++;
                        continue;
                    }
                }
                size_t next = (e->head + 1) % e->ring.size();
                if (next == e->tail) {         // ring full → drop oldest
                    e->tail = (e->tail + 1) % e->ring.size();
                    e->dropped_ring++;
                }
                Packet& p = e->ring[e->head];
                p.rx_time = now;
                p.ip = ip;
                p.port = port;
                p.len = (uint16_t)n;
                std::memcpy(p.data, buf, n);
                e->head = next;
            }
            e->cv.notify_all();
            e->rx_count++;
        }
    }
}

} // namespace

extern "C" {

// returns an opaque handle, or null on failure
void* dht_udp_create(uint16_t port, uint32_t ring_size,
                     uint32_t global_rps, uint32_t per_ip_rps,
                     int32_t exempt_loopback) {
    Engine* e = new Engine();
    e->exempt_loopback = exempt_loopback != 0;
    e->fd = socket(AF_INET, SOCK_DGRAM, 0);
    if (e->fd < 0) { delete e; return nullptr; }
    int one = 1;
    setsockopt(e->fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(port);
    if (bind(e->fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
        close(e->fd);
        delete e;
        return nullptr;
    }
    socklen_t alen = sizeof(addr);
    getsockname(e->fd, (sockaddr*)&addr, &alen);
    e->bound_port = ntohs(addr.sin_port);
    e->ring.resize(ring_size ? ring_size : 16384);
    // defaults mirror network_engine.h:424 (1600 global, 200 per-IP rps)
    e->global_limit = RateWindow(global_rps, 1.0);
    e->per_ip_quota = per_ip_rps;
    e->running = true;
    e->rcv = std::thread(rcv_loop, e);
    return e;
}

uint16_t dht_udp_port(void* h) { return ((Engine*)h)->bound_port; }

void dht_udp_destroy(void* h) {
    Engine* e = (Engine*)h;
    e->running = false;
    if (e->rcv.joinable()) e->rcv.join();
    if (e->fd >= 0) close(e->fd);
    delete e;
}

int dht_udp_send(void* h, const uint8_t* data, uint32_t len,
                 uint32_t ip_host_order, uint16_t port) {
    Engine* e = (Engine*)h;
    sockaddr_in to{};
    to.sin_family = AF_INET;
    to.sin_addr.s_addr = htonl(ip_host_order);
    to.sin_port = htons(port);
    ssize_t n = sendto(e->fd, data, len, 0, (sockaddr*)&to, sizeof(to));
    if (n == (ssize_t)len) { e->tx_count++; return 0; }
    return errno ? errno : -1;
}

// Drain up to max_pkts packets.  Layout per packet in out:
//   f64 rx_time | u32 ip | u16 port | u16 len | u8 data[len]
// Returns the number of packets written; out_bytes receives bytes used.
int32_t dht_udp_poll(void* h, uint8_t* out, uint64_t out_cap,
                     int32_t max_pkts, uint64_t* out_bytes) {
    Engine* e = (Engine*)h;
    int32_t count = 0;
    uint64_t off = 0;
    std::lock_guard<std::mutex> lk(e->mtx);
    while (count < max_pkts && e->tail != e->head) {
        Packet& p = e->ring[e->tail];
        uint64_t need = 8 + 4 + 2 + 2 + p.len;
        if (off + need > out_cap) break;
        std::memcpy(out + off, &p.rx_time, 8); off += 8;
        std::memcpy(out + off, &p.ip, 4); off += 4;
        std::memcpy(out + off, &p.port, 2); off += 2;
        std::memcpy(out + off, &p.len, 2); off += 2;
        std::memcpy(out + off, p.data, p.len); off += p.len;
        e->tail = (e->tail + 1) % e->ring.size();
        ++count;
    }
    *out_bytes = off;
    return count;
}

// has packets waiting?
int32_t dht_udp_pending(void* h) {
    Engine* e = (Engine*)h;
    std::lock_guard<std::mutex> lk(e->mtx);
    return e->tail != e->head ? 1 : 0;
}

// Block until a packet is pending or timeout_ms elapses; returns 1 if
// pending.  ctypes releases the GIL around the call, so a Python waiter
// thread can sleep here without starving the interpreter.
int32_t dht_udp_wait(void* h, int32_t timeout_ms) {
    Engine* e = (Engine*)h;
    std::unique_lock<std::mutex> lk(e->mtx);
    if (e->tail != e->head) return 1;
    e->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms));
    return e->tail != e->head ? 1 : 0;
}

void dht_udp_stats(void* h, uint64_t* out6) {
    Engine* e = (Engine*)h;
    out6[0] = e->rx_count.load();
    out6[1] = e->tx_count.load();
    out6[2] = e->dropped_ring.load();
    out6[3] = e->dropped_rate.load();
    out6[4] = e->dropped_martian.load();
    std::lock_guard<std::mutex> lk(e->mtx);
    out6[5] = (e->head + e->ring.size() - e->tail) % e->ring.size();
}

} // extern "C"
