"""In-flight RPC state machine (reference src/request.h).

PENDING → COMPLETED (reply matched by tid) | EXPIRED (3 attempts × 1 s
timed out) | CANCELLED.  ``on_expired(req, done)`` fires once with
done=False after the first re-attempt (early hint used to solicit other
candidates) and once with done=True on final expiry.

Every terminal transition feeds the telemetry spine: completion counts
into ``dht_net_requests_completed_total{type=}`` with the request's RTT
observed into ``dht_net_rtt_seconds{type=}`` (reply_time − start, both
stamped by the engine on scheduler time), expiry into
``dht_net_requests_expired_total{type=}`` (plus the censored-attempt
counter ``dht_net_attempt_timeouts_total{type=}`` — an expired
request's attempts all timed out and never reached the RTT histogram,
so without it loss silently thins the latency surface — ISSUE-19),
cancellation into ``dht_net_requests_cancelled_total{type=}``.  The
matching send-side counters (sent / per-attempt timeouts) live in
:mod:`~opendht_tpu.net.engine`.

Round 23 (ISSUE-19): the engine attaches the per-peer ledger
(:mod:`~opendht_tpu.peers`) to ``ledger`` at send time and stamps
``rto`` with the peer's adaptive retransmit timeout (exactly
``MAX_RESPONSE_TIME`` when the knob is off or the peer has no RTT
samples — the fixed-timeout behaviour pin); terminal transitions
report back so per-peer completed/expired/cancelled counts and the
Jacobson/Karels estimator stay attributed per link.

Distributed tracing (ISSUE-4): a request sent under a sampled trace
context carries the engine-opened per-hop client span in
``trace_span``; the terminal transition stamps the outcome and closes
it, so the span's duration is the full send→reply (or →expiry) life of
the RPC including retries.  Expiry/cancellation additionally drop a
flight-recorder event (the exceptional state transitions; completions
are already the span)."""

from __future__ import annotations

import enum
from typing import TYPE_CHECKING, Callable, Dict, Optional

from .. import telemetry, tracing
from .node import MAX_RESPONSE_TIME, Node

if TYPE_CHECKING:
    from .parsed_message import MessageType, ParsedMessage

MAX_ATTEMPT_COUNT = 3           # request.h:108

_NEVER = float("-inf")

# metric handles cached per (event, message-type): the lifecycle runs
# once per RPC, but a busy node retires thousands of RPCs per second —
# the registry's get-or-create lock stays off that path
_m_cache: Dict[tuple, object] = {}


def _metric(kind: str, name: str, mtype: "MessageType"):
    key = (name, mtype)
    m = _m_cache.get(key)
    if m is None:
        reg = telemetry.get_registry()
        # the wire name ("put"/"get"/...) — matches the type labels the
        # engine's dht_net_messages_total counters use
        label = mtype.value if hasattr(mtype, "value") else str(mtype)
        m = (reg.histogram(name, type=label) if kind == "histogram"
             else reg.counter(name, type=label))
        _m_cache[key] = m
    return m


class RequestState(enum.Enum):
    PENDING = "pending"
    CANCELLED = "cancelled"
    EXPIRED = "expired"
    COMPLETED = "completed"


class Request:
    __slots__ = ("node", "tid", "type", "msg", "on_done", "on_expired",
                 "socket_id", "state", "attempt_count", "start", "last_try",
                 "reply_time", "trace_span", "rto", "ledger")

    def __init__(self, msg_type: "MessageType", tid: int, node: Node,
                 msg: bytes,
                 on_done: Optional[Callable[["Request", "ParsedMessage"], None]],
                 on_expired: Optional[Callable[["Request", bool], None]],
                 socket_id: int = 0, trace_span=None):
        self.node = node
        self.tid = tid
        self.type = msg_type
        self.msg = msg
        self.on_done = on_done
        self.on_expired = on_expired
        self.socket_id = socket_id
        self.state = RequestState.PENDING
        self.attempt_count = 0
        self.start = _NEVER
        self.last_try = _NEVER
        self.reply_time = _NEVER
        self.trace_span = trace_span      # per-hop client span (ISSUE-4)
        # per-attempt retransmit timeout; the engine overwrites it from
        # the peer ledger when Config.peers.adaptive_rto is on, and the
        # ledger default keeps it at the reference's fixed value
        # (ISSUE-19)
        self.rto = MAX_RESPONSE_TIME
        self.ledger = None                # peers.PeerLedger, set at send

    # -- state predicates --------------------------------------------------
    @property
    def pending(self) -> bool:
        return self.state is RequestState.PENDING

    @property
    def completed(self) -> bool:
        return self.state is RequestState.COMPLETED

    @property
    def expired(self) -> bool:
        return self.state is RequestState.EXPIRED

    @property
    def cancelled(self) -> bool:
        return self.state is RequestState.CANCELLED

    @property
    def over(self) -> bool:
        return not self.pending

    def is_expired(self, now: float) -> bool:
        """All attempts used and the last one timed out (request.h:110-112).
        ``>=``, not ``>``: retries are scheduled at exactly
        last_try + rto, and discrete-event drivers land on that
        instant — strict compare would retry dead nodes forever.
        ``rto`` is the per-peer adaptive timeout when enabled and is
        pinned to ``MAX_RESPONSE_TIME`` otherwise (ISSUE-19)."""
        return (self.pending
                and now >= self.last_try + self.rto
                and self.attempt_count >= MAX_ATTEMPT_COUNT)

    # -- transitions (request.h:88-105) ------------------------------------
    def _finish_span(self, outcome: str) -> None:
        sp = self.trace_span
        if sp is not None:
            sp.set(outcome=outcome, attempts=self.attempt_count,
                   tid=self.tid)
            sp.end()
            self.trace_span = None

    def set_expired(self) -> None:
        if self.pending:
            self.state = RequestState.EXPIRED
            _metric("counter", "dht_net_requests_expired_total",
                    self.type).inc()
            # ISSUE-19 satellite: every attempt of an expired request
            # timed out without reaching dht_net_rtt_seconds — count
            # the censored attempts so loss shows up next to RTT
            # instead of silently thinning the histogram (a request
            # expired before any attempt — node.set_expired — still
            # censors one solicited answer)
            _metric("counter", "dht_net_attempt_timeouts_total",
                    self.type).inc(max(self.attempt_count, 1))
            if self.ledger is not None:
                self.ledger.on_request_expired(self)
            tr = tracing.get_tracer()
            if tr.enabled:
                tr.event("request_expired", type=self.type.value,
                         tid=self.tid, attempts=self.attempt_count)
            self._finish_span("expired")
            if self.on_expired:
                self.on_expired(self, True)
            self._clear()

    def set_done(self, msg: "ParsedMessage") -> None:
        if self.pending:
            self.state = RequestState.COMPLETED
            _metric("counter", "dht_net_requests_completed_total",
                    self.type).inc()
            rtt = None
            if self.reply_time != _NEVER and self.start != _NEVER:
                rtt = max(self.reply_time - self.start, 0.0)
                _metric("histogram", "dht_net_rtt_seconds", self.type) \
                    .observe(rtt)
                # ISSUE-15: the same RTT is the waterfall's rpc_wait
                # stage — the network plane of the per-op story (runs
                # concurrent with the device stages, so it is excluded
                # from the per-op sum pin); a hop sent under a sampled
                # trace stamps its bucket with the hop span's trace id
                from .. import waterfall
                wf = waterfall.get_profiler()
                if wf.enabled:
                    sp = self.trace_span
                    wf.observe("rpc_wait", rtt,
                               exemplar=(sp.ctx.trace_hex
                                         if sp is not None else None))
            if self.ledger is not None:
                self.ledger.on_request_completed(self, rtt)
            self._finish_span("completed")
            if self.on_done:
                self.on_done(self, msg)
            self._clear()

    def cancel(self) -> None:
        if self.pending:
            self.state = RequestState.CANCELLED
            _metric("counter", "dht_net_requests_cancelled_total",
                    self.type).inc()
            if self.ledger is not None:
                self.ledger.on_request_cancelled(self)
            tr = tracing.get_tracer()
            if tr.enabled:
                tr.event("request_cancelled", type=self.type.value,
                         tid=self.tid)
            self._finish_span("cancelled")
            self._clear()

    def close_socket(self) -> int:
        sid = self.socket_id
        self.socket_id = 0
        return sid

    def _clear(self) -> None:
        self.on_done = None
        self.on_expired = None
        self.msg = b""
        self.ledger = None

    def state_char(self) -> str:
        return {"pending": "f", "cancelled": "c", "expired": "e",
                "completed": "a"}[self.state.value]
