"""The transport engine (reference src/network_engine.cpp,
include/opendht/network_engine.h).

Serializes each RPC as a msgpack map (key order byte-identical to the
reference), drives the request lifecycle (3 × 1 s retries via the
scheduler), parses and dispatches incoming packets to the nine upward
callbacks, fragments/reassembles oversized values, applies per-IP and
global ingress rate limits, filters martians, blacklists misbehaving
peers, and packs closest-node sets into compact 26 B / 38 B triples.

Transport-agnostic: datagrams leave through an injected
``send_fn(data: bytes, addr: SockAddr) -> int`` (0 on success, errno
otherwise) so the same engine runs over asyncio UDP, the native C++
datagram engine, or a loopback test harness."""

from __future__ import annotations

import socket as _socket
from dataclasses import dataclass, field as _field
from typing import Callable, Dict, List, Optional, Tuple

from .. import telemetry, tracing, waterfall
from ..infohash import InfoHash
from ..rate_limiter import RateLimiter
from ..scheduler import Scheduler
from ..sockaddr import SockAddr
from ..utils import DhtException, WANT4, WANT6, pack_msg, wall_now
from ..core.value import Query, Value, FieldValueIndex
from .node import Node, SocketCb
from .node_cache import NodeCache
from .parsed_message import (
    MessageType, ParsedMessage, REQUEST_TYPES, pack_tid, unpack_tid,
)
from .request import Request

# ---- constants (network_engine.h:424-441, network_engine.cpp:61-62) -------
MAX_REQUESTS_PER_SEC = 1600
SEND_NODES = 8
NODE4_INFO_BUF_LEN = 20 + 4 + 2
NODE6_INFO_BUF_LEN = 20 + 16 + 2
UDP_REPLY_TIME = 15.0
RX_MAX_PACKET_TIME = 10.0
RX_TIMEOUT = 3.0
BLACKLISTED_MAX = 10
MTU = 1280
MAX_PACKET_VALUE_SIZE = 600
AGENT = "RNG1"                      # my_v, network_engine.cpp:54

_FATAL_SEND_ERRNOS = frozenset({
    101,  # ENETUNREACH
    113,  # EHOSTUNREACH
    97,   # EAFNOSUPPORT
    32,   # EPIPE
    1,    # EPERM
})
_EAGAIN = 11


class DhtProtocolException(DhtException):
    """Peer protocol errors (network_engine.h:47-79)."""

    NON_AUTHORITATIVE_INFORMATION = 203   # incomplete request packet
    UNAUTHORIZED = 401                    # wrong token
    NOT_FOUND = 404                       # storage not found
    INVALID_TID_SIZE = 421
    UNKNOWN_TID = 422
    WRONG_NODE_INFO_BUF_LEN = 423

    GET_NO_INFOHASH = "Get_values with no info_hash"
    LISTEN_NO_INFOHASH = "Listen with no info_hash"
    LISTEN_WRONG_TOKEN = "Listen with wrong token"
    PUT_NO_INFOHASH = "Put with no info_hash"
    PUT_WRONG_TOKEN = "Put with wrong token"
    PUT_INVALID_ID = "Put with invalid id"
    STORAGE_NOT_FOUND = "Access operation for unknown storage"

    def __init__(self, code: int, msg: str = "", failing_node_id: InfoHash = None):
        super().__init__(msg)
        self.code = code
        self.msg = msg
        self.failing_node_id = failing_node_id or InfoHash()


@dataclass
class RequestAnswer:
    """What a reply carries back up to the DHT layer
    (network_engine.h:86-97)."""
    ntoken: bytes = b""
    vid: int = 0
    values: List[Value] = _field(default_factory=list)
    refreshed_values: List[int] = _field(default_factory=list)
    expired_values: List[int] = _field(default_factory=list)
    fields: List[FieldValueIndex] = _field(default_factory=list)
    nodes4: List[Node] = _field(default_factory=list)
    nodes6: List[Node] = _field(default_factory=list)

    @classmethod
    def from_msg(cls, msg: ParsedMessage) -> "RequestAnswer":
        return cls(ntoken=msg.token, vid=msg.value_id, values=msg.values,
                   refreshed_values=msg.refreshed_values,
                   expired_values=msg.expired_values, fields=msg.fields,
                   nodes4=msg.nodes4, nodes6=msg.nodes6)


@dataclass
class EngineCallbacks:
    """The nine upward callbacks into the DHT core
    (network_engine.h:123-201)."""
    on_error: Callable[[Request, DhtProtocolException], None] = lambda r, e: None
    on_new_node: Callable[[Node, int], None] = lambda n, c: None
    on_reported_addr: Callable[[InfoHash, SockAddr], None] = lambda i, a: None
    on_ping: Callable[[Node], "RequestAnswer"] = lambda n: RequestAnswer()
    on_find_node: Callable[[Node, InfoHash, int], "RequestAnswer"] = \
        lambda n, t, w: RequestAnswer()
    on_get_values: Callable[[Node, InfoHash, int, Query], "RequestAnswer"] = \
        lambda n, h, w, q: RequestAnswer()
    on_listen: Callable[[Node, InfoHash, bytes, int, Query], "RequestAnswer"] = \
        lambda n, h, t, s, q: RequestAnswer()
    on_announce: Callable[[Node, InfoHash, bytes, List[Value], Optional[float]],
                          "RequestAnswer"] = lambda n, h, t, v, c: RequestAnswer()
    on_refresh: Callable[[Node, InfoHash, bytes, int], "RequestAnswer"] = \
        lambda n, h, t, v: RequestAnswer()


@dataclass
class MessageStats:
    ping: int = 0
    find: int = 0
    get: int = 0
    put: int = 0
    listen: int = 0
    refresh: int = 0

    def as_list(self) -> List[int]:
        return [self.ping, self.find, self.get, self.listen, self.put]


class _PartialMessage:
    __slots__ = ("from_addr", "start", "last_part", "msg")

    def __init__(self, from_addr: SockAddr, now: float, msg: ParsedMessage):
        self.from_addr = from_addr
        self.start = now
        self.last_part = now
        self.msg = msg


def is_martian(addr: SockAddr) -> bool:
    """Addresses no sane peer sends from (network_engine.cpp:361-386)."""
    if addr.port == 0 or addr.ip is None:
        return True
    packed = addr.ip.packed
    if addr.family == _socket.AF_INET:
        return packed[0] == 0 or (packed[0] & 0xE0) == 0xE0
    if addr.family == _socket.AF_INET6:
        return (packed[0] == 0xFF
                or (packed[0] == 0xFE and (packed[1] & 0xC0) == 0x80)
                or packed == bytes(16)
                or packed[:12] == b"\0" * 10 + b"\xff\xff")
    return True


class NetworkEngine:
    def __init__(self, myid: InfoHash, network: int,
                 send_fn: Callable[[bytes, SockAddr], int],
                 scheduler: Scheduler,
                 callbacks: EngineCallbacks,
                 is_client: bool = False,
                 max_req_per_sec: int = MAX_REQUESTS_PER_SEC):
        self.myid = myid
        self.network = network
        self._send_fn = send_fn
        self.scheduler = scheduler
        self.cb = callbacks
        self.is_client = is_client
        self.cache = NodeCache()
        self.requests: Dict[int, Request] = {}       # anonymous-node requests
        self._partials: Dict[int, _PartialMessage] = {}
        self.in_stats = MessageStats()
        self.out_stats = MessageStats()
        self.blacklist: set[SockAddr] = set()
        self.reply_via: Optional[Node] = None   # see deserialize_nodes
        # configurable ingress budget (the reference hardcodes 1600/s
        # global + 200/s per IP, network_engine.h:424,519-523)
        self.max_req_per_sec = max(int(max_req_per_sec), 8)
        self._rate_limiter = RateLimiter(self.max_req_per_sec)
        self._ip_limiters: Dict[tuple, RateLimiter] = {}  # keyed by ip only
        self._limiter_maintenance = 0
        # telemetry: the registry mirrors of the MessageStats island
        # (counters labeled by direction+type) plus the send-side request
        # lifecycle; handles cached — one dict lookup per packet
        reg = telemetry.get_registry()
        self._m_msgs: Dict[tuple, telemetry.Counter] = {
            (d, t): reg.counter("dht_net_messages_total", direction=d, type=t)
            for d in ("in", "out")
            for t in ("ping", "find", "get", "put", "listen", "refresh")}
        self._m_ratelimit_drops = reg.counter("dht_net_ratelimit_drops_total")
        self._m_sent: Dict[object, telemetry.Counter] = {}
        self._m_timeouts = reg.counter("dht_net_request_timeouts_total")
        # distributed tracing (ISSUE-4): client spans on outgoing
        # queries (the wire context is the span's own ctx), server
        # spans around incoming request dispatch, flight-recorder
        # events on drops/timeouts.  One tracer per process; spans are
        # tagged with this engine's node id so multi-node test
        # processes still assemble per-node trees.
        self._tracer = tracing.get_tracer()
        self._node_tag = str(myid)
        # adversarial chaos plane (ISSUE-13): optional per-packet fault
        # hook consulted by _send.  None (the default) leaves the send
        # path byte-identical to pre-chaos builds; armed by
        # opendht_tpu/chaos.py arm_engine under the Config.chaos_enabled
        # guard.  hook(data, addr) -> True means the hook consumed the
        # packet (dropped, or rescheduled with extra delay).
        self.fault_hook: Optional[Callable[[bytes, SockAddr], bool]] = None
        # per-peer network observatory (ISSUE-19): optional
        # peers.PeerLedger attached by runtime.dht.Dht under the
        # Config.peers guard.  None (the default) leaves the request
        # lifecycle byte- and timing-identical to pre-round-23 builds;
        # attached, every request carries the ledger + the peer's
        # adaptive RTO (MAX_RESPONSE_TIME until RTT samples exist).
        self.peers = None

    def _count_msg(self, direction: str, mtype: str) -> None:
        c = self._m_msgs.get((direction, mtype))
        if c is not None:
            c.inc()

    def _count_sent(self, req: Request) -> None:
        c = self._m_sent.get(req.type)
        if c is None:
            c = self._m_sent[req.type] = telemetry.get_registry().counter(
                "dht_net_requests_sent_total", type=req.type.value)
        c.inc()

    # ------------------------------------------------------------------ util
    def _header(self, body_key: str, body: dict, y: str, tid: int,
                query: Optional[str] = None,
                trace: "tracing.TraceContext | None" = None) -> bytes:
        """Assemble the outer packet map in the reference's key order:
        a/r/e, [q], t, y, v, [n], [tr] (network_engine.cpp:677-1305;
        ``tr`` is this port's optional trace-context key — appended
        LAST so every byte before it is unchanged when absent, and old
        parsers skip it as an unknown top-level key)."""
        out: dict = {body_key: body}
        if query is not None:
            out["q"] = query
        if self.is_client:
            # advertise client mode so peers keep us out of routing tables
            # (parsed on rx as 's', parsed_message.h:143-144; the reference
            # reads but never sends it — emitting is forward-compatible)
            out["s"] = True
        out["t"] = pack_tid(tid)
        out["y"] = y
        out["v"] = AGENT
        if self.network:
            out["n"] = self.network
        if trace is not None:
            out[tracing.TRACE_WIRE_KEY] = trace.to_wire()
        return pack_msg(out)

    def _trace_client(self, mtype: str, node: Node):
        """Open the per-RPC client span when an ambient sampled trace
        context is active (the runner op / search step activated it);
        returns ``(span_or_None, wire_ctx_or_None)``.  The span's OWN
        context is what rides the wire, so the receiving node's server
        span parents to this hop."""
        ctx = tracing.current()
        if ctx is None or not ctx.sampled or not self._tracer.enabled:
            return None, None
        span = self._tracer.span("dht.rpc." + mtype, parent=ctx,
                                 kind="client", node=self._node_tag,
                                 peer=str(node.addr))
        return span, span.ctx

    def _send(self, data: bytes, addr: SockAddr) -> int:
        hook = self.fault_hook
        if hook is not None and hook(data, addr):
            return 0
        try:
            return self._send_fn(data, addr) or 0
        except OSError as e:
            return e.errno or 1

    @staticmethod
    def _want_list(want: int) -> list:
        fams = []
        if want & WANT4:
            fams.append(_socket.AF_INET)
        if want & WANT6:
            fams.append(_socket.AF_INET6)
        return fams

    def get_cached_nodes(self, target: InfoHash, family: int, count: int
                         ) -> List[Node]:
        return self.cache.get_cached_nodes(target, family, count)

    def get_node_message_stats(self, incoming: bool) -> List[int]:
        st = self.in_stats if incoming else self.out_stats
        out = st.as_list()
        st.__init__()
        return out

    def connectivity_changed(self, family: int = 0) -> None:
        self.cache.clear_bad_nodes(family)

    def clear(self) -> None:
        for req in self.requests.values():
            req.cancel()
            req.node.set_expired()
        self.requests.clear()

    def blacklist_node(self, node: Node) -> None:
        node.set_expired()
        self.blacklist.add(node.addr)

    def is_blacklisted(self, addr: SockAddr) -> bool:
        return addr in self.blacklist

    # ---------------------------------------------------- request lifecycle
    def _send_request(self, req: Request) -> None:
        """(network_engine.cpp:323-336)"""
        if not req.node.id:
            self.requests[req.tid] = req
        req.start = self.scheduler.time()
        req.node.requested(req)
        self._count_sent(req)
        peers = self.peers
        if peers is not None:
            req.ledger = peers
            req.rto = peers.rto(req.node)
            peers.on_send(req.node, req.type.value, len(req.msg))
        self._request_step(req)

    def _request_step(self, req: Request) -> None:
        """One attempt + retry scheduling (network_engine.cpp:279-321)."""
        if not req.pending:
            return
        now = self.scheduler.time()
        node = req.node
        if req.is_expired(now):
            node.set_expired()
            if not node.id:
                self.requests.pop(req.tid, None)
            # ISSUE-15: an expired RPC is the rpc_wait stage's tail —
            # set_done only sees replies, so without this sample the
            # waterfall's network plane would show nothing but the
            # happy path (the 3.5 s stage budget ≈ full expiry)
            if req.start != float("-inf"):
                wf = waterfall.get_profiler()
                if wf.enabled:
                    sp = req.trace_span
                    wf.observe("rpc_wait", max(0.0, now - req.start),
                               exemplar=(sp.ctx.trace_hex
                                         if sp is not None else None))
            req.set_expired()
            return
        if req.attempt_count == 1 and req.on_expired:
            req.on_expired(req, False)     # early hint: first retry underway

        err = self._send(req.msg, node.addr)
        if err in _FATAL_SEND_ERRNOS:
            node.set_expired()
            if not node.id:
                self.requests.pop(req.tid, None)
        else:
            if err != _EAGAIN:
                if req.attempt_count >= 1:
                    # a real retransmission: the previous attempt timed
                    # out (counting here, not at step entry, so EAGAIN
                    # reschedules of the SAME attempt count once)
                    self._m_timeouts.inc()
                    if req.ledger is not None:
                        # ISSUE-19: per-peer attempt-timeout + resent
                        # bytes, then refresh the RTO for the NEXT
                        # attempt (the estimator may have new samples
                        # from the peer's other in-flight requests)
                        req.ledger.on_retransmit(req)
                        req.rto = req.ledger.rto(node)
                    if self._tracer.enabled:
                        self._tracer.event(
                            "request_timeout", node=self._node_tag,
                            type=req.type.value, tid=req.tid,
                            attempt=req.attempt_count)
                req.attempt_count += 1
            req.last_try = now
            self.scheduler.add(req.last_try + req.rto,
                               lambda: self._request_step(req))

    # -------------------------------------------------------- rate limiting
    def _rate_limit(self, addr: SockAddr) -> bool:
        """(network_engine.cpp:340-359): per-IP (200/s) then global
        (1600/s) sliding windows."""
        now = self.scheduler.time()
        self._limiter_maintenance += 1
        if self._limiter_maintenance == self.max_req_per_sec // 8:
            for key in list(self._ip_limiters):
                if self._ip_limiters[key].maintain(now) == 0:
                    del self._ip_limiters[key]
            self._limiter_maintenance = 0
        key = (addr.family, addr.ip.packed if addr.ip else b"")
        lim = self._ip_limiters.get(key)
        if lim is None:
            lim = self._ip_limiters[key] = RateLimiter(
                self.max_req_per_sec // 8)
        return lim.limit(now) and self._rate_limiter.limit(now)

    # ------------------------------------------------------------ rx path
    def process_message(self, data: bytes, from_addr: SockAddr) -> None:
        """Entry point for every received datagram
        (network_engine.cpp:403-489)."""
        if is_martian(from_addr) or self.is_blacklisted(from_addr):
            return
        try:
            msg = ParsedMessage.from_bytes(data)
        except Exception:
            return
        if msg.network != self.network:
            return
        now = self.scheduler.time()

        if msg.type is MessageType.VALUE_DATA:
            pm = self._partials.get(msg.tid)
            if pm is None or not pm.from_addr.same_ip(from_addr):
                self._rate_limit(from_addr)
                return
            if pm.msg.append(msg):
                pm.last_part = now
                if pm.msg.complete():
                    del self._partials[msg.tid]
                    self._process(pm.msg, from_addr)
                else:
                    self.scheduler.add(
                        now + RX_TIMEOUT,
                        lambda t=msg.tid: self._maintain_rx_buffer(t))
            return

        if msg.id == self.myid or not msg.id:
            return          # self-message
        if msg.type in REQUEST_TYPES and not self._rate_limit(from_addr):
            self._m_ratelimit_drops.inc()
            if self._tracer.enabled:
                self._tracer.event("ratelimit_drop", node=self._node_tag,
                                   type=msg.type.value,
                                   addr=str(from_addr))
            return

        if not msg.value_parts:
            self._process(msg, from_addr, nbytes=len(data))
        elif msg.tid not in self._partials:
            self._partials[msg.tid] = _PartialMessage(from_addr, now, msg)
            self.scheduler.add(now + RX_MAX_PACKET_TIME,
                               lambda t=msg.tid: self._maintain_rx_buffer(t))
            self.scheduler.add(now + RX_TIMEOUT,
                               lambda t=msg.tid: self._maintain_rx_buffer(t))

    def _maintain_rx_buffer(self, tid: int) -> None:
        """Drop stalled partial messages (network_engine.cpp:1293-1305)."""
        pm = self._partials.get(tid)
        if pm is None:
            return
        now = self.scheduler.time()
        if (pm.start + RX_MAX_PACKET_TIME < now
                or pm.last_part + RX_TIMEOUT < now):
            del self._partials[tid]

    def _process(self, msg: ParsedMessage, from_addr: SockAddr,
                 nbytes: int = 0) -> None:
        """Dispatch one complete message (network_engine.cpp:491-633).
        ``nbytes`` is the raw datagram size for per-peer byte
        attribution (0 for reassembled multi-part values — the
        fragments' raw sizes are not retained)."""
        now = self.scheduler.time()
        node = self.cache.get_node(msg.id, from_addr, now, confirm=True,
                                   client=msg.is_client)
        if self.peers is not None:
            self.peers.on_received(node, msg.type.value, nbytes)
        # ISSUE-4: an incoming request carrying a sampled wire context
        # records a server span around the whole handler + reply send,
        # parented to the sender's per-hop client span — that link is
        # what the cross-node assembler stitches trees from.
        tctx = msg.trace_ctx
        span = (self._tracer.span("dht.server." + msg.type.value,
                                  parent=tctx, kind="server",
                                  node=self._node_tag,
                                  peer=str(from_addr))
                if (tctx is not None and tctx.sampled
                    and msg.type in REQUEST_TYPES
                    and self._tracer.enabled)
                else tracing.NOOP_SPAN)
        try:
            with span:
                try:
                    self._dispatch(msg, node, from_addr, now)
                except DhtProtocolException as e:
                    span.set(error=e.code)      # before the span ends
                    raise
        except DhtProtocolException as e:
            if msg.type in REQUEST_TYPES:
                self.send_error(from_addr, msg.tid, e.code, e.msg,
                                include_id=True)

    def _dispatch(self, msg: ParsedMessage, node: Node, from_addr: SockAddr,
                  now: float) -> None:
        if msg.type is MessageType.VALUE_UPDATE:
            rsocket = node.get_socket(msg.tid)
            if rsocket is None:
                raise DhtProtocolException(DhtProtocolException.UNKNOWN_TID,
                                           "Can't find socket", msg.id)
            node.received(now)
            # reply-confirmed nodes are reported unconditionally; the
            # client filter only applies to confirm=1 query paths
            # (network_engine.cpp:496-528,570-572)
            self.cb.on_new_node(node, 2)
            self.deserialize_nodes(msg, from_addr, via=node)
            rsocket.on_receive(node, msg)
            return

        if msg.type in (MessageType.ERROR, MessageType.REPLY):
            rsocket = node.get_socket(msg.tid)
            req = node.get_request(msg.tid)
            if req is None and rsocket is None:
                # maybe an answer to an anonymous (bootstrap) request
                anon = self.requests.get(msg.tid)
                if anon is not None and not anon.node.id:
                    req = anon
                    req.node = node
                    del self.requests[msg.tid]
                else:
                    node.received(now, req)
                    if not node.is_client:
                        self.cb.on_new_node(node, 1)
                    raise DhtProtocolException(
                        DhtProtocolException.UNKNOWN_TID,
                        "Can't find transaction", msg.id)
            node.received(now, req)
            self.cb.on_new_node(node, 2)
            self.cb.on_reported_addr(msg.id, msg.addr)

            if req is not None and req.over:
                return      # response to a dead request

            if msg.type is MessageType.ERROR:
                if (msg.id and req is not None and (
                        (msg.error_code == DhtProtocolException.NOT_FOUND
                         and req.type is MessageType.REFRESH)
                        or (msg.error_code == DhtProtocolException.UNAUTHORIZED
                            and req.type in (MessageType.ANNOUNCE_VALUE,
                                             MessageType.LISTEN)))):
                    req.last_try = float("-inf")
                    req.reply_time = float("-inf")
                    self.cb.on_error(req, DhtProtocolException(msg.error_code))
                return

            if req is not None:
                if req.type in (MessageType.ANNOUNCE_VALUE, MessageType.LISTEN):
                    node.auth_success()
                req.reply_time = now
                self.deserialize_nodes(msg, from_addr, via=node)
                req.set_done(msg)
            else:
                self.deserialize_nodes(msg, from_addr, via=node)
                rsocket.on_receive(node, msg)
            return

        # -------- incoming requests
        node.received(now)
        if not node.is_client:
            self.cb.on_new_node(node, 1)
        if msg.type is MessageType.PING:
            self.in_stats.ping += 1
            self._count_msg("in", "ping")
            self.cb.on_ping(node)
            self.send_pong(from_addr, msg.tid)
        elif msg.type is MessageType.FIND_NODE:
            self.in_stats.find += 1
            self._count_msg("in", "find")
            answer = self.cb.on_find_node(node, msg.target, msg.want)
            n4, n6 = self.buffer_nodes(from_addr.family, msg.target, msg.want,
                                       answer.nodes4, answer.nodes6)
            self.send_nodes_values(from_addr, msg.tid, n4, n6, [], Query(),
                                   answer.ntoken)
        elif msg.type is MessageType.GET_VALUES:
            self.in_stats.get += 1
            self._count_msg("in", "get")
            answer = self.cb.on_get_values(node, msg.info_hash, msg.want,
                                           msg.query)
            n4, n6 = self.buffer_nodes(from_addr.family, msg.info_hash,
                                       msg.want, answer.nodes4, answer.nodes6)
            self.send_nodes_values(from_addr, msg.tid, n4, n6, answer.values,
                                   msg.query, answer.ntoken)
        elif msg.type is MessageType.ANNOUNCE_VALUE:
            self.in_stats.put += 1
            self._count_msg("in", "put")
            self.cb.on_announce(node, msg.info_hash, msg.token, msg.values,
                                msg.created)
            # if the store failed we still confirm, to stop backtracking
            # polluting the DHT (network_engine.cpp:600-607)
            for v in msg.values:
                self.send_value_announced(from_addr, msg.tid, v.id)
        elif msg.type is MessageType.REFRESH:
            self.in_stats.refresh += 1
            self._count_msg("in", "refresh")
            self.cb.on_refresh(node, msg.info_hash, msg.token, msg.value_id)
            self.send_value_announced(from_addr, msg.tid, msg.value_id)
        elif msg.type is MessageType.LISTEN:
            self.in_stats.listen += 1
            self._count_msg("in", "listen")
            self.cb.on_listen(node, msg.info_hash, msg.token, msg.socket_id,
                              msg.query)
            self.send_listen_confirmation(from_addr, msg.tid)

    # ------------------------------------------------- node (de)serialization
    def deserialize_nodes(self, msg: ParsedMessage, from_addr: SockAddr,
                          via: Optional[Node] = None) -> None:
        """Unpack compact n4/n6 blobs into interned Nodes
        (network_engine.cpp:851-887).

        ``via`` (the replying node) is exposed as ``self.reply_via`` for
        the duration of the on_new_node callbacks, so the DHT core can
        attribute discoveries to the reply that carried them (per-search
        hop accounting, live_search.SearchNode.depth).  The engine is
        single-threaded under the scheduler, so a context attribute is
        race-free."""
        if (len(msg.nodes4_raw) % NODE4_INFO_BUF_LEN
                or len(msg.nodes6_raw) % NODE6_INFO_BUF_LEN):
            raise DhtProtocolException(
                DhtProtocolException.WRONG_NODE_INFO_BUF_LEN)
        now = self.scheduler.time()
        self.reply_via = via
        try:
            for raw, step, fam, out in (
                    (msg.nodes4_raw, NODE4_INFO_BUF_LEN, _socket.AF_INET,
                     msg.nodes4),
                    (msg.nodes6_raw, NODE6_INFO_BUF_LEN, _socket.AF_INET6,
                     msg.nodes6)):
                for off in range(0, len(raw), step):
                    ni = raw[off:off + step]
                    ni_id = InfoHash(ni[:20])
                    if ni_id == self.myid:
                        continue
                    addr = SockAddr(ni[20:step - 2],
                                    int.from_bytes(ni[step - 2:step], "big"))
                    if addr.is_loopback() and from_addr.family == fam:
                        # peer told us about a node on its own loopback:
                        # reinterpret relative to the peer's address
                        addr = SockAddr(from_addr.ip, addr.port)
                    if is_martian(addr) or self.is_blacklisted(addr):
                        continue
                    n = self.cache.get_node(ni_id, addr, now, confirm=False)
                    out.append(n)
                    self.cb.on_new_node(n, 0)
        finally:
            self.reply_via = None

    def buffer_nodes(self, family: int, target: InfoHash, want: int,
                     nodes4: List[Node], nodes6: List[Node]
                     ) -> Tuple[bytes, bytes]:
        """Sort by XOR distance to target, truncate to SEND_NODES, pack
        compact (network_engine.cpp:1002-1050)."""
        if want < 0:
            want = WANT4 if family == _socket.AF_INET else WANT6

        def pack(nodes: List[Node]) -> bytes:
            key_sorted = sorted(
                nodes,
                key=lambda n: bytes(target.xor(n.id)))
            return b"".join(
                bytes(n.id) + n.addr.to_compact()
                for n in key_sorted[:SEND_NODES])

        b4 = pack(nodes4) if want & WANT4 else b""
        b6 = pack(nodes6) if want & WANT6 else b""
        return b4, b6

    # ------------------------------------------------------------ tx: queries
    def send_ping(self, node: Node, on_done=None, on_expired=None) -> Request:
        tid = node.get_new_tid()
        span, tctx = self._trace_client("ping", node)
        data = self._header("a", {"id": bytes(self.myid)}, "q", tid,
                            query="ping", trace=tctx)
        req = Request(MessageType.PING, tid, node, data,
                      (lambda r, m: on_done(r, RequestAnswer.from_msg(m)))
                      if on_done else None,
                      on_expired, trace_span=span)
        self._send_request(req)
        self.out_stats.ping += 1
        self._count_msg("out", "ping")
        return req

    def send_find_node(self, node: Node, target: InfoHash, want: int = -1,
                       on_done=None, on_expired=None) -> Request:
        tid = node.get_new_tid()
        body: dict = {"id": bytes(self.myid), "target": bytes(target)}
        if want > 0:
            body["w"] = self._want_list(want)
        span, tctx = self._trace_client("find", node)
        data = self._header("a", body, "q", tid, query="find", trace=tctx)
        req = Request(MessageType.FIND_NODE, tid, node, data,
                      (lambda r, m: on_done(r, RequestAnswer.from_msg(m)))
                      if on_done else None,
                      on_expired, trace_span=span)
        self._send_request(req)
        self.out_stats.find += 1
        self._count_msg("out", "find")
        return req

    def send_get_values(self, node: Node, info_hash: InfoHash, query: Query,
                        want: int = -1, on_done=None, on_expired=None) -> Request:
        tid = node.get_new_tid()
        body: dict = {"id": bytes(self.myid), "h": bytes(info_hash)}
        if not query.where.empty() or not query.select.empty():
            body["q"] = query.wire_obj()
        if want > 0:
            body["w"] = self._want_list(want)
        span, tctx = self._trace_client("get", node)
        data = self._header("a", body, "q", tid, query="get", trace=tctx)
        req = Request(MessageType.GET_VALUES, tid, node, data,
                      (lambda r, m: on_done(r, RequestAnswer.from_msg(m)))
                      if on_done else None,
                      on_expired, trace_span=span)
        self._send_request(req)
        self.out_stats.get += 1
        self._count_msg("out", "get")
        return req

    def send_listen(self, node: Node, info_hash: InfoHash, query: Query,
                    token: bytes, previous: Optional[Request],
                    on_done=None, on_expired=None,
                    socket_cb: Optional[SocketCb] = None) -> Optional[Request]:
        """(network_engine.cpp:1053-1117): reuse the previous contract's
        push socket on refresh, else open a fresh one."""
        if previous is not None and previous.node is node:
            sid = previous.socket_id
        else:
            sid = node.open_socket(socket_cb) if socket_cb else 0
        if not sid:
            return None
        tid = node.get_new_tid()
        body: dict = {"id": bytes(self.myid), "h": bytes(info_hash),
                      "token": token, "sid": pack_tid(sid)}
        if not query.where.empty() or not query.select.empty():
            body["q"] = query.wire_obj()
        span, tctx = self._trace_client("listen", node)
        data = self._header("a", body, "q", tid, query="listen", trace=tctx)
        req = Request(MessageType.LISTEN, tid, node, data,
                      (lambda r, m: on_done(r, RequestAnswer.from_msg(m)))
                      if on_done else None,
                      on_expired, socket_id=sid, trace_span=span)
        self._send_request(req)
        self.out_stats.listen += 1
        self._count_msg("out", "listen")
        return req

    def send_announce_value(self, node: Node, info_hash: InfoHash, value: Value,
                            created: Optional[float], token: bytes,
                            on_done=None, on_expired=None) -> Request:
        tid = node.get_new_tid()
        values_wire, parts = self._pack_values([value])
        body: dict = {"id": bytes(self.myid), "h": bytes(info_hash),
                      "values": values_wire}
        if created is not None and created < wall_now():
            body["c"] = int(created)
        body["token"] = token
        span, tctx = self._trace_client("put", node)
        data = self._header("a", body, "q", tid, query="put", trace=tctx)

        def done(r, m: ParsedMessage):
            if m.value_id != Value.INVALID_ID and on_done:
                on_done(r, RequestAnswer(vid=m.value_id))

        req = Request(MessageType.ANNOUNCE_VALUE, tid, node, data,
                      done if on_done else None, on_expired,
                      trace_span=span)
        self._send_request(req)
        if parts:
            self._send_value_parts(tid, parts, node.addr)
        self.out_stats.put += 1
        self._count_msg("out", "put")
        return req

    def send_refresh_value(self, node: Node, info_hash: InfoHash, vid: int,
                           token: bytes, on_done=None, on_expired=None) -> Request:
        tid = node.get_new_tid()
        body = {"id": bytes(self.myid), "h": bytes(info_hash), "vid": vid,
                "token": token}
        span, tctx = self._trace_client("refresh", node)
        data = self._header("a", body, "q", tid, query="refresh", trace=tctx)

        def done(r, m: ParsedMessage):
            if m.value_id != Value.INVALID_ID and on_done:
                on_done(r, RequestAnswer(vid=m.value_id))

        req = Request(MessageType.REFRESH, tid, node, data,
                      done if on_done else None, on_expired,
                      trace_span=span)
        self._send_request(req)
        self.out_stats.refresh += 1
        self._count_msg("out", "refresh")
        return req

    # ------------------------------------------------------------ tx: replies
    def send_pong(self, addr: SockAddr, tid: int) -> None:
        body = {"id": bytes(self.myid), "sa": addr.ip.packed}
        self._send(self._header("r", body, "r", tid), addr)

    def send_listen_confirmation(self, addr: SockAddr, tid: int) -> None:
        self.send_pong(addr, tid)

    def send_value_announced(self, addr: SockAddr, tid: int, vid: int) -> None:
        body = {"id": bytes(self.myid), "vid": vid, "sa": addr.ip.packed}
        self._send(self._header("r", body, "r", tid), addr)

    def send_nodes_values(self, addr: SockAddr, tid: int, nodes4: bytes,
                          nodes6: bytes, values: List[Value], query: Query,
                          token: bytes) -> None:
        """(network_engine.cpp:944-1000)"""
        body: dict = {"id": bytes(self.myid), "sa": addr.ip.packed}
        if nodes4:
            body["n4"] = nodes4
        if nodes6:
            body["n6"] = nodes6
        if token:
            body["token"] = token
        parts: List[bytes] = []
        if values:
            fields = query.select.get_selection()
            if not fields:
                body["values"], parts = self._pack_values(values)
            else:
                flat: list = []
                for v in values:
                    flat.extend(v.pack_fields(fields))
                body["fields"] = {"f": [int(f) for f in fields], "v": flat}
        self._send(self._header("r", body, "r", tid), addr)
        if parts:
            self._send_value_parts(tid, parts, addr)

    def send_error(self, addr: SockAddr, tid: int, code: int, message: str,
                   include_id: bool = False) -> None:
        out: dict = {"e": [code, message]}
        if include_id:
            out["r"] = {"id": bytes(self.myid)}
        out["t"] = pack_tid(tid)
        out["y"] = "e"
        out["v"] = AGENT
        if self.network:
            out["n"] = self.network
        self._send(pack_msg(out), addr)

    # ------------------------------------------------- listen push channel
    def tell_listener(self, node: Node, socket_id: int, info_hash: InfoHash,
                      want: int, ntoken: bytes, nodes4: List[Node],
                      nodes6: List[Node], values: List[Value],
                      query: Query) -> None:
        """Push changed values over the peer's listen socket
        (network_engine.cpp:173-185)."""
        n4, n6 = self.buffer_nodes(node.family, info_hash, want, nodes4, nodes6)
        self.send_nodes_values(node.addr, socket_id, n4, n6, values, query,
                               ntoken)

    def _tell_listener_ids(self, node: Node, socket_id: int, token: bytes,
                           vids: List[int], key: str) -> None:
        body: dict = {"id": bytes(self.myid)}
        if token:
            body["token"] = token
        if vids:
            body[key] = vids
        # the u-channel packs 't' as a plain msgpack uint — the ONE
        # departure from the bin4 TransId every other message uses
        # (tellListenerRefreshed/Expired pack the Tid integer directly,
        # network_engine.cpp:206,236; both sides' parsers accept both
        # forms, parsed_message.h:29-36, but byte-compat means emitting
        # what the reference emits)
        out: dict = {"u": body, "t": int(socket_id), "y": "r", "v": AGENT}
        if self.network:
            out["n"] = self.network
        self._send(pack_msg(out), node.addr)

    def tell_listener_refreshed(self, node: Node, socket_id: int,
                                info_hash: InfoHash, token: bytes,
                                vids: List[int]) -> None:
        self._tell_listener_ids(node, socket_id, token, vids, "re")

    def tell_listener_expired(self, node: Node, socket_id: int,
                              info_hash: InfoHash, token: bytes,
                              vids: List[int]) -> None:
        self._tell_listener_ids(node, socket_id, token, vids, "exp")

    # ------------------------------------------------------- fragmentation
    def _pack_values(self, values: List[Value]) -> Tuple[list, List[bytes]]:
        """Pack a value set for the 'values' wire array: inline wire
        objects when everything fits one packet, else integer sizes + the
        serialized blobs to stream as parts (network_engine.cpp:889-911)."""
        svals = [v.get_packed() for v in values]
        total = sum(len(b) for b in svals)
        if len(svals) < 50 and total < MAX_PACKET_VALUE_SIZE:
            return [v.wire_obj() for v in values], []
        return [len(b) for b in svals], svals

    def _send_value_parts(self, tid: int, svals: List[bytes],
                          addr: SockAddr) -> None:
        """Stream serialized values as MTU-sized ValueData packets
        (network_engine.cpp:913-941)."""
        for i, blob in enumerate(svals):
            start = 0
            while True:
                end = min(start + MTU, len(blob))
                out: dict = {}
                if self.network:
                    out["n"] = self.network
                out["y"] = "v"
                out["t"] = pack_tid(tid)
                out["p"] = {i: {"o": start, "d": blob[start:end]}}
                self._send(pack_msg(out), addr)
                start = end
                if start >= len(blob):
                    break
