"""Wire-format decoder (reference src/parsed_message.h, src/net.h).

One msgpack map per UDP packet.  Top-level keys:
``y`` "q"/"r"/"e" (query/reply/error), ``p`` (value-part packet),
``t`` transaction id (4B bin or int), ``v`` agent string, ``n`` network
id, ``q`` query verb ∈ {ping, find, get, listen, put, refresh}, ``a``
(query args) / ``r`` (reply body) / ``e`` [code, msg] / ``u`` (value
update body), and the OPTIONAL ``tr`` distributed-trace context
(ISSUE-4; strictly bounded decode, ignored by parsers that predate it
— unknown top-level keys are skipped by construction).  Body keys:
id, h, target, sid, token, vid, values, fields, exp, re, n4, n6, sa,
c, w, q(uery).

Fragmentation: a value too large for one packet is announced as an
integer size in the ``values`` array, then streamed as ``y:"v"``
packets carrying ``p: {index: {o: offset, d: chunk}}``; ``append`` +
``complete`` reassemble (parsed_message.h:87-123)."""

from __future__ import annotations

import enum
import socket as _socket
from typing import Dict, List, Optional, Tuple

from ..infohash import InfoHash
from ..sockaddr import SockAddr
from ..tracing import TRACE_WIRE_KEY, decode_wire
from ..utils import unpack_msg
from ..core.value import MAX_VALUE_SIZE, Field, FieldValueIndex, Query, Value


class MessageType(enum.Enum):
    ERROR = "error"
    REPLY = "reply"
    PING = "ping"
    FIND_NODE = "find"
    GET_VALUES = "get"
    ANNOUNCE_VALUE = "put"
    REFRESH = "refresh"
    LISTEN = "listen"
    VALUE_DATA = "value_data"
    VALUE_UPDATE = "value_update"


_QUERY_TYPES = {
    "ping": MessageType.PING,
    "find": MessageType.FIND_NODE,
    "get": MessageType.GET_VALUES,
    "listen": MessageType.LISTEN,
    "put": MessageType.ANNOUNCE_VALUE,
    "refresh": MessageType.REFRESH,
}

#: request types are rate-limited; replies/errors are not
REQUEST_TYPES = frozenset(_QUERY_TYPES.values())


def unpack_tid(o) -> int:
    """tid arrives as a 4-byte big-endian bin or a plain int
    (parsed_message.h:29-36).  Out-of-range int tids are rejected here
    — a hostile 2^63 tid would otherwise crash the engine later when it
    echoes the tid into a reply header (found by tests/test_wire_fuzz.py)."""
    if isinstance(o, int):
        if not 0 <= o < 1 << 32:
            raise ValueError(f"bad tid value {o}")
        return o
    b = bytes(o)
    if len(b) != 4:
        raise ValueError(f"bad tid length {len(b)}")
    return int.from_bytes(b, "big")


def pack_tid(tid: int) -> bytes:
    return int(tid).to_bytes(4, "big")


class ParsedMessage:
    __slots__ = (
        "type", "id", "network", "is_client", "info_hash", "target", "tid",
        "socket_id", "token", "value_id", "created", "nodes4_raw",
        "nodes6_raw", "nodes4", "nodes6", "values", "refreshed_values",
        "expired_values", "fields", "value_parts", "query", "want",
        "error_code", "ua", "addr", "trace_ctx",
    )

    def __init__(self):
        self.type: Optional[MessageType] = None
        self.id = InfoHash()
        self.network = 0
        self.is_client = False
        self.info_hash = InfoHash()
        self.target = InfoHash()
        self.tid = 0
        self.socket_id = 0
        self.token = b""
        self.value_id = 0
        self.created: Optional[float] = None
        self.nodes4_raw = b""
        self.nodes6_raw = b""
        self.nodes4: list = []          # filled by engine.deserialize_nodes
        self.nodes6: list = []
        self.values: List[Value] = []
        self.refreshed_values: List[int] = []
        self.expired_values: List[int] = []
        self.fields: List[FieldValueIndex] = []
        # index -> [expected_total_or_offset, bytearray]
        self.value_parts: Dict[int, Tuple[int, bytearray]] = {}
        self.query = Query()
        self.want = -1
        self.error_code = 0
        self.ua = ""
        self.addr = SockAddr()
        self.trace_ctx = None           # ISSUE-4: optional wire context

    # -- decoding ----------------------------------------------------------
    @classmethod
    def from_bytes(cls, data: bytes) -> "ParsedMessage":
        return cls.from_obj(unpack_msg(data))

    @classmethod
    def from_obj(cls, msg) -> "ParsedMessage":
        if not isinstance(msg, dict):
            raise ValueError("packet is not a map")
        self = cls()
        y = msg.get("y")
        r = msg.get("r")
        u = msg.get("u")
        e = msg.get("e")
        p = msg.get("p")

        if "t" in msg:
            self.tid = unpack_tid(msg["t"])
        if "v" in msg:
            self.ua = str(msg["v"])
        if "n" in msg:
            self.network = int(msg["n"])
        if "s" in msg:
            self.is_client = bool(msg["s"])
        if TRACE_WIRE_KEY in msg:
            # bounded decode: any malformed / hostile oversized blob is
            # ignored (None), never raised, never echoed — and every
            # OTHER unknown top-level key is skipped by construction
            # (tests/test_wire_fuzz.py proves both directions)
            self.trace_ctx = decode_wire(msg[TRACE_WIRE_KEY])
        q = msg.get("q")

        # type inference (parsed_message.h:153-176)
        if e is not None:
            self.type = MessageType.ERROR
        elif r is not None:
            self.type = MessageType.REPLY
        elif p is not None:
            self.type = MessageType.VALUE_DATA
        elif u is not None:
            self.type = MessageType.VALUE_UPDATE
        elif y is not None and y != "q":
            raise ValueError(f"unknown y: {y!r}")
        elif q in _QUERY_TYPES:
            self.type = _QUERY_TYPES[q]
        else:
            raise ValueError(f"unknown message type (q={q!r})")

        if self.type is MessageType.VALUE_DATA:
            # {index: {o: offset, d: chunk}}
            if not isinstance(p, dict):
                raise ValueError("malformed value-part packet")
            for idx, part in p.items():
                if not isinstance(part, dict) or "o" not in part or "d" not in part:
                    continue
                self.value_parts[int(idx)] = (int(part["o"]),
                                              bytearray(part["d"]))
            return self

        a = msg.get("a")
        if a is None and r is None and e is None and u is None:
            raise ValueError("no message body")
        req = a if a is not None else (r if r is not None else
                                       (u if u is not None else e))

        if e is not None:
            if not isinstance(e, (list, tuple)) or not e:
                raise ValueError("malformed error body")
            self.error_code = int(e[0])
            req = msg.get("r", {})   # optional id map alongside the error

        if not isinstance(req, dict):
            req = {}

        if "sid" in req:
            self.socket_id = unpack_tid(req["sid"])
        if "id" in req:
            self.id = InfoHash(req["id"])
        if "h" in req:
            self.info_hash = InfoHash(req["h"])
        if "target" in req:
            self.target = InfoHash(req["target"])
        if "q" in req:
            self.query = Query.from_wire_obj(req["q"])
        if "token" in req:
            self.token = bytes(req["token"])
        if "vid" in req:
            self.value_id = int(req["vid"])
        if "n4" in req:
            self.nodes4_raw = bytes(req["n4"])
        if "n6" in req:
            self.nodes6_raw = bytes(req["n6"])
        if "sa" in req:
            raw = bytes(req["sa"])
            # address echo carries no port (parsed_message.h:263-281)
            if len(raw) in (4, 16):
                self.addr = SockAddr(raw, 0)
        if "c" in req:
            self.created = float(req["c"])

        if "values" in req:
            vals = req["values"]
            if not isinstance(vals, (list, tuple)):
                raise ValueError("malformed values array")
            for i, packed in enumerate(vals):
                if isinstance(packed, int):
                    # oversized value announced by size; margin for header
                    if packed > MAX_VALUE_SIZE + 32:
                        continue
                    self.value_parts[i] = (packed, bytearray())
                else:
                    try:
                        self.values.append(Value.from_wire_obj(packed))
                    except Exception:
                        pass
        elif "fields" in req:
            raw_fields = req["fields"]
            if not isinstance(raw_fields, dict) or "f" not in raw_fields:
                raise ValueError("malformed fields")
            fset = sorted(Field(f) for f in raw_fields["f"])
            rvalues = raw_fields.get("v")
            if isinstance(rvalues, (list, tuple)) and fset:
                nf = len(fset)
                for i in range(len(rvalues) // nf):
                    try:
                        self.fields.append(FieldValueIndex.unpack_fields(
                            fset, rvalues[i * nf:(i + 1) * nf]))
                    except Exception:
                        pass
        elif "exp" in req:
            self.expired_values = [int(v) for v in req["exp"]]
        elif "re" in req:
            self.refreshed_values = [int(v) for v in req["re"]]

        if "w" in req:
            w = req["w"]
            if not isinstance(w, (list, tuple)):
                raise ValueError("malformed want")
            self.want = 0
            for fam in w:
                if fam == _socket.AF_INET:
                    self.want |= 1      # WANT4
                elif fam == _socket.AF_INET6:
                    self.want |= 2      # WANT6
        else:
            self.want = -1
        return self

    # -- fragment reassembly (parsed_message.h:87-123) ---------------------
    def append(self, block: "ParsedMessage") -> bool:
        """Merge a ValueData block into this header message; True if any
        chunk advanced (in-order only, like the reference)."""
        progressed = False
        for idx, (offset, chunk) in block.value_parts.items():
            slot = self.value_parts.get(idx)
            if slot is None:
                continue
            total, buf = slot
            if len(buf) >= total:
                continue
            if offset != len(buf):
                continue            # out-of-order: dropped, sender retries
            buf.extend(chunk)
            progressed = True
        return progressed

    def complete(self) -> bool:
        """True when all announced parts arrived; decodes them into
        ``values``."""
        for total, buf in self.value_parts.values():
            if len(buf) < total:
                return False
        for _, buf in self.value_parts.values():
            self.values.append(Value.from_packed(bytes(buf)))
        return True
