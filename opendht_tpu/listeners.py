"""Wave-scale listen/push: the device-resident listener table.

Round 24 (ISSUE-20).  Every serving layer learned to batch — lookups
ride ``[Q]`` ingest waves (round 12), hot gets are served from one
XOR-compare probe (round 16) — but listener matching stayed the last
host-side dict probe on the hot path: each ``storage_store`` walked
Python listener records one put at a time, and the proxy pushed one
dispatch per value.  The reference's proxy layer exists almost
entirely to fan values out to subscribers (``DhtProxyServer`` push,
``Dht::storageChanged`` → ``tell_listener``), so at chat/presence/feed
scale (dhtchat with a million idle-but-subscribed users) that probe IS
the serving cost.

This module is the device half of the fix:

- :class:`ListenerTable` — a bounded table of canonical 20-byte key
  ids (uint32 ``[L, 5]`` limbs on device — the operand of
  ``ops/listener_match.py``) tracking exactly the keys that currently
  have ≥1 listener (local API listeners, remote ``(node, sid)``
  sockets — ``runtime/dht.py`` syncs the per-key count on every
  listener mutation).  Slots are append+tombstone+compact, the
  ``ops/sorted_table.py`` churn discipline: a cancelled/expired key
  tombstones its row (``valid=False`` — never matches), and compaction
  re-packs live rows when tombstones pile past the threshold.  Keys
  past capacity overflow to a host-side set (matched by dict, so
  correctness never depends on fitting).
- **Delivery batching** — with ``listen_batching="on"``,
  ``Dht._storage_changed`` buffers each stored put here instead of
  probing listeners synchronously; the next ingest wave (or the flush
  deadline, whichever first) answers membership for the WHOLE buffer
  in ONE ``listener_match`` launch, and the Dht dispatches one
  coalesced callback / ``tell_listener`` / proxy push per wave per
  listener — same values, same per-listener order as the synchronous
  path, just fewer dispatches (pinned result-equivalent in
  tests/test_listener.py + testing/listener_smoke.py).
- **Go-dark on device failure** (the hotcache contract): any exception
  in the match launch disables the table, clears its state, reports
  unknown (-1) gauges — and hands the in-flight buffer back for HOST
  delivery, so a dead device can delay a delivery by one flush but
  never lose one.  ``listen_batching="off"`` is the escape hatch: the
  exact pre-round-24 synchronous path, no table, no launch.

Surfaces: ``dht_listener_*`` occupancy/match/delivery-latency series
on ``get_metrics()``/proxy ``GET /stats``/the history ring, a
``GET /listeners`` proxy route, the ``listeners`` REPL cmd, the
scanner section, ``dhtmon --max-listener-lag`` off the windowed
``dht_listener_lag_p95`` gauge, and the ``listener_match`` cost gate +
``listener_wave_1m`` OPEN bound in perf_budgets.json.

Import-light by design (the keyspace.py rule): stdlib + the telemetry
spine at module scope; the device side (ops.listener_match, and
through it jax) is looked up lazily on first flush, and a failed
backend degrades to synchronous delivery instead of failing the node.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry

log = logging.getLogger("opendht_tpu.listeners")

__all__ = ["ListenerTableConfig", "ListenerTable"]

# local mirrors of ops.ids constants — ops.ids imports jax at module
# top, so importing them here would defeat the lazy-device design;
# _ensure_device() cross-checks against the real module (the
# hotcache.py convention)
HASH_BYTES = 20
N_LIMBS = 5


# ========================================================== configuration
@dataclass
class ListenerTableConfig:
    """Declarative listener-table configuration (lives on
    ``runtime.config.Config.listeners``; the ``listen_batching``
    on/off switch is a top-level Config field, mirroring
    ``ingest_batching``)."""

    #: master switch for the table itself; off = no device table, no
    #: metrics, every delivery synchronous (identical results — the
    #: table only batches dispatches, it never changes what a listener
    #: receives)
    enabled: bool = True
    #: bounded table slots (canonical 20-byte key ids on device);
    #: keys with listeners beyond it overflow to a host-side set, so
    #: capacity bounds device memory, never correctness
    capacity: int = 1024
    #: max seconds a table entry may sit without a listener-count
    #: re-sync before the flush sweep re-checks it against the live
    #: store (remote listeners silently expire NODE_EXPIRE_TIME after
    #: their last refresh — the sweep is how their rows leave the
    #: table without an explicit cancel)
    entry_ttl: float = 600.0
    #: max seconds a buffered stored-put may wait for an ingest wave
    #: before a deadline flush delivers it anyway (idle nodes still
    #: deliver promptly; busy nodes piggyback on the wave cadence)
    flush_deadline: float = 0.01
    #: buffered puts that force an immediate flush (bounds host memory
    #: under a put flood between waves)
    buffer_max: int = 4096
    #: tombstone count that triggers compaction at the next flush
    #: (also compacts when live rows can't otherwise fit — the
    #: sorted_table churn discipline: append+tombstone, re-pack when
    #: the wasted lanes matter)
    compact_min: int = 64


# ============================================================== the table
class ListenerTable:
    """Bounded device key-id table + host delivery buffer (module
    docstring).  One per :class:`~opendht_tpu.runtime.dht.Dht`
    (``dht.listener_table``); standalone construction is the unit-test
    surface — call :meth:`sync_key`/:meth:`note_stored`/:meth:`flush`
    manually."""

    def __init__(self, cfg: Optional[ListenerTableConfig] = None, *,
                 node: str = "", batching: str = "on",
                 live_count: Optional[Callable[[bytes], int]] = None,
                 clock: Optional[Callable[[], float]] = None,
                 request_flush: Optional[Callable[[float], None]] = None):
        """``live_count(key_bytes) -> int`` re-counts a key's live
        listeners at TTL-sweep time (``runtime/dht.py`` wires the
        storage walk); ``request_flush(delay_s)`` asks the owner to
        run :meth:`flush` within ``delay_s`` seconds (the Dht arms a
        scheduler job); ``clock`` defaults to a monotonic host clock
        (nodes pass ``scheduler.time``)."""
        import time as _time
        self.cfg = cfg or ListenerTableConfig()
        self.batching = batching
        self.node = node
        self._labels = {"node": node} if node else {}
        self._live_count = live_count
        self._clock = clock or _time.monotonic
        self._request_flush = request_flush
        self._lock = threading.Lock()
        cap = max(1, int(self.cfg.capacity))
        # host mirror of the device table, maintained incrementally —
        # only a DIRTY table is re-pushed to device, and only at flush
        # (listener churn between flushes costs numpy row writes, not
        # transfers)
        self._ids = np.zeros((cap, N_LIMBS), np.uint32)
        self._valid = np.zeros((cap,), bool)
        self._slot_of: Dict[bytes, int] = {}
        self._expires: Dict[bytes, float] = {}
        self._top = 0                 # first never-used slot
        self._tombstones = 0
        self._overflow: set = set()   # keys past capacity (host-matched)
        self._dirty = True
        # delivery buffer: key -> [(value, new_value)] in arrival
        # order (dict preserves both key and per-key value order — the
        # per-listener ordering guarantee rides on it)
        self._buf: Dict[bytes, List[Tuple[object, bool]]] = {}
        self._buf_t0: Dict[bytes, float] = {}
        # device state (lazy; a failed backend goes dark)
        self._device_ok: "bool | None" = None if self._tracking else False
        self._ids_dev = None
        self._valid_dev = None
        # windowed delivery-lag samples (rolled on the history frame —
        # the dht_listener_lag_p95 gauge reads the LAST window, the
        # dhtmon --max-imbalance lesson applied to delivery latency)
        self._win_lags: List[float] = []
        self._lag_p95: Optional[float] = None
        # metric handles only for an ACTIVE table — a disabled/off
        # component must never register permanently-zero series (the
        # round-14 rule)
        if self._tracking:
            reg = telemetry.get_registry()
            self._m_occ = reg.gauge("dht_listener_occupancy", **self._labels)
            self._m_tomb = reg.gauge("dht_listener_tombstones",
                                     **self._labels)
            self._m_lag = reg.gauge("dht_listener_lag_p95", **self._labels)
            reg.gauge("dht_listener_capacity", **self._labels).set(cap)
            self._m_matches = reg.counter("dht_listener_matches_total",
                                          **self._labels)
            self._m_misses = reg.counter("dht_listener_misses_total",
                                         **self._labels)
            self._m_flushes = reg.counter("dht_listener_flushes_total",
                                          **self._labels)
            self._m_deliv = reg.counter("dht_listener_deliveries_total",
                                        **self._labels)
            self._m_values = reg.counter("dht_listener_values_total",
                                         **self._labels)
            self._m_compact = reg.counter("dht_listener_compactions_total",
                                          **self._labels)
            self._m_match_s = reg.histogram("dht_listener_match_seconds",
                                            **self._labels)
            self._m_deliv_s = reg.histogram("dht_listener_delivery_seconds",
                                            **self._labels)
            self._m_occ.set(0)
            self._m_tomb.set(0)
            self._m_lag.set(-1.0)     # -1 = unknown (no window yet)

    # ------------------------------------------------------------- state
    @property
    def _tracking(self) -> bool:
        """Whether this table participates at all (config-level)."""
        return self.cfg.enabled and self.batching != "off"

    @property
    def enabled(self) -> bool:
        """Config-on AND the device hasn't gone dark — when False,
        ``note_stored`` refuses the buffer and every delivery takes
        the synchronous host path (the escape-hatch semantics)."""
        return self._tracking and self._device_ok is not False

    def pending(self) -> int:
        return len(self._buf)

    def tracked(self) -> int:
        with self._lock:
            return len(self._slot_of) + len(self._overflow)

    # ------------------------------------------------------------- device
    @staticmethod
    def _pack(kb: bytes) -> np.ndarray:
        """Big-endian uint32 limbs for ONE canonical 20-byte key —
        the incremental-row mirror of ``ops.ids.ids_from_bytes``
        (pinned bit-identical in tests/test_listener.py; inlined so a
        listener registration never imports jax)."""
        b = np.frombuffer(kb, dtype=np.uint8).astype(np.uint32)
        b = b.reshape(N_LIMBS, 4)
        return (b[:, 0] << 24) | (b[:, 1] << 16) | (b[:, 2] << 8) | b[:, 3]

    def _ensure_device(self) -> bool:
        if self._device_ok is not None:
            return self._device_ok
        try:
            from .ops import ids as _ids
            from .ops import listener_match as _lm   # noqa: F401
            if (_ids.HASH_BYTES, _ids.N_LIMBS) != (HASH_BYTES, N_LIMBS):
                raise AssertionError(
                    "listener-table constant mirrors drifted from ops.ids")
            self._device_ok = True
        except Exception:
            log.warning("listener match unavailable (no jax backend?); "
                        "batched delivery disabled", exc_info=True)
            self._device_ok = False
        return self._device_ok

    def _go_dark_locked(self) -> None:
        """Device failure mid-match: disable AND clear every row
        (callers hold the lock) — a dead table must report unknown and
        hand delivery back to the host path, never serve a frozen
        membership set (the hotcache go-dark contract)."""
        self._device_ok = False
        self._slot_of.clear()
        self._expires.clear()
        self._overflow.clear()
        self._valid[:] = False
        self._top = 0
        self._tombstones = 0
        self._ids_dev = self._valid_dev = None
        self._win_lags = []
        self._lag_p95 = None
        self._dirty = True
        if self._tracking:
            self._m_occ.set(-1.0)
            self._m_tomb.set(-1.0)
            self._m_lag.set(-1.0)

    # ----------------------------------------------------------- registry
    def sync_key(self, kb: bytes, count: int) -> None:
        """Re-sync one key's listener count after a mutation
        (``runtime/dht.py`` calls this from listen/cancel/remote-add/
        expiry — every site that changes a Storage's listener sets).
        ``count > 0`` ensures the key has a live row (or overflow
        membership) and refreshes its TTL; ``count == 0`` tombstones
        it."""
        if not self.enabled:
            return
        with self._lock:
            if count > 0:
                self._insert_locked(kb)
            else:
                self._remove_locked(kb)
        self._export_gauges()

    def _insert_locked(self, kb: bytes) -> None:
        now = self._clock()
        if kb in self._slot_of:
            self._expires[kb] = now + self.cfg.entry_ttl
            return
        if kb in self._overflow:
            return
        cap = self._ids.shape[0]
        if self._top >= cap and self._tombstones > 0:
            self._compact_locked()
        if self._top < cap:
            slot = self._top
            self._top += 1
            self._ids[slot] = self._pack(kb)
            self._valid[slot] = True
            self._slot_of[kb] = slot
            self._expires[kb] = now + self.cfg.entry_ttl
            self._dirty = True
        else:
            self._overflow.add(kb)

    def _remove_locked(self, kb: bytes) -> None:
        slot = self._slot_of.pop(kb, None)
        self._expires.pop(kb, None)
        if slot is not None:
            self._valid[slot] = False
            self._tombstones += 1
            self._dirty = True
            if self._overflow:
                # a slot freed up (after compaction) — promote an
                # overflow key so capacity pressure self-heals
                self._insert_locked(self._overflow.pop())
        else:
            self._overflow.discard(kb)

    def _compact_locked(self) -> None:
        """Re-pack live rows to the front (the sorted_table churn
        discipline: tombstones accumulate cheaply, one compaction
        amortizes them away).  Slots move; the device copy is rebuilt
        at the next flush."""
        keys = list(self._slot_of)
        self._valid[:] = False
        for i, kb in enumerate(keys):
            self._ids[i] = self._pack(kb)
            self._valid[i] = True
            self._slot_of[kb] = i
        self._top = len(keys)
        self._tombstones = 0
        self._dirty = True
        if self._tracking:
            self._m_compact.inc()

    def _sweep_locked(self) -> None:
        """TTL sweep at flush time: entries past ``entry_ttl`` without
        a re-sync are re-counted against the live store (remote
        listeners expire silently — no cancel reaches sync_key) and
        refreshed or tombstoned; then compaction if tombstones piled
        past the threshold."""
        now = self._clock()
        stale = [kb for kb, t in self._expires.items() if t <= now]
        for kb in stale:
            n = 0
            if self._live_count is not None:
                try:
                    n = int(self._live_count(kb) or 0)
                except Exception:
                    log.exception("listener live-count probe failed")
            if n > 0:
                self._expires[kb] = now + self.cfg.entry_ttl
            else:
                self._remove_locked(kb)
        if self._tombstones > max(int(self.cfg.compact_min),
                                  len(self._slot_of) // 4):
            self._compact_locked()

    # ----------------------------------------------------------- buffering
    def note_stored(self, kb: bytes, value, new_value: bool) -> bool:
        """Buffer one stored put for the next wave's match launch.
        Returns True when buffered (the caller defers delivery) or
        False when the synchronous path must run NOW (batching off,
        table disabled, or gone dark) — the Dht branches on this, so
        go-dark degrades to the exact pre-round-24 behavior."""
        if not self.enabled:
            return False
        if not self._slot_of and not self._overflow:
            # nobody listens on ANY key right now: the synchronous
            # path would walk empty dicts to the same no-delivery end
            # — skip buffer, launch and flush job entirely (an idle
            # table must not tax the put path; the <1% overhead
            # capture rides on this).  Unlocked read is safe: all
            # mutations run on the DHT thread.
            return True
        arm: Optional[float] = None
        with self._lock:
            items = self._buf.get(kb)
            if items is None:
                self._buf[kb] = [(value, new_value)]
                self._buf_t0[kb] = self._clock()
                if len(self._buf) == 1:
                    arm = self.cfg.flush_deadline
            else:
                items.append((value, new_value))
            if len(self._buf) >= max(1, int(self.cfg.buffer_max)):
                arm = 0.0
        if arm is not None and self._request_flush is not None:
            try:
                self._request_flush(arm)
            except Exception:
                log.exception("listener flush arm failed")
        return True

    # -------------------------------------------------------------- flush
    def flush(self) -> List[Tuple[bytes, List[Tuple[object, bool]]]]:
        """Answer membership for the whole buffer in ONE
        ``listener_match`` launch and hand back ``[(key_bytes,
        [(value, new_value), ...]), ...]`` — exactly the puts whose
        key currently has listeners, in arrival order, for the Dht to
        dispatch coalesced.  Any device failure goes dark and returns
        the ENTIRE buffer (host fallback): a delivery can be late,
        never lost."""
        with self._lock:
            if not self._buf:
                return []
            buf, t0s = self._buf, self._buf_t0
            self._buf, self._buf_t0 = {}, {}
            if not self.enabled:
                # dark between buffer and flush: everything falls back
                return list(buf.items())
            self._sweep_locked()
            n_live = len(self._slot_of)
            overflow = set(self._overflow)
        if not self._ensure_device():
            return list(buf.items())
        keys = list(buf)
        if n_live == 0:
            # nobody listens on-table: the launch would answer all-miss
            # — skip it (an idle table must not cost the wave a launch,
            # the hotcache active() rule); overflow still matches host-side
            hit = np.zeros(len(keys), bool)
        else:
            import time as _time
            try:
                import jax.numpy as jnp
                from .ops.ids import ids_from_bytes
                from .ops.listener_match import listener_match
                with self._lock:
                    if self._dirty or self._ids_dev is None:
                        self._ids_dev = jnp.asarray(self._ids)
                        self._valid_dev = jnp.asarray(self._valid)
                        self._dirty = False
                    ids_dev, valid_dev = self._ids_dev, self._valid_dev
                stored = ids_from_bytes(b"".join(keys))
                t_launch = _time.time()
                hit, _slot = listener_match(ids_dev, valid_dev, stored)
                hit = np.asarray(hit)
                self._m_match_s.observe(max(0.0, _time.time() - t_launch))
            except Exception:
                log.exception("listener match failed; going dark "
                              "(synchronous delivery from here on)")
                with self._lock:
                    self._go_dark_locked()
                return list(buf.items())
        self._m_flushes.inc()
        now = self._clock()
        out: List[Tuple[bytes, List[Tuple[object, bool]]]] = []
        hits = misses = 0
        lags: List[float] = []
        for i, kb in enumerate(keys):
            if bool(hit[i]) or kb in overflow:
                out.append((kb, buf[kb]))
                hits += 1
                lags.append(max(0.0, now - t0s.get(kb, now)))
            else:
                misses += 1
        if hits:
            self._m_matches.inc(hits)
            for lag in lags:
                self._m_deliv_s.observe(lag)
            with self._lock:
                self._win_lags.extend(lags)
        if misses:
            self._m_misses.inc(misses)
        self._export_gauges()
        return out

    def note_delivered(self, dispatches: int, values: int) -> None:
        """Post-dispatch accounting from the Dht: ``dispatches``
        coalesced callback/tell_listener/push dispatches fanned
        ``values`` value deliveries this flush."""
        if not self._tracking:
            return
        if dispatches:
            self._m_deliv.inc(dispatches)
        if values:
            self._m_values.inc(values)

    # ---------------------------------------------------------- read side
    def frame_tick(self) -> None:
        """History-ring frame hook: roll the windowed delivery-lag p95
        into the ``dht_listener_lag_p95`` gauge (-1 = no deliveries in
        the window — unknown never violates the dhtmon gate)."""
        if not self._tracking:
            return
        with self._lock:
            lags = self._win_lags
            self._win_lags = []
        if lags and self._device_ok is not False:
            lags.sort()
            self._lag_p95 = lags[min(len(lags) - 1,
                                     int(0.95 * len(lags)))]
        else:
            self._lag_p95 = None
        self._m_lag.set(-1.0 if self._lag_p95 is None else self._lag_p95)

    def lag_p95(self) -> Optional[float]:
        """Last completed window's delivery-lag p95 (None = unknown)."""
        return self._lag_p95 if self.enabled else None

    def _export_gauges(self) -> None:
        if not self._tracking or self._device_ok is False:
            return
        with self._lock:
            occ = len(self._slot_of) + len(self._overflow)
            tomb = self._tombstones
        self._m_occ.set(occ)
        self._m_tomb.set(tomb)

    def snapshot(self) -> dict:
        """JSON-able table state — the proxy ``GET /listeners`` body,
        the ``listeners`` REPL command and the scanner section."""
        if not self.cfg.enabled or self.batching == "off":
            return {"enabled": False, "batching": self.batching}
        with self._lock:
            occ = len(self._slot_of)
            overflow = len(self._overflow)
            tomb = self._tombstones
            buf = len(self._buf)
            now = self._clock()
            entries = [{"key": kb.hex(),
                        "ttl_s": round(self._expires.get(kb, now) - now, 1)}
                       for kb in sorted(
                           self._slot_of,
                           key=lambda k: self._expires.get(k, now))[:32]]
        dark = self._device_ok is False
        return {
            "enabled": bool(self.enabled),
            "batching": self.batching,
            "dark": dark,
            "capacity": int(self.cfg.capacity),
            "occupancy": (-1 if dark else occ),
            "overflow": overflow,
            "tombstones": (-1 if dark else tomb),
            "buffered": buf,
            "entry_ttl_s": self.cfg.entry_ttl,
            "flush_deadline_s": self.cfg.flush_deadline,
            "matches": int(self._m_matches.value),
            "misses": int(self._m_misses.value),
            "flushes": int(self._m_flushes.value),
            "deliveries": int(self._m_deliv.value),
            "values_delivered": int(self._m_values.value),
            "compactions": int(self._m_compact.value),
            "lag_p95_s": self._lag_p95,
            "entries": entries,
        }
