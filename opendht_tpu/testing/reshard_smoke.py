"""End-to-end load-aware resharding smoke (ISSUE-17 CI satellite).

Boots a 3-node real-UDP cluster + REST proxy, floods one hot key so the
keyspace observatory's folded imbalance climbs past the rebalance
threshold, and asserts the closed loop the unit tier cannot:

1. **Hysteresis holds**: while the burst is shorter than the sustain
   window, rebalance ticks fire but ZERO swaps happen — the
   ``dht_reshard_skips_total{reason=hysteresis}`` counter advances and
   ``dhtmon --max-imbalance`` exits 1 on the skewed cluster.
2. **The sustained flood swaps**: once the overload outlives the
   sustain window, exactly the rebalance path runs — ``GET /reshard``
   reports a new layout generation (virtual mode on this unsharded
   cluster), a ``reshard_swap`` event lands in the flight recorder,
   and the ``dht_reshard_*`` series ride the proxy's ``GET /stats``
   exposition.
3. **The imbalance actually drops**: fold attribution follows the new
   traffic-weighted edges, the live ``dht_shard_imbalance`` gauge
   falls back under the gate, and the SAME ``dhtmon --max-imbalance``
   invocation flips 1 -> 0.
4. **Serving is identical across the swap**: every pre-swap get result
   is reproduced post-swap, a fresh put lands, and a listener
   registered BEFORE the swap still delivers a post-swap put.

Run directly (CI does)::

    python -m opendht_tpu.testing.reshard_smoke
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request

from ..core.value import Value
from ..infohash import InfoHash
from ..runtime.config import Config, NodeStatus
from ..runtime.runner import DhtRunner, RunnerConfig
from ..tools import dhtmon

N_NODES = 3
N_COLD = 8
OP_TIMEOUT = 60.0
#: the rebalance threshold doubles as the dhtmon gate: skewed > gate
#: before the swap, refolded < gate after it
GATE = 2.0


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _get_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/%s" % (port, path), timeout=10) as r:
        return json.loads(r.read().decode())


def _vals(values) -> set:
    return set((v.id, bytes(v.data)) for v in values)


def main(argv=None) -> int:
    from ..proxy import DhtProxyServer

    runners = []
    proxy = None
    try:
        for i in range(N_NODES):
            cfg = Config(node_id=InfoHash.get("reshard-smoke-node-%d" % i))
            # fast observatory cadence (keyspace_smoke's rationale: the
            # serialized get_sync stream is slow against the tick, so
            # decay gently and sample every id)
            cfg.keyspace.tick = 0.5
            cfg.keyspace.decay = 0.98
            cfg.keyspace.sample_stride = 1
            cfg.keyspace.min_observed = 24
            if i == 0:
                # fast rebalance ticks; the sustain window starts LONG
                # so the flood's first seconds are provably a transient
                # burst (phase 1), then the smoke shortens it to prove
                # the sustained overload swaps (phase 2)
                cfg.reshard.period = 0.4
                cfg.reshard.rebalance_threshold = GATE
                cfg.reshard.sustain = 3600.0
                cfg.reshard.min_interval = 1.0
            else:
                cfg.reshard.enabled = False
            r = DhtRunner()
            r.run(0, RunnerConfig(dht_config=cfg))
            runners.append(r)
            if i == 0:
                proxy = DhtProxyServer(r, 0)
            else:
                r.bootstrap("127.0.0.1", runners[0].get_bound_port())
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners)), \
            "cluster failed to connect"
        rs = runners[0]._dht.reshard

        hot = InfoHash.get("reshard-smoke-hot")
        cold = [InfoHash.get("reshard-smoke-cold-%d" % i)
                for i in range(N_COLD)]
        assert runners[0].put_sync(hot, Value(b"rh", value_id=99),
                                   timeout=OP_TIMEOUT)
        for i, key in enumerate(cold):
            assert runners[1 + i % (N_NODES - 1)].put_sync(
                key, Value(b"rc-%d" % i, value_id=i + 1),
                timeout=OP_TIMEOUT)

        # pre-swap serving baseline + a listener that must survive the
        # swap (get/put/listen identical across the boundary rebuild)
        pre = {k: _vals(runners[0].get_sync(k, timeout=OP_TIMEOUT))
               for k in [hot] + cold}
        assert pre[hot] == {(99, b"rh")}, pre[hot]
        heard: list = []
        tok = runners[0].listen(cold[0], lambda vals, exp: heard.extend(
            v.id for v in vals if not exp) or True)
        tok.result(OP_TIMEOUT)

        def flood(rounds: int) -> None:
            for _ in range(rounds):
                runners[0].get_sync(hot, timeout=OP_TIMEOUT)
                # yield the DHT loop so the scheduler's observatory/
                # reshard ticks aren't starved by the serialized get
                # stream on a loaded CI box
                time.sleep(0.02)

        # --- phase 1: the flood trips the imbalance but the sustain
        # window (still huge) holds — ticks skip with reason=hysteresis
        # and ZERO swaps happen
        def burst_proven() -> bool:
            snap = _get_json(proxy.port, "reshard")
            ks = _get_json(proxy.port, "keyspace")["shards"]
            return (snap["skips"].get("hysteresis", 0) >= 2
                    and ks["imbalance"] is not None
                    and ks["imbalance"] > GATE)
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline and not burst_proven():
            flood(8)
        assert burst_proven(), \
            "flood never armed the latch: %r / %r" % (
                _get_json(proxy.port, "reshard"),
                _get_json(proxy.port, "keyspace")["shards"])
        snap = _get_json(proxy.port, "reshard")
        assert snap["swaps"] == 0 and snap["gen"] == 0, \
            "transient burst swapped: %r" % (snap,)
        rc = dhtmon.main(["--nodes", "127.0.0.1:%d" % proxy.port,
                          "--max-imbalance", "%g" % GATE])
        assert rc == 1, "dhtmon missed the pre-swap skew (rc=%d)" % rc

        # --- phase 2: the overload is now SUSTAINED — shorten the
        # window (the latch has been armed since phase 1) and a tick
        # landing a sustain-width past an above-threshold tick swaps.
        # 0.8 s keeps the latch mechanism in play while tolerating a
        # loaded box where ticks starve seconds apart and a stall can
        # reset the latch mid-phase (the flood re-arms it).
        rs.cfg.sustain = 0.8

        def swapped() -> bool:
            return _get_json(proxy.port, "reshard")["swaps"] >= 1
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline and not swapped():
            flood(4)
        snap = _get_json(proxy.port, "reshard")
        assert swapped(), "sustained flood never swapped: %r / %r" % (
            snap, _get_json(proxy.port, "keyspace")["shards"])
        assert snap["gen"] >= 1 and snap["mode"] == "virtual", snap
        lay = snap["layout"]
        assert lay["t"] >= 2 and len(lay["edges"]) == lay["t"] - 1
        assert all(a <= b for a, b in zip(lay["edges"], lay["edges"][1:]))
        # the refold of the swap-time histogram at the solved edges is
        # balanced — the number the gauge converges to
        assert snap["post_imbalance"] is not None \
            and snap["post_imbalance"] < GATE, snap
        fr = runners[0].get_flight_recorder(name="reshard_swap")
        assert any(e["attrs"].get("gen") == snap["gen"]
                   for e in fr["events"]), \
            "no reshard_swap flight event: %r" % (fr["events"],)

        # --- phase 3: fold attribution follows the new edges — the
        # LIVE gauge drops under the gate and dhtmon flips to 0
        def rebalanced() -> bool:
            imb = _get_json(proxy.port, "keyspace")["shards"]["imbalance"]
            return imb is not None and imb < GATE
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline and not rebalanced():
            flood(4)
        ks = _get_json(proxy.port, "keyspace")["shards"]
        assert rebalanced(), \
            "imbalance never dropped after the swap: %r" % (ks,)
        assert ks["t"] == lay["t"] and ks["virtual"] is True, ks
        rc = dhtmon.main(["--nodes", "127.0.0.1:%d" % proxy.port,
                          "--max-imbalance", "%g" % GATE])
        assert rc == 0, \
            "dhtmon still red after the rebalance (rc=%d): %r" % (rc, ks)

        # the dht_reshard_* series ride the Prometheus exposition
        node0 = str(runners[0].get_node_id())
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/stats" % proxy.port, timeout=10) as r:
            text = r.read().decode()
        for series in ("dht_reshard_swaps_total", "dht_reshard_gen",
                       "dht_reshard_post_imbalance",
                       "dht_reshard_skips_total"):
            assert any(ln.startswith(series) and node0 in ln
                       for ln in text.splitlines()), \
                "%s missing from /stats" % series

        # --- phase 4: serving identity across the swap — every
        # pre-swap get reproduces, a fresh put lands, the pre-swap
        # listener delivers a post-swap put
        for k in [hot] + cold:
            got = _vals(runners[0].get_sync(k, timeout=OP_TIMEOUT))
            assert got == pre[k], (str(k), got, pre[k])
        assert runners[1].put_sync(cold[0], Value(b"rc-post", value_id=77),
                                   timeout=OP_TIMEOUT)
        assert _wait(lambda: 77 in heard, timeout=20.0), \
            "pre-swap listener never saw the post-swap put: %r" % (heard,)
        want = pre[cold[0]] | {(77, b"rc-post")}
        assert _wait(lambda: _vals(runners[0].get_sync(
            cold[0], timeout=OP_TIMEOUT)) == want, timeout=20.0), \
            "post-swap put not visible on get"
        runners[0].cancel_listen(cold[0], tok)

        print("reshard_smoke: OK — burst held (%d hysteresis skips, 0 "
              "swaps, dhtmon 1), sustained flood swapped gen=%d t=%d "
              "(post refold %.2f), live imbalance %.2f < gate %.1f -> "
              "dhtmon 0, get/put/listen identical across the swap"
              % (snap["skips"].get("hysteresis", 0), snap["gen"],
                 lay["t"], snap["post_imbalance"],
                 ks["imbalance"], GATE))
        return 0
    finally:
        if proxy is not None:
            proxy.stop()
        for r in runners:
            r.join()


if __name__ == "__main__":
    sys.exit(main())
