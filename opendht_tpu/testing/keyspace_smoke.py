"""End-to-end keyspace-observatory smoke (ISSUE-10 CI satellite).

Boots a 3-node real-UDP cluster + REST proxy and asserts the four
things the unit tier cannot:

1. **The hot key is detected on live traffic**: Zipf-skewed gets driven
   through the wave builder (the hottest key carries ~25% of the
   stream, the tail is uniform) surface the hot key at the top of the
   ``GET /keyspace`` heavy-hitter list with ``hot: true``, and a
   ``hot_key_emerged`` event lands in the flight recorder.
2. **The imbalance gauge exports**: ``dht_shard_imbalance`` appears in
   the proxy's ``GET /stats`` Prometheus exposition with a real
   (non-unknown) value once the window has traffic.
3. **dhtmon gates green on balanced-enough traffic**:
   ``--max-imbalance`` exits 0 while the Zipf mix keeps the folded
   per-shard loads inside the gate.  The gate is set ABOVE the
   measured mixed-phase imbalance (which includes honest maintenance
   traffic concentrated near the node's own id — bucket-refresh
   targets are real keyspace load, not noise to filter) and well
   below the single-key-flood ceiling, so the check is robust to
   timing-dependent traffic composition.
4. **A single-key flood trips the gate**: gets on ONLY the hot key
   concentrate the window into one histogram bin; after the decay
   ticks wash out the mixed phase, the same ``dhtmon
   --max-imbalance`` invocation exits 1.

Run directly (CI does)::

    python -m opendht_tpu.testing.keyspace_smoke
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request

from ..core.value import Value
from ..infohash import InfoHash
from ..runtime.config import Config, NodeStatus
from ..runtime.runner import DhtRunner, RunnerConfig
from ..tools import dhtmon

N_NODES = 3
N_COLD = 24
OP_TIMEOUT = 30.0
#: gate margin over the measured mixed-phase imbalance; the flood must
#: clear gate + margin so both dhtmon verdicts have headroom
GATE_MARGIN = 0.75


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _keyspace(port: int) -> dict:
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/keyspace" % port, timeout=10) as r:
        return json.loads(r.read().decode())


def main(argv=None) -> int:
    from ..proxy import DhtProxyServer

    runners = []
    proxy = None
    try:
        for i in range(N_NODES):
            cfg = Config(node_id=InfoHash.get("keyspace-smoke-node-%d" % i))
            # fast observatory cadence so the smoke converges in
            # seconds; gentle decay so a drive phase survives until
            # its read; stride 1 = every observed id is a candidate
            cfg.keyspace.tick = 0.5
            # phase 1-3 run near-cumulative (the serialized get_sync
            # stream is slow against the tick cadence — a fast decay
            # would make the window a noisy tail of the last round);
            # the flood phase flips node 0 to a fast decay so the
            # mixed residue washes out in a few ticks
            cfg.keyspace.decay = 0.98
            cfg.keyspace.sample_stride = 1
            cfg.keyspace.hot_min_count = 16
            # the smoke's serialized get_sync stream is slow against
            # the fast decay cadence; two dozen windowed ids is plenty
            # of evidence at this scale
            cfg.keyspace.min_observed = 24
            r = DhtRunner()
            r.run(0, RunnerConfig(dht_config=cfg))
            runners.append(r)
            if i == 0:
                proxy = DhtProxyServer(r, 0)
            else:
                r.bootstrap("127.0.0.1", runners[0].get_bound_port())
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners)), \
            "cluster failed to connect"

        hot = InfoHash.get("keyspace-smoke-hot")
        # cold keys chosen (deterministically) to spread EXACTLY 3 per
        # 8-way virtual shard — hashed names clump (the first candidate
        # set put 8 of 24 cold keys in the hot key's shard), and the
        # mixed phase's imbalance must sit well below the gate so only
        # the flood trips it
        cold = []
        per_shard: dict = {}
        i = 0
        while len(cold) < N_COLD:
            k = InfoHash.get("keyspace-smoke-cold-%d" % i)
            i += 1
            s = bytes(k)[0] * 8 // 256
            if per_shard.get(s, 0) < N_COLD // 8:
                per_shard[s] = per_shard.get(s, 0) + 1
                cold.append(k)
        for i, key in enumerate(cold):
            assert runners[1 + i % (N_NODES - 1)].put_sync(
                key, Value(b"kc-%d" % i, value_id=i + 1),
                timeout=OP_TIMEOUT)
        assert runners[0].put_sync(hot, Value(b"kh", value_id=99),
                                   timeout=OP_TIMEOUT)

        # --- phase 1: Zipf-skewed mix through node 0's wave builder —
        # per round, 8 hot gets INTERLEAVED with every cold key once
        # (~25% hot share; interleaving keeps the window's composition
        # stable whenever a tick samples it)
        def drive_mixed(rounds: int) -> None:
            for _ in range(rounds):
                for i, key in enumerate(cold):
                    if i % 3 == 0:
                        runners[0].get_sync(hot, timeout=OP_TIMEOUT)
                    runners[0].get_sync(key, timeout=OP_TIMEOUT)

        drive_mixed(3)

        # --- 1: the hot key surfaces in GET /keyspace as HOT
        def hot_detected() -> bool:
            try:
                doc = _keyspace(proxy.port)
            except Exception:
                return False
            return hot.hex() in doc.get("hot_keys", [])
        # keep a trickle flowing so decay doesn't wash the window out
        # while we wait for a tick to publish
        for _ in range(20):
            if hot_detected():
                break
            drive_mixed(1)
        doc = _keyspace(proxy.port)
        assert hot.hex() in doc["hot_keys"], \
            "hot key not detected: %r" % (doc["top"],)
        top0 = doc["top"][0]
        assert top0["key"] == hot.hex() and top0["hot"], doc["top"]
        fr = runners[0].get_flight_recorder(name="hot_key_emerged")
        assert any(e["attrs"].get("key") == hot.hex()
                   for e in fr["events"]), \
            "no hot_key_emerged event for the hot key"

        # --- 2: the imbalance gauge exports on GET /stats with a
        # known (>= 0) value — keep traffic flowing so decay doesn't
        # drop the window below min_observed between tick and scrape
        node0 = str(runners[0].get_node_id())

        def imbalance_known():
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/stats" % proxy.port,
                    timeout=10) as r:
                text = r.read().decode()
            mine = [ln for ln in text.splitlines()
                    if ln.startswith("dht_shard_imbalance")
                    and node0 in ln]
            assert mine, "dht_shard_imbalance missing from /stats"
            return float(mine[0].rsplit(" ", 1)[1])
        for _ in range(20):
            if imbalance_known() >= 1.0:
                break
            drive_mixed(1)
        assert imbalance_known() >= 1.0, \
            "imbalance gauge stayed unknown under live traffic"

        # --- 3: dhtmon green under the mixed load.  The gate sits one
        # margin above the measured mixed imbalance (sanity-bounded:
        # the mix must stay clearly under the 8x single-shard ceiling
        # so the flood has room to trip it)
        imb_mixed = _keyspace(proxy.port)["shards"]["imbalance"]
        assert imb_mixed is not None and imb_mixed < 8.0 - 2 * GATE_MARGIN, \
            "mixed-phase imbalance leaves no flood headroom: %r" % imb_mixed
        gate = imb_mixed + GATE_MARGIN
        rc = dhtmon.main(["--nodes", "127.0.0.1:%d" % proxy.port,
                          "--max-imbalance", "%g" % gate])
        assert rc == 0, \
            "dhtmon flagged the balanced cluster (rc=%d): %r" \
            % (rc, _keyspace(proxy.port)["shards"])

        # --- 4: single-key flood — every get targets the hot key; node
        # 0's observatory flips to a fast decay so the mixed residue
        # washes out in a few ticks and the whole window lands in one
        # histogram bin -> imbalance ~= shard count
        runners[0]._dht.keyspace.cfg.decay = 0.5

        def flooded() -> bool:
            doc = _keyspace(proxy.port)
            imb = doc["shards"]["imbalance"]
            return imb is not None and imb > gate + GATE_MARGIN
        for _ in range(40):
            if flooded():
                break
            for _ in range(24):
                runners[0].get_sync(hot, timeout=OP_TIMEOUT)
        doc = _keyspace(proxy.port)
        assert flooded(), "flood never tripped the imbalance: %r" \
            % (doc["shards"],)
        rc = dhtmon.main(["--nodes", "127.0.0.1:%d" % proxy.port,
                          "--max-imbalance", "%g" % gate])
        assert rc == 1, \
            "dhtmon missed the single-key flood (rc=%d): %r" \
            % (rc, doc["shards"])

        print("keyspace_smoke: OK — hot key %s detected (est %d, share "
              "%.0f%%, hot_key_emerged in ring), imbalance %.2f -> "
              "dhtmon 0 at gate %.2f, flood -> %.2f -> dhtmon 1"
              % (hot.hex()[:12], top0["estimate"], top0["share"] * 100,
                 imb_mixed, gate, doc["shards"]["imbalance"] or 0.0))
        return 0
    finally:
        if proxy is not None:
            proxy.stop()
        for r in runners:
            r.join()


if __name__ == "__main__":
    sys.exit(main())
