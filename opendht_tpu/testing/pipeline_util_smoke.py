"""Pipeline-utilization CI smoke (round-22 tentpole).

Boots the same real-UDP 3-node cluster + REST proxy as
``pipeline_smoke`` and drives a Zipf-skewed get flood through the
depth-2 wave pipeline, then asserts the three things only a live
cluster can about the utilization observatory:

1. **The occupancy plane measures real serving**: after the flood the
   ``dht_pipeline_occupancy`` gauge is a known value > 0 that is
   CONSISTENT with the stage histograms (every dispatched wave
   observed exactly one device-stage sample, so device-stage count <=
   observatory waves, both > 0; busy seconds stay under the wall
   window), ``GET /pipeline`` serves the snapshot (occupancy, bubble
   ledger, overlap ratio) with ``?fmt=trace`` returning a Perfetto
   document whose lane pids are populated, and both
   ``dht_pipeline_occupancy`` and ``dht_pipeline_waves_total`` ride
   the proxy's Prometheus ``GET /stats`` exposition.
2. **An admission choke is attributed, not lost**: traffic pauses (the
   forced choke — the queue stays empty while the device idles), then
   a single op fires; the idle gap must land in the bubble ledger as
   ``queue_empty`` — healthy idleness, classified, never starving the
   health signal.
3. **dhtmon gates on the measured occupancy**: ``--min-occupancy``
   exits 0 at a floor below the measured gauge and flips to 1 at an
   impossible floor (0.999) — the same per-node worst / unknown-never-
   violates contract as the other gauge gates.

Run directly (CI does)::

    python -m opendht_tpu.testing.pipeline_util_smoke
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.request

from .. import telemetry, waterfall
from ..core.value import Value
from ..infohash import InfoHash
from ..pipeline_observatory import BUBBLE_CAUSES
from ..runtime.config import Config, NodeStatus
from ..runtime.runner import DhtRunner, RunnerConfig
from ..tools import dhtmon

N_NODES = 3
N_COLD = 16
ZIPF_ROUNDS = 6
OP_TIMEOUT = 30.0


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _get_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            "http://127.0.0.1:%d%s" % (port, path), timeout=10) as r:
        return json.loads(r.read().decode())


def main(argv=None) -> int:
    reg = telemetry.get_registry()
    reg.reset()
    runners = []
    proxy = None
    try:
        for i in range(N_NODES):
            cfg = Config(node_id=InfoHash.get("pipeutil-smoke-node-%d" % i),
                         ingest_pipeline_depth=2)
            r = DhtRunner()
            r.run(0, RunnerConfig(dht_config=cfg))
            if runners:
                r.bootstrap("127.0.0.1", runners[0].get_bound_port())
            runners.append(r)
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners[1:])), \
            "cluster failed to connect"

        from ..proxy import DhtProxyServer
        proxy = DhtProxyServer(runners[0], 0)

        hot = InfoHash.get("pipeutil-hot")
        cold = [InfoHash.get("pipeutil-cold-%d" % i) for i in range(N_COLD)]
        assert runners[1].put_sync(hot, Value(b"pu-hot", value_id=1),
                                   timeout=OP_TIMEOUT)
        for i, k in enumerate(cold[:4]):
            assert runners[1].put_sync(k, Value(b"pu-%d" % i,
                                                value_id=i + 2),
                                       timeout=OP_TIMEOUT)

        # ---- Zipf-skewed flood through node 0's wave builder: per
        # round, 8 hot gets interleaved with every cold key once (~33%
        # hot share), all ops posted concurrently so the builder fires
        # real coalesced waves back to back
        def drive_round():
            done = []
            ev = threading.Event()
            seq = []
            for j in range(8):
                seq.append(hot)
                seq.extend(cold[j * 2:(j + 1) * 2])
            total = len(seq)

            def fire(k):
                runners[0].get(
                    k, lambda vs: True,
                    lambda ok, ns: (done.append(ok),
                                    ev.set() if len(done) >= total
                                    else None))
            threads = [threading.Thread(target=fire, args=(k,))
                       for k in seq]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert ev.wait(OP_TIMEOUT), "zipf round stalled"

        for _ in range(ZIPF_ROUNDS):
            drive_round()

        # ---- 1: the occupancy plane measured the flood, consistently
        # with the stage histograms
        obs = runners[0]._dht.wave_builder.observatory
        assert obs.enabled
        g_occ = reg.gauge("dht_pipeline_occupancy")
        assert _wait(lambda: g_occ.value >= 0.0, timeout=10), \
            "occupancy gauge stayed unknown under live traffic"
        occ = float(g_occ.value)
        assert 0.0 < occ <= 1.0, "implausible occupancy %r" % occ

        pipe = _get_json(proxy.port, "/pipeline")
        assert pipe["enabled"] and pipe["waves_total"] > 0, pipe
        wf_snap = waterfall.get_profiler().snapshot()["stages"]
        dev_count = (wf_snap["device_compile"]["count"]
                     + wf_snap["device_wait"]["count"])
        assert 0 < dev_count <= pipe["waves_total"], (
            "stage histograms inconsistent with the observatory: "
            "%d device-stage samples vs %d waves"
            % (dev_count, pipe["waves_total"]))
        acct = runners[0]._dht.wave_builder.observatory.account()
        assert acct["busy_s"] <= acct["span_s"] + 1e-6, acct
        assert set(pipe["bubbles"]) == set(BUBBLE_CAUSES)

        trace = _get_json(proxy.port, "/pipeline?fmt=trace")
        lanes = {e["args"]["name"] for e in trace["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        assert {"lane:fill", "lane:device", "lane:drain"} <= lanes, lanes

        with urllib.request.urlopen(
                "http://127.0.0.1:%d/stats" % proxy.port, timeout=10) as r:
            prom = r.read().decode()
        for series in ("dht_pipeline_occupancy", "dht_pipeline_waves_total"):
            assert series in prom, "proxy /stats missing %s" % series

        # ---- 2: the forced admission choke — no traffic while the
        # device idles, then one op; the gap lands as queue_empty.
        # Fresh (never-cached) keys so the op really dispatches, and a
        # throwaway first cycle so any cache/backpressure flag still
        # pending from the flood's last event is consumed at its
        # dispatch instead of naming the measured gap.
        h_qe = reg.histogram("dht_pipeline_bubble_seconds",
                             cause="queue_empty")

        def choke_get(tag):
            ev = threading.Event()
            runners[0].get(InfoHash.get(tag), lambda vs: True,
                           lambda ok, ns: ev.set())
            assert ev.wait(OP_TIMEOUT), "choke op %s stalled" % tag

        time.sleep(0.4)
        choke_get("pipeutil-choke-flush")
        qe0 = h_qe.count
        time.sleep(0.4)                       # the choke: device idle
        choke_get("pipeutil-choke")
        assert _wait(lambda: h_qe.count > qe0, timeout=10), \
            "admission choke never attributed a queue_empty bubble"

        # ---- 3: dhtmon gates on the measured occupancy, both verdicts
        ep = ["--nodes", "127.0.0.1:%d" % proxy.port]
        rc = dhtmon.main(ep + ["--min-occupancy", "1e-9"])
        assert rc == 0, \
            "dhtmon flagged a busy pipeline (rc=%d, occupancy %r)" \
            % (rc, float(g_occ.value))
        rc = dhtmon.main(ep + ["--min-occupancy", "0.999"])
        assert rc == 1, \
            "dhtmon missed the occupancy floor (rc=%d, occupancy %r)" \
            % (rc, float(g_occ.value))

        print("pipeline_util_smoke: OK — occupancy %.3f over %d waves "
              "(%d device-stage samples), queue_empty choke attributed, "
              "dhtmon 0 at 1e-9 -> 1 at 0.999, top bubble %r"
              % (occ, pipe["waves_total"], dev_count,
                 pipe["top_bubble_cause"]))
        return 0
    finally:
        if proxy is not None:
            proxy.stop()
        for r in runners:
            r.join()


if __name__ == "__main__":
    sys.exit(main())
