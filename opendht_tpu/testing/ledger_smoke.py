"""End-to-end kernel-cost-ledger smoke (ISSUE-6 CI satellite).

Boots one node + its REST proxy, computes a subset of the kernel cost
ledger (opendht_tpu/profiling.py — the subset keeps the CI step in
seconds; ci/perf_gate.py lowers the FULL set in the same run), then
asserts the ledger actually reaches both export surfaces the spine
serves:

1. ``DhtRunner.get_metrics()`` carries ``dht_kernel_*`` gauges with
   the lowered cost-model values;
2. the proxy's ``GET /stats`` Prometheus exposition carries the same
   series and still parses line-by-line against the v0.0.4 grammar
   (reusing telemetry_smoke's validator);
3. the two exports agree on the values (one registry, two views).

Run directly (CI does)::

    python -m opendht_tpu.testing.ledger_smoke
"""

from __future__ import annotations

import sys
import urllib.request

from ..runtime.runner import DhtRunner
from .telemetry_smoke import parse_exposition

#: lowered in the smoke — small, fast, and covering one kernel from
#: each family (window lookup / gather / maintenance)
SMOKE_KERNELS = ["expanded_topk", "fused_gather_planar",
                 "maintenance_sweep"]


def main(argv=None) -> int:
    from .. import profiling
    from ..proxy import DhtProxyServer

    node = DhtRunner()
    proxy = None
    try:
        node.run(0)
        led = profiling.get_ledger()
        entries = led.compute(SMOKE_KERNELS)
        bad = {n: e["error"] for n, e in entries.items() if "error" in e}
        if bad:
            print("ledger_smoke: kernels failed to lower: %s" % bad,
                  file=sys.stderr)
            return 1
        led.export_to_registry()

        # surface 1: get_metrics JSON
        metrics = node.get_metrics()
        gauges = metrics.get("gauges", {})
        for name in SMOKE_KERNELS:
            key = 'dht_kernel_bytes_accessed{kernel="%s"}' % name
            if key not in gauges:
                print("ledger_smoke: %s missing from get_metrics()" % key,
                      file=sys.stderr)
                return 1
            if gauges[key] != entries[name]["bytes_accessed"]:
                print("ledger_smoke: %s = %r disagrees with the ledger "
                      "entry %r" % (key, gauges[key],
                                    entries[name]["bytes_accessed"]),
                      file=sys.stderr)
                return 1

        # surface 2: the proxy's Prometheus exposition
        proxy = DhtProxyServer(node, 0)
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/stats" % proxy.port, timeout=10.0) as r:
            text = r.read().decode()
        series = parse_exposition(text)         # raises on grammar errors
        for name in SMOKE_KERNELS:
            for fam in ("dht_kernel_flops", "dht_kernel_bytes_accessed",
                        "dht_kernel_hbm_bytes"):
                key = '%s{kernel="%s"}' % (fam, name)
                if key not in series:
                    print("ledger_smoke: %s missing from GET /stats"
                          % key, file=sys.stderr)
                    return 1
                if series[key] != float(
                        entries[name][fam.replace("dht_kernel_", "")]):
                    print("ledger_smoke: /stats %s disagrees with the "
                          "ledger" % key, file=sys.stderr)
                    return 1
        print("ledger_smoke ok: %d kernels exported, %d exposition "
              "series parsed" % (len(SMOKE_KERNELS), len(series)))
        return 0
    finally:
        if proxy is not None:
            try:
                proxy.stop()
            except Exception:
                pass
        node.join()


if __name__ == "__main__":
    sys.exit(main())
