"""Wave-pipeline CI smoke (round-20 tentpole).

Boots the same real-UDP 3-node cluster + REST proxy as
``ingest_smoke`` and runs the same concurrent mixed burst (puts, gets,
standing listeners), but exercises the round-20 double-buffered wave
pipeline and asserts the three things only a live cluster can:

1. **The pipeline actually holds waves in flight**: with
   ``ingest_pipeline_depth=2`` the ``dht_ingest_pipeline_inflight_peak``
   gauge reaches ≥ 2 under sustained traffic (a slow-ready shim on one
   node's launch handle makes the deferral deterministic — live
   cluster tables are host-scan sized, so real handles materialize
   before the next fire), and both pipeline series ride the proxy's
   Prometheus ``GET /stats`` exposition.
2. **Stage histograms advance with async dispatch**: the always-on
   waterfall still observes queue_wait / device stage / scatter_back
   for pipelined waves (the device stage is measured at *consume*
   since round 20 — dispatch + blocking wait, see waterfall.py).
3. **Depth-2 equivalence on every surface**: the identical workload
   rerun with ``ingest_pipeline_depth=1`` (the exact pre-pipeline
   serial path) returns the same values to every get, delivers the
   same values to every listener, and leaves the same per-node
   storage state.

Run directly (CI does)::

    python -m opendht_tpu.testing.pipeline_smoke
"""

from __future__ import annotations

import sys
import threading
import time
import urllib.request

from .. import telemetry, waterfall
from ..core.value import Value
from ..infohash import InfoHash
from ..runtime.config import Config, NodeStatus
from ..runtime.runner import DhtRunner, RunnerConfig

N_NODES = 3
N_KEYS = 16
OP_TIMEOUT = 30.0


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return pred()


class _SlowReady:
    """Launch-handle wrapper that reports not-ready until a NEWER
    launch exists (or a 50 ms fallback for tail waves with no
    successor).  Cluster tables are host-scan sized, so real handles
    materialize instantly — without this shim a wave always drains
    before the next one fires and the pipeline never visibly stacks.
    Results are untouched: ``consume()`` is the real handle's."""

    def __init__(self, handle, state, idx):
        self._h = handle
        self.shard_t = handle.shard_t
        self._state = state
        self._idx = idx
        self._t0 = time.monotonic()

    def ready(self):
        # our own launch already bumped the counter to idx+1 — a NEWER
        # launch exists only beyond that
        if self._state["launches"] <= self._idx + 1 \
                and time.monotonic() - self._t0 < 0.05:
            return False
        return self._h.ready()

    def consume(self):
        return self._h.consume()


def _slow_launches(runner) -> dict:
    """Shim every launch handle of ``runner``'s inner Dht slow-ready;
    returns the shared launch-counter state (the stack probe watches
    it to time its second op)."""
    inner = runner._dht._dht
    real = inner.find_closest_nodes_launch
    state = {"launches": 0}

    def launch(targets, af, count):
        idx = state["launches"]
        state["launches"] = idx + 1
        return _SlowReady(real(targets, af, count), state, idx)

    inner.find_closest_nodes_launch = launch
    return state


def _run_phase(depth: int) -> dict:
    """One full cluster lifecycle at the given pipeline depth; returns
    the result-equivalence record (get results, listen deliveries,
    per-node storage) plus the phase's telemetry surfaces."""
    reg = telemetry.get_registry()
    reg.reset()
    keys = [InfoHash.get("pipeline-smoke-%d" % i) for i in range(N_KEYS)]
    listen_keys = keys[:2]

    runners = []
    proxy = None
    try:
        for i in range(N_NODES):
            cfg = Config(node_id=InfoHash.get("pipeline-smoke-node-%d" % i),
                         ingest_pipeline_depth=depth)
            r = DhtRunner()
            r.run(0, RunnerConfig(dht_config=cfg))
            if runners:
                r.bootstrap("127.0.0.1", runners[0].get_bound_port())
            runners.append(r)
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners[1:])), \
            "cluster failed to connect (depth=%d)" % depth
        states = []
        if depth > 1:
            states = [_slow_launches(r) for r in runners]

        from ..proxy import DhtProxyServer
        proxy = DhtProxyServer(runners[0], 0)

        heard: dict = {}
        heard_lock = threading.Lock()

        def on_values(vals, expired):
            if not expired:
                with heard_lock:
                    for v in vals:
                        heard[v.data] = True
            return True

        tokens = [runners[1].listen(k, on_values) for k in listen_keys]
        for t in tokens:
            assert t.result(OP_TIMEOUT) != 0, "listen shed at admission"

        # ---- concurrent burst (same shape as ingest_smoke: every op
        # posted before any completes → the builder fires real waves
        # back to back, which is what keeps the pipeline stacked)
        put_done = {i: threading.Event() for i in range(N_KEYS)}
        put_ok = {}

        def fire_put(i):
            src = runners[1 + (i % (N_NODES - 1))]
            src.put(keys[i], Value(b"pipeline-%d" % i, value_id=i + 1),
                    lambda ok, ns, _i=i: (put_ok.setdefault(_i, ok),
                                          put_done[_i].set()))

        threads = [threading.Thread(target=fire_put, args=(i,))
                   for i in range(N_KEYS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(N_KEYS):
            assert put_done[i].wait(OP_TIMEOUT), "put %d stalled" % i
            assert put_ok[i], "put %d failed (depth=%d)" % (i, depth)

        got: dict = {}
        get_done = {i: threading.Event() for i in range(N_KEYS)}

        def fire_get(i):
            vals: list = []
            runners[0].get(
                keys[i], lambda vs, _a=vals: _a.extend(vs) or True,
                lambda ok, ns, _i=i, _a=vals: (
                    got.setdefault(_i, sorted(v.data for v in _a)),
                    get_done[_i].set()))

        threads = [threading.Thread(target=fire_get, args=(i,))
                   for i in range(N_KEYS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(N_KEYS):
            assert get_done[i].wait(OP_TIMEOUT), "get %d stalled" % i
            assert got[i] == [b"pipeline-%d" % i], \
                "get %d returned %r (depth=%d)" % (i, got[i], depth)

        assert _wait(lambda: len(heard) >= len(listen_keys)), \
            "listeners missed burst values: %r" % sorted(heard)

        if depth > 1:
            # ---- stack probe: organic localhost traffic serializes
            # per builder (every concurrent refill coalesces into one
            # wave, and the NEXT wave's submits only exist once this
            # wave's results are out), so force the stack explicitly:
            # op A's wave launches and is held by the shim; op B's wave
            # then fires while A is still in flight — the in-flight
            # peak gauge records 2 the moment B's wave is appended.
            st = states[0]
            base = st["launches"]
            ev_a, ev_b = threading.Event(), threading.Event()
            runners[0].get(InfoHash.get("pipeline-stack-a"),
                           lambda vs: True,
                           lambda ok, ns: ev_a.set())
            assert _wait(lambda: st["launches"] > base, step=0.005), \
                "stack probe: op A's wave never launched"
            runners[0].get(InfoHash.get("pipeline-stack-b"),
                           lambda vs: True,
                           lambda ok, ns: ev_b.set())
            assert ev_a.wait(OP_TIMEOUT) and ev_b.wait(OP_TIMEOUT), \
                "stack probe ops stalled"

        snap = reg.snapshot()
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/stats" % proxy.port, timeout=10) as r:
            prom = r.read().decode()

        storage = []
        for r in runners:
            exported = sorted(
                (key.hex(), sorted(bytes(p) for _c, p in vals))
                for key, vals in r.export_values())
            storage.append(exported)
        return {
            "gets": got,
            "heard": sorted(heard),
            "storage": storage,
            "snapshot": snap,
            "prometheus": prom,
        }
    finally:
        if proxy is not None:
            proxy.stop()
        for r in runners:
            r.join()


def main(argv=None) -> int:
    wf_before = {
        s: d.get("count", 0)
        for s, d in waterfall.get_profiler().snapshot()["stages"].items()}

    piped = _run_phase(2)

    # 1. the pipeline held ≥ 2 waves in flight, on the export surface
    peak = piped["snapshot"]["gauges"].get(
        "dht_ingest_pipeline_inflight_peak", 0)
    assert peak >= 2, (
        "pipeline never held 2 waves in flight (peak gauge %r)" % (peak,))
    for series in ("dht_ingest_pipeline_inflight",
                   "dht_ingest_pipeline_inflight_peak"):
        assert series in piped["prometheus"], \
            "proxy /stats missing %s" % series
    sheds = sum(v for k, v in piped["snapshot"]["counters"].items()
                if k.startswith("dht_ingest_sheds_total"))
    assert sheds == 0, "admitted workload was shed (%d drops)" % sheds

    # 2. async dispatch still feeds the waterfall (device stage is
    # observed at consume now — counts must advance, not freeze)
    wf_after = {
        s: d.get("count", 0)
        for s, d in waterfall.get_profiler().snapshot()["stages"].items()}
    for stage in ("queue_wait", "scatter_back"):
        assert wf_after.get(stage, 0) > wf_before.get(stage, 0), (
            "stage %s froze under the pipeline (%r -> %r)"
            % (stage, wf_before.get(stage), wf_after.get(stage)))
    dev = sum(wf_after.get(s, 0) - wf_before.get(s, 0)
              for s in ("device_compile", "device_launch"))
    assert dev > 0, "device stage froze under async dispatch"

    serial = _run_phase(1)

    # 3. the acceptance-criteria equivalence: depth 2 == depth 1 on
    # every surface
    assert piped["gets"] == serial["gets"], "get results diverged"
    assert piped["heard"] == serial["heard"], "listen deliveries diverged"
    assert piped["storage"] == serial["storage"], (
        "per-node storage state diverged between depth 2 and depth 1")

    waves = int(piped["snapshot"]["counters"].get(
        "dht_ingest_waves_total", 0))
    print("pipeline_smoke: OK — %d waves, inflight peak %d, 0 sheds, "
          "depth2 == depth1 on %d gets / %d listens / %d nodes"
          % (waves, peak, N_KEYS, len(piped["heard"]), N_NODES))
    return 0


if __name__ == "__main__":
    sys.exit(main())
