"""Cluster wire-map assembly over per-peer ledgers (round 23).

The round-9 trace assembler stitches ONE operation's span tree, the
round-17 timeline assembler stitches the cluster's metrics history —
this module stitches the cluster's WIRE: every node's per-peer ledger
snapshot (``GET /peers``, opendht_tpu/peers.py) merged into one
directed link graph, so a soak harness or game-day scorecard can
answer "which edge is slow / lossy / flapping" instead of reading
cluster-wide aggregates that smear a single bad link over every node.
A chaos-plane ``LinkRule`` injected on ONE link shows up on exactly
that directed edge (pinned in testing/peer_smoke.py).

Sources accepted by :func:`assemble_wiremap` (the assemblers' shared
duck-typing): a ``GET /peers`` document (:func:`scrape_peers` stamps
``scraped_at`` so skew is estimable), a ``DhtRunner``-like
(``get_peers()``), or a raw :class:`~opendht_tpu.peers.PeerLedger`.

**Skew**: each scrape document carries the serving node's clock
(``time``) next to the scraper's (``scraped_at``); their difference
estimates that node's offset and every edge's ``first_seen`` /
``last_seen`` gains an adjusted ``*_adj`` twin before comparison
(same-host clusters estimate ~0).  **Sanity** is checked per node like
the timeline assembler's monotonicity pass: a peer row stamped after
its own snapshot time (``last_seen > time + CLOCK_SLACK``) or with
``first_seen > last_seen`` is REPORTED in ``violations``, never
dropped — a post-mortem tool must degrade, not lie.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request
from typing import List, Optional

#: tolerance for "a peer row from the future": rows stamp the ledger
#: clock per event, snapshots stamp it once — only lock-release
#: ordering jitter remains (the round-17 CLOCK_SLACK)
CLOCK_SLACK = 0.050


def scrape_peers(endpoint: str, timeout: float = 10.0) -> Optional[dict]:
    """One node's ``GET /peers`` document with the LOCAL wall clock
    stamped as ``scraped_at`` so :func:`assemble_wiremap` can estimate
    skew.  ``None`` when the node does not export the route (scrape
    error or ledger disabled)."""
    base = "http://" + endpoint.rstrip("/")
    try:
        with urllib.request.urlopen(base + "/peers", timeout=timeout) as r:
            doc = json.loads(r.read().decode())
    except Exception:
        return None
    if not isinstance(doc, dict) or not doc.get("enabled"):
        return None
    doc["endpoint"] = endpoint
    doc["scraped_at"] = time.time()
    return doc


def _extract(source) -> Optional[dict]:
    """Normalize one source into a peers document (or None)."""
    if isinstance(source, dict):
        return source if source.get("enabled") else None
    if hasattr(source, "get_peers"):               # DhtRunner-like
        return _extract(source.get_peers())
    if hasattr(source, "snapshot"):                # raw PeerLedger
        return _extract(source.snapshot())
    return None


def _skew(doc: dict) -> float:
    """Serving-node clock minus scraper wall clock at scrape time —
    0.0 when either stamp is missing (in-process sources share the
    clock)."""
    t = doc.get("time")
    at = doc.get("scraped_at")
    if t is None or at is None:
        return 0.0
    return float(t) - float(at)


def assemble_wiremap(sources) -> dict:
    """Merge every source's per-peer ledger into one directed link
    graph.

    Returns ``{"nodes", "edges", "skew", "violations"}``: ``nodes``
    lists one entry per scraped ledger (id, endpoint, tracked count,
    estimated skew); ``edges`` is one directed entry per (scraping
    node -> tracked peer) with the full per-peer attribution (srtt /
    rttvar / rto, outcome counts, attempt timeouts, spurious
    retransmits, fail ratio, bytes by type, status + flaps) plus
    skew-adjusted ``first_seen_adj`` / ``last_seen_adj`` and ``known``
    (True when the peer id is itself one of the scraped nodes — the
    edge's far end is inside the map)."""
    nodes: List[dict] = []
    docs: List[dict] = []
    violations: List[str] = []
    for si, source in enumerate(sources):
        doc = _extract(source)
        if doc is None:
            violations.append("source %d: no per-peer ledger" % si)
            continue
        docs.append(doc)
    ids = {d.get("node", "") for d in docs if d.get("node")}
    skews = {}
    edges: List[dict] = []
    for si, doc in enumerate(docs):
        src = doc.get("node") or ("source-%d" % si)
        skew = _skew(doc)
        skews[src] = skew
        snap_t = float(doc.get("time") or 0.0)
        nodes.append({
            "id": src,
            "endpoint": doc.get("endpoint", ""),
            "tracked": doc.get("tracked", 0),
            "evicted": doc.get("evicted", 0),
            "adaptive_rto": bool(doc.get("adaptive_rto")),
            "skew": skew,
        })
        for p in doc.get("peers") or []:
            first = float(p.get("first_seen") or 0.0)
            last = float(p.get("last_seen") or 0.0)
            if last > snap_t + CLOCK_SLACK:
                violations.append(
                    "node %s: peer %s last seen %.3fs after its own "
                    "snapshot" % (src, p.get("peer"), last - snap_t))
            if first > last:
                violations.append(
                    "node %s: peer %s first_seen %.3f after last_seen "
                    "%.3f" % (src, p.get("peer"), first, last))
            e = dict(p)
            e["src"] = src
            e["dst"] = p.get("id") or p.get("addr", "")
            e["known"] = e["dst"] in ids
            e["first_seen_adj"] = first - skew
            e["last_seen_adj"] = last - skew
            edges.append(e)
    return {"nodes": nodes, "edges": edges, "skew": skews,
            "violations": violations}


def rank_edges(wiremap: dict, metric: str = "fail_ratio",
               descending: bool = True) -> List[dict]:
    """Edges ordered by one attribution metric, worst first by
    default; edges where the metric is None/absent (unknown — e.g. no
    RTT sample yet, or below the ledger's signal floor) are EXCLUDED,
    the same never-violates contract every per-peer reader follows."""
    known = [e for e in wiremap["edges"] if e.get(metric) is not None]
    return sorted(known, key=lambda e: e[metric], reverse=descending)


def worst_edge(wiremap: dict, metric: str = "fail_ratio"
               ) -> Optional[dict]:
    """The single worst edge by ``metric`` (None when every edge is
    unknown) — the wire-level answer behind
    ``dhtmon --max-peer-fail``'s cluster verdict."""
    ranked = rank_edges(wiremap, metric)
    return ranked[0] if ranked else None


def find_edge(wiremap: dict, src: str, dst: str) -> Optional[dict]:
    """The directed edge src -> dst (full node ids), or None — lets a
    harness assert an injected fault landed on exactly the link it was
    armed on."""
    for e in wiremap["edges"]:
        if e["src"] == src and e["dst"] == dst:
            return e
    return None


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        description="assemble the cluster wire map from GET /peers")
    p.add_argument("endpoints", nargs="+", metavar="HOST:PORT",
                   help="proxy endpoints to scrape")
    p.add_argument("--json", action="store_true",
                   help="dump the assembled map as JSON")
    p.add_argument("--metric", default="fail_ratio",
                   help="ranking metric for the edge table "
                        "(default: fail_ratio)")
    args = p.parse_args(argv)
    docs = []
    for ep in args.endpoints:
        doc = scrape_peers(ep)
        if doc is None:
            print("wiremap: %s exports no per-peer ledger" % ep,
                  file=sys.stderr)
        else:
            docs.append(doc)
    wm = assemble_wiremap(docs)
    if args.json:
        json.dump(wm, sys.stdout)
        print()
    else:
        print("%d node(s), %d directed edge(s)" % (
            len(wm["nodes"]), len(wm["edges"])))
        for e in rank_edges(wm, args.metric):
            srtt = e.get("srtt")
            print("%s -> %s  %s=%.4g  srtt=%s  sent=%d expired=%d "
                  "flaps=%d" % (
                      e["src"][:12], str(e["dst"])[:12], args.metric,
                      e[args.metric],
                      "%.1fms" % (srtt * 1e3) if srtt is not None
                      else "-", e.get("sent", 0), e.get("expired", 0),
                      e.get("flaps", 0)))
        for v in wm["violations"]:
            print("VIOLATION:", v, file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
