"""network_monitor: continuous end-to-end put→listen health probe.

Analog of the reference monitor (reference python/tools/
network_monitor.py:26-83): two local nodes bootstrap to the monitored
network; node1 listens on N keys, node2 puts a fresh random value on
every key each period, and the monitor reports how long the full
put→propagate→listen round trip takes.  A timeout exits non-zero so the
tool can drive alerting.

Differences from the reference: ``--rounds`` bounds the loop (0 = run
forever like the reference) and ``--local`` spins up a private two-node
network instead of joining a public bootstrap, so the tool is runnable
in sealed environments and tests.

Telemetry (ISSUE-3): every key's put→listen round trip is observed into
the ``dht_monitor_roundtrip_seconds`` histogram of the unified registry,
and each round reports the cumulative p50/p95 from that histogram — not
just the last round's wall time.  Alerting is configurable per
percentile: ``--alert p95=2.5`` (repeatable) exits non-zero as soon as
the cumulative percentile crosses the threshold, so one flag drives
pager policy off whichever tail matters.

Round 23 (ISSUE-19): the monitor's latency view is no longer
roundtrip-only — each round also folds the per-peer
``dht_peer_rtt_seconds{peer=}`` histograms the round-23 ledger
maintains (one shared instrumentation point; the monitor adds no
private wire-RTT bookkeeping) into cumulative per-hop wire
percentiles, and names the slowest link by smoothed RTT.  The
end-to-end roundtrip and the wire RTT bracket the same probe: a slow
round with fast wire RTTs is storage/propagation, a slow round with
one slow link is that link.

Usage::

    python -m opendht_tpu.testing.network_monitor --local -n 4 --rounds 3
    python -m opendht_tpu.testing.network_monitor -b host:port -p 60 \
        --alert p50=1.0 --alert p95=5.0
"""

from __future__ import annotations

import argparse
import sys
import threading
import time
from datetime import datetime

from .. import telemetry
# the --alert PCT=SEC grammar and the cumulative-percentile check are
# shared with testing/health_monitor.py and tools/dhtmon.py (ISSUE-9
# satellite) — one copy, in opendht_tpu/health.py; parse_alerts stays
# importable from this module for existing callers
from ..health import parse_alerts, percentile_breaches  # noqa: F401
from ..infohash import InfoHash
from ..core.value import Value
from ..runtime.config import NodeStatus
from ..runtime.runner import DhtRunner


class Monitor:
    def __init__(self, bootstrap: "tuple[str, int] | None", num_ops: int,
                 timeout: float):
        self.timeout = timeout
        self.node1 = DhtRunner()
        self.node2 = DhtRunner()
        self.node1.run(0)
        self.node2.run(0)
        self._local = None
        if bootstrap is None:
            # private network: node1 doubles as the bootstrap
            self.node2.bootstrap("127.0.0.1", self.node1.get_bound_port())
        else:
            host, port = bootstrap
            self.node1.bootstrap(host, port)
            self.node2.bootstrap(host, port)
        self.keys = [InfoHash.get_random() for _ in range(num_ops)]
        self.pending: dict = {}          # key-hex -> (expected Value, t_put)
        self._cv = threading.Condition()
        self.rtt = telemetry.get_registry().histogram(
            "dht_monitor_roundtrip_seconds")
        for key in self.keys:
            self.node1.listen(key, self._make_cb(key))

    def _make_cb(self, key: InfoHash):
        kstr = key.hex()

        def cb(values, expired):
            if expired:
                return True
            with self._cv:
                ent = self.pending.get(kstr)
                if ent is not None and any(v.id == ent[0].id for v in values):
                    self.pending.pop(kstr, None)
                    # per-key round trip → the histogram the round
                    # report and --alert percentiles read from
                    self.rtt.observe(time.monotonic() - ent[1])
                    self._cv.notify_all()
            return True
        return cb

    def wait_connected(self, timeout: float = 30.0) -> bool:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            if (self.node1.get_status() is NodeStatus.CONNECTED
                    and self.node2.get_status() is NodeStatus.CONNECTED):
                return True
            time.sleep(0.1)
        return False

    def run_test(self) -> float:
        """One round: put a fresh value on every key, wait until every
        listener heard its value.  Returns elapsed seconds; raises
        TimeoutError on expiry (reference monitor exits 1)."""
        start = time.monotonic()
        with self._cv:
            for i, key in enumerate(self.keys):
                val = Value(InfoHash.get_random().hex().encode(),
                            value_id=int(start * 1000) * 1000 + i + 1)
                self.pending[key.hex()] = (val, time.monotonic())
                self.node2.put(key, val, lambda ok, nodes: None)
            while self.pending:
                remaining = self.timeout - (time.monotonic() - start)
                if remaining <= 0 or not self._cv.wait(timeout=remaining):
                    missing = list(self.pending)
                    self.pending.clear()
                    raise TimeoutError("no listen callback for %d keys: %s"
                                       % (len(missing), missing[:4]))
        return time.monotonic() - start

    def percentiles(self, pcts=(50, 95)) -> dict:
        """Cumulative put→listen round-trip percentiles (seconds) from
        the ``dht_monitor_roundtrip_seconds`` histogram."""
        return {p: self.rtt.quantile(p / 100.0) for p in pcts}

    def wire_percentiles(self, pcts=(50, 95)) -> dict:
        """Cumulative per-hop wire-RTT percentiles folded over EVERY
        ``dht_peer_rtt_seconds{peer=}`` histogram the round-23 per-peer
        ledger maintains (merged bucket-exactly — the same log buckets,
        one summed map) — the monitor reuses the ledger's
        instrumentation instead of keeping a wire view of its own.
        All-None when no ledger sample exists yet."""
        merged: dict = {}
        total = 0
        for m in telemetry.get_registry().series(
                "dht_peer_rtt_seconds").values():
            cnt, _s, buckets = m.raw()
            total += cnt
            for i, c in buckets.items():
                merged[i] = merged.get(i, 0) + c
        if total <= 0:
            return {p: None for p in pcts}
        items = sorted(merged.items())
        return {p: telemetry.quantile_from_buckets(items, total, p / 100.0)
                for p in pcts}

    def worst_link(self):
        """``(peer_label, srtt_seconds)`` of the slowest tracked link
        by smoothed RTT across both probe nodes' ledgers; None before
        any link has an RTT sample."""
        worst = None
        for node in (self.node1, self.node2):
            snap = node.get_peers()
            if not snap.get("enabled"):
                continue
            for pd in snap.get("peers", []):
                if pd.get("srtt") is not None and (
                        worst is None or pd["srtt"] > worst[1]):
                    worst = (pd["peer"], pd["srtt"])
        return worst

    def close(self) -> None:
        self.node1.join()
        self.node2.join()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="monitor a DHT network with periodic put->listen probes")
    p.add_argument("-b", "--bootstrap",
                   help="bootstrap address host:port (default: private net)")
    p.add_argument("-n", "--num-ops", type=int, default=8,
                   help="concurrent keys probed per round")
    p.add_argument("-p", "--period", type=float, default=60.0,
                   help="seconds between rounds")
    p.add_argument("-t", "--timeout", type=float, default=15.0,
                   help="per-round timeout")
    p.add_argument("--rounds", type=int, default=0,
                   help="stop after N rounds (0 = forever)")
    p.add_argument("--local", action="store_true",
                   help="run against a private 2-node network")
    p.add_argument("--alert", action="append", default=[], metavar="PCT=SEC",
                   help="exit non-zero when the cumulative round-trip "
                        "percentile exceeds SEC (e.g. --alert p95=2.5; "
                        "repeatable, one threshold per percentile)")
    args = p.parse_args(argv)
    try:
        alerts = parse_alerts(args.alert)
    except ValueError as e:
        print("network_monitor:", e, file=sys.stderr)
        return 2

    bootstrap = None
    if args.bootstrap and not args.local:
        host, _, port = args.bootstrap.partition(":")
        bootstrap = (host, int(port or 4222))

    mon = Monitor(bootstrap, args.num_ops, args.timeout)
    try:
        if not mon.wait_connected():
            print("monitor: nodes failed to connect", file=sys.stderr)
            return 1
        next_test = time.monotonic()
        done_rounds = 0
        while args.rounds == 0 or done_rounds < args.rounds:
            try:
                dt = mon.run_test()
            except TimeoutError as e:
                print("Test timeout !", e, file=sys.stderr)
                return 1
            pcts = mon.percentiles(tuple(sorted({50, 95, *alerts})))
            print(datetime.now().strftime("%Y-%m-%d %H:%M:%S"),
                  "Test completed successfully in", round(dt, 3),
                  "| round-trip " + " ".join(
                      "p%g=%.3fs" % (p, v) for p, v in sorted(pcts.items())))
            wire = mon.wire_percentiles()
            if any(v is not None for v in wire.values()):
                wl = mon.worst_link()
                print("  wire RTT " + " ".join(
                    "p%g=%.3fs" % (p, v)
                    for p, v in sorted(wire.items()) if v is not None)
                    + (" | slowest link %s srtt=%.3fs" % wl
                       if wl is not None else ""))
            breaches = percentile_breaches(
                lambda q: mon.rtt.quantile(q), alerts)
            if breaches:
                for pct, v, thr in breaches:
                    print("ALERT: round-trip p%g %.3fs exceeds %.3fs"
                          % (pct, v, thr), file=sys.stderr)
                return 1
            done_rounds += 1
            if args.rounds and done_rounds >= args.rounds:
                break
            next_test += args.period
            now = time.monotonic()
            if next_test > now:
                time.sleep(next_test - now)
    finally:
        mon.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
