"""Real-kernel network tier: clusters in Linux network NAMESPACES.

The reference's distributed test infra runs node clusters in network
namespaces joined by bridges/veth with netem shaping
(reference python/tools/dht/virtual_network_builder.py:1-121,
network.py NSPopen).  This tier reproduces the namespace/veth/routing
half on this kernel: each cluster is a :class:`ClusterSubProcess`
living in its OWN netns, reached through a veth pair, with the root
namespace forwarding between cluster subnets — so DHT traffic crosses
REAL kernel interfaces (device queues, ARP, IP routing), not a
userspace switch.

What stays with the deterministic virtual-clock tier
(testing/virtual_net.py): loss/delay shaping.  This kernel ships no
``sch_netem`` (``tc qdisc add ... netem`` → "Specified qdisc kind is
unknown") and no iptables/nftables userland, so in-kernel loss is not
buildable here; the capability is probed, not assumed —
:func:`netem_available` documents the hole and the tier degrades to
loss-free real-kernel plumbing.

Requires CAP_NET_ADMIN (root).  All state is torn down in ``close()``;
names are prefixed ``odt`` to avoid collisions.
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import List, Optional

from .subproc_cluster import ClusterSubProcess

_SUBNET = "10.77.%d"


def _sh(*argv, check: bool = True) -> subprocess.CompletedProcess:
    return subprocess.run(argv, capture_output=True, text=True,
                          check=check)


def _uniq() -> str:
    """Short per-process prefix: concurrent sessions must not race on
    kernel object names (interface names cap at 15 chars)."""
    return "odt%d" % (os.getpid() % 100000)


def netns_available() -> bool:
    """True when namespaces + veth can actually be created here.
    Stale probe artifacts from a killed prior run are cleared first so
    one crash can never permanently disable the tier; names are
    per-process so concurrent sessions cannot corrupt each other."""
    u = _uniq()
    try:
        _sh("ip", "netns", "del", u + "pr", check=False)
        _sh("ip", "link", "del", u + "p0", check=False)
        _sh("ip", "netns", "add", u + "pr")
    except (OSError, subprocess.CalledProcessError):
        return False
    try:
        _sh("ip", "link", "add", u + "p0", "type", "veth",
            "peer", "name", u + "p1")
        _sh("ip", "link", "del", u + "p0")
        return True
    except (OSError, subprocess.CalledProcessError):
        return False
    finally:
        _sh("ip", "netns", "del", u + "pr", check=False)


def netem_available() -> bool:
    """True when the kernel can attach a netem qdisc (loss/delay).
    False on this build host — recorded as the environment bound.
    Must never raise: a missing ``tc`` binary (no iproute2-tc userland)
    is one of the exact environments this probe documents."""
    u = _uniq()
    try:
        _sh("ip", "link", "del", u + "q0", check=False)
        _sh("ip", "link", "add", u + "q0", "type", "veth",
            "peer", "name", u + "q1")
    except (OSError, subprocess.CalledProcessError):
        return False
    try:
        r = _sh("tc", "qdisc", "add", "dev", u + "q0", "root",
                "netem", "delay", "1ms", check=False)
        return r.returncode == 0
    except OSError:                      # tc binary absent
        return False
    finally:
        _sh("ip", "link", "del", u + "q0", check=False)


class NetnsClusterNet:
    """N cluster subprocesses, each in its own namespace, routed
    through the root namespace.

    Topology per cluster i (subnet 10.77.i.0/24):

        root ns:  odtv{i}h  10.77.i.1/24   (also the clusters' gateway)
        odtns{i}: odtv{i}c  10.77.i.2/24, default route via 10.77.i.1

    A DHT node in the root namespace (bound 0.0.0.0) is reachable from
    every cluster at its gateway address; clusters reach EACH OTHER
    through root-namespace IP forwarding — every packet crosses two
    real veth devices and the kernel's forwarding path.
    """

    def __init__(self):
        self.clusters: List[ClusterSubProcess] = []
        self._ns: List[str] = []
        self._links: List[str] = []
        self._prefix = _uniq()
        self._saved_ip_forward: Optional[str] = None

    def add_cluster(self, n_nodes: int, *, timeout: float = 120.0
                    ) -> ClusterSubProcess:
        i = len(self._ns)
        p = self._prefix
        ns, vh, vc = f"{p}n{i}", f"{p}v{i}h", f"{p}v{i}c"
        sub = _SUBNET % i
        # clear stale artifacts from a killed prior run of THIS pid slot
        _sh("ip", "netns", "del", ns, check=False)
        _sh("ip", "link", "del", vh, check=False)
        _sh("ip", "netns", "add", ns)
        self._ns.append(ns)
        _sh("ip", "link", "add", vh, "type", "veth", "peer", "name", vc)
        self._links.append(vh)
        _sh("ip", "link", "set", vc, "netns", ns)
        _sh("ip", "addr", "add", f"{sub}.1/24", "dev", vh)
        _sh("ip", "link", "set", vh, "up")
        for cmd in ((f"ip addr add {sub}.2/24 dev {vc}"),
                    (f"ip link set {vc} up"),
                    ("ip link set lo up"),
                    (f"ip route add default via {sub}.1")):
            _sh("ip", "netns", "exec", ns, *cmd.split())
        # forwarding is load-bearing for cross-cluster traffic: write
        # /proc directly (no sysctl-binary dependency) and VERIFY — a
        # silently-off forward would blackhole a<->b packets and
        # surface later as an opaque lookup miss.  The prior value is
        # saved once and restored in close(): flipping a host-global
        # routing knob must not outlive the harness.
        with open("/proc/sys/net/ipv4/ip_forward") as f:
            cur = f.read().strip()
        if cur != "1":
            if self._saved_ip_forward is None:
                self._saved_ip_forward = cur
            try:
                with open("/proc/sys/net/ipv4/ip_forward", "w") as f:
                    f.write("1")
            except OSError:
                pass
            with open("/proc/sys/net/ipv4/ip_forward") as f:
                if f.read().strip() != "1":
                    raise RuntimeError(
                        "cannot enable net.ipv4.ip_forward — "
                        "cross-cluster routing unavailable in this "
                        "container")
        cl = ClusterSubProcess(argv_prefix=("ip", "netns", "exec", ns),
                               timeout=timeout)
        self.clusters.append(cl)
        if n_nodes:
            cl.launch(n_nodes)
        return cl

    def cluster_addr(self, i: int) -> str:
        """The cluster's address as seen from the root namespace."""
        return (_SUBNET % i) + ".2"

    def gateway_addr(self, i: int) -> str:
        """The root namespace's address on cluster i's subnet (where a
        root-ns node is reachable from that cluster)."""
        return (_SUBNET % i) + ".1"

    def close(self) -> None:
        for cl in self.clusters:
            try:
                if cl.proc.poll() is None:
                    cl.quit()
            except Exception:
                cl.kill()
        time.sleep(0.1)
        for vh in self._links:
            _sh("ip", "link", "del", vh, check=False)
        for ns in self._ns:
            _sh("ip", "netns", "del", ns, check=False)
        if self._saved_ip_forward is not None:
            try:
                with open("/proc/sys/net/ipv4/ip_forward", "w") as f:
                    f.write(self._saved_ip_forward)
            except OSError:
                pass
            self._saved_ip_forward = None
        self.clusters.clear()
        self._ns.clear()
        self._links.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
