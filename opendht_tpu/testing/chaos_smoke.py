"""End-to-end adversarial chaos smoke (ISSUE-13, CI satellite).

Three tiers in one smoke, closing the produce→judge loop the round-9/12
observability stack was built for:

1. **Real-UDP partition + heal**: a scripted FaultPlan isolates the
   proxied node of a 4-node cluster (symmetric partition at the
   engine fault hooks — the same seam the virtual net uses).  Its gets
   fail, the availability SLO burns, ``GET /healthz`` degrades, a
   black-box bundle auto-captures on the unhealthy transition, and
   ``dhtmon --since`` flags the burn window.  Healing (plan disarmed,
   node re-bootstrapped) rolls the verdict back: /healthz 200,
   ``dhtmon --since`` clean.
2. **Virtual-net storm with the chaos-off pin**: the same seeded
   scenario run unarmed and with an armed-but-EMPTY FaultPlan delivers
   identical results with zero drops (chaos-off == baseline); then a
   real storm (per-link loss + dup + reorder rules, an asymmetric
   partition phase, join/leave storm steps) runs through its phases
   with per-rule drop accounting and the cluster still serves every
   key after the plan ends.
3. **Device swarm storm**: a 4096-node SwarmSim steps a scripted
   join/leave storm plus partition-and-heal on device; the
   lookup-success and replica-coverage invariants degrade during the
   cut and are restored after healing, deterministic under the seed.

Run directly (CI does)::

    python -m opendht_tpu.testing.chaos_smoke
"""

from __future__ import annotations

import sys
import time

from .. import chaos
from ..core.value import Value
from ..health import HEALTHY
from ..infohash import InfoHash
from ..runtime.config import Config, NodeStatus
from ..tools import dhtmon

N_NODES = 4
TICK = 0.25
OP_TIMEOUT = 30.0


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return pred()


# ------------------------------------------------------- 1: real-UDP tier
def real_udp_partition_heal() -> None:
    from ..proxy import DhtProxyServer
    from .network import DhtNetwork

    cfg = Config()
    cfg.health.period = TICK
    cfg.history.period = TICK
    # short burn windows so recovery rolls the latched SLO clean within
    # smoke time (the defaults keep a burn in the 600 s slow window for
    # ten minutes — correct in production, hostile to a CI smoke)
    cfg.health.fast_window = 3.0
    cfg.health.slow_window = 10.0
    net = DhtNetwork(N_NODES, config=cfg)
    runners = net.nodes
    proxy = None
    try:
        proxy = DhtProxyServer(runners[0], 0)
        assert net.wait_connected(), "cluster failed to connect"
        ep = "127.0.0.1:%d" % proxy.port

        keys = [InfoHash.get("chaos-smoke-%d" % i) for i in range(6)]
        for i, key in enumerate(keys):
            assert runners[1 + i % (N_NODES - 1)].put_sync(
                key, Value(b"cv-%d" % i), timeout=OP_TIMEOUT)
        assert runners[0].get_sync(keys[0], timeout=OP_TIMEOUT)
        time.sleep(4 * TICK)          # frames + healthy baseline
        assert runners[0].get_health()["verdict"] == HEALTHY
        pre_bundles = len(runners[0].get_bundles())

        # --- scripted partition: node 0 isolated via the harness's
        # public arm() (one injector, per-engine fault hooks; the cut
        # is enforced at each sender — netem egress semantics)
        plan = chaos.FaultPlan([chaos.Phase(
            "island", start=0.0, duration=None,
            partition=chaos.Partition(block=[("island", "mainland")],
                                      symmetric=True))])
        inj = net.arm(plan, groups={0: "island"},
                      default_group="mainland")

        fails = []
        for i in range(8):
            runners[0].get(InfoHash.get("chaos-miss-%d" % i),
                           lambda vals: True,
                           lambda ok, ns: fails.append(ok))
        assert _wait(lambda: len(fails) == 8, timeout=60.0), \
            "partitioned gets never completed (%d/8)" % len(fails)
        assert not any(fails), "gets succeeded across the partition"
        assert inj.dropped_by_rule().get("partition:island", 0) > 0

        assert _wait(lambda: runners[0].get_health()["verdict"]
                     == "unhealthy", timeout=30.0), \
            "verdict never burned: %r" % (runners[0].get_health(),)
        # /healthz degrades over the proxy
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen("http://%s/healthz" % ep,
                                        timeout=10) as resp:
                code = resp.status
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 503, "healthz should be 503 mid-partition"
        # black-box bundle auto-captured on the unhealthy transition
        assert _wait(lambda: len(runners[0].get_bundles()) > pre_bundles,
                     timeout=15.0), "no auto bundle after the burn"
        bundle = runners[0].get_bundles()[-1]
        assert bundle["transition"]["to"] == "unhealthy"
        # dhtmon --since flags the burn window
        rc = dhtmon.main(["--nodes", ep, "--min-success", "0.99",
                          "--since", "60"])
        assert rc != 0, "dhtmon --since missed the burn"

        # --- heal: plan disarmed through the harness, node
        # re-bootstrapped
        net.disarm()
        runners[0].bootstrap("127.0.0.1", runners[1].get_bound_port())
        assert _wait(lambda: runners[0].get_status()
                     is NodeStatus.CONNECTED, timeout=30.0), \
            "node never reconnected after heal"
        # healthy traffic again: the healed node serves stored values
        for key in keys:
            assert runners[0].get_sync(key, timeout=OP_TIMEOUT), \
                "healed node cannot read stored values"
        assert _wait(lambda: runners[0].get_health()["verdict"]
                     != "unhealthy", timeout=30.0), \
            "verdict never recovered: %r" % (runners[0].get_health(),)
        time.sleep(8 * TICK)          # roll the burn out of short window
        rc = dhtmon.main(["--nodes", ep, "--min-success", "0.99",
                          "--since", "1.0"])
        assert rc == 0, "dhtmon --since still alerting after recovery"
        print("chaos_smoke[udp]: OK — partition burned the SLO "
              "(healthz 503, bundle captured, dhtmon --since 1), heal "
              "recovered (healthz 200, dhtmon --since 0)")
    finally:
        if proxy is not None:
            proxy.stop()
        net.shutdown()


# ---------------------------------------------------- 2: virtual-net tier
def virtual_net_storm() -> None:
    from .virtual_net import VirtualNet

    def scenario(plan):
        net = VirtualNet(seed=31, plan=plan)
        seed = net.add_node()
        for _ in range(11):
            net.add_node()
        net.bootstrap_all(seed)
        assert net.run(60, net.all_connected)
        nodes = list(net.nodes.values())
        key = InfoHash.get("chaos-smoke-pin")
        nodes[2].put(key, Value(b"pin"))
        got, done = [], {}
        nodes[7].get(key, lambda vals: got.extend(vals) or True,
                     lambda ok, ns: done.update(ok=ok))
        assert net.run(60, lambda: "ok" in done)
        return [v.data for v in got], net.dropped, dict(net.dropped_by_rule)

    base = scenario(None)
    armed = scenario(chaos.FaultPlan([]))
    assert base == armed and base[1] == 0, (base, armed)

    # the storm: per-link loss + dup + reorder, a timed asymmetric
    # partition phase, and join/leave storm steps
    net = VirtualNet(seed=32)
    seed_node = net.add_node()
    for _ in range(23):
        net.add_node()
    net.bootstrap_all(seed_node)
    assert net.run(120, net.all_connected)
    nodes = list(net.nodes.values())
    keys = [InfoHash.get("storm-key-%d" % i) for i in range(4)]
    for i, k in enumerate(keys):
        done = {}
        nodes[2 + i].put(k, Value(b"storm-%d" % i),
                         lambda ok, ns, d=done: d.update(ok=ok))
        assert net.run(60, lambda d=done: "ok" in d) and done["ok"]

    half = [d for d in nodes[:12]]
    plan = chaos.FaultPlan([
        chaos.Phase("weather", start=0.0, duration=30.0, rules=[
            chaos.LinkRule(name="loss", loss=0.25),
            chaos.LinkRule(name="dup", dup=0.1),
            chaos.LinkRule(name="reorder", reorder=0.2,
                           reorder_delay=0.2)]),
        chaos.Phase("cut", start=5.0, duration=15.0,
                    partition=chaos.Partition(block=[("west", "east")])),
    ], seed=5)
    net.arm(plan)
    for d in nodes:
        net.set_group(d, "west" if d in half else "east")
    storm = chaos.Storm(leave_rate=0.15, join_rate=0.1)
    for _ in range(3):
        net.step_storm(storm, seed_node)
        net.settle(10.0)
    net.settle(15.0)              # plan phases over: healed
    for rule in ("loss", "partition:cut"):
        assert net.dropped_by_rule.get(rule, 0) > 0, \
            "%s never accounted: %r" % (rule, net.dropped_by_rule)
    assert net.injector.counts.get("dup", {}).get("dup", 0) > 0
    assert net.injector.counts.get("reorder", {}).get("reordered", 0) > 0
    # storm survival: every stored key still resolvable post-heal
    for i, k in enumerate(keys):
        got, done = [], {}
        survivor = [d for d in net.nodes.values()][5]
        survivor.get(k, lambda vals, g=got: g.extend(vals) or True,
                     lambda ok, ns, d=done: d.update(ok=ok))
        assert net.run(120, lambda d=done: "ok" in d), \
            "post-heal get %d never completed" % i
        assert any(v.data == b"storm-%d" % i for v in got), \
            "key %d lost in the storm" % i
    print("chaos_smoke[vnet]: OK — chaos-off == baseline pinned, storm "
          "dropped %r, all %d keys survived"
          % (net.dropped_by_rule, len(keys)))


# -------------------------------------------------------- 3: device swarm
def swarm_storm(n_nodes: int = 4096) -> None:
    from ..ops.swarm import SwarmSim

    plan = chaos.FaultPlan([
        chaos.Phase("storm", start=1.0, duration=3.0,
                    storm=chaos.Storm(leave_rate=0.1, join_rate=0.1)),
        chaos.Phase("refill", start=4.0, duration=3.0,
                    storm=chaos.Storm(join_rate=0.5)),
        chaos.Phase("split", start=8.0, duration=6.0,
                    partition=chaos.Partition(block=[("g0", "g1")],
                                              symmetric=True)),
    ], seed=3)
    sim = SwarmSim(plan, n_nodes=n_nodes, n_keys=48, n_groups=2,
                   seed=5, sweep_sample=32, repub_every=2)
    hist = sim.run(22)
    assert hist[0]["verdict"] == HEALTHY
    assert any(m["verdict"] != HEALTHY for m in hist[9:13]), \
        "partition never degraded the swarm invariants"
    last = hist[-1]
    assert last["verdict"] == HEALTHY, last
    assert last["lookup_success"] >= 0.95
    assert last["replica_coverage"] >= 0.95
    print("chaos_smoke[swarm]: OK — %d-node swarm degraded to %s mid-"
          "partition, healed to success=%.2f coverage=%.2f"
          % (n_nodes, min(m["verdict"] for m in hist[9:13]),
             last["lookup_success"], last["replica_coverage"]))


def main(argv=None) -> int:
    real_udp_partition_heal()
    virtual_net_storm()
    swarm_storm()
    return 0


if __name__ == "__main__":
    sys.exit(main())
