"""End-to-end per-op latency waterfall smoke (round 19, CI satellite).

Boots a 3-node real-UDP cluster + REST proxy, runs mixed put/get
traffic, and asserts what the unit tier cannot:

1. **The always-on stage histograms advance under real traffic**:
   ``dht_stage_seconds{stage=}`` counts for the admission queue, the
   device launch (compile or execute) and the scatter-back all move on
   the scraped ``GET /stats`` exposition, and the network hop stage
   (``rpc_wait``) moves off the real UDP RTTs.
2. **``GET /profile`` serves the waterfall over the proxy**: the JSON
   snapshot (stages + budgets + per-op records + live OPEN-bound
   comparison), the ``?fmt=folded`` flamegraph stacks as text, and a
   400 on an unknown ``fmt``.
3. **A hot-bucket exemplar resolves through the trace assembler**: a
   trace id stamped on a stage bucket by serving traffic reassembles
   into a span tree via :func:`trace_assembler.assemble_trace` — the
   histogram-to-trace pivot the round-19 acceptance demands.
4. **dhtmon gates on stage p95s**: with the threshold set strictly
   above the measured healthy baseline, ``--max-stage scatter_back=``
   exits 0; after an injected scatter-path stall (sleeping wave
   callbacks inflate the real per-wave scatter-back span — no clock
   mocking), the SAME threshold exits 1.
5. **The OPEN-bound tracker drops a well-formed settling record**:
   ``refresh()`` measures live series, every bound reports
   ``status="unsettled"`` on CPU, and ``write_record`` round-trips
   through JSON with metric + settle fields per bound.

Run directly (CI does)::

    python -m opendht_tpu.testing.waterfall_smoke
"""

from __future__ import annotations

import json
import socket
import sys
import tempfile
import time
import urllib.error
import urllib.request

from ..core.value import Value
from ..infohash import InfoHash
from ..runtime.config import Config, NodeStatus
from ..runtime.runner import DhtRunner, RunnerConfig
from ..tools import dhtmon
from ..waterfall import OPEN_BOUND_KEYS, STAGES
from . import health_monitor as hm
from . import trace_assembler as tra

N_NODES = 3
N_KEYS = 10
OP_TIMEOUT = 30.0
TICK = 0.25
STALL_S = 2.0


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _stage_counts(series: dict) -> dict:
    return {s: series.get('dht_stage_seconds_count{stage="%s"}' % s, 0.0)
            for s in STAGES}


def main(argv=None) -> int:
    from ..proxy import DhtProxyServer

    runners = []
    proxy = None
    try:
        for i in range(N_NODES):
            cfg = Config(node_id=InfoHash.get("waterfall-smoke-node-%d" % i))
            cfg.health.period = TICK
            cfg.waterfall.open_bound_period = TICK
            r = DhtRunner()
            r.run(0, RunnerConfig(dht_config=cfg))
            runners.append(r)
            if i == 0:
                proxy = DhtProxyServer(r, 0)
            else:
                r.bootstrap("127.0.0.1", runners[0].get_bound_port())
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners)), \
            "cluster failed to connect"
        ep = "127.0.0.1:%d" % proxy.port

        before = _stage_counts(hm.scrape_node(ep)["series"])

        # --- mixed traffic so every serving stage sees real work
        keys = [InfoHash.get("waterfall-smoke-%d" % i)
                for i in range(N_KEYS)]
        for i, key in enumerate(keys):
            assert runners[1 + i % (N_NODES - 1)].put_sync(
                key, Value(b"wf-%d" % i, value_id=i + 1),
                timeout=OP_TIMEOUT)
        for key in keys:
            assert runners[0].get_sync(key, timeout=OP_TIMEOUT)

        # --- 1: the stage histograms advanced on the scrape
        series = hm.scrape_node(ep)["series"]
        after = _stage_counts(series)
        assert after["queue_wait"] > before["queue_wait"], (before, after)
        assert after["scatter_back"] > before["scatter_back"], \
            (before, after)
        dev = (after["device_compile"] + after["device_launch"]) - \
            (before["device_compile"] + before["device_launch"])
        assert dev > 0, "device stage never observed: %r" % (after,)
        assert after["rpc_wait"] > before["rpc_wait"], \
            "real-UDP hops left rpc_wait untouched: %r" % (after,)

        # --- 2: GET /profile over the proxy: JSON, folded, 400
        with urllib.request.urlopen(
                "http://%s/profile" % ep, timeout=10) as r:
            prof = json.loads(r.read().decode())
        assert prof["enabled"] is True
        assert set(prof["stages"]) == set(STAGES), sorted(prof["stages"])
        assert prof["ops"], "no per-op decomposition records"
        for op in prof["ops"]:
            s = sum(op["stages"].values())
            assert s <= op["end_to_end"] + 1e-6, op
        ob = prof.get("open_bounds")
        assert ob and set(ob["bounds"]) == set(OPEN_BOUND_KEYS), ob
        with urllib.request.urlopen(
                "http://%s/profile?fmt=folded" % ep, timeout=10) as r:
            assert r.headers.get_content_type() == "text/plain"
            folded = r.read().decode()
        assert any(ln.startswith("dht;op;") for ln in folded.splitlines()), \
            folded
        try:
            urllib.request.urlopen(
                "http://%s/profile?fmt=bogus" % ep, timeout=10)
            raise AssertionError("bad fmt did not 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400, e.code

        # --- 3: a stage-bucket exemplar pivots into a full trace
        tid = None
        for s in STAGES:
            for _le, _v, t in prof["stages"][s].get("exemplars", []):
                if t:
                    tid = t
                    break
            if tid:
                break
        assert tid, "no stage bucket carried an exemplar trace id"
        trace = tra.assemble_trace(runners, tid)
        assert trace["spans"] >= 1, trace
        assert trace["roots"], "exemplar trace did not reassemble: %r" % (
            trace,)

        # --- 4: dhtmon --max-stage: 0 healthy, 1 under an injected
        # stall.  The healthy baseline is NOT tiny on cold CPU runs
        # (first-wave jit compiles run inside the scatter callbacks),
        # so the gate sits strictly above the measured baseline — the
        # 0 -> 1 flip is then attributable to the stall alone.
        def _scatter_p95() -> float:
            p95s = dhtmon._stage_p95s(hm.scrape_node(ep)["series"])
            return p95s.get("scatter_back") or 0.0

        gate = _scatter_p95() + STALL_S / 2.0
        rc = dhtmon.main(["--nodes", ep, "--min-success", "0.5",
                          "--max-stage", "scatter_back=%g" % gate])
        assert rc == 0, "healthy cluster tripped the stage gate (rc=%d)" \
            % rc
        # stall the scatter path for real: sleeping wave callbacks run
        # inside the scatter loop, so the per-wave scatter_back span
        # genuinely inflates — no clock mocking.  Each stall entry rides
        # its own wave; inject until the scraped p95 crosses the gate.
        wb = runners[0]._dht.wave_builder
        for i in range(12):
            if _scatter_p95() > gate:
                break
            done = []
            wb.submit(InfoHash.get("waterfall-stall-%d" % i),
                      socket.AF_INET, 8,
                      lambda nodes, done=done: (time.sleep(STALL_S),
                                                done.append(1)),
                      kind="stall")
            assert _wait(lambda: done, timeout=15.0), \
                "stall wave %d never scattered" % i
        assert _scatter_p95() > gate, \
            "injected stalls never moved the scatter_back p95"
        rc = dhtmon.main(["--nodes", ep, "--min-success", "0.5",
                          "--max-stage", "scatter_back=%g" % gate])
        assert rc == 1, "dhtmon missed the scatter stall (rc=%d)" % rc

        # --- 5: OPEN-bound settling record, live off this traffic
        tracker = runners[0]._open_bounds
        assert tracker is not None
        measured = tracker.refresh()
        # live serving traffic lights up the op-latency and ingest
        # bounds; the mode="single"/"tp" wave bounds only measure under
        # the benchmark drivers and stay at the -1 "no data" sentinel
        assert measured["cache_flood_p50"]["value"] is not None, measured
        assert measured["ingest_wave_occupancy"]["value"] is not None, \
            measured
        n_live = sum(1 for b in measured.values()
                     if b["value"] is not None)
        assert n_live >= 2, measured
        with tempfile.TemporaryDirectory(prefix="odt-wf-smoke-") as d:
            path = tracker.write_record(d)
            assert path, "settling record not written"
            with open(path) as f:
                doc = json.load(f)
        assert doc["name"] == "open_bounds"
        assert doc["status"] == "unsettled", doc["status"]  # CPU run
        assert doc["bounds"], doc
        for k, b in doc["bounds"].items():
            assert k in OPEN_BOUND_KEYS, k
            assert b["metric"] and b["settle"], b
            assert b["status"] == "unsettled", b
        n_gauges = sum(1 for name in series
                       if name.startswith("dht_open_bound{"))
        assert n_gauges == len(OPEN_BOUND_KEYS), \
            "expected %d open-bound gauges, scraped %d" % (
                len(OPEN_BOUND_KEYS), n_gauges)

        print("waterfall_smoke: OK — stages advanced (device +%d), "
              "/profile json+folded+400, exemplar %s -> %d spans, "
              "dhtmon --max-stage 0 then 1 (gate %.3fs, stalled p95 "
              "%.3fs), %d/%d bounds measured unsettled"
              % (int(dev), tid[:8], trace["spans"], gate,
                 _scatter_p95(), len(doc["bounds"]),
                 len(OPEN_BOUND_KEYS)))
        return 0
    finally:
        if proxy is not None:
            proxy.stop()
        for r in runners:
            r.join()


if __name__ == "__main__":
    sys.exit(main())
