"""Burst-ingest CI smoke (round-12 satellite, ISSUE-7).

Boots a real-UDP cluster + REST proxy, fires concurrent gets/puts/
listens from threads — the traffic shape the continuous-batching wave
builder exists for — and asserts the three things the unit tier cannot:

1. **Live coalescing actually happens**: the mean of the new
   ``dht_ingest_wave_occupancy`` histogram is > 1 under concurrent
   load (ops genuinely shared device launches; nothing was shed), and
   the ``dht_ingest_*`` series ride the proxy's Prometheus ``GET
   /stats`` exposition (satellite 6's export surface).
2. **Result equivalence**: the identical workload rerun with
   ``ingest_batching="off"`` (the per-op dispatch escape hatch, on the
   same deterministic node ids) returns the same values to every get,
   delivers the same values to every listener, and leaves the same
   per-node storage state.
3. **Backpressure discipline**: nothing was dropped mid-search — the
   shed counter stayed zero for the whole admitted workload.

Run directly (CI does)::

    python -m opendht_tpu.testing.ingest_smoke
"""

from __future__ import annotations

import sys
import threading
import time
import urllib.request

from .. import telemetry
from ..core.value import Value
from ..infohash import InfoHash
from ..runtime.config import Config, NodeStatus
from ..runtime.runner import DhtRunner, RunnerConfig

N_NODES = 3
N_KEYS = 16
OP_TIMEOUT = 30.0


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _run_phase(batching: str) -> dict:
    """One full cluster lifecycle under the given ingest mode; returns
    the result-equivalence record (get results, listen deliveries,
    per-node storage) plus the phase's ingest telemetry."""
    reg = telemetry.get_registry()
    reg.reset()
    keys = [InfoHash.get("ingest-smoke-%d" % i) for i in range(N_KEYS)]
    listen_keys = keys[:2]

    runners = []
    proxy = None
    try:
        for i in range(N_NODES):
            cfg = Config(node_id=InfoHash.get("ingest-smoke-node-%d" % i),
                         ingest_batching=batching)
            r = DhtRunner()
            r.run(0, RunnerConfig(dht_config=cfg))
            if runners:
                r.bootstrap("127.0.0.1", runners[0].get_bound_port())
            runners.append(r)
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners[1:])), \
            "cluster failed to connect (batching=%s)" % batching

        from ..proxy import DhtProxyServer
        proxy = DhtProxyServer(runners[0], 0)

        # standing listeners (registered before the burst; their values
        # must flow regardless of ingest mode)
        heard: dict = {}
        heard_lock = threading.Lock()

        def on_values(vals, expired):
            if not expired:
                with heard_lock:
                    for v in vals:
                        heard[v.data] = True
            return True

        tokens = [runners[1].listen(k, on_values) for k in listen_keys]
        for t in tokens:
            assert t.result(OP_TIMEOUT) != 0, "listen shed at admission"

        # ---- concurrent burst: every op posted before any completes,
        # from several threads, so the runner drains them in shared
        # pumps and the wave builder sees real concurrency
        put_done = {i: threading.Event() for i in range(N_KEYS)}
        put_ok = {}

        def fire_put(i):
            src = runners[1 + (i % (N_NODES - 1))]
            src.put(keys[i], Value(b"ingest-%d" % i, value_id=i + 1),
                    lambda ok, ns, _i=i: (put_ok.setdefault(_i, ok),
                                          put_done[_i].set()))

        threads = [threading.Thread(target=fire_put, args=(i,))
                   for i in range(N_KEYS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(N_KEYS):
            assert put_done[i].wait(OP_TIMEOUT), "put %d stalled" % i
            assert put_ok[i], "put %d failed (batching=%s)" % (i, batching)

        got: dict = {}
        get_done = {i: threading.Event() for i in range(N_KEYS)}

        def fire_get(i):
            vals: list = []
            runners[0].get(
                keys[i], lambda vs, _a=vals: _a.extend(vs) or True,
                lambda ok, ns, _i=i, _a=vals: (
                    got.setdefault(_i, sorted(v.data for v in _a)),
                    get_done[_i].set()))

        threads = [threading.Thread(target=fire_get, args=(i,))
                   for i in range(N_KEYS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i in range(N_KEYS):
            assert get_done[i].wait(OP_TIMEOUT), "get %d stalled" % i
            assert got[i] == [b"ingest-%d" % i], \
                "get %d returned %r (batching=%s)" % (i, got[i], batching)

        assert _wait(lambda: len(heard) >= len(listen_keys)), \
            "listeners missed burst values: %r" % sorted(heard)

        # ---- phase telemetry + the proxy export surface
        snap = reg.snapshot()
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/stats" % proxy.port, timeout=10) as r:
            prom = r.read().decode()
        import json as _json
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/" % proxy.port, timeout=10) as r:
            node_info = _json.loads(r.read().decode())

        # ---- per-node storage state (created stamps differ run to
        # run; the packed value payloads must not)
        storage = []
        for r in runners:
            exported = sorted(
                (key.hex(), sorted(bytes(p) for _c, p in vals))
                for key, vals in r.export_values())
            storage.append(exported)
        return {
            "gets": got,
            "heard": sorted(heard),
            "storage": storage,
            "snapshot": snap,
            "prometheus": prom,
            "node_info": node_info,
        }
    finally:
        if proxy is not None:
            proxy.stop()
        for r in runners:
            r.join()


def main(argv=None) -> int:
    batched = _run_phase("on")

    occ = batched["snapshot"]["histograms"].get(
        "dht_ingest_wave_occupancy", {"count": 0, "sum": 0.0})
    assert occ["count"] > 0, "no ingest waves fired under load"
    mean_occ = occ["sum"] / occ["count"]
    assert mean_occ > 1.0, (
        "no live coalescing: mean wave occupancy %.3f <= 1 over %d waves"
        % (mean_occ, occ["count"]))
    sheds = sum(v for k, v in batched["snapshot"]["counters"].items()
                if k.startswith("dht_ingest_sheds_total"))
    assert sheds == 0, "admitted workload was shed (%d drops)" % sheds
    for series in ("dht_ingest_queue_depth", "dht_ingest_wave_occupancy",
                   "dht_ingest_queue_seconds", "dht_ingest_waves_total"):
        assert series in batched["prometheus"], \
            "proxy /stats missing %s" % series
    assert batched["node_info"].get("ingest", {}).get("batching") == "on", \
        "proxy GET / missing the ingest section"

    off = _run_phase("off")
    occ_off = off["snapshot"]["histograms"].get(
        "dht_ingest_wave_occupancy", {"count": 0})
    assert occ_off["count"] == 0, "batching=off must never build waves"

    # ---- the acceptance-criteria equivalence: same values returned,
    # same listener deliveries, same storage state
    assert batched["gets"] == off["gets"], "get results diverged"
    assert batched["heard"] == off["heard"], "listen deliveries diverged"
    assert batched["storage"] == off["storage"], (
        "per-node storage state diverged between batched and per-op "
        "dispatch")

    waves = int(batched["snapshot"]["counters"].get(
        "dht_ingest_waves_total", 0))
    print("ingest_smoke: OK — %d waves, mean occupancy %.2f (p-ops %d), "
          "0 sheds, batched == per-op on %d gets / %d listens / %d nodes"
          % (waves, mean_occ, N_KEYS * 2 + len(batched["heard"]),
             N_KEYS, len(batched["heard"]), N_NODES))
    return 0


if __name__ == "__main__":
    sys.exit(main())
