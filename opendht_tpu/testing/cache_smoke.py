"""End-to-end hot-cache smoke (ISSUE-11 CI satellite).

Boots a 3-node real-UDP cluster + REST proxy (node 0 caches; nodes 1-2
run cache-off — the live halves of the cache-on == cache-off pin) and
asserts the five things the unit tier cannot:

1. **The observe→act loop closes on live traffic**: a Zipf single-key
   flood through node 0's wave builder surfaces the hot key
   (``hot_key_emerged`` in the ring), the cache ADMITS it off the
   observatory tick (``cache_admit`` event, ``GET /cache`` occupancy),
   and subsequent hot gets SERVE FROM CACHE — ``dht_cache_hits_total``
   advances while the ingest wave occupancy attributable to the hot key
   stays ~0 (the histogram's total barely moves under a pure hot-get
   burst).
2. **Hit ratio under flood**: the windowed ``dht_cache_hit_ratio``
   reaches >= 0.9 and ``dhtmon --min-cache-hit`` exits 0; a cold-key
   miss storm then drags the next window down and the same gate exits 1.
3. **Freshness**: a fresh put to the hot key invalidates the entry
   (``dht_cache_invalidations_total`` advances, occupancy drops) and the
   NEXT get sees the new value — never a stale hit.
4. **Result equivalence on every surface**: the cache-served value set
   on node 0 equals the full-path set on cache-off node 1 (runner ops),
   equals the proxy REST ``GET /{hash}`` stream, before AND after the
   invalidating put.
5. **Listeners are untouched**: a listener on the hot key still
   delivers a post-warm put (listens are never cache-served).

Run directly (CI does)::

    python -m opendht_tpu.testing.cache_smoke
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request

from ..core.value import Value
from ..infohash import InfoHash
from ..runtime.config import Config, NodeStatus
from ..runtime.runner import DhtRunner, RunnerConfig
from ..tools import dhtmon

N_NODES = 3
OP_TIMEOUT = 30.0


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _get_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/%s" % (port, path), timeout=10) as r:
        return json.loads(r.read().decode())


def _vals(values) -> set:
    return set((v.id, bytes(v.data)) for v in values)


def main(argv=None) -> int:
    from ..proxy import DhtProxyServer

    runners = []
    proxy = None
    try:
        for i in range(N_NODES):
            cfg = Config(node_id=InfoHash.get("cache-smoke-node-%d" % i))
            # fast observatory cadence so admission converges in
            # seconds (the keyspace-smoke settings); node 0 caches,
            # the others are the cache-off equivalence arm
            cfg.keyspace.tick = 0.5
            cfg.keyspace.decay = 0.98
            cfg.keyspace.sample_stride = 1
            cfg.keyspace.hot_min_count = 16
            cfg.keyspace.min_observed = 24
            cfg.cache.enabled = (i == 0)
            r = DhtRunner()
            r.run(0, RunnerConfig(dht_config=cfg))
            runners.append(r)
            if i == 0:
                proxy = DhtProxyServer(r, 0)
            else:
                r.bootstrap("127.0.0.1", runners[0].get_bound_port())
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners)), \
            "cluster failed to connect"

        hot = InfoHash.get("cache-smoke-hot")
        assert runners[0].put_sync(hot, Value(b"hot-v1", value_id=11),
                                   timeout=OP_TIMEOUT)
        before = _vals(runners[0].get_sync(hot, timeout=OP_TIMEOUT))
        assert before, "hot key unreadable before the flood"

        def metrics() -> dict:
            return runners[0].get_metrics()

        def counter(m, name, default=0.0):
            return float(m.get("counters", {}).get(name, default))

        def gauge(m, name, default=-1.0):
            return float(m.get("gauges", {}).get(name, default))

        node0 = str(runners[0].get_node_id())
        hits_key = 'dht_cache_hits_total{node="%s"}' % node0

        # --- 1: flood until the loop closes (hot detected -> admitted
        # -> a hot get actually SERVES from cache)
        def cache_serving() -> bool:
            return counter(metrics(), hits_key) > 0
        for _ in range(60):
            if cache_serving():
                break
            for _ in range(8):
                runners[0].get_sync(hot, timeout=OP_TIMEOUT)
        assert cache_serving(), \
            "hot gets never served from cache: %r" % (
                runners[0].get_cache(),)
        fr = runners[0].get_flight_recorder(name="hot_key_emerged")
        assert any(e["attrs"].get("key") == hot.hex()
                   for e in fr["events"]), "no hot_key_emerged event"
        fr = runners[0].get_flight_recorder(name="cache_admit")
        assert any(e["attrs"].get("key") == hot.hex()
                   for e in fr["events"]), "no cache_admit event"
        csnap = _get_json(proxy.port, "cache")
        assert csnap["enabled"] and csnap["occupancy"] >= 1, csnap
        assert hot.hex() in [e["key"] for e in csnap["entries"]], csnap

        # --- hot gets skip the [Q] lookup launch: under a pure hot-get
        # burst the hit counter advances ~1:1 while the ingest wave
        # occupancy histogram's total (entries that actually JOINED a
        # launch) stays ~0 — background maintenance may add a few
        m0 = metrics()
        occ_key = "dht_ingest_wave_occupancy"
        occ0 = float(m0.get("histograms", {}).get(occ_key, {})
                     .get("sum", 0.0))
        hits0 = counter(m0, hits_key)
        burst = 24
        for _ in range(burst):
            runners[0].get_sync(hot, timeout=OP_TIMEOUT)
        m1 = metrics()
        occ1 = float(m1.get("histograms", {}).get(occ_key, {})
                     .get("sum", 0.0))
        hits1 = counter(m1, hits_key)
        assert hits1 - hits0 >= burst * 0.9, \
            "burst not cache-served: hits %+g" % (hits1 - hits0)
        assert occ1 - occ0 <= burst * 0.25, \
            "hot gets still joined lookup launches: occupancy %+g " \
            "over a %d-get burst" % (occ1 - occ0, burst)

        # --- 2: hit ratio >= 0.9 under the flood, dhtmon gates on it.
        # Keep hot gets flowing so the NEXT observatory window rolls
        # with a hot-dominated probe mix.
        def ratio() -> float:
            return gauge(metrics(),
                         'dht_cache_hit_ratio{node="%s"}' % node0)
        for _ in range(40):
            if ratio() >= 0.9:
                break
            for _ in range(8):
                runners[0].get_sync(hot, timeout=OP_TIMEOUT)
        flood_ratio = ratio()
        assert flood_ratio >= 0.9, \
            "flood hit ratio %.3f < 0.9" % flood_ratio
        rc = dhtmon.main(["--nodes", "127.0.0.1:%d" % proxy.port,
                          "--min-cache-hit", "0.9"])
        assert rc == 0, "dhtmon flagged a >=0.9 hit ratio (rc=%d)" % rc

        # --- miss storm: eligible cold-key gets drag the next window's
        # ratio down; the same gate violates
        def miss_window() -> bool:
            r_ = ratio()
            return 0.0 <= r_ < 0.5
        i = 0
        for _ in range(40):
            if miss_window():
                break
            for _ in range(8):
                runners[0].get_sync(InfoHash.get("cache-miss-%d" % i),
                                    timeout=OP_TIMEOUT)
                i += 1
        assert miss_window(), "miss storm never dropped the ratio: %r" \
            % ratio()
        rc = dhtmon.main(["--nodes", "127.0.0.1:%d" % proxy.port,
                          "--min-cache-hit", "0.9"])
        assert rc == 1, "dhtmon missed the miss storm (rc=%d)" % rc

        # --- 4a: equivalence before the put — cache-served node 0 ==
        # full-path cache-off node 1 == the proxy REST stream
        v0 = _vals(runners[0].get_sync(hot, timeout=OP_TIMEOUT))
        v1 = _vals(runners[1].get_sync(hot, timeout=OP_TIMEOUT))
        assert v0 == v1 == before, (v0, v1, before)
        req = urllib.request.Request(
            "http://127.0.0.1:%d/%s" % (proxy.port, hot.hex()))
        with urllib.request.urlopen(req, timeout=30) as resp:
            rest = [json.loads(ln) for ln in resp.read().splitlines() if ln]
        assert set(int(o["id"]) for o in rest) \
            == set(i_ for i_, _ in v0), rest

        # --- 5: a listener on the hot key still delivers a fresh put
        # (listens are never cache-served)
        got = []
        tok = runners[0].listen(hot, lambda vals, exp: got.extend(
            v.id for v in vals if not exp) or True)
        tok.result(10.0)

        # --- 3: freshness — a fresh put invalidates; the next get
        # sees the new value on EVERY surface, never the stale set
        m2 = metrics()
        inval0 = counter(m2, 'dht_cache_invalidations_total{node="%s"}'
                         % node0)
        assert runners[1].put_sync(hot, Value(b"hot-v2", value_id=22),
                                   timeout=OP_TIMEOUT)
        assert _wait(lambda: counter(
            metrics(), 'dht_cache_invalidations_total{node="%s"}'
            % node0) > inval0, timeout=15.0), \
            "put never invalidated the cached hot key"
        want = {(11, b"hot-v1"), (22, b"hot-v2")}

        def fresh_visible() -> bool:
            return _vals(runners[0].get_sync(
                hot, timeout=OP_TIMEOUT)) == want
        assert _wait(fresh_visible, timeout=20.0), \
            "stale cache hit after a fresh put: %r" % (
                _vals(runners[0].get_sync(hot, timeout=OP_TIMEOUT)),)
        assert _vals(runners[1].get_sync(hot, timeout=OP_TIMEOUT)) == want
        assert _wait(lambda: 22 in got, timeout=15.0), \
            "listener never saw the post-warm put: %r" % (got,)
        runners[0].cancel_listen(hot, tok)

        csnap = _get_json(proxy.port, "cache")
        print("cache_smoke: OK — hot key %s admitted+served (hits %d, "
              "flood ratio %.2f -> dhtmon 0/1), put invalidated "
              "(%d invalidations) with fresh values on all surfaces"
              % (hot.hex()[:12], csnap["hits"], flood_ratio,
                 csnap["invalidations"]))
        return 0
    finally:
        if proxy is not None:
            proxy.stop()
        for r in runners:
            r.join()


if __name__ == "__main__":
    sys.exit(main())
