"""Cluster timeline assembly over per-node metrics histories (round 17).

The round-9 trace assembler stitches ONE operation's span tree across
the cluster; this module stitches the cluster's METRICS TIMELINE: every
node's flight-data-recorder frames (opendht_tpu/history.py) merged into
one time-ordered sequence, so a soak harness or post-mortem can answer
"what was the whole cluster doing between t0 and t1" — the windowed
view ``dhtmon --since`` gates on instead of scrape-diff-scrape.

Sources accepted by :func:`assemble_timeline` (mirroring the trace
assembler's duck-typing):

- a ``GET /history`` document (``testing/health_monitor.scrape_history``
  stamps ``scraped_at`` so skew is estimable),
- a post-mortem black-box bundle (``history.BUNDLE_KIND``; its flight
  events — ``health_transition``, ``slo_violation``, ... — join the
  timeline alongside the frames),
- a ``DhtRunner``-like (``get_history()``), a raw
  :class:`~opendht_tpu.history.MetricsHistory`, or a plain frame list.

**Skew**: each scrape document carries the serving node's wall clock
(``time``) next to the scraper's (``scraped_at``); their difference
estimates that node's clock offset and every frame/event timestamp is
shifted by it before merging (same-host clusters estimate ~0).
**Monotonicity** is checked per node like the round-9 span-tree check:
frame ``seq``/``t`` must be non-decreasing within one node's history —
violations are REPORTED, not dropped (a post-mortem tool must degrade,
not lie).

:func:`window_series` reduces a timeline window back to the summed
``{series: value}`` shape the dhtmon invariants read — the same
one-delta-codepath contract as ``history.frames_to_series``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..history import BUNDLE_KIND, frames_to_series

#: tolerance for per-node timestamp monotonicity: frames stamp
#: ``time.time()`` once per tick, so only scheduling jitter remains
#: (the round-9 CLOCK_SLACK, relaxed to the tick cadence)
CLOCK_SLACK = 0.050


def _extract(source) -> dict:
    """Normalize one source into ``{"node", "frames", "events",
    "skew"}``."""
    if isinstance(source, dict):
        if source.get("kind") == BUNDLE_KIND:
            hist = source.get("history") or {}
            return {
                "node": source.get("node_id", ""),
                "frames": list(hist.get("frames") or []),
                "events": list((source.get("flight_recorder") or {})
                               .get("events") or []),
                "skew": _skew(source),
            }
        # a GET /history document (or anything frame-shaped)
        return {
            "node": source.get("node_id", source.get("endpoint", "")),
            "frames": list(source.get("frames") or []),
            "events": [],
            "skew": _skew(source),
        }
    if hasattr(source, "get_history"):            # DhtRunner-like
        return _extract(source.get_history())
    if hasattr(source, "frames"):                 # MetricsHistory
        return {"node": getattr(source, "node", ""),
                "frames": source.frames(), "events": [], "skew": 0.0}
    return {"node": "", "frames": list(source), "events": [],
            "skew": 0.0}                          # raw frame list


def _skew(doc: dict) -> float:
    """Serving-node wall clock minus scraper wall clock at scrape time
    — 0.0 when either stamp is missing (in-process sources share the
    clock)."""
    t = doc.get("time")
    at = doc.get("scraped_at")
    if t is None or at is None:
        return 0.0
    return float(t) - float(at)


def assemble_timeline(sources) -> dict:
    """Merge every source's frames (and bundle flight events) into one
    skew-adjusted, time-ordered cluster timeline.

    Returns ``{"nodes", "frames", "events", "skew", "violations",
    "span"}`` — frames/events each gain ``"node"`` and an adjusted
    ``"t_adj"`` (original timestamps untouched); ``violations`` lists
    per-node monotonicity breaks (non-decreasing ``seq``/``t``, the
    round-9 contract); ``span`` is the adjusted ``[t_min, t_max]`` the
    timeline covers (None when empty)."""
    nodes: List[str] = []
    frames: List[dict] = []
    events: List[dict] = []
    skews: Dict[str, float] = {}
    violations: List[str] = []
    for si, source in enumerate(sources):
        ex = _extract(source)
        node = ex["node"] or ("source-%d" % si)
        nodes.append(node)
        skews[node] = ex["skew"]
        prev_seq: Optional[int] = None
        prev_t: Optional[float] = None
        for f in ex["frames"]:
            seq = f.get("seq")
            t = f.get("t", 0.0)
            if prev_seq is not None and seq is not None \
                    and seq <= prev_seq:
                violations.append(
                    "node %s: frame seq %s not after %s"
                    % (node, seq, prev_seq))
            if prev_t is not None and t < prev_t - CLOCK_SLACK:
                violations.append(
                    "node %s: frame at %.3f is %.3fs before its "
                    "predecessor" % (node, t, prev_t - t))
            prev_seq = seq if seq is not None else prev_seq
            prev_t = max(prev_t, t) if prev_t is not None else t
            g = dict(f)
            g["node"] = node
            g["t_adj"] = t - ex["skew"]
            frames.append(g)
        for e in ex["events"]:
            g = dict(e)
            g["node"] = g.get("node") or node
            g["t_adj"] = e.get("t", 0.0) - ex["skew"]
            events.append(g)
    frames.sort(key=lambda f: f["t_adj"])
    events.sort(key=lambda e: e["t_adj"])
    ts = [f["t_adj"] for f in frames] + [e["t_adj"] for e in events]
    return {
        "nodes": nodes,
        "frames": frames,
        "events": events,
        "skew": skews,
        "violations": violations,
        "span": [min(ts), max(ts)] if ts else None,
    }


def window_series(timeline: dict, t0: Optional[float] = None,
                  t1: Optional[float] = None) -> Dict[str, float]:
    """Summed ``{series: value}`` over the timeline's frames with
    adjusted time in ``(t0, t1]`` — the exact map
    ``testing/health_monitor.lookup_success`` / ``cluster_quantile``
    read, so cluster invariants evaluate over an assembled timeline
    through the same code path dhtmon uses."""
    frames = [f for f in timeline["frames"]
              if (t0 is None or f["t_adj"] > t0)
              and (t1 is None or f["t_adj"] <= t1)]
    return frames_to_series(frames)


def find_events(timeline: dict, name: str) -> List[dict]:
    """Timeline events whose name contains ``name`` (the flight
    recorder's substring convention) — e.g.
    ``find_events(tl, "health_transition")`` locates every verdict
    change across the cluster, in time order."""
    return [e for e in timeline["events"] if name in e.get("ev", "")]
