"""End-to-end health observatory smoke (ISSUE-9 CI satellite).

Boots a 3-node real-UDP cluster + REST proxy and asserts the four
things the unit tier cannot:

1. **Readiness flips through bootstrap**: the first node's
   ``GET /healthz`` is 503 while it is alone/disconnected and flips to
   200 (verdict healthy/degraded) once the cluster connects.
2. **Cluster invariants hold when healthy**: ``dhtmon`` exits 0 with
   ``--require-ready --min-success``, and the batched replica-coverage
   probe (ONE closest-8 launch for the whole sampled key set) reports
   full coverage of the stored keys on the live cluster.
3. **A real degradation degrades the verdict**: choking ingest
   admission (queue bound to zero — every new op sheds, the
   backpressure failure mode of round 12) drives the availability SLO
   into fast burn; the verdict leaves ``healthy``, a
   ``health_transition`` event (and an ``slo_violation``) lands in the
   flight recorder, and ``/healthz`` answers 503 again.
4. **dhtmon exits non-zero on the violated cluster invariant** (global
   lookup success below threshold).

Run directly (CI does)::

    python -m opendht_tpu.testing.health_smoke
"""

from __future__ import annotations

import json
import sys
import time
import urllib.error
import urllib.request

from ..core.value import Value
from ..infohash import InfoHash
from ..runtime.config import Config, NodeStatus
from ..runtime.runner import DhtRunner, RunnerConfig
from ..tools import dhtmon
from . import health_monitor as hm

N_NODES = 3
N_KEYS = 12
OP_TIMEOUT = 30.0


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _healthz(port: int):
    """(status_code, body_dict) of GET /healthz."""
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/healthz" % port, timeout=10) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}")


def main(argv=None) -> int:
    from ..proxy import DhtProxyServer

    # fast health cadence so the smoke converges in seconds; the SLO
    # set stays the default (99% availability on get/put/listen)
    runners = []
    proxy = None
    try:
        for i in range(N_NODES):
            cfg = Config(node_id=InfoHash.get("health-smoke-node-%d" % i))
            cfg.health.period = 0.25
            r = DhtRunner()
            r.run(0, RunnerConfig(dht_config=cfg))
            runners.append(r)
            if i == 0:
                proxy = DhtProxyServer(r, 0)
                # --- 1a: alone + disconnected => not ready (503)
                assert _wait(lambda: _healthz(proxy.port)[0] == 503,
                             timeout=10.0), \
                    "lone node reported ready before bootstrap"
                code, body = _healthz(proxy.port)
                assert body["ready"] is False, body
                assert body["verdict"] in ("unknown", "unhealthy"), body
            else:
                r.bootstrap("127.0.0.1", runners[0].get_bound_port())
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners)), \
            "cluster failed to connect"

        # --- 1b: connected => readiness flips to 200
        assert _wait(lambda: _healthz(proxy.port)[0] == 200), \
            "healthz did not flip to 200 after bootstrap: %r" \
            % (_healthz(proxy.port),)
        code, body = _healthz(proxy.port)
        assert body["ready"] is True and \
            body["verdict"] in ("healthy", "degraded"), body
        # readiness (200) flips at "degraded" already; the connectivity
        # signal itself recovers to "healthy" one hysteresis tick later
        # — wait for the level, don't assert one snapshot
        assert _wait(lambda: _healthz(proxy.port)[1]["health"]["signals"]
                     ["connectivity"]["level"] == "healthy"), \
            "connectivity signal never recovered: %r" \
            % (_healthz(proxy.port)[1]["health"]["signals"],)

        # --- traffic so the SLOs and the coverage probe have data
        keys = [InfoHash.get("health-smoke-%d" % i) for i in range(N_KEYS)]
        for i, key in enumerate(keys):
            assert runners[1 + i % (N_NODES - 1)].put_sync(
                key, Value(b"hv-%d" % i, value_id=i + 1),
                timeout=OP_TIMEOUT)
        for key in keys:
            assert runners[0].get_sync(key, timeout=OP_TIMEOUT)

        # --- 2a: replica coverage on the live cluster — every stored
        # key's true closest-8 (one batched launch; 3 nodes < 8, so
        # every node is an expected replica) actually holds the value
        cov = hm.replica_coverage(runners, sample_max=N_KEYS)
        assert cov["keys"] > 0, "probe sampled no stored keys"
        assert cov["mean_coverage"] is not None \
            and cov["mean_coverage"] >= 0.5, cov
        # --- 2b: dhtmon green on the healthy cluster
        rc = dhtmon.main(["--nodes", "127.0.0.1:%d" % proxy.port,
                          "--min-success", "0.99", "--require-ready",
                          "--alert", "p99=%g" % (OP_TIMEOUT * 4)])
        assert rc == 0, "dhtmon flagged a healthy cluster (rc=%d)" % rc

        # --- 3: inject a real degradation — choke ingest admission on
        # node 0 so every NEW op sheds at the round-12 backpressure
        # boundary (the queue-bound failure mode), which fails the ops
        # and fast-burns the availability SLO
        wb = runners[0]._dht.wave_builder
        saved_max = wb.queue_max
        wb.queue_max = 0
        fails = []
        for i in range(10):
            runners[0].get(keys[i % N_KEYS], lambda vals: True,
                           lambda ok, ns: fails.append(ok))
        assert _wait(lambda: len(fails) == 10), "shed gets never completed"
        assert not any(fails), "gets unexpectedly succeeded while choked"
        # wait for the SPECIFIC injected failure — the get-availability
        # SLO fast-burning to unhealthy — not just any verdict motion
        # (an unrelated signal wobble must not satisfy this check)
        assert _wait(lambda: runners[0].get_health()["slo"].get(
            "get_availability", {}).get("level") == "unhealthy",
            timeout=15.0), \
            "get SLO never fast-burned: %r" % (runners[0].get_health(),)
        rep = runners[0].get_health()
        assert rep["verdict"] == "unhealthy", rep
        assert "get_availability" in rep["causes"], rep
        # the degradation is trace-correlatable: health_transition and
        # slo_violation events in the flight recorder (name-filtered
        # dump — the ISSUE-9 satellite surface)
        fr = runners[0].get_flight_recorder(name="health_transition")
        assert any(e["attrs"].get("to") == "unhealthy"
                   for e in fr["events"]), fr["events"]
        fr = runners[0].get_flight_recorder(name="slo_violation")
        assert fr["events"], "no slo_violation event recorded"
        code, body = _healthz(proxy.port)
        assert code == 503 and body["verdict"] == "unhealthy", (code, body)

        # --- 4: dhtmon exits non-zero on the violated cluster
        # invariant (global lookup success dropped below threshold)
        rc = dhtmon.main(["--nodes", "127.0.0.1:%d" % proxy.port,
                          "--min-success", "0.99"])
        assert rc == 1, "dhtmon missed the success-rate violation " \
            "(rc=%d)" % rc
        wb.queue_max = saved_max
        # windowed invariant (review finding): the since-boot ratio
        # remembers the choke forever, but a windowed dhtmon evaluates
        # only recent traffic.  Since round 17 the window reads the
        # LAST 1 s of each node's history frames (no wait inside
        # dhtmon), so first let the burn roll out of that window —
        # with the choke lifted and no failures left in it, dhtmon no
        # longer alerts
        time.sleep(2.5)
        rc = dhtmon.main(["--nodes", "127.0.0.1:%d" % proxy.port,
                          "--min-success", "0.99", "--window", "1.0"])
        assert rc == 0, "windowed dhtmon alerted on a recovered " \
            "cluster (rc=%d)" % rc

        print("health_smoke: OK — healthz 503->200->503, verdict "
              "healthy->unhealthy (causes %s), coverage %.2f over %d "
              "keys (one batched closest-8 launch), dhtmon 0 then 1"
              % (rep["causes"], cov["mean_coverage"], cov["keys"]))
        return 0
    finally:
        if proxy is not None:
            proxy.stop()
        for r in runners:
            r.join()


if __name__ == "__main__":
    sys.exit(main())
