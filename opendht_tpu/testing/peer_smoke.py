"""Per-peer observatory CI smoke (round-23 tentpole).

Boots real-UDP 3-node clusters and injects chaos-plane faults on ONE
link, then asserts the four things only a live wire can about the
per-peer ledger (opendht_tpu/peers.py):

1. **The adaptive RTO beats the fixed timetable under jitter** — the
   same delay+jitter ``LinkRule`` (one-way ~[0.4, 0.7]s, so RTTs
   straddle the fixed ``MAX_RESPONSE_TIME = 1.0``) runs twice: once
   with ``adaptive_rto`` off (every slow-but-alive reply is preceded
   by a pointless retransmit) and once with it on (Karn backoff climbs
   out of the stale fast estimate, a clean sample seeds
   srtt + 4*rttvar above the link's real RTT).  The adaptive run must
   record MEASURABLY FEWER spurious retransmits to the lagged peer —
   the acceptance bar — while the fixed run's surfaced RTO stays
   exactly 1.0 (the escape-hatch pin).
2. **Attribution is per-link, not cluster-smeared** — the lagged
   link's srtt/RTO adapt on exactly that peer's row; the untouched
   peer's row keeps a millisecond srtt and a clamped RTO.
3. **Loss lands on the right directed edge of the wire map** — a
   one-way 85% loss rule on node0 -> node2 drives that edge's (and
   only that edge's) fail ratio up; the cluster wire map assembled
   from every node's ``GET /peers`` (testing/wiremap_assembler.py)
   names node0 -> node2 as the worst edge while the REVERSE edge and
   the node0 -> node1 edge stay healthy.
4. **dhtmon gates on the worst link** — ``--max-peer-fail`` exits 0 at
   a ceiling above the injected fail ratio and flips to 1 at a floor
   below it (the same per-node worst / unknown-never-violates
   contract as the other gauge gates), and the censored-attempt
   counter ``dht_net_attempt_timeouts_total{type=}`` ticked at the
   EXPIRED transitions the loss caused.

Run directly (CI does)::

    python -m opendht_tpu.testing.peer_smoke
"""

from __future__ import annotations

import sys
import threading
import time

from .. import chaos, telemetry
from ..infohash import InfoHash
from ..net.node import MAX_RESPONSE_TIME
from ..peers import PeersConfig
from ..runtime.config import Config
from ..tools import dhtmon
from . import wiremap_assembler as wma
from .network import DhtNetwork

#: one-way delay/jitter of the lagged link: RTT = out + back lands in
#: [0.8, 1.4]s — straddling the fixed 1.0 s retransmit timer, the
#: regime where a fixed timetable retransmits into in-flight replies
ONE_WAY_DELAY = 0.4
ONE_WAY_JITTER = 0.3
#: requests to the lagged peer before the jitter verdict is read
MIN_REQUESTS = 24
OP_TIMEOUT = 90.0


def _drive(node, keys, timeout=OP_TIMEOUT) -> None:
    """Fire one concurrent get per key and wait for every done
    callback (the values don't exist; the point is request traffic)."""
    evs = []
    for k in keys:
        ev = threading.Event()
        evs.append(ev)
        node.get(k, lambda vs: True, lambda ok, ns, _e=ev: _e.set())
    deadline = time.monotonic() + timeout
    for ev in evs:
        rem = deadline - time.monotonic()
        assert rem > 0 and ev.wait(rem), "get flood stalled"


def _row(snap: dict, peer_id: str):
    for p in snap.get("peers", []):
        if p["id"] == peer_id:
            return p
    return None


def _jitter_phase(net: DhtNetwork, tag: str) -> dict:
    """Arm delay+jitter on the node0<->node1 link only, drive gets
    from node0 until >= MIN_REQUESTS reached the lagged peer, disarm,
    and return node0's ledger snapshot."""
    plan = chaos.FaultPlan(
        [chaos.Phase("jitter", 0.0, None, rules=[
            chaos.LinkRule(name="lag", src="a", dst="b",
                           delay=ONE_WAY_DELAY, jitter=ONE_WAY_JITTER,
                           symmetric=True)])],
        seed=23)
    net.arm(plan, groups={0: "a", 1: "b"})
    src = net.nodes[0]
    lag_id = str(net.nodes[1].get_node_id())
    for rnd in range(12):
        _drive(src, [InfoHash.get("peersmoke-%s-%d-%d" % (tag, rnd, i))
                     for i in range(6)])
        row = _row(src.get_peers(), lag_id)
        if row is not None and row["sent"] >= MIN_REQUESTS:
            break
    net.disarm()
    snap = src.get_peers()
    row = _row(snap, lag_id)
    assert row is not None and row["sent"] >= 12, \
        "too little traffic reached the lagged peer: %r" % (row,)
    return snap


def main(argv=None) -> int:
    reg = telemetry.get_registry()

    # ---- run A: FIXED timetable under jitter -------------------------
    reg.reset()
    net = DhtNetwork(3, config=Config(
        peers=PeersConfig(adaptive_rto=False, min_signal_events=4)),
        seed=7)
    try:
        assert net.wait_connected(), "fixed cluster failed to connect"
        lag_id = str(net.nodes[1].get_node_id())
        snap = _jitter_phase(net, "fixed")
        f_row = _row(snap, lag_id)
        # the escape-hatch pin on a live wire: knob off => the surfaced
        # per-peer RTO is exactly the fixed constant, even though the
        # ledger measured the real (much larger) srtt
        assert f_row["rto"] == MAX_RESPONSE_TIME, f_row
        assert f_row["attempt_timeouts"] > 0, \
            "fixed run never retransmitted under 0.8-1.4s RTTs: %r" % f_row
        f_spur = f_row["spurious_retransmits"]
        assert f_spur >= 5, \
            "fixed timetable produced too few spurious retransmits " \
            "to compare (%d): %r" % (f_spur, f_row)
    finally:
        net.shutdown()

    # ---- run B: ADAPTIVE RTO under the same jitter -------------------
    reg.reset()
    net = DhtNetwork(3, config=Config(
        peers=PeersConfig(adaptive_rto=True, min_signal_events=4)),
        seed=7)
    proxies = []
    try:
        assert net.wait_connected(), "adaptive cluster failed to connect"
        id0 = str(net.nodes[0].get_node_id())
        id1 = str(net.nodes[1].get_node_id())
        id2 = str(net.nodes[2].get_node_id())
        snap = _jitter_phase(net, "adaptive")
        a_row = _row(snap, id1)
        q_row = _row(snap, id2)
        # 1: measurably fewer spurious retransmits than the fixed run
        a_spur = a_row["spurious_retransmits"]
        assert a_spur < f_spur, \
            "adaptive RTO did not beat the fixed timetable: " \
            "%d spurious vs %d fixed" % (a_spur, f_spur)
        # 2: the estimate adapted on THIS link only
        assert a_row["samples"] >= 1 and a_row["srtt"] > 0.3, a_row
        assert a_row["rto"] > MAX_RESPONSE_TIME, \
            "adaptive RTO failed to climb above the fixed timer: %r" % a_row
        assert q_row is None or q_row["srtt"] is None \
            or q_row["srtt"] < 0.2, \
            "untouched link's srtt drifted: %r" % q_row
        assert q_row is None or q_row["rto"] <= MAX_RESPONSE_TIME + 1e-9, \
            "untouched link's RTO left baseline: %r" % q_row
        assert q_row is None or q_row["spurious_retransmits"] <= 1, \
            "untouched link retransmitted spuriously: %r" % q_row

        # ---- loss on ONE directed link: node0 -> node2 ---------------
        plan = chaos.FaultPlan(
            [chaos.Phase("loss", 0.0, None, rules=[
                chaos.LinkRule(name="lossy", src="a", dst="c",
                               loss=0.85)])],
            seed=29)
        net.arm(plan, groups={0: "a", 2: "c"})
        for rnd in range(10):
            _drive(net.nodes[0],
                   [InfoHash.get("peersmoke-loss-%d-%d" % (rnd, i))
                    for i in range(5)])
            row = _row(net.nodes[0].get_peers(), id2)
            if row is not None and row["expired"] >= 6 \
                    and row["completed"] >= 2:
                break
        net.disarm()
        row = _row(net.nodes[0].get_peers(), id2)
        assert row is not None and row["expired"] >= 3, \
            "loss rule never expired a request: %r" % (row,)

        # satellite: the censored-attempt counter ticked at EXPIRED
        tot = sum(m.value for m in
                  reg.series("dht_net_attempt_timeouts_total").values())
        assert tot > 0, "dht_net_attempt_timeouts_total never ticked"

        # 3: the wire map attributes the loss to exactly that edge
        from ..proxy import DhtProxyServer
        proxies = [DhtProxyServer(r, 0) for r in net.nodes]
        docs = [wma.scrape_peers("127.0.0.1:%d" % p.port)
                for p in proxies]
        assert all(d is not None for d in docs), \
            "a node's GET /peers was missing"
        wm = wma.assemble_wiremap(docs)
        assert not wm["violations"], wm["violations"]
        assert len(wm["nodes"]) == 3
        worst = wma.worst_edge(wm, "fail_ratio")
        assert worst is not None and worst["src"] == id0 \
            and worst["dst"] == id2, \
            "loss attributed to the wrong edge: %s -> %s" \
            % (worst and worst["src"], worst and worst["dst"])
        # the ledger is cumulative since boot, so the healthy pre-loss
        # completions on this link dilute the ratio — the bar is clear
        # separation from the healthy edges, not the raw loss rate
        assert worst["fail_ratio"] > 0.1 and worst["known"], worst
        rev = wma.find_edge(wm, id2, id0)
        assert rev is None or rev["fail_ratio"] is None \
            or rev["fail_ratio"] < 0.2, \
            "one-way loss leaked onto the reverse edge: %r" % rev
        side = wma.find_edge(wm, id0, id1)
        assert side is None or side["fail_ratio"] is None \
            or side["fail_ratio"] < 0.3, \
            "loss smeared onto the untouched edge: %r" % side

        # 4: dhtmon gates on the worst link, both verdicts
        eps = ",".join("127.0.0.1:%d" % p.port for p in proxies)
        rc = dhtmon.main(["--nodes", eps, "--max-peer-fail", "0.95"])
        assert rc == 0, \
            "dhtmon flagged a link under its ceiling (rc=%d)" % rc
        rc = dhtmon.main(["--nodes", eps, "--max-peer-fail", "0.05"])
        assert rc == 1, \
            "dhtmon missed the dying link (rc=%d, fail %r)" \
            % (rc, worst["fail_ratio"])

        print("peer_smoke: OK — spurious retransmits %d fixed -> %d "
              "adaptive (lag srtt %.3fs rto %.3fs; quiet rto %.3fs), "
              "loss edge %s->%s fail %.2f, dhtmon 0 at 0.95 -> 1 at "
              "0.05"
              % (f_spur, a_spur, a_row["srtt"], a_row["rto"],
                 q_row["rto"] if q_row else float("nan"),
                 worst["src"][:8], worst["dst"][:8],
                 worst["fail_ratio"]))
        return 0
    finally:
        for p in proxies:
            p.stop()
        net.shutdown()


if __name__ == "__main__":
    sys.exit(main())
