"""Multi-node test/bench harness (↔ reference python/tools/dht/*).

Two backends:

- :class:`VirtualNet` — deterministic in-process virtual UDP network
  over ``Dht`` cores with a virtual clock (replaces the reference's
  netns + netem tier, virtual_network_builder.py).
- :class:`DhtNetwork` — N real ``DhtRunner`` nodes on localhost UDP
  (the reference's in-namespace node cluster, dht/network.py:283-436).

Scenario suites (↔ dht/tests.py): :class:`PerformanceTest` (gets latency
histograms, node-kill delete test), :class:`PersistenceTest` (value
survival under churn).  CLI driver: ``python -m
opendht_tpu.testing.benchmark`` (↔ benchmark.py).
"""

from .virtual_net import VirtualNet

# The real-UDP backends ride DhtRunner and therefore the
# ``cryptography`` wheel; resolve them lazily (PEP 562, same rule as
# the package root) so plain `import opendht_tpu.testing` — and with it
# the crypto-free virtual-clock tier the hop-parity ladder uses —
# works everywhere.  (A STAR import still materializes every __all__
# name and so still needs the wheel, as the fully-eager module did.)
_LAZY_EXPORTS = {
    "DhtNetwork": ".network",
    "PerformanceTest": ".scenarios",
    "PersistenceTest": ".scenarios",
    "LatencyStats": ".scenarios",
}


def __getattr__(name):
    mod = _LAZY_EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    try:
        value = getattr(importlib.import_module(mod, __name__), name)
    except ModuleNotFoundError as e:
        # soft-introspection rule of the package root's __getattr__
        raise AttributeError(
            f"opendht_tpu.testing.{name} requires the optional "
            f"'{e.name}' package (VirtualNet and the virtual-clock "
            f"tier work without it)") from e
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY_EXPORTS))


__all__ = ["VirtualNet", "DhtNetwork", "PerformanceTest",
           "PersistenceTest", "LatencyStats"]
