"""Cross-node span assembly + the CI tracing smoke (ISSUE-4).

:func:`assemble_trace` scrapes every cluster node's flight-recorder
ring (``DhtRunner.get_trace``; any object with a ``get_trace`` method,
or a raw span list, works — a remote node's ``GET /trace/<id>`` JSON
plugs straight in) and reconstructs the full span tree of one
operation: op root span → per-hop client RPC spans → remote server
spans.  Spans are deduped by span id, so in-process clusters sharing
one tracer ring assemble identically to one-ring-per-process
deployments.

The smoke (``python -m opendht_tpu.testing.trace_assembler``, wired
into ci/run_ci.sh) boots a real-UDP cluster, runs one traced put+get,
asserts the assembled tree has ≥ 3 contributing nodes with correct
parentage and monotone timestamps, round-trips the Chrome trace dump
through ``json.loads`` with the exact ``ph``/``pid``/``tid``/``ts``/
``dur`` fields Perfetto requires, and checks the ring's
bounded-memory property (10× capacity pushed → oldest evicted,
RSS-stable).
"""

from __future__ import annotations

import json
import sys
import time

from .. import tracing

#: tolerance for child-starts-before-parent comparisons: spans stamp
#: ``time.time()`` on different hosts/threads; within one machine the
#: clock is shared and only scheduling jitter remains
CLOCK_SLACK = 0.050


def collect_spans(nodes, trace_id) -> list:
    """Union of one trace's spans over every node's ring, deduped by
    span id.  ``nodes``: DhtRunner-likes (``get_trace``), Tracers
    (``spans``), or plain span-dict lists."""
    want = None
    seen = {}
    for n in nodes:
        if hasattr(n, "get_trace"):
            spans = n.get_trace(trace_id)
        elif hasattr(n, "spans"):
            spans = n.spans(trace_id)
        else:
            want = tracing._trace_hex(trace_id)
            spans = [s for s in n if s.get("trace_id") == want]
        for s in spans:
            seen.setdefault(s["span_id"], s)
    return list(seen.values())


def assemble_trace(nodes, trace_id) -> dict:
    """Reconstruct one trace's span tree across the cluster.

    Returns ``{"trace_id", "spans": N, "nodes": [tags], "roots":
    [tree]}`` where each tree node is the span dict plus a
    ``"children"`` list (sorted by start time).  Spans whose parent is
    not in the collected set (e.g. rotated out of a busy ring) surface
    as additional roots rather than being dropped — a postmortem tool
    must degrade, not lie."""
    spans = collect_spans(nodes, trace_id)
    by_id = {}
    for s in spans:
        t = dict(s)
        t["children"] = []
        by_id[t["span_id"]] = t
    roots = []
    for t in by_id.values():
        parent = by_id.get(t.get("parent_id") or "")
        if parent is not None:
            parent["children"].append(t)
        else:
            roots.append(t)
    for t in by_id.values():
        t["children"].sort(key=lambda c: c["start"])
    roots.sort(key=lambda c: c["start"])
    return {
        "trace_id": tracing._trace_hex(trace_id),
        "spans": len(by_id),
        "nodes": sorted({t.get("node", "") for t in by_id.values()}),
        "roots": roots,
    }


def check_tree(tree: dict) -> list:
    """Structural assertions on an assembled tree; returns a list of
    violation strings (empty = clean): every server span parents to a
    client RPC span, every RPC span parents into the op tree, and child
    start times are monotone vs their parent."""
    bad = []

    def walk(t, parent):
        if parent is not None and t["start"] < parent["start"] - CLOCK_SLACK:
            bad.append("span %s starts %.3fs before its parent %s"
                       % (t["span_id"], parent["start"] - t["start"],
                          parent["span_id"]))
        if t["kind"] == "server":
            if parent is None or not parent["name"].startswith("dht.rpc."):
                bad.append("server span %s (%s) not parented to an rpc "
                           "client span" % (t["span_id"], t["name"]))
            elif parent.get("node") == t.get("node"):
                bad.append("server span %s on the same node as its "
                           "client hop" % t["span_id"])
        if t["name"].startswith("dht.rpc.") and parent is None:
            bad.append("rpc span %s has no parent in the tree"
                       % t["span_id"])
        for c in t["children"]:
            walk(c, t)

    for r in tree["roots"]:
        walk(r, None)
    return bad


# --------------------------------------------------------------- CI smoke
def _wait_connected(nodes, timeout=30.0) -> bool:
    from ..runtime.config import NodeStatus
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if all(n.get_status() is NodeStatus.CONNECTED for n in nodes):
            return True
        time.sleep(0.05)
    return False


def ring_bounded_check(factor: int = 10) -> None:
    """Push ``factor``× a small ring's capacity of fat events: the ring
    must stay at capacity, evict oldest-first, and not retain memory
    proportional to the push count (RSS-stable)."""
    import resource

    cap = 512
    tr = tracing.Tracer(capacity=cap, node="ringcheck")
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    payload = "x" * 256
    total = cap * factor
    for i in range(total):
        tr.event("flood", seq_no=i, payload=payload)
    recs = tr.records()
    assert len(recs) == cap, "ring grew past capacity: %d" % len(recs)
    oldest = min(r["attrs"]["seq_no"] for r in recs)
    assert oldest == total - cap, \
        "oldest retained is %d, expected %d" % (oldest, total - cap)
    rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # ru_maxrss is KiB on linux; the retained set is ~cap*payload —
    # allow generous allocator slack while still catching O(total)
    # retention (which would be ≥ 10× the band)
    grown_kib = rss1 - rss0
    assert grown_kib < 16 * 1024, \
        "RSS grew %d KiB over a %d-event flood" % (grown_kib, total)


def main(argv=None) -> int:
    from ..infohash import InfoHash
    from ..core.value import Value
    from ..runtime.runner import DhtRunner

    n_nodes = 5
    tracer = tracing.get_tracer()
    nodes = []
    try:
        for i in range(n_nodes):
            n = DhtRunner()
            n.run(0)
            if nodes:
                n.bootstrap("127.0.0.1", nodes[0].get_bound_port())
            nodes.append(n)
        if not _wait_connected(nodes):
            print("trace smoke: cluster failed to connect", file=sys.stderr)
            return 1

        key = InfoHash.get("trace-smoke")
        root = tracing.TraceContext.new_root()
        with tracing.activate(root):
            assert nodes[-1].put_sync(key, Value(b"traced"), timeout=20.0)
            vals = nodes[-1].get_sync(key, timeout=20.0)
        assert vals and any(v.data == b"traced" for v in vals)

        # ---- cross-node assembly ---------------------------------------
        tree = assemble_trace(nodes, root.trace_id)
        assert tree["spans"] >= 5, \
            "expected a multi-hop tree, got %d spans" % tree["spans"]
        contributing = [n for n in tree["nodes"] if n]
        assert len(contributing) >= 3, \
            "expected >=3 nodes contributing spans, got %r" % contributing
        violations = check_tree(tree)
        assert not violations, "span-tree violations:\n  " + \
            "\n  ".join(violations)
        ops = [r["name"] for r in tree["roots"]]
        assert any(o.startswith("dht.op.") for o in ops), ops

        # ---- chrome trace round-trip -----------------------------------
        dump = tracing.to_chrome_trace(
            collect_spans(nodes, root.trace_id))
        text = json.dumps(dump)
        back = json.loads(text)
        xs = [e for e in back["traceEvents"] if e.get("ph") == "X"]
        assert xs, "no complete events in the chrome dump"
        for e in xs:
            for field in ("pid", "tid", "ts", "dur", "name"):
                assert field in e, "chrome event missing %r" % field
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)

        # ---- flight-recorder dump parses -------------------------------
        fr = nodes[0].get_flight_recorder(limit=200)
        json.loads(json.dumps(fr))
        assert fr["capacity"] == tracer.capacity

        # ---- ring bounded memory ---------------------------------------
        ring_bounded_check()

        print("trace smoke ok: %d spans over %d nodes, chrome dump "
              "%d events, ring bounded" % (tree["spans"],
                                           len(contributing), len(xs)))
        return 0
    finally:
        for n in nodes:
            n.join()


if __name__ == "__main__":
    sys.exit(main())
