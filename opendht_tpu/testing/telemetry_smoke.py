"""End-to-end telemetry smoke (ISSUE-3 CI satellite).

Boots a small real-UDP cluster, runs puts/gets/listens, then scrapes the
telemetry surface both ways — ``DhtRunner.get_metrics()`` (JSON) and the
proxy's ``GET /stats`` (Prometheus text exposition) — and asserts that
(1) the exposition parses line-by-line against the v0.0.4 grammar,
(2) the counters the exercised paths must advance actually advanced, and
(3) the two exports describe the same registry.

Run directly (CI does)::

    python -m opendht_tpu.testing.telemetry_smoke
"""

from __future__ import annotations

import json
import re
import sys
import time
import urllib.request

from ..infohash import InfoHash
from ..core.value import Value
from ..runtime.config import NodeStatus
from ..runtime.runner import DhtRunner

# one line of text exposition: comment/TYPE, or `name{labels} value`
_LINE_RE = re.compile(
    r"^(#.*|[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(\\.|[^\"\\])*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(\\.|[^\"\\])*\")*\})?"
    r" [-+]?([0-9.eE+-]+|[0-9]+|\+Inf|NaN))$")


def parse_exposition(text: str) -> dict:
    """Validate every line and return {series: float}; raises on any
    line the v0.0.4 grammar rejects."""
    out = {}
    for ln in text.splitlines():
        if not ln.strip():
            continue
        if not _LINE_RE.match(ln):
            raise ValueError("bad exposition line: %r" % ln)
        if ln.startswith("#"):
            continue
        series, val = ln.rsplit(" ", 1)
        out[series] = float(val)
    return out


def _wait_connected(nodes, timeout=30.0) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if all(n.get_status() is NodeStatus.CONNECTED for n in nodes):
            return True
        time.sleep(0.05)
    return False


def main(argv=None) -> int:
    from ..proxy import DhtProxyServer

    n_ops = 4
    node1, node2 = DhtRunner(), DhtRunner()
    proxy = None
    try:
        node1.run(0)
        node2.run(0)
        node2.bootstrap("127.0.0.1", node1.get_bound_port())
        if not _wait_connected([node1, node2]):
            print("telemetry_smoke: cluster failed to connect",
                  file=sys.stderr)
            return 1

        keys = [InfoHash.get("telemetry-smoke-%d" % i) for i in range(n_ops)]
        for i, key in enumerate(keys):
            assert node2.put_sync(key, Value(b"v%d" % i), timeout=15.0)
        got = 0
        for key in keys:
            got += len(node1.get_sync(key, timeout=15.0))
        assert got >= n_ops, "expected >= %d values, got %d" % (n_ops, got)

        # ---- JSON surface -------------------------------------------------
        snap = node2.get_metrics()
        json.dumps(snap)                      # must be JSON-able
        counters = snap["counters"]

        def counter_sum(prefix: str) -> float:
            return sum(v for k, v in counters.items()
                       if k == prefix or k.startswith(prefix + "{"))

        expect_advanced = [
            'dht_ops_total{ok="true",op="put"}',
            'dht_ops_total{ok="true",op="get"}',
            'dht_net_requests_sent_total{type="put"}',
            'dht_net_requests_sent_total{type="get"}',
            'dht_net_requests_completed_total{type="put"}',
        ]
        for series in expect_advanced:
            assert counters.get(series, 0) > 0, \
                "counter %s did not advance: %r" % (
                    series, sorted(counters)[:40])
        assert counter_sum("dht_net_messages_total") > 0
        hists = snap["histograms"]
        assert any(k.startswith("dht_op_seconds") for k in hists)
        assert any(k.startswith("dht_net_rtt_seconds") for k in hists)
        # routing gauges refreshed by get_metrics (the old stats island)
        assert any(k.startswith("dht_routing_good{")
                   for k in snap["gauges"])

        # ---- Prometheus surface -------------------------------------------
        proxy = DhtProxyServer(node1, 0)
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/stats" % proxy.port, timeout=10) as r:
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
        assert "text/plain" in ctype, ctype
        series = parse_exposition(text)
        for s in expect_advanced:
            assert series.get(s, 0) > 0, "scrape missing %s" % s
        assert series.get("dht_proxy_requests_total", 0) >= 1
        # same registry both ways: every JSON counter appears in the
        # scrape with a value at least as recent (counters only grow)
        for k, v in counters.items():
            assert k in series, "JSON counter %s missing from /stats" % k
            assert series[k] >= v, (k, series[k], v)
        print("telemetry smoke ok: %d exposition series, "
              "%d counters advanced" % (len(series), len(expect_advanced)))
        return 0
    finally:
        if proxy is not None:
            proxy.stop()
        node1.join()
        node2.join()


if __name__ == "__main__":
    sys.exit(main())
