"""Cluster benchmark driver (↔ reference python/tools/dht/benchmark.py).

Usage::

    python -m opendht_tpu.testing.benchmark -t gets -n 32 -r 10 -g 50
    python -m opendht_tpu.testing.benchmark -t delete -n 32
    python -m opendht_tpu.testing.benchmark -t persistence -n 24
    python -m opendht_tpu.testing.benchmark -t gets --real -n 8

Default backend is the deterministic virtual network (latencies are in
*virtual* seconds — the simulated wire delay, -d, dominates); ``--real``
runs on real localhost UDP runners and reports wall-clock latencies.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def run_gets_virtual(args) -> dict:
    from ..runtime.config import Config
    from .scenarios import PerformanceTest, build_net
    net = build_net(args.nodes, delay=args.delay, loss=args.loss,
                    seed=args.seed)
    stats = PerformanceTest(net, seed=args.seed).gets_times(
        rounds=args.rounds, gets_per_round=args.gets,
        replace=args.replace, config=Config())
    return {"test": "gets", "backend": "virtual", "nodes": args.nodes,
            **stats.summary()}


def run_gets_real(args) -> dict:
    from ..infohash import InfoHash
    from .network import DhtNetwork
    from .scenarios import LatencyStats
    stats = LatencyStats()
    with DhtNetwork(args.nodes, seed=args.seed) as net:
        net.wait_connected()
        for _ in range(args.rounds):
            for _ in range(args.gets):
                t0 = time.monotonic()
                net.get(InfoHash.get_random(), timeout=30.0)
                stats.add(time.monotonic() - t0)
            if args.replace:
                net.replace_cluster(args.replace)
                net.wait_connected()
    return {"test": "gets", "backend": "real", "nodes": args.nodes,
            **stats.summary()}


def run_delete(args) -> dict:
    from .scenarios import PerformanceTest, build_net
    net = build_net(args.nodes, delay=args.delay, loss=args.loss,
                    seed=args.seed)
    survived, holders = PerformanceTest(net, seed=args.seed).delete_test()
    return {"test": "delete", "nodes": args.nodes,
            "holders_killed": holders, "value_survived": survived}


def run_persistence(args) -> dict:
    from ..runtime.config import Config
    from .scenarios import PersistenceTest, build_net
    conf = Config(maintain_storage=True)
    net = build_net(args.nodes, delay=args.delay, loss=args.loss,
                    seed=args.seed, config=conf)
    ok = PersistenceTest(net, seed=args.seed).churn_survival(
        kills=args.replace or 4, config=conf)
    return {"test": "persistence", "nodes": args.nodes, "survived": ok}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="OpenDHT-TPU cluster benchmark")
    p.add_argument("-t", "--test", default="gets",
                   choices=["gets", "delete", "persistence"])
    p.add_argument("-n", "--nodes", type=int, default=32)
    p.add_argument("-r", "--rounds", type=int, default=10)
    p.add_argument("-g", "--gets", type=int, default=50)
    p.add_argument("--replace", type=int, default=0,
                   help="nodes replaced between rounds / churn kills")
    p.add_argument("-d", "--delay", type=float, default=0.005,
                   help="virtual wire delay seconds (netem analogue)")
    p.add_argument("-l", "--loss", type=float, default=0.0,
                   help="virtual packet loss [0..1] (netem analogue)")
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--real", action="store_true",
                   help="real localhost UDP runners instead of the "
                        "virtual network")
    args = p.parse_args(argv)
    if args.real and args.test != "gets":
        p.error("--real is only implemented for -t gets")

    import jax
    # from the tools PACKAGE, not tools.common: common eagerly imports
    # the crypto-backed runner stack, and the VIRTUAL harness must stay
    # runnable without the optional ``cryptography`` wheel (the --real
    # mode imports it on use)
    from ..tools import force_cpu_jax
    force_cpu_jax()
    if jax.default_backend() != "cpu":
        # the axon TPU tunnel admits one client; never grab it by accident
        p.exit(1, "could not pin JAX to CPU; refusing to risk the "
                  "single-client TPU tunnel\n")

    if args.test == "gets":
        out = run_gets_real(args) if args.real else run_gets_virtual(args)
    elif args.test == "delete":
        out = run_delete(args)
    else:
        out = run_persistence(args)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
