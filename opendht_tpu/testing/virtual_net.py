"""In-process virtual UDP network for driving whole Dht nodes.

The tier-3 analogue of the reference's netns cluster harness
(python/tools/dht/network.py, virtual_network_builder.py) with no real
sockets: every node's injected ``send_fn`` enqueues datagrams on a
shared event queue, a virtual clock advances to the next packet arrival
or scheduler wakeup, and delivery calls the destination's
``periodic(data, from_addr)``.  Deterministic, immune to wall-clock
flakiness, and able to jump hours of protocol time (token rotation,
value expiry) in milliseconds.  Optional per-packet loss and delay play
the role of netem (benchmark.py -l/-d).
"""

from __future__ import annotations

import heapq
import itertools
import random
from typing import Callable, Dict, List, Optional

from opendht_tpu.chaos import FaultInjector, FaultPlan, LinkRule, Phase
from opendht_tpu.runtime import Config, Dht
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr
from opendht_tpu.utils import TIME_MAX

#: held-back duplicate copies arrive this far after the original so the
#: two deliveries are distinct events, like real dup-on-retransmit
DUP_GAP = 1e-4


class VirtualNet:
    def __init__(self, *, delay: float = 0.01, jitter: float = 0.0,
                 loss: float = 0.0, seed: int = 42,
                 plan: Optional[FaultPlan] = None):
        self.clock = 0.0
        self.delay = delay
        self.jitter = jitter
        self.loss = loss
        self.rng = random.Random(seed)
        self.nodes: Dict[tuple, Dht] = {}
        self._queue: list = []          # (arrival, seq, data, src, dst_key)
        self._seq = itertools.count()
        self._next_port = 20000
        self.dropped = 0
        #: drop accounting split per netem rule: the legacy uniform
        #: loss counts under "uniform"; FaultPlan rules/partitions
        #: under their own names (ISSUE-13 satellite)
        self.dropped_by_rule: Dict[str, int] = {}
        # adversarial chaos plane (ISSUE-13): an armed FaultInjector is
        # consulted on every send BEFORE the uniform netem — per-link
        # asymmetric loss/dup/reorder/partitions ride the one seam the
        # real-UDP harness and the live engine share.
        self.injector: Optional[FaultInjector] = None
        if plan is not None:
            self.arm(plan)
        # lazy min-heap over per-node next-job times: O(log N) per event
        # instead of scanning every scheduler per event (the O(N·events)
        # scan capped clusters at a few hundred nodes; hop-parity needs
        # 2K-8K).  Entries (t, key) are stale unless _ntimes[key] == t.
        self._ntimes: Dict[tuple, float] = {}
        self._sheap: list = []

    # --------------------------------------------------------- chaos plane
    def arm(self, plan: FaultPlan) -> FaultInjector:
        """Arm a FaultPlan at the current virtual time; phase windows
        are relative to now.  Partitions heal when their phase ends."""
        self.injector = FaultInjector(plan)
        self.injector.arm(self.clock)
        return self.injector

    def disarm(self) -> None:
        self.injector = None

    def add_link_rule(self, rule: LinkRule,
                      membership: Optional[Dict[tuple, str]] = None) -> None:
        """Static (always-on) per-link netem without writing a plan —
        the -l/-d uniform knobs generalized to asymmetric per-link
        loss/dup/reorder/delay."""
        if self.injector is None:
            self.arm(FaultPlan([Phase("netem")], membership=membership))
        elif membership:
            self.injector.plan.membership.update(membership)
        for ph in self.injector.plan.phases:
            if ph.name == "netem" and ph.duration is None:
                ph.rules.append(rule)
                return
        self.injector.plan.phases.append(Phase("netem", rules=[rule]))

    def set_group(self, dht: Dht, group: str) -> None:
        """Assign a node to a plan group (partitions/link rules match
        on groups)."""
        if self.injector is None:
            self.arm(FaultPlan([]))
        key = (dht.bound_addr.host, dht.bound_addr.port)
        self.injector.plan.membership[key] = group

    def _drop(self, rule: str) -> None:
        self.dropped += 1
        self.dropped_by_rule[rule] = self.dropped_by_rule.get(rule, 0) + 1

    def _enqueue(self, arrival: float, data: bytes, src, dst_key) -> None:
        heapq.heappush(self._queue,
                       (arrival, next(self._seq), data, src, dst_key))

    # ------------------------------------------------------------- topology
    def add_node(self, config: Optional[Config] = None,
                 host: str = "127.0.0.1", port: Optional[int] = None) -> Dht:
        if port is None:
            port = self._next_port
            self._next_port += 1
        addr = SockAddr(host, port)
        key = (addr.host, addr.port)

        def send_fn(data: bytes, dest: SockAddr, _src=addr) -> int:
            src_key = (_src.host, _src.port)
            dst_key = (dest.host, dest.port)
            extra = 0.0
            copies = 1
            inj = self.injector
            if inj is not None and inj.armed:
                fate = inj.fate(src_key, dst_key, self.clock)
                if fate.drop:
                    self._drop(fate.rule or "chaos")
                    return 0
                extra = fate.delay
                copies += fate.dup
            arrival = self.clock + self.delay + extra + \
                (self.rng.random() * self.jitter if self.jitter else 0.0)
            # netem order: duplication happens in the network, then
            # each copy is independently subject to the uniform loss;
            # copies trail THEIR original's (jittered) arrival so a
            # dup can never overtake it
            for i in range(copies):
                if self.loss and self.rng.random() < self.loss:
                    self._drop("uniform")
                    continue
                self._enqueue(arrival + i * DUP_GAP, data, _src, dst_key)
            return 0

        dht = Dht(send_fn, config, Scheduler(clock=lambda: self.clock),
                  has_v6=False)
        dht.bound_addr = addr
        self.nodes[key] = dht
        return dht

    def bootstrap_node(self, dht: Dht, seed_node: Dht) -> None:
        """Point one node at the seed and ping it (↔ the runner's
        bootstrap thread, reference src/dhtrunner.cpp:819-875)."""
        dht.insert_node(seed_node.myid, seed_node.bound_addr)
        dht.ping_node(seed_node.bound_addr)

    def remove_node(self, dht: Dht) -> None:
        """Kill a node: it stops receiving and its scheduler stops running
        (↔ DhtNetworkSubProcess node shutdown, reference
        python/tools/dht/network.py:377-436)."""
        key = (dht.bound_addr.host, dht.bound_addr.port)
        self.nodes.pop(key, None)
        # drop the cached wakeup too: a later add_node on the same
        # (host, port) with an equal next_job_time would otherwise be
        # skipped by _refresh's equality check and never run
        self._ntimes.pop(key, None)

    def replace_cluster(self, count: int, seed_node: Dht,
                        config: Optional[Config] = None) -> List[Dht]:
        """Kill ``count`` random nodes (never the seed) and start as many
        fresh ones bootstrapped at the seed (↔ the reference's cluster
        replacement during PerformanceTest rounds, dht/tests.py:905-910)."""
        candidates = [d for d in self.nodes.values() if d is not seed_node]
        victims = self.rng.sample(candidates, min(count, len(candidates)))
        for v in victims:
            self.remove_node(v)
        fresh = []
        for _ in victims:
            d = self.add_node(config)
            self.bootstrap_node(d, seed_node)
            fresh.append(d)
        return fresh

    def step_storm(self, storm, seed_node: Dht,
                   config: Optional[Config] = None) -> tuple:
        """Apply one join/leave storm step from a :class:`~opendht_tpu.
        chaos.Storm`: every non-seed node leaves with ``leave_rate``,
        and ``join_rate`` × current-size fresh nodes bootstrap at the
        seed.  Returns (left, joined) counts; deterministic under the
        net's seed."""
        victims = [d for d in list(self.nodes.values())
                   if d is not seed_node
                   and self.rng.random() < storm.leave_rate]
        for v in victims:
            self.remove_node(v)
        joins = 0
        target = int(storm.join_rate * max(len(self.nodes), 1))
        for _ in range(target):
            d = self.add_node(config)
            self.bootstrap_node(d, seed_node)
            joins += 1
        return len(victims), joins

    def storers_of(self, key) -> List[Dht]:
        """Nodes currently holding values for ``key`` locally."""
        return [d for d in self.nodes.values() if d.get_local(key)]

    def seed_converged(self, *, k: int = 8, quiesce: bool = True,
                       seed: int = 0) -> None:
        """Install a CONVERGED Kademlia routing table in every node
        directly — up to ``k`` random peers per common-prefix bucket —
        instead of hundreds of virtual seconds of bootstrap chatter.

        A converged network's steady state is exactly "≤ k live peers
        in every occupied cb(self, ·) bucket" (the admission rule of
        reference src/routing_table.cpp:204-262), so building it by
        construction changes nothing the protocol tests observe except
        the cost: the 8192-node hop-parity point drops from ~90 min of
        event processing (the round-4 RUN_XL_CLUSTER gate) to the cost
        of one vectorized O(N²)-byte common-prefix pass + bulk loads.

        ``quiesce`` pushes every node's confirm-nodes maintenance an
        hour out so a seeded cluster stays silent until the test drives
        traffic (observer lookups complete in ~1 virtual second; the
        N-node self-search storm at +3-5 s would otherwise dominate the
        run for zero reply-quality gain at that horizon).
        """
        import numpy as np
        import socket as _socket
        items = [d for d in self.nodes.values()
                 if _socket.AF_INET in d.tables]
        n = len(items)
        if n < 2:
            return
        from opendht_tpu.ops import ids as IK
        rng = np.random.default_rng(seed)
        ids_bytes = np.stack([
            np.frombuffer(bytes(d.myid), dtype=np.uint8) for d in items])
        ids_u32 = IK.ids_from_bytes(ids_bytes)      # canonical limb packing
        addrs = [d.bound_addr for d in items]
        clz8 = 8 - np.array([int(v).bit_length() for v in range(256)],
                            dtype=np.int16)
        now = self.clock
        for i, d in enumerate(items):
            x = ids_bytes ^ ids_bytes[i]                     # [n, 20]
            nzmask = x != 0
            first = np.argmax(nzmask, axis=1)
            anynz = nzmask.any(axis=1)
            cb = np.where(anynz,
                          8 * first + clz8[x[np.arange(n), first]],
                          160).astype(np.int16)
            # per-bucket pick of ≤ k peers, uniformly random via a
            # shuffle + stable sort; self (cb=160) excluded by mask
            perm = rng.permutation(n)
            cbp = cb[perm]
            order = np.argsort(cbp, kind="stable")
            cbs = cbp[order]
            rank = np.arange(n) - np.searchsorted(cbs, cbs, side="left")
            takes = order[(rank < k) & (cbs < 160)]
            sel = perm[takes]
            d.tables[_socket.AF_INET].bulk_load(
                ids_u32[sel], now, replied=True,
                addrs=[addrs[j] for j in sel],
                buckets=cb[sel])
            if quiesce and d._next_nodes_confirmation is not None:
                d._next_nodes_confirmation = d.scheduler.edit(
                    d._next_nodes_confirmation, now + 3600.0)
        for key in self.nodes:
            self._refresh(key)

    def bootstrap_all(self, seed_node: Dht) -> None:
        """Point every other node at the seed and ping it (↔ the runner's
        bootstrap thread, reference src/dhtrunner.cpp:819-875)."""
        for dht in self.nodes.values():
            if dht is not seed_node:
                self.bootstrap_node(dht, seed_node)

    # ------------------------------------------------------------ event loop
    def _refresh(self, key) -> None:
        """Re-cache one node's next scheduler wakeup in the lazy heap."""
        dht = self.nodes.get(key)
        if dht is None:
            self._ntimes.pop(key, None)
            return
        t = dht.scheduler.next_job_time()
        if self._ntimes.get(key) != t:
            self._ntimes[key] = t
            if t < TIME_MAX:
                heapq.heappush(self._sheap, (t, key))

    def _peek_sched(self) -> float:
        while self._sheap:
            t, key = self._sheap[0]
            if key in self.nodes and self._ntimes.get(key) == t:
                return t
            heapq.heappop(self._sheap)          # stale
        return TIME_MAX

    def _next_event_time(self) -> float:
        t = self._queue[0][0] if self._queue else TIME_MAX
        return min(t, self._peek_sched())

    def run(self, max_time: float = 30.0,
            until: Optional[Callable[[], bool]] = None,
            max_events: int = 5_000_000, check_every: int = 32) -> bool:
        """Advance virtual time; returns True as soon as `until()` holds.

        ``until`` is evaluated every ``check_every`` events (it is often
        an O(N) sweep like all_connected — per-event evaluation made big
        clusters quadratic).  Each run() entry re-syncs every node's
        cached wakeup once, so jobs scheduled by direct test calls
        between runs (obs.get(...), bootstrap) are picked up.
        """
        deadline = self.clock + max_time
        for key in self.nodes:
            self._refresh(key)
        for i in range(max_events):
            if until is not None and i % check_every == 0 and until():
                return True
            t = self._next_event_time()
            if t > deadline:
                self.clock = deadline
                break
            self.clock = max(self.clock, t)
            touched = set()
            # deliver all packets due now
            while self._queue and self._queue[0][0] <= self.clock:
                _, _, data, src, dst_key = heapq.heappop(self._queue)
                dst = self.nodes.get(dst_key)
                if dst is not None:
                    dst.periodic(data, src)
                    touched.add(dst_key)
            # run due scheduler jobs (each due node once, via the heap)
            while True:
                ts = self._peek_sched()
                if ts > self.clock:
                    break
                _, key = heapq.heappop(self._sheap)
                self._ntimes.pop(key, None)
                dht = self.nodes.get(key)
                if dht is not None:
                    dht.periodic(None, None)
                    touched.add(key)
            for key in touched:
                self._refresh(key)
        return until() if until is not None else False

    def settle(self, seconds: float) -> None:
        """Run with no exit condition for `seconds` of virtual time."""
        self.run(max_time=seconds, until=None)

    # ------------------------------------------------------------- helpers
    def connected_count(self) -> int:
        from opendht_tpu.runtime import NodeStatus
        return sum(1 for d in self.nodes.values()
                   if d.get_status() is NodeStatus.CONNECTED)

    def all_connected(self) -> bool:
        return self.connected_count() == len(self.nodes)
