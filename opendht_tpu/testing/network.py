"""DhtNetwork: a cluster of real DhtRunner nodes on localhost UDP
(↔ reference python/tools/dht/network.py:283-436 — the in-namespace
node cluster; the netns/veth/netem tier is replaced by
:class:`~opendht_tpu.testing.virtual_net.VirtualNet`'s simulated
delay/loss)."""

from __future__ import annotations

import random
import time
from typing import Dict, List, Optional

from .. import chaos
from ..core.value import Value
from ..infohash import InfoHash
from ..runtime.config import Config, NodeStatus
from ..runtime.runner import DhtRunner, RunnerConfig


class DhtNetwork:
    """Manage N in-process runners bound to localhost
    (↔ DhtNetwork, network.py:283-340)."""

    def __init__(self, num_nodes: int = 8, *,
                 config: Optional[Config] = None, seed: int = 0):
        self.config = config or Config()
        self.rng = random.Random(seed)
        self.nodes: List[DhtRunner] = []
        self.bootstrap_addr = None
        self.injector: Optional["chaos.FaultInjector"] = None
        self._default_group: Optional[str] = None
        for _ in range(num_nodes):
            self.launch_node()

    # ------------------------------------------------------------- topology
    def launch_node(self, group: Optional[str] = None) -> DhtRunner:
        """(↔ DhtNetwork.launch_node, network.py:341-360).  While a
        FaultPlan is armed, the fresh node's engine is hooked too so a
        partition cannot silently leak through churn replacements; it
        joins ``group`` (or the arm-time ``default_group``, else the
        wildcard group)."""
        r = DhtRunner()
        r.run(0, RunnerConfig(dht_config=self.config))
        # hook BEFORE bootstrap: the loop thread must not get a first
        # packet out ahead of the fault hook (a replacement node in a
        # blocked group could otherwise leak one datagram across an
        # armed partition)
        if self.injector is not None:
            self._arm_one(r, group if group is not None
                          else self._default_group)
        if self.bootstrap_addr is None:
            self.bootstrap_addr = ("127.0.0.1", r.get_bound_port())
        else:
            r.bootstrap(*self.bootstrap_addr)
        self.nodes.append(r)
        return r

    def _arm_one(self, r: DhtRunner, group: Optional[str]) -> None:
        key = ("127.0.0.1", r.get_bound_port())
        if group is not None:
            self.injector.plan.membership.setdefault(key, group)
        chaos.arm_engine(r._dht._dht.engine, self.injector, key)

    def shutdown_node(self, node: Optional[DhtRunner] = None) -> None:
        """Stop one node (random non-seed by default)
        (↔ DhtNetworkSubProcess shutdown requests, network.py:377-436)."""
        if node is None:
            if len(self.nodes) <= 1:
                return
            node = self.rng.choice(self.nodes[1:])
        self.nodes.remove(node)
        node.join()

    def replace_cluster(self, count: int) -> List[DhtRunner]:
        """Kill ``count`` random non-seed nodes, launch replacements
        (↔ cluster replacement during test rounds, dht/tests.py:905-910)."""
        victims = self.rng.sample(self.nodes[1:],
                                  min(count, len(self.nodes) - 1))
        for v in victims:
            self.shutdown_node(v)
        return [self.launch_node() for _ in victims]

    def shutdown(self) -> None:
        self.disarm()
        for r in self.nodes:
            r.join()
        self.nodes.clear()

    # --------------------------------------------------------- chaos plane
    def arm(self, plan: "chaos.FaultPlan",
            groups: Optional[Dict[int, str]] = None,
            default_group: Optional[str] = None
            ) -> "chaos.FaultInjector":
        """Arm a FaultPlan across the live cluster (ISSUE-13): one
        shared injector, per-node fault hooks on every engine's send
        path — the same seam the virtual net and the live engine use.
        ``groups`` maps node INDEX → plan group; membership is derived
        from each runner's bound port so link rules and partitions
        match real datagrams.  An asymmetric partition is enforced at
        the SENDER (each direction's source drops), exactly netem's
        egress qdisc semantics.  Nodes launched later (churn
        replacements) are hooked automatically and join
        ``default_group`` (wildcard when None)."""
        groups = groups or {}
        self._default_group = default_group
        self.injector = chaos.FaultInjector(plan)
        self.injector.arm(time.monotonic())
        for i, r in enumerate(self.nodes):
            self._arm_one(r, groups.get(i, default_group))
        return self.injector

    def disarm(self) -> None:
        if getattr(self, "injector", None) is None:
            return
        for r in self.nodes:
            try:
                chaos.disarm_dht(r._dht._dht)
            except Exception:
                pass
        self.injector = None
        self._default_group = None

    # ------------------------------------------------------------- plumbing
    def wait_connected(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(r.get_status() is NodeStatus.CONNECTED
                   for r in self.nodes):
                return True
            time.sleep(0.05)
        return False

    def random_node(self) -> DhtRunner:
        return self.rng.choice(self.nodes)

    def get(self, key: InfoHash, timeout: float = 30.0) -> List[Value]:
        return self.random_node().get_sync(key, timeout=timeout)

    def put(self, key: InfoHash, value: Value, timeout: float = 30.0) -> bool:
        """(↔ the cluster put request, network.py:252-266)"""
        return self.random_node().put_sync(key, value, timeout=timeout)

    def __len__(self) -> int:
        return len(self.nodes)

    def __enter__(self) -> "DhtNetwork":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
