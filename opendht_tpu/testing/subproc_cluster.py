"""Out-of-process cluster tier: a whole DHT cluster in a CHILD process,
remote-controlled over a msgpack-stdin RPC channel.

Analog of the reference's ``DhtNetworkSubProcess`` (reference
python/tools/dht/network.py:42-281), which spawns clusters in separate
processes (there: via NSPopen into a netns) and drives them with
line-commands over stdin.  The TPU build keeps the process boundary —
it is what makes concurrency bugs in runner/engine visible instead of
GIL-masked, and lets a test kill an entire cluster with one signal —
but upgrades the control channel to length-delimited msgpack request/
response frames (the project wire codec) instead of ad-hoc text.

Protocol (child stdin → request, child stdout → response, stderr free
for logs):  each frame is one msgpack map ``{"op": str, ...}`` /
``{"ok": bool, ...}``.  Ops:

  launch {n}            → {ok, ports: [int], ids: [bytes]}
  resize {n}            → {ok, n}
  bootstrap {host,port} → {ok}   (every node dials the address —
                                  interconnects clusters across processes)
  put {key, value}      → {ok, stored: bool}
  get {key}             → {ok, values: [bytes]}
  ids {}                → {ok, ids: [bytes]}
  stats {}              → {ok, n, msgs: int}
  quit {}               → {ok} then child exits

The child pins JAX to CPU before any backend touch (a fresh process on
this machine would otherwise grab the single-client TPU tunnel).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Optional

import msgpack


# ---------------------------------------------------------------------------
# parent side
# ---------------------------------------------------------------------------

class ClusterSubProcess:
    """Parent-side handle: spawn, RPC, and (ungracefully) kill a child
    process hosting a whole cluster of live UDP DHT nodes."""

    def __init__(self, n_nodes: int = 0, *, timeout: float = 60.0,
                 argv_prefix: tuple = ()):
        """``argv_prefix``: argv prepended to the child command — e.g.
        ``("ip", "netns", "exec", ns)`` runs the whole cluster inside a
        network namespace (the real-kernel tier, testing/netns_net.py)."""
        self.timeout = timeout
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # The CPU pin must land BEFORE the first opendht_tpu import:
        # package import materializes device arrays, and on hosts where
        # a sitecustomize routes jax to an accelerator backend (e.g. the
        # single-client TPU tunnel) a `-m` child would grab it during
        # module resolution — jax.config.update after that is too late
        # (observed: 20 s remote compiles inside the child's packet loop,
        # every request timing out).  `-c` sequences the pin first.
        boot = ("import jax; jax.config.update('jax_platforms','cpu'); "
                "import sys; "
                "from opendht_tpu.testing.subproc_cluster import _child_main; "
                "sys.exit(_child_main())")
        self.proc = subprocess.Popen(
            [*argv_prefix, sys.executable, "-c", boot],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, env=env)
        self._unpacker = msgpack.Unpacker(raw=True)
        self.ports: list[int] = []
        self.ids: list[bytes] = []
        if n_nodes:
            self.launch(n_nodes)

    # -- framing -----------------------------------------------------------
    def _call(self, op: str, **kw) -> dict:
        import selectors
        req = {"op": op, **kw}
        self.proc.stdin.write(msgpack.packb(req, use_bin_type=True))
        self.proc.stdin.flush()
        deadline = time.monotonic() + self.timeout
        sel = selectors.DefaultSelector()
        sel.register(self.proc.stdout, selectors.EVENT_READ)
        try:
            while True:
                for msg in self._unpacker:
                    out = {k.decode(): v for k, v in msg.items()}
                    if not out.get("ok"):
                        raise RuntimeError(
                            f"child {op} failed: {out.get('error')!r}")
                    return out
                # poll with a bounded wait so a hung-but-alive child
                # raises TimeoutError instead of blocking read1 forever
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"child {op} timed out after {self.timeout}s")
                if not sel.select(timeout=min(left, 1.0)):
                    continue
                chunk = self.proc.stdout.read1(65536)
                if not chunk:
                    raise RuntimeError(
                        f"child died mid-{op} (rc={self.proc.poll()})")
                self._unpacker.feed(chunk)
        finally:
            sel.close()

    # -- cluster ops -------------------------------------------------------
    def launch(self, n: int) -> list[int]:
        out = self._call("launch", n=n)
        self.ports = list(out["ports"])
        self.ids = list(out["ids"])
        return self.ports

    def resize(self, n: int) -> None:
        self._call("resize", n=n)

    def bootstrap(self, host: str, port: int) -> None:
        self._call("bootstrap", host=host, port=port)

    def put(self, key: bytes, value: bytes) -> bool:
        return bool(self._call("put", key=key, value=value)["stored"])

    def get(self, key: bytes) -> list[bytes]:
        return list(self._call("get", key=key)["values"])

    def node_ids(self) -> list[bytes]:
        return list(self._call("ids")["ids"])

    def stats(self) -> dict:
        return self._call("stats")

    # -- lifecycle ---------------------------------------------------------
    def quit(self) -> None:
        """Graceful shutdown: nodes join, child exits 0."""
        try:
            self._call("quit")
        except Exception:
            pass
        self.proc.wait(timeout=self.timeout)

    def kill(self) -> None:
        """Simulate whole-cluster failure: SIGKILL, no goodbyes — every
        node in the child vanishes without expiring its peers' routing
        entries (↔ the reference churn scenarios killing NSPopen
        clusters)."""
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=self.timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self.proc.poll() is None:
            self.quit()


# ---------------------------------------------------------------------------
# child side
# ---------------------------------------------------------------------------

def _child_main() -> int:
    # NOTE: the platform pin happens in the parent's spawn bootstrap
    # (before any opendht_tpu import — see ClusterSubProcess.__init__);
    # by the time this runs, importing this module has already touched
    # the backend, so a pin here would be too late.
    from ..infohash import InfoHash
    from ..core.value import Value
    from .dhtcluster import NodeCluster

    # Warm the device lookup kernels BEFORE any node exchanges packets:
    # the first find_closest triggers several jit compiles (sort /
    # expand / lookup, a few seconds on CPU) and a compile stall inside
    # the packet path drops every in-flight request — observed as the
    # first put of a fresh child hanging until search expiry.
    from ..core.table import NodeTable
    _warm = NodeTable(InfoHash.get("warmup-self"))
    _warm.insert(InfoHash.get("warmup-peer"), None)
    _warm.find_closest([InfoHash.get("warmup-target")])
    del _warm

    cluster = NodeCluster()
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    unpacker = msgpack.Unpacker(raw=True)

    def reply(**kw):
        stdout.write(msgpack.packb({"ok": True, **kw}, use_bin_type=True))
        stdout.flush()

    def fail(err):
        import traceback
        text = ("".join(traceback.format_exception(err)).strip()
                if isinstance(err, BaseException) else str(err))
        stdout.write(msgpack.packb({"ok": False, "error": text},
                                   use_bin_type=True))
        stdout.flush()

    while True:
        chunk = stdin.read1(65536)
        if not chunk:
            break
        unpacker.feed(chunk)
        for msg in unpacker:
            req = {k.decode(): v for k, v in msg.items()}
            op = req.get("op", b"").decode() \
                if isinstance(req.get("op"), bytes) else req.get("op")
            try:
                if op == "launch":
                    cluster.resize(int(req["n"]))
                    reply(ports=[n.get_bound_port() for n in cluster.nodes],
                          ids=[bytes(n.get_node_id())
                               for n in cluster.nodes])
                elif op == "resize":
                    cluster.resize(int(req["n"]))
                    reply(n=len(cluster.nodes))
                elif op == "bootstrap":
                    host = req["host"]
                    host = host.decode() if isinstance(host, bytes) else host
                    for n in cluster.nodes:
                        n.bootstrap(host, int(req["port"]))
                    reply()
                elif op == "put":
                    ok = cluster.nodes[0].put_sync(
                        InfoHash(req["key"]), Value(req["value"]),
                        timeout=30.0)
                    reply(stored=bool(ok))
                elif op == "get":
                    vals = cluster.nodes[0].get_sync(
                        InfoHash(req["key"]), timeout=30.0) or []
                    reply(values=[bytes(v.data) for v in vals])
                elif op == "ids":
                    reply(ids=[bytes(n.get_node_id())
                               for n in cluster.nodes])
                elif op == "stats":
                    msgs = 0
                    for n in cluster.nodes:
                        st = n.get_node_message_stats()
                        msgs += sum(st) if st else 0
                    reply(n=len(cluster.nodes), msgs=msgs)
                elif op == "quit":
                    reply()
                    cluster.resize(0)
                    return 0
                else:
                    fail(f"unknown op {op!r}")
            except Exception as e:                      # keep serving
                fail(e)
    cluster.resize(0)
    return 0


if __name__ == "__main__":
    if "--child" in sys.argv:
        sys.exit(_child_main())
    print(__doc__)
