"""Cluster health aggregator (ISSUE-9 tentpole, cluster half).

The node side (opendht_tpu/health.py) rolls each node's signals into a
verdict; this module answers the *cluster*-level questions the paper's
invariants pose (PAPER.md layer map; ROADMAP item 4's measurement
half):

- **Scrape**: every node's ``GET /healthz`` verdict and ``GET /stats``
  Prometheus exposition (:func:`scrape_node`), summed into cluster
  series (:func:`merge_series`).
- **Global lookup success rate** (:func:`lookup_success`): cluster-wide
  ``dht_ops_total{op="get",ok=}`` ratio — the "lookups succeed"
  invariant.
- **Cluster op-latency percentiles** (:func:`cluster_quantile`): the
  per-op ``dht_op_seconds_bucket`` series merged across nodes and
  interpolated with the same log-bucket math the node histograms use
  (health.quantile_from_cumulative) — drives the shared
  ``--alert PCT=SEC`` grammar.
- **Batched replica-coverage probe** (:func:`replica_coverage`): the
  paper invariant "a value lives on the 8 XOR-closest nodes", checked
  directly: sample stored keys across the cluster, resolve the TRUE
  closest-8 for the whole sample in ONE
  ``NodeTable.find_closest`` launch over a census table of the live
  node ids (the round-5/round-13 batched kernel — pass ``mesh=`` to
  ride the t-sharded table), then cross-check which of those nodes
  actually hold each value.  K sampled keys cost one lane-padded
  launch, not K — pinned equal to the per-key scalar loop in
  tests/test_health.py.

``tools/dhtmon.py`` is the CLI over these helpers (exit-code
thresholds for CI and soak); ``testing/health_smoke.py`` drives both
against a live cluster in CI.
"""

from __future__ import annotations

import json
import random
import re
import time
import urllib.error
import urllib.request
from typing import Dict, Iterable, List, Optional, Tuple

from ..health import quantile_from_cumulative
from ..infohash import InfoHash
from ..sockaddr import SockAddr

#: ``dht_op_seconds_bucket{op="get",le="0.25"}`` → (op, le)
_BUCKET_RE = re.compile(
    r'^dht_op_seconds_bucket\{le="([^"]+)",op="([^"]+)"\}$'
    r'|^dht_op_seconds_bucket\{op="([^"]+)",le="([^"]+)"\}$')


# ================================================================ scraping
def scrape_node(endpoint: str, timeout: float = 10.0) -> dict:
    """One node's health + stats off its proxy: ``{"endpoint",
    "ready", "verdict", "health", "series"}``.  ``endpoint`` is
    ``host:port`` of the node's REST proxy."""
    base = "http://" + endpoint.rstrip("/")
    req = urllib.request.Request(base + "/healthz")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            hz = json.loads(r.read().decode())
            code = r.status
    except urllib.error.HTTPError as e:       # 503 carries the body too
        hz = json.loads(e.read().decode() or "{}")
        code = e.code
    with urllib.request.urlopen(base + "/stats", timeout=timeout) as r:
        text = r.read().decode()
    from .telemetry_smoke import parse_exposition
    return {
        "endpoint": endpoint,
        "ready": code == 200,
        "verdict": hz.get("verdict", "unknown"),
        "health": hz.get("health", {}),
        "series": parse_exposition(text),
    }


def scrape_history(endpoint: str, since: float,
                   timeout: float = 10.0) -> Optional[dict]:
    """One node's ``GET /history?since=SEC`` document (round 17), with
    the LOCAL wall clock stamped as ``scraped_at`` so the timeline
    assembler can estimate skew.  ``None`` when the node does not
    export history (route missing, scrape error, or recorder disabled)
    — the caller's signal to fall back to scrape-diff-scrape."""
    base = "http://" + endpoint.rstrip("/")
    try:
        with urllib.request.urlopen(
                base + "/history?since=%g" % since, timeout=timeout) as r:
            doc = json.loads(r.read().decode())
    except Exception:
        return None
    if not isinstance(doc, dict) or not doc.get("enabled"):
        return None
    doc["endpoint"] = endpoint
    doc["scraped_at"] = time.time()
    return doc


def merge_history_series(histories: Iterable[dict]) -> Dict[str, float]:
    """Sum every node's history frames into ONE windowed series map of
    the exact shape :func:`merge_series` builds from ``GET /stats``
    scrapes — so :func:`lookup_success` / :func:`cluster_quantile`
    evaluate windowed invariants over history through the same code
    path as the scrape-diff mode (one delta codepath, round 17)."""
    from ..history import frames_to_series
    out: Dict[str, float] = {}
    for h in histories:
        for k, v in frames_to_series(h.get("frames") or []).items():
            out[k] = out.get(k, 0.0) + v
    return out


def merge_series(scrapes: Iterable[dict]) -> Dict[str, float]:
    """Sum every Prometheus series across node scrapes (counters and
    cumulative buckets sum; the cluster invariants below only read
    summed series)."""
    out: Dict[str, float] = {}
    for sc in scrapes:
        for k, v in sc["series"].items():
            out[k] = out.get(k, 0.0) + v
    return out


# ===================================================== cluster invariants
def lookup_success(series: Dict[str, float],
                   op: str = "get") -> Optional[Tuple[float, float]]:
    """Cluster-wide op success ratio from the summed
    ``dht_ops_total{op=,ok=}`` counters: ``(ratio, total_ops)``; None
    with zero traffic (unknown is not a violation)."""
    ok = series.get('dht_ops_total{ok="true",op="%s"}' % op, 0.0)
    bad = series.get('dht_ops_total{ok="false",op="%s"}' % op, 0.0)
    total = ok + bad
    if total <= 0:
        return None
    return ok / total, total


def op_latency_buckets(series: Dict[str, float]
                       ) -> Dict[str, List[Tuple[float, float]]]:
    """Per-op cumulative ``(le, count)`` pairs from the summed
    ``dht_op_seconds_bucket`` series (the +Inf bucket dropped — the
    finite edges carry the distribution)."""
    out: Dict[str, list] = {}
    for name, v in series.items():
        m = _BUCKET_RE.match(name)
        if not m:
            continue
        le_s, op = (m.group(1), m.group(2)) if m.group(1) is not None \
            else (m.group(4), m.group(3))
        if le_s == "+Inf":
            continue
        out.setdefault(op, []).append((float(le_s), v))
    return {op: sorted(pairs) for op, pairs in out.items()}


def cluster_quantile(series: Dict[str, float], op: str,
                     q: float) -> Optional[float]:
    """Cluster-merged latency quantile of one op family; None without
    data."""
    pairs = op_latency_buckets(series).get(op)
    return quantile_from_cumulative(pairs, q) if pairs else None


# ================================================= replica-coverage probe
def census_table(nodes: List[Tuple[InfoHash, Optional[SockAddr]]],
                 now: float):
    """A :class:`~opendht_tpu.core.table.NodeTable` holding every live
    cluster node as a confirmed, reachable peer — the ground-truth
    membership the closest-8 invariant is defined over (the observer
    id is random, so no cluster node is excluded as "self").  Bucket
    admission is widened to the census size: a routing table may
    legitimately cache-and-drop far peers, a census must not."""
    from ..core.table import NodeTable
    nodes = list(nodes)
    t = NodeTable(InfoHash.get_random(), k=max(8, len(nodes)))
    for nid, addr in nodes:
        t.insert(nid, addr if addr is not None
                 else SockAddr("127.0.0.1", 1), now, confirm=2)
    return t


def closest_ids(table, keys: List[InfoHash], k: int = 8, mesh=None,
                now: Optional[float] = None) -> List[List[InfoHash]]:
    """TRUE closest-``k`` node ids for MANY keys from ONE batched
    ``find_closest`` resolve (the round-5 kernel; ``mesh`` row-shards
    the resolve over ``t`` devices, round 13).  The scalar oracle —
    one ``find_closest`` per key — is pinned equal in
    tests/test_health.py."""
    if not keys:
        return []
    if now is None:
        now = time.monotonic()
    rows, _dist = table.find_closest(list(keys), k=k, now=now, mesh=mesh)
    ids = table.ids_of_rows(rows)
    k_out = rows.shape[1]
    return [[ids[qi * k_out + j] for j in range(k_out)
             if rows[qi, j] >= 0]
            for qi in range(rows.shape[0])]


def stored_keys(runners) -> Dict[InfoHash, set]:
    """``key -> {node-id hex}`` of every non-empty storage across the
    cluster's runners (in-process probe surface)."""
    held: Dict[InfoHash, set] = {}
    for r in runners:
        nid = str(r.get_node_id())
        for key, st in r._dht.store.items():
            if not st.empty():
                held.setdefault(key, set()).add(nid)
    return held


def replica_coverage(runners, sample_max: int = 64, k: int = 8,
                     mesh=None, seed: int = 0) -> dict:
    """The batched replica-coverage probe over an in-process cluster:
    sample up to ``sample_max`` stored keys, resolve every key's true
    closest-``k`` in ONE device launch, and report what fraction of
    those expected replicas actually hold the value."""
    now = time.monotonic()
    held = stored_keys(runners)
    keys = sorted(held, key=bytes)
    if len(keys) > sample_max:
        random.Random(seed).shuffle(keys)
        keys = sorted(keys[:sample_max], key=bytes)
    nodes = [(r.get_node_id(),
              SockAddr("127.0.0.1", r.get_bound_port() or 1))
             for r in runners]
    table = census_table(nodes, now)
    per_key = []
    for key, closest in zip(keys, closest_ids(table, keys, k=k,
                                              mesh=mesh, now=now)):
        want = [str(nid) for nid in closest]
        have = sum(1 for w in want if w in held[key])
        per_key.append({
            "key": key.hex(),
            "expected": len(want),
            "held": have,
            "coverage": (have / len(want)) if want else 1.0,
        })
    covs = [p["coverage"] for p in per_key]
    return {
        "keys": len(per_key),
        "nodes": len(runners),
        "k": k,
        "sampled_of": len(held),
        "mean_coverage": (sum(covs) / len(covs)) if covs else None,
        "min_coverage": min(covs) if covs else None,
        "per_key": per_key,
    }
