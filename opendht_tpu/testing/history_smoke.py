"""End-to-end flight-data-recorder smoke (round 17, CI satellite).

Boots a 3-node real-UDP cluster + REST proxy and asserts what the unit
tier cannot:

1. **dhtmon windows read history, not scrape-diff-scrape**: with every
   node exporting ``GET /history``, ``run_checks(window=...)`` sources
   its windowed invariants from the recorders (``window_source ==
   "history"``, no wait) and the result is PINNED EQUAL to the legacy
   evaluation of the same interval.
2. **An induced SLO burn materializes a black-box bundle**: choking
   ingest admission fast-burns the availability SLO (the round-14
   failure mode); the unhealthy transition auto-captures a bundle whose
   history frames SHOW the burn (``ok="false"`` get deltas), and
   ``GET /debug/bundle`` serves fresh bundles over the proxy.
3. **dhtmon --since gates on the windowed invariant**: nonzero while
   the burn sits in the history window, 0 again once recovery rolls it
   out — no second scrape, no sleep inside dhtmon.
4. **The bundle round-trips through the cluster timeline assembler**
   with the health transition present and per-node frame monotonicity
   clean.
5. **Ring and spill stay bounded under a 10x flood** (RSS- and
   disk-stable; oldest evicted on both).

Run directly (CI does)::

    python -m opendht_tpu.testing.history_smoke
"""

from __future__ import annotations

import json
import sys
import tempfile
import time

from ..core.value import Value
from ..infohash import InfoHash
from ..history import BUNDLE_KIND, HistoryConfig, MetricsHistory
from ..runtime.config import Config, NodeStatus
from ..runtime.runner import DhtRunner, RunnerConfig
from ..telemetry import MetricsRegistry
from ..tools import dhtmon
from . import health_monitor as hm
from . import timeline_assembler as ta

N_NODES = 3
N_KEYS = 10
OP_TIMEOUT = 30.0
TICK = 0.25


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return pred()


def ring_spill_bounded_check(factor: int = 10) -> None:
    """10x the ring capacity of busy frames: the ring must stay at
    capacity (oldest evicted), the spill at its segment bound (oldest
    segment deleted), and RSS must not retain O(total)."""
    import resource

    cap, seg, max_seg = 128, 16, 3
    reg = MetricsRegistry()
    clock = [0.0]
    with tempfile.TemporaryDirectory(prefix="odt-hist-flood-") as d:
        rec = MetricsHistory(
            HistoryConfig(period=1.0, capacity=cap, spill_dir=d,
                          spill_segment_frames=seg,
                          spill_max_segments=max_seg),
            registry=reg, clock=lambda: clock[0])
        c = reg.counter("flood_total")
        h = reg.histogram("flood_seconds")
        rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        total = cap * factor
        rec.tick()
        for i in range(total):
            clock[0] += 1.0
            c.inc(i + 1)
            h.observe(float(i % 7) + 0.1)
            rec.tick()
        frames = rec.frames()
        assert len(frames) == cap, \
            "ring grew past capacity: %d" % len(frames)
        assert frames[0]["seq"] == total - cap + 1, \
            "oldest retained is %d, expected %d" % (
                frames[0]["seq"], total - cap + 1)
        assert rec.spill_segments <= max_seg, \
            "spill grew past its bound: %d segments" % rec.spill_segments
        spilled = rec.spilled_frames()
        assert 0 < len(spilled) <= max_seg * seg
        rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        grown_kib = rss1 - rss0
        assert grown_kib < 32 * 1024, \
            "RSS grew %d KiB over a %d-frame flood" % (grown_kib, total)


def main(argv=None) -> int:
    from ..proxy import DhtProxyServer

    runners = []
    proxy = None
    try:
        for i in range(N_NODES):
            cfg = Config(node_id=InfoHash.get("history-smoke-node-%d" % i))
            cfg.health.period = TICK
            cfg.history.period = TICK
            r = DhtRunner()
            r.run(0, RunnerConfig(dht_config=cfg))
            runners.append(r)
            if i == 0:
                proxy = DhtProxyServer(r, 0)
            else:
                r.bootstrap("127.0.0.1", runners[0].get_bound_port())
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners)), \
            "cluster failed to connect"
        ep = "127.0.0.1:%d" % proxy.port

        # --- traffic so the windows have data
        keys = [InfoHash.get("history-smoke-%d" % i) for i in range(N_KEYS)]
        for i, key in enumerate(keys):
            assert runners[1 + i % (N_NODES - 1)].put_sync(
                key, Value(b"hv-%d" % i, value_id=i + 1),
                timeout=OP_TIMEOUT)
        for key in keys:
            assert runners[0].get_sync(key, timeout=OP_TIMEOUT)
        # let the recorders tick the traffic into frames, then quiesce
        time.sleep(3 * TICK)

        # --- 1: dhtmon's window comes from history (no wait), pinned
        # equal to the legacy paths over the same interval.  The
        # cluster is quiet now, so (a) a long history window holds
        # exactly the cumulative traffic (all ops happened after the
        # first recorder tick), and (b) a scrape-diff window would
        # measure an empty interval — the history path must agree with
        # each.
        t0 = time.monotonic()
        _v, doc_h = dhtmon.run_checks([ep], min_success=0.5, window=60.0)
        assert doc_h["window_source"] == "history", doc_h
        assert time.monotonic() - t0 < 5.0, \
            "history-backed window should not sleep out the window"
        _v, doc_c = dhtmon.run_checks([ep], min_success=0.5)
        assert doc_h["lookup_success"] == doc_c["lookup_success"], \
            (doc_h["lookup_success"], doc_c["lookup_success"])
        saved_scrape = hm.scrape_history
        try:
            hm.scrape_history = lambda *a, **kw: None   # node "lacks" it
            _v, doc_f = dhtmon.run_checks([ep], min_success=0.5,
                                          window=1.0)
        finally:
            hm.scrape_history = saved_scrape
        assert doc_f["window_source"] == "scrape-diff", doc_f
        _v, doc_q = dhtmon.run_checks([ep], min_success=0.5, window=1.0)
        assert doc_q["window_source"] == "history"
        # both quiet-window evaluations see no traffic: unknown, equal
        assert doc_q["lookup_success"] == doc_f["lookup_success"], \
            (doc_q["lookup_success"], doc_f["lookup_success"])

        # --- 2: induce the SLO burn (round-12 backpressure choke) and
        # assert the black box materializes
        assert not runners[0].get_bundles(), \
            "unexpected pre-burn auto bundle"
        wb = runners[0]._dht.wave_builder
        saved_max = wb.queue_max
        wb.queue_max = 0
        fails = []
        for i in range(10):
            runners[0].get(keys[i % N_KEYS], lambda vals: True,
                           lambda ok, ns: fails.append(ok))
        assert _wait(lambda: len(fails) == 10), "shed gets never completed"
        assert not any(fails), "gets unexpectedly succeeded while choked"
        assert _wait(lambda: runners[0].get_health()["verdict"]
                     == "unhealthy", timeout=20.0), \
            "verdict never reached unhealthy: %r" % (
                runners[0].get_health(),)
        assert _wait(lambda: runners[0].get_bundles(), timeout=10.0), \
            "no auto-captured bundle after the unhealthy transition"
        bundle = runners[0].get_bundles()[-1]
        assert bundle["kind"] == BUNDLE_KIND
        assert bundle["reason"] == "health_transition"
        assert bundle["transition"]["to"] == "unhealthy"
        burn = sum(f["counters"].get(
            'dht_ops_total{ok="false",op="get"}', 0)
            for f in bundle["history"]["frames"])
        assert burn > 0, "burn not visible in the bundle's frames"
        # fresh bundles serve over the proxy and list the auto capture
        import urllib.request
        with urllib.request.urlopen(
                "http://%s/debug/bundle" % ep, timeout=10) as r:
            fresh = json.loads(r.read().decode())
        assert fresh["kind"] == BUNDLE_KIND
        assert fresh["auto_captures"], fresh["auto_captures"]

        # --- 3: dhtmon --since trips on the windowed invariant...
        rc = dhtmon.main(["--nodes", ep, "--min-success", "0.99",
                          "--since", "60"])
        assert rc == 1, "dhtmon --since missed the burn (rc=%d)" % rc
        # ...and clears once recovery rolls it out of the window — the
        # burn stays in the LONG window (the ring remembers), so the
        # short --since is what recovers; no sleep inside dhtmon
        wb.queue_max = saved_max
        time.sleep(8 * TICK)          # let the short window roll clean
        rc = dhtmon.main(["--nodes", ep, "--min-success", "0.99",
                          "--since", "1.0"])
        assert rc == 0, "dhtmon --since alerted on a recovered " \
            "cluster (rc=%d)" % rc

        # --- 4: the bundle round-trips through the timeline assembler
        # with the transition present
        bundle_rt = json.loads(json.dumps(bundle))
        sources = [hm.scrape_history(ep, 120.0),
                   runners[1].get_history(), runners[2].get_history(),
                   bundle_rt]
        assert sources[0] is not None
        tl = ta.assemble_timeline(sources)
        assert not tl["violations"], tl["violations"]
        assert len(tl["frames"]) > 3
        evs = ta.find_events(tl, "health_transition")
        assert any(e["attrs"].get("to") == "unhealthy" for e in evs), evs
        series = ta.window_series(tl)
        assert series.get('dht_ops_total{ok="false",op="get"}', 0) > 0

        # --- 5: bounded under flood
        ring_spill_bounded_check()

        print("history_smoke: OK — windows via %s (pinned equal), "
              "bundle captured on burn (%d failed-get deltas in "
              "frames), dhtmon --since 1 then 0, timeline %d frames/"
              "%d transition events, ring+spill bounded"
              % (doc_h["window_source"], int(burn),
                 len(tl["frames"]), len(evs)))
        return 0
    finally:
        if proxy is not None:
            proxy.stop()
        for r in runners:
            r.join()


if __name__ == "__main__":
    sys.exit(main())
