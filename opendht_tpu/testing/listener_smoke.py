"""End-to-end wave-scale listen/push smoke (ISSUE-20 CI satellite).

Boots a 3-node real-UDP cluster + REST proxy (node 0 runs the batched
listener table; node 1 runs ``listen_batching="off"`` — the live half
of the batched == off pin) and asserts the four things the unit tier
cannot:

1. **Scale**: >= 512 live listeners register across runner ops and
   proxy SUBSCRIBE/LISTEN registrations and ALL of them deliver.
2. **Result equivalence on every delivery surface**: a Zipf put flood
   delivers through node 0's batched match with the same per-key value
   sets as node 1's synchronous path — on runner callbacks (every one
   of the key's listeners agrees), on the proxy LISTEN stream, and on
   SUBSCRIBE push dispatches (observed through the injected
   ``push_sender``).
3. **Observability**: ``dht_listener_*`` occupancy/latency series
   advance on the proxy's Prometheus ``GET /stats`` and ``GET
   /listeners`` reflects the table.
4. **The dhtmon gate**: ``--max-listener-lag`` reads 0 on the healthy
   cluster and flips to 1 under an injected drain stall (the flush
   path wedged while puts buffer, then released — the delivery arrives
   LATE and the windowed lag p95 crosses the gate).

Run directly (CI does)::

    python -m opendht_tpu.testing.listener_smoke
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.request

from ..core.value import Value
from ..infohash import InfoHash
from ..runtime.config import Config, NodeStatus
from ..runtime.runner import DhtRunner, RunnerConfig
from ..tools import dhtmon

N_NODES = 3
N_KEYS = 24                 # flood keys
PER_KEY = 21                # node-0 runner listeners per key (24*21 = 504)
N_SUBSCRIBE = 15            # proxy push registrations (keys 0..14)
OP_TIMEOUT = 30.0
LAG_GATE = 0.25             # dhtmon --max-listener-lag threshold (s)
STALL_S = 0.8               # injected drain-stall duration


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _get_text(port: int, path: str) -> str:
    with urllib.request.urlopen(
            "http://127.0.0.1:%d/%s" % (port, path), timeout=10) as r:
        return r.read().decode()


def _series(stats_text: str, prefix: str) -> dict:
    out = {}
    for ln in stats_text.splitlines():
        if ln.startswith(prefix) and " " in ln:
            name, val = ln.rsplit(" ", 1)
            try:
                out[name] = float(val)
            except ValueError:
                pass
    return out


def main(argv=None) -> int:
    from ..proxy import DhtProxyServer

    runners = []
    proxy = None
    stream_resp = None
    try:
        pushes = []                     # (client_id, payload) dispatches

        for i in range(N_NODES):
            cfg = Config(node_id=InfoHash.get("listener-smoke-node-%d" % i))
            if i == 1:
                cfg.listen_batching = "off"   # the equivalence arm
            if i == 0:
                # slow frame cadence: the lag-p95 gauge holds each
                # completed window long enough for dhtmon to scrape it
                cfg.history.period = 2.0
            r = DhtRunner()
            r.run(0, RunnerConfig(dht_config=cfg))
            runners.append(r)
            if i == 0:
                proxy = DhtProxyServer(
                    r, 0, push_sender=lambda cid, data:
                        pushes.append((cid, data)))
            else:
                r.bootstrap("127.0.0.1", runners[0].get_bound_port())
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners)), \
            "cluster failed to connect"

        keys = [InfoHash.get("listener-smoke-key-%d" % i)
                for i in range(N_KEYS)]

        # --- 1: register the fleet.  node 0: PER_KEY runner listeners
        # per key (each its own collector, so per-listener agreement is
        # checkable); node 1: one off-arm collector per key; proxy: a
        # LISTEN stream + N_SUBSCRIBE push registrations on node 0.
        heard0 = [[set() for _ in range(PER_KEY)] for _ in range(N_KEYS)]
        heard1 = [set() for _ in range(N_KEYS)]

        def collector(sink: set):
            def cb(vals, expired):
                if not expired:
                    sink.update(v.id for v in vals)
                return True
            return cb

        live = 0
        futs = []

        def _drain():
            nonlocal live
            for f in futs:
                tok = f.result(OP_TIMEOUT)
                assert tok != 0, "listen shed by ingest backpressure"
                live += 1
            del futs[:]

        for ki, key in enumerate(keys):
            for li in range(PER_KEY):
                futs.append(runners[0].listen(
                    key, collector(heard0[ki][li])))
            futs.append(runners[1].listen(key, collector(heard1[ki])))
            _drain()                    # chunked: one key's fleet at a time

        # LISTEN stream on key 0 (one JSON line per value; heartbeat
        # lines carry no "id")
        stream_ids: set = set()
        stream_resp = urllib.request.urlopen(urllib.request.Request(
            "http://127.0.0.1:%d/%s" % (proxy.port, keys[0].hex()),
            method="LISTEN"), timeout=120)

        def _drain_stream():
            for ln in stream_resp:
                try:
                    obj = json.loads(ln)
                except ValueError:
                    continue
                if "id" in obj and not obj.get("expired"):
                    stream_ids.add(int(obj["id"]))
        threading.Thread(target=_drain_stream, daemon=True).start()
        live += 1

        for si in range(N_SUBSCRIBE):
            req = urllib.request.Request(
                "http://127.0.0.1:%d/%s" % (proxy.port, keys[si].hex()),
                data=json.dumps({"client_id": "push-client-%d" % si,
                                 "token": si + 1}).encode(),
                method="SUBSCRIBE")
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert json.loads(resp.read())["token"], "subscribe failed"
            live += 1
        assert live >= 512, "only %d live listeners registered" % live

        # let the registration burst's search traffic settle before the
        # flood (the 500-listener spike can briefly backlog the reader)
        time.sleep(2.0)
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners)), \
            "cluster lost connectivity under the listener fleet"

        # --- 2: Zipf put flood from node 2 — key i draws ~ 1/(i+1)
        # of the traffic, unique value ids per key
        expect = [set() for _ in range(N_KEYS)]
        vid = 0
        for rank, key in enumerate(keys):
            n_puts = max(1, 36 // (rank + 1))
            for _ in range(n_puts):
                vid += 1
                v = Value(b"flood-%05d" % vid, value_id=vid)
                ok = False
                for _attempt in range(3):     # ride out transient backlog
                    if runners[2].put_sync(key, v, timeout=OP_TIMEOUT):
                        ok = True
                        break
                    time.sleep(0.5)
                assert ok, "put %d failed after retries" % vid
                expect[rank].add(vid)

        # batched == off on every surface, all listeners agree
        def all_delivered() -> bool:
            for ki in range(N_KEYS):
                if heard1[ki] != expect[ki]:
                    return False
                for li in range(PER_KEY):
                    if heard0[ki][li] != expect[ki]:
                        return False
            return stream_ids == expect[0]
        assert _wait(all_delivered, timeout=60.0), \
            "batched/off delivery sets diverged: key0 batched %r off %r " \
            "stream %r expect %r" % (heard0[0][0], heard1[0],
                                     stream_ids, expect[0])
        for si in range(N_SUBSCRIBE):
            want = expect[si]
            got = set()
            for cid, data in list(pushes):
                if cid == "push-client-%d" % si and not data.get("expired"):
                    got.update(int(i) for i in data.get("ids", []))
            assert want <= got, \
                "push surface missed values for key %d: %r vs %r" \
                % (si, sorted(got), sorted(want))

        # --- 3: series advance on the Prometheus surface
        stats = _get_text(proxy.port, "stats")
        occ = _series(stats, "dht_listener_occupancy")
        fl = _series(stats, "dht_listener_flushes_total")
        mt = _series(stats, "dht_listener_matches_total")
        lag = _series(stats, "dht_listener_lag_p95")
        assert occ and max(occ.values()) >= N_KEYS, occ
        assert fl and max(fl.values()) > 0, fl
        assert mt and max(mt.values()) > 0, mt
        assert lag, "no dht_listener_lag_p95 series on /stats"
        lsnap = json.loads(_get_text(proxy.port, "listeners"))
        assert lsnap["enabled"] and lsnap["occupancy"] >= N_KEYS, lsnap

        # --- 4: dhtmon gate — 0 healthy, 1 under an injected drain
        # stall.  Healthy first: nothing above the gate (unknown/-1
        # never violates, live lags sit ~flush_deadline << LAG_GATE).
        node = "127.0.0.1:%d" % proxy.port
        rc = dhtmon.main(["--nodes", node,
                          "--max-listener-lag", str(LAG_GATE)])
        assert rc == 0, "dhtmon flagged a healthy listener path (rc=%d)" \
            % rc

        # stall injection: wedge the drain (flush no-ops while puts
        # buffer), release after STALL_S, kick a wave — the buffered
        # delivery lands LATE and the next lag window crosses the gate
        lt = runners[0]._dht.listener_table
        flipped = False
        for attempt in range(3):
            vid += 1
            lt.pending = lambda: 0            # wedge: flush sees empty
            try:
                assert runners[1].put_sync(
                    keys[0], Value(b"stalled-%d" % vid, value_id=vid),
                    timeout=OP_TIMEOUT)
                time.sleep(STALL_S)
            finally:
                del lt.pending                # release the drain
            runners[0].get_sync(keys[0], timeout=OP_TIMEOUT)  # fire a wave
            if not _wait(lambda: (lt.lag_p95() or -1.0) > LAG_GATE,
                         timeout=8.0, step=0.1):
                continue
            if dhtmon.main(["--nodes", node, "--max-listener-lag",
                            str(LAG_GATE)]) == 1:
                flipped = True
                break
        assert flipped, "dhtmon never flagged the injected drain stall"

        print("listener_smoke: OK — %d live listeners, %d Zipf puts "
              "batched==off on runner/stream/push surfaces, series "
              "advanced (occupancy %d, flushes %d), lag gate 0 -> 1 "
              "under a %.1fs drain stall"
              % (live, vid, int(max(occ.values())),
                 int(max(fl.values())), STALL_S))
        return 0
    finally:
        if stream_resp is not None:
            try:
                stream_resp.close()
            except Exception:
                pass
        if proxy is not None:
            proxy.stop()
        for r in runners:
            r.join()


if __name__ == "__main__":
    sys.exit(main())
